"""Continuous profiling plane: span-correlated wall-clock sampling.

The third telemetry pillar next to metrics (obs/metrics.py) and spans
(obs/trace.py): an always-on, low-overhead **sampling profiler** that
answers the question the critical-path walk cannot — *what was the CPU
actually doing* during the intervals no span explains.

:class:`SamplingProfiler` is a timer thread over
``sys._current_frames()``: every ``1/hz`` seconds it snapshots every
thread's stack (bounded depth), tags each sample with the sampled
thread's

- **tenant** (``tenancy.tenant_of_ident`` — the cross-thread view of
  the ``tenant_scope`` thread-local),
- **active span category** (``trace.active_span_of_ident`` → the
  innermost open span, classified by ``attr.classify`` into the fixed
  attribution vocabulary; ``untraced`` when no span is open), and
- **role** (the executor id / process role, as a metric label),

and folds samples into a collapsed-stack table (root-first
``mod:func;mod:func`` keys). Tables ride the existing telemetry plane:
``Heartbeater.beat()`` drains the fold into the heartbeat payload's
``"profile"`` field, and the driver-side :class:`TelemetryHub` routes
it into a :class:`ProfileHub` that merges cluster-wide and renders
folded-stack text or a self-contained HTML flamegraph
(``python -m sparkrdma_tpu.obs --flamegraph``).

A bounded recent-sample ring (timestamped on the ``perf_counter``
axis) additionally lets ``obs/critpath.py`` annotate critical-path
**gap segments** with the dominant frames observed inside each gap
(:func:`annotate_gaps`), so ``last_breakdown`` shows idle-untraced
intervals as "blocked in ``socket.recv``" rather than a blank.

Overhead is budgeted, measured, and gated: the sampler's own self-time
accrues to ``profile.overhead_ms``, and ``bench.py
--ab profiler_overhead`` holds the throughput delta at default hz to
≤2% (docs/OBSERVABILITY.md "Continuous profiling").

Stdlib-only and jax-free, like the rest of ``obs/``.
"""

from __future__ import annotations

import html
import json
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from sparkrdma_tpu.obs import trace as _trace
from sparkrdma_tpu.obs.attr import classify
from sparkrdma_tpu.obs.metrics import get_registry

# span-category tag for samples on threads with no open span
UNTRACED = "untraced"


def _tenant_of_ident(ident: int) -> str:
    # lazy: tenancy's submodules import the obs package, so a module-
    # level import here would close a cycle through obs/__init__
    from sparkrdma_tpu.tenancy import tenant_of_ident

    return tenant_of_ident(ident)

# modules whose frames are pure profiler/telemetry plumbing; stacks
# that bottom out here are the plane observing itself, not workload
_SELF_MODULE = __name__


class SamplingProfiler:
    """Wall-clock sampling profiler for one process.

    One daemon timer thread; ``sample_once`` walks
    ``sys._current_frames()`` (excluding itself), folds each stack into
    the per-window collapsed table, and appends to the recent-sample
    ring used for gap annotation. All hot structures are plain dicts
    under one short-lived lock — the sampler never calls back into
    workload code and never holds a named (lock-order-tracked) lock.
    """

    def __init__(self, registry=None, *, role: str = "proc", hz: int = 19,
                 max_frames: int = 48, window_ms: int = 2000,
                 max_stacks: int = 4000, recent_samples: int = 8192):
        self.registry = registry if registry is not None else get_registry()
        self.role = role
        self.hz = max(1, int(hz))
        self.max_frames = max(4, int(max_frames))
        self.window_ms = max(100, int(window_ms))
        self.max_stacks = max(16, int(max_stacks))
        self._fold: Dict[Tuple[str, str, str], int] = {}
        self._fold_lock = threading.Lock()
        # (perf_counter_t, tenant, category, stack) — bounded ring for
        # time-windowed queries (gap annotation, flight-record window)
        self._recent: "deque[Tuple[float, str, str, str]]" = deque(
            maxlen=max(256, int(recent_samples))
        )
        self._stop_ev = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._c_samples = self.registry.counter("profile.samples", role=role)
        self._c_dropped = self.registry.counter("profile.dropped", role=role)
        self._c_overhead = self.registry.counter(
            "profile.overhead_ms", role=role
        )
        self._g_stacks = self.registry.gauge("profile.stacks", role=role)

    # -- lifecycle --------------------------------------------------------
    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            return self
        _trace.set_span_watch(True)
        self._stop_ev.clear()
        t = threading.Thread(
            target=self._run, name="sparkrdma-profiler", daemon=True
        )
        self._thread = t
        t.start()
        return self

    def stop(self) -> None:
        self._stop_ev.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None
        _trace.set_span_watch(False)

    def _run(self) -> None:
        period = 1.0 / self.hz
        while not self._stop_ev.wait(period):
            try:
                self.sample_once()
            except Exception:
                # a torn frame walk (thread exiting mid-snapshot) is a
                # dropped sample, never a crashed profiler
                self._c_dropped.inc()

    # -- sampling ---------------------------------------------------------
    def _fold_stack(self, frame) -> str:
        parts: List[str] = []
        depth = 0
        f = frame
        while f is not None and depth < 4 * self.max_frames:
            code = f.f_code
            parts.append(f"{f.f_globals.get('__name__', '?')}:{code.co_name}")
            f = f.f_back
            depth += 1
        parts.reverse()  # root-first, flamegraph.pl folded convention
        if len(parts) > self.max_frames:
            parts = ["..."] + parts[-self.max_frames:]
        return ";".join(parts)

    def sample_once(self) -> int:
        """One snapshot of every thread; returns samples recorded."""
        t0 = time.perf_counter()
        frames = sys._current_frames()
        own = threading.get_ident()
        rows: List[Tuple[str, str, str]] = []
        for ident, frame in frames.items():
            if ident == own:
                continue
            stack = self._fold_stack(frame)
            if not stack:
                continue
            tenant = _tenant_of_ident(ident)
            sp = _trace.active_span_of_ident(ident)
            category = classify(sp.name) if sp is not None else UNTRACED
            rows.append((tenant, category, stack))
        del frames  # drop the frame refs before doing anything else
        t_sample = time.perf_counter()
        n = 0
        dropped = 0
        with self._fold_lock:
            for key in rows:
                cnt = self._fold.get(key)
                if cnt is not None:
                    self._fold[key] = cnt + 1
                elif len(self._fold) < self.max_stacks:
                    self._fold[key] = 1
                else:
                    dropped += 1
                    continue
                n += 1
        for tenant, category, stack in rows:
            self._recent.append((t_sample, tenant, category, stack))
        if n:
            self._c_samples.inc(n)
        if dropped:
            self._c_dropped.inc(dropped)
        self._g_stacks.set(len(self._fold))
        self._c_overhead.inc((time.perf_counter() - t0) * 1e3)
        return n

    # -- table export -----------------------------------------------------
    def drain(self) -> Optional[dict]:
        """Swap out the collapsed-stack table folded since the last
        drain — the heartbeat's ``"profile"`` payload. None when no
        samples landed (so idle beats stay small)."""
        with self._fold_lock:
            if not self._fold:
                return None
            fold, self._fold = self._fold, {}
        rows = [[t, c, s, n] for (t, c, s), n in fold.items()]
        return {"hz": self.hz, "rows": rows}

    def window_rows(self, window_ms: Optional[int] = None) -> List[list]:
        """Collapsed rows for the trailing ``window_ms`` only (from the
        recent-sample ring) — the flight recorder's last-window view."""
        win_s = (window_ms if window_ms is not None else self.window_ms) / 1e3
        cutoff = time.perf_counter() - win_s
        fold: Dict[Tuple[str, str, str], int] = {}
        for t, tenant, category, stack in list(self._recent):
            if t >= cutoff:
                key = (tenant, category, stack)
                fold[key] = fold.get(key, 0) + 1
        return [[t, c, s, n] for (t, c, s), n in fold.items()]

    def frames_between(self, t0: float, t1: float,
                       top: int = 3) -> List[list]:
        """Dominant leaf frames sampled inside ``[t0, t1]`` as
        ``[[frame, count], ...]``. The interval may be on either time
        axis: raw ``perf_counter`` (in-process critical paths) or
        wall-clock seconds (epoch-rebased merges) — wall-clock inputs
        are shifted back by the process epoch anchor."""
        if t1 <= t0:
            return []
        if t0 > 1e8:  # wall-clock axis (perf_counter is process uptime)
            shift = _trace.epoch_anchor()
            t0, t1 = t0 - shift, t1 - shift
        counts: Dict[str, int] = {}
        for t, _tenant, _category, stack in list(self._recent):
            if t0 <= t <= t1:
                leaf = stack.rsplit(";", 1)[-1]
                counts[leaf] = counts.get(leaf, 0) + 1
        ranked = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
        return [[frame, cnt] for frame, cnt in ranked[:max(1, top)]]


# ----------------------------------------------------------------------
# process-wide profiler (refcounted: contexts/workers share one)
# ----------------------------------------------------------------------
_proc_lock = threading.Lock()
_proc_profiler: Optional[SamplingProfiler] = None
_proc_refs = 0


def acquire_profiler(conf=None, *, role: str = "proc",
                     registry=None) -> Optional[SamplingProfiler]:
    """Refcounted process-wide sampler. Returns None when
    ``tpu.shuffle.obs.profile.enabled`` is off; otherwise starts (or
    shares) the singleton — one timer thread per process no matter how
    many contexts/managers are live. Pair with :func:`release_profiler`.
    """
    global _proc_profiler, _proc_refs
    if conf is not None and not conf.profile_enabled:
        return None
    with _proc_lock:
        if _proc_profiler is None:
            kwargs = {}
            if conf is not None:
                kwargs = dict(
                    hz=conf.profile_hz,
                    max_frames=conf.profile_max_frames,
                    window_ms=conf.profile_window_ms,
                )
            _proc_profiler = SamplingProfiler(
                registry, role=role, **kwargs
            ).start()
            _proc_refs = 0
        _proc_refs += 1
        return _proc_profiler


def release_profiler(profiler: Optional[SamplingProfiler]) -> None:
    """Drop one reference; the last release stops the sampler thread."""
    global _proc_profiler, _proc_refs
    if profiler is None:
        return
    with _proc_lock:
        if profiler is not _proc_profiler:
            profiler.stop()  # a privately constructed sampler
            return
        _proc_refs -= 1
        if _proc_refs > 0:
            return
        _proc_profiler = None
        _proc_refs = 0
    profiler.stop()


def get_profiler() -> Optional[SamplingProfiler]:
    """The live process-wide sampler, or None."""
    return _proc_profiler


def annotate_gaps(path, top: int = 3) -> int:
    """Attach ``frames`` ([[frame, count], ...]) to every gap segment
    of a :class:`~sparkrdma_tpu.obs.critpath.CriticalPath` from the
    process profiler's recent samples. No-op (0) without a live
    profiler; returns the number of gaps annotated."""
    profiler = _proc_profiler
    if profiler is None:
        return 0
    n = 0
    for seg in path.segments:
        if seg.kind != "gap":
            continue
        frames = profiler.frames_between(seg.t0, seg.t1, top=top)
        if frames:
            seg.frames = frames
            n += 1
    return n


# ----------------------------------------------------------------------
# driver-side cluster merge
# ----------------------------------------------------------------------
class ProfileHub:
    """Merges per-executor collapsed-stack tables cluster-wide.

    Fed by ``TelemetryHub.ingest`` with each heartbeat's ``"profile"``
    payload; keeps (a) the bounded cluster-wide fold keyed
    ``(executor, tenant, category, stack)``, (b) the last non-empty
    window per executor (flight recorder), and (c) per-executor sample
    rates so counts convert to self-time.
    """

    def __init__(self, max_stacks: int = 20000, clock=time.time):
        self._lock = threading.Lock()
        self._merged: Dict[Tuple[str, str, str, str], int] = {}
        self._hz: Dict[str, float] = {}
        self._last_window: Dict[str, dict] = {}
        self._samples = 0
        self._dropped = 0
        self.max_stacks = max(16, int(max_stacks))
        self._clock = clock

    def ingest(self, executor_id: str, profile: Optional[dict],
               wall_ms: Optional[float] = None) -> int:
        """Fold one executor's drained table in; returns rows merged."""
        if not profile:
            return 0
        rows = profile.get("rows") or []
        hz = float(profile.get("hz") or 0.0)
        if not rows:
            return 0
        with self._lock:
            if hz > 0:
                self._hz[executor_id] = hz
            for tenant, category, stack, n in rows:
                key = (executor_id, str(tenant), str(category), str(stack))
                cnt = self._merged.get(key)
                if cnt is not None:
                    self._merged[key] = cnt + int(n)
                elif len(self._merged) < self.max_stacks:
                    self._merged[key] = int(n)
                else:
                    self._dropped += int(n)
                    continue
                self._samples += int(n)
            self._last_window[executor_id] = {
                "wall_ms": float(wall_ms if wall_ms is not None
                                 else self._clock() * 1e3),
                "hz": hz,
                "rows": [list(r) for r in rows],
            }
        return len(rows)

    def ingest_local(self, profiler: Optional[SamplingProfiler],
                     executor_id: Optional[str] = None) -> int:
        """Drain a same-process sampler straight into the merge (no
        heartbeat hop) — the CLI demo / driver-role path."""
        if profiler is None:
            return 0
        return self.ingest(executor_id or profiler.role, profiler.drain())

    # -- views ------------------------------------------------------------
    @property
    def total_samples(self) -> int:
        with self._lock:
            return self._samples

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def executors(self) -> List[str]:
        with self._lock:
            return sorted({k[0] for k in self._merged})

    def merged_rows(self) -> List[list]:
        """``[[executor, tenant, category, stack, count], ...]`` —
        descending by count."""
        with self._lock:
            items = sorted(self._merged.items(), key=lambda kv: -kv[1])
        return [[e, t, c, s, n] for (e, t, c, s), n in items]

    def category_self_ms(self) -> Dict[str, float]:
        """Per-span-category self-time (ms) implied by sample counts at
        each executor's sampling rate."""
        out: Dict[str, float] = {}
        with self._lock:
            for (executor, _t, category, _s), n in self._merged.items():
                hz = self._hz.get(executor) or 1.0
                out[category] = out.get(category, 0.0) + n * 1e3 / hz
        return {k: round(v, 3) for k, v in sorted(out.items())}

    def last_windows(self, top_rows: int = 40) -> Dict[str, dict]:
        """Last non-empty profile window per executor, rows trimmed to
        the ``top_rows`` hottest — the flight recorder attachment."""
        with self._lock:
            out = {}
            for executor, win in self._last_window.items():
                rows = sorted(win["rows"], key=lambda r: -r[3])[:top_rows]
                out[executor] = {
                    "wall_ms": win["wall_ms"], "hz": win["hz"], "rows": rows,
                }
            return out

    def summary(self) -> dict:
        with self._lock:
            return {
                "samples": self._samples,
                "stacks": len(self._merged),
                "dropped": self._dropped,
                "executors": sorted({k[0] for k in self._merged}),
            }

    # -- rendering --------------------------------------------------------
    def folded(self, tags: bool = True) -> str:
        """flamegraph.pl collapsed-stack text: one
        ``frame;frame;... count`` line per stack. With ``tags`` the
        executor / ``tenant:`` / ``span:`` tags lead the stack as
        synthetic frames, so any folded-stack tool groups by them."""
        lines = []
        for executor, tenant, category, stack, n in self.merged_rows():
            if tags:
                prefix = f"{executor};tenant:{tenant};span:{category};"
            else:
                prefix = ""
            lines.append(f"{prefix}{stack} {n}")
        return "\n".join(lines) + ("\n" if lines else "")

    def flamegraph_html(self, title: str = "sparkrdma_tpu profile",
                        tags: bool = True) -> str:
        """Self-contained HTML flamegraph (no external assets)."""
        stacks: List[Tuple[List[str], int]] = []
        for executor, tenant, category, stack, n in self.merged_rows():
            frames = stack.split(";")
            if tags:
                frames = [executor, f"tenant:{tenant}",
                          f"span:{category}"] + frames
            stacks.append((frames, n))
        return render_flamegraph_html(stacks, title=title)


# ----------------------------------------------------------------------
# self-contained HTML flamegraph renderer
# ----------------------------------------------------------------------
def _fold_tree(stacks: Sequence[Tuple[Sequence[str], int]]) -> dict:
    root: dict = {"n": "all", "v": 0, "c": {}}
    for frames, count in stacks:
        root["v"] += count
        node = root
        for frame in frames:
            child = node["c"].get(frame)
            if child is None:
                child = {"n": frame, "v": 0, "c": {}}
                node["c"][frame] = child
            child["v"] += count
            node = child
    def _listify(node: dict) -> dict:
        return {
            "n": node["n"], "v": node["v"],
            "c": [_listify(ch) for ch in sorted(
                node["c"].values(), key=lambda d: -d["v"])],
        }
    return _listify(root)


_FLAME_TEMPLATE = """<!doctype html>
<html><head><meta charset="utf-8"><title>__TITLE__</title>
<style>
 body { font: 12px monospace; margin: 12px; background: #fff; }
 #hdr { margin-bottom: 8px; }
 #status { color: #555; margin-top: 6px; min-height: 1.2em; }
 .fr { position: absolute; box-sizing: border-box; height: 17px;
       overflow: hidden; white-space: nowrap; cursor: pointer;
       border: 1px solid #fff; border-radius: 2px; padding: 0 3px;
       color: #222; }
 .fr:hover { border-color: #000; }
 #flame { position: relative; width: 100%; }
 a { color: #36c; cursor: pointer; }
</style></head><body>
<div id="hdr"><b>__TITLE__</b> — <span id="total"></span> samples
 · click a frame to zoom · <a id="reset">reset</a>
 <div id="status"></div></div>
<div id="flame"></div>
<script>
var DATA = __DATA__;
var flame = document.getElementById('flame');
var status_ = document.getElementById('status');
document.getElementById('total').textContent = DATA.v;
function color(name, depth) {
  if (name.indexOf('tenant:') === 0) return '#c8e6c9';
  if (name.indexOf('span:') === 0) return '#bbdefb';
  var h = 0;
  for (var i = 0; i < name.length; i++) h = (h * 31 + name.charCodeAt(i)) >>> 0;
  return 'hsl(' + (20 + h % 35) + ',' + (60 + h % 30) + '%,' +
         (62 + (h >> 8) % 14) + '%)';
}
function render(root) {
  flame.innerHTML = '';
  var W = flame.clientWidth || 960;
  var maxDepth = 0;
  function walk(node, x, depth, scale) {
    var w = node.v * scale;
    if (w < 1) return;
    if (depth > maxDepth) maxDepth = depth;
    var d = document.createElement('div');
    d.className = 'fr';
    d.style.left = x + 'px';
    d.style.top = (depth * 17) + 'px';
    d.style.width = Math.max(1, w - 1) + 'px';
    d.style.background = color(node.n, depth);
    d.textContent = node.n;
    d.title = node.n + ' — ' + node.v + ' samples (' +
              (100 * node.v / DATA.v).toFixed(1) + '%)';
    d.onclick = function (ev) { ev.stopPropagation(); zoom(node); };
    flame.appendChild(d);
    var cx = x;
    for (var i = 0; i < node.c.length; i++) {
      walk(node.c[i], cx, depth + 1, scale);
      cx += node.c[i].v * scale;
    }
  }
  walk(root, 0, 0, W / root.v);
  flame.style.height = ((maxDepth + 1) * 17 + 4) + 'px';
}
function zoom(node) {
  status_.textContent = (node === DATA) ? '' :
    'zoomed: ' + node.n + ' (' + node.v + ' samples)';
  render(node);
}
document.getElementById('reset').onclick = function () { zoom(DATA); };
window.onresize = function () { render(DATA); };
render(DATA);
</script></body></html>
"""


def render_flamegraph_html(stacks: Sequence[Tuple[Sequence[str], int]],
                           title: str = "profile") -> str:
    """Render collapsed stacks (``(frames_root_first, count)`` pairs)
    as one fully inline HTML document — no network, no external JS."""
    tree = _fold_tree(stacks)
    return (_FLAME_TEMPLATE
            .replace("__TITLE__", html.escape(title))
            .replace("__DATA__", json.dumps(tree)))
