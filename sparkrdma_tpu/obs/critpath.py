"""Critical-path extraction over the causal span DAG.

``obs/trace.py`` gives every span explicit causal edges: ``parent_id``
(contextvar nesting) and ``follows`` (queue/wire hand-offs, the
``SpanHandle`` seams catalogued in docs/OBSERVABILITY.md). This module
turns one job's span set into its **critical path** — the single
backward chain of spans that bounded the job's wall time — so
``obs/attr.py`` can fold the chain into a per-category
:class:`~sparkrdma_tpu.obs.attr.TimeBreakdown` verdict ("this job was
62% host-read, 20% decode, 8% rpc, 10% untraced").

Algorithm (backward walk, latest-ending-predecessor):

1. take every span overlapping the job window ``[t0, t1]`` (times on
   the merged wall-clock timeline — per-tracer epochs applied, so
   cross-process merges walk one axis);
2. start at the window end; repeatedly attribute ``[pred_end, cursor]``
   to the current span and jump to its best predecessor: an explicit
   causal edge (``follows`` origin, else the enclosing parent) when one
   ends at-or-before the cursor, else the latest-ending span that was
   running at the cursor (time containment — the fallback that keeps
   the walk alive across span-dark layers);
3. when the best predecessor ends strictly before the cursor, the
   uncovered interval becomes an explicit **gap segment** — the
   idle/untraced bucket that the ≥90% coverage acceptance gate bounds.

Loadable from live tracers (:func:`job_breakdown`, wired into
``TpuContext.run_job``) or from a saved Chrome-trace export
(:func:`spans_from_chrome`, the ``python -m sparkrdma_tpu.obs
--critical-path`` CLI).

Stdlib-only and jax-free, like the rest of ``obs/``.
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, List, Mapping, Optional, Sequence

from sparkrdma_tpu.obs.metrics import get_registry

# Attribution ignores intervals shorter than this (float jitter between
# adjacent queue hand-offs, not real idle time).
_EPS = 1e-6


class PSpan:
    """Placed span: a span projected onto the merged wall timeline.

    Mirrors the :class:`~sparkrdma_tpu.obs.trace.Span` attributes the
    walk needs, with ``t0``/``t1`` already epoch-rebased — one shape
    for live spans, heartbeat-merged remote spans, and spans
    reconstructed from a Chrome-trace file."""

    __slots__ = ("name", "role", "span_id", "parent_id", "follows",
                 "t0", "t1", "args")

    def __init__(self, name: str, role: str, span_id: int, parent_id: int,
                 t0: float, t1: float, follows: Optional[List[int]] = None,
                 args: Optional[Dict[str, object]] = None):
        self.name = name
        self.role = role
        self.span_id = int(span_id)
        self.parent_id = int(parent_id)
        self.t0 = float(t0)
        self.t1 = float(t1)
        self.follows = follows or []
        self.args = args or {}


def place_spans(spans: Iterable,
                epochs: Optional[Mapping[str, float]] = None) -> List[PSpan]:
    """Project ``Span`` objects (or ``(span, epoch)`` pairs) onto one
    timeline. With plain spans and no ``epochs`` map the raw
    ``perf_counter`` axis is kept — correct whenever every span came
    from this process (all tracers share the module anchor)."""
    epochs = epochs or {}
    out: List[PSpan] = []
    for item in spans:
        sp, ep = item if isinstance(item, tuple) else (item, 0.0)
        ep = epochs.get(sp.role, ep)
        out.append(PSpan(
            sp.name, sp.role, sp.span_id, sp.parent_id,
            ep + sp.start, ep + sp.end,
            [origin_id for _, origin_id in (sp.follows or ())],
            dict(sp.args),
        ))
    return out


class Seg:
    """One critical-path segment: ``[t0, t1]`` attributed to one span
    (``kind == "span"``) or to nothing (``kind == "gap"``).

    Gap segments may carry ``frames`` — the dominant leaf frames the
    sampling profiler observed inside the gap interval
    (``obs/profiler.py::annotate_gaps``), as ``[[frame, count], ...]``
    — turning "idle-untraced" into "what the CPU was actually doing".
    """

    __slots__ = ("kind", "name", "role", "span_id", "t0", "t1", "frames")

    def __init__(self, kind: str, name: str, role: str, span_id: int,
                 t0: float, t1: float):
        self.kind = kind
        self.name = name
        self.role = role
        self.span_id = span_id
        self.t0 = t0
        self.t1 = t1
        self.frames: Optional[List[list]] = None

    @property
    def dur_s(self) -> float:
        return max(0.0, self.t1 - self.t0)

    def to_dict(self) -> dict:
        out = {
            "kind": self.kind, "name": self.name, "role": self.role,
            "span_id": self.span_id,
            "ms": round(self.dur_s * 1e3, 3),
        }
        if self.frames:
            out["frames"] = self.frames
        return out


class CriticalPath:
    """The extracted path over one window: segments in time order."""

    __slots__ = ("t0", "t1", "segments")

    def __init__(self, t0: float, t1: float, segments: List[Seg]):
        self.t0 = t0
        self.t1 = t1
        self.segments = segments

    @property
    def wall_s(self) -> float:
        return max(0.0, self.t1 - self.t0)

    @property
    def traced_s(self) -> float:
        return sum(s.dur_s for s in self.segments if s.kind == "span")

    @property
    def coverage(self) -> float:
        """Fraction of the window attributed to real spans (0..1)."""
        wall = self.wall_s
        return (self.traced_s / wall) if wall > _EPS else 1.0

    def top_segments(self, n: int = 10) -> List[Seg]:
        return sorted(self.segments, key=lambda s: -s.dur_s)[:n]

    def to_dict(self) -> dict:
        return {
            "wall_ms": round(self.wall_s * 1e3, 3),
            "coverage": round(self.coverage, 4),
            "segments": [s.to_dict() for s in self.segments],
        }


def extract(spans: Sequence, t0: float, t1: float,
            exclude: Iterable[int] = (),
            epochs: Optional[Mapping[str, float]] = None) -> CriticalPath:
    """Walk the longest causal chain backward across ``[t0, t1]``.

    ``spans`` may be ``Span`` objects, ``(span, epoch)`` pairs, or
    pre-placed :class:`PSpan` — anything overlapping the window joins
    the DAG. ``exclude`` drops span ids (the enclosing job span itself,
    which would otherwise trivially cover the whole window)."""
    excluded = set(exclude)
    if spans and not isinstance(spans[0], PSpan):
        placed = place_spans(spans, epochs)
    else:
        placed = list(spans)
    pool = [
        p for p in placed
        if p.span_id not in excluded and p.t1 > t0 + _EPS and p.t0 < t1 - _EPS
    ]
    by_id: Dict[int, PSpan] = {p.span_id: p for p in pool}
    # time-containment fallback index: spans sorted by end descending,
    # scanned for "latest end at-or-before cursor, still running"
    by_end = sorted(pool, key=lambda p: -p.t1)

    def fallback_at(cursor: float) -> Optional[PSpan]:
        best: Optional[PSpan] = None
        for p in by_end:
            eff = min(p.t1, cursor)
            if p.t0 >= cursor - _EPS or eff <= t0 + _EPS:
                continue
            if best is None or eff > min(best.t1, cursor):
                best = p
            if p.t1 <= cursor and best is p:
                break  # by_end is end-sorted: nothing later can beat it
        return best

    segments: List[Seg] = []
    cursor = t1
    current = fallback_at(cursor)
    if current is not None and min(current.t1, cursor) < cursor - _EPS:
        # nothing was running at the window end: the tail is untraced
        segments.append(Seg("gap", "", "", 0, min(current.t1, cursor), cursor))
        cursor = min(current.t1, cursor)
    steps = 0
    limit = 2 * len(pool) + 64
    while cursor > t0 + _EPS and steps < limit:
        steps += 1
        if current is None:
            segments.append(Seg("gap", "", "", 0, t0, cursor))
            break
        lo = max(current.t0, t0)
        hi = min(current.t1, cursor)
        if hi > lo + _EPS:
            segments.append(Seg(
                "span", current.name, current.role, current.span_id, lo, hi,
            ))
        cursor = lo
        if cursor <= t0 + _EPS:
            break
        # explicit causal predecessors first: follows origins, then the
        # enclosing parent; both must have been live before the cursor
        nxt: Optional[PSpan] = None
        for oid in current.follows:
            cand = by_id.get(oid)
            if cand is not None and cand.t0 < cursor - _EPS:
                if nxt is None or min(cand.t1, cursor) > min(nxt.t1, cursor):
                    nxt = cand
        if nxt is None:
            parent = by_id.get(current.parent_id)
            if parent is not None and parent.t0 < cursor - _EPS:
                nxt = parent
        if nxt is None:
            nxt = fallback_at(cursor)
        if nxt is not None and min(nxt.t1, cursor) < cursor - _EPS:
            # predecessor ends before the hand-off: untraced interval
            gap_lo = min(nxt.t1, cursor)
            segments.append(Seg("gap", "", "", 0, max(gap_lo, t0), cursor))
            cursor = max(gap_lo, t0)
        current = nxt
    segments.reverse()
    return CriticalPath(t0, t1, segments)


# ----------------------------------------------------------------------
# Chrome-trace reconstruction (CLI over saved artifacts)
# ----------------------------------------------------------------------
def spans_from_chrome(doc: Mapping) -> List[PSpan]:
    """Rebuild placed spans from a ``to_chrome_trace`` export.

    Complete events (``ph:"X"``) carry ``args.span_id`` /
    ``args.parent_span``; the causal edges ride the flow events'
    ``args.from_span`` / ``args.to_span`` pairs. Events without a
    ``span_id`` (foreign traces) are skipped."""
    events = doc.get("traceEvents") or []
    pid_names: Dict[int, str] = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pid_names[ev.get("pid", 0)] = (ev.get("args") or {}).get("name", "")
    spans: Dict[int, PSpan] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        sid = args.get("span_id")
        if not sid:
            continue
        t0 = float(ev.get("ts", 0.0)) / 1e6
        t1 = t0 + float(ev.get("dur", 0.0)) / 1e6
        spans[int(sid)] = PSpan(
            str(ev.get("name", "")),
            pid_names.get(ev.get("pid", 0), str(ev.get("pid", ""))),
            int(sid), int(args.get("parent_span", 0) or 0),
            t0, t1, args=dict(args),
        )
    for ev in events:
        if ev.get("ph") != "s" or ev.get("cat") != "critpath":
            continue
        args = ev.get("args") or {}
        follower = spans.get(int(args.get("to_span", 0) or 0))
        origin_id = int(args.get("from_span", 0) or 0)
        if follower is not None and origin_id:
            follower.follows.append(origin_id)
    return list(spans.values())


# ----------------------------------------------------------------------
# the engine's entry point: one finished job span -> TimeBreakdown
# ----------------------------------------------------------------------
def job_breakdown(job_span, spans: Optional[Sequence] = None,
                  role: str = "driver"):
    """Build the critical path across ``job_span``'s window and fold it
    into a :class:`~sparkrdma_tpu.obs.attr.TimeBreakdown`. Registers
    the ``critpath.*`` build metrics. ``spans`` defaults to every live
    tracer's spans (in-process cluster)."""
    from sparkrdma_tpu.obs.attr import attribute, publish_breakdown
    from sparkrdma_tpu.obs.profiler import annotate_gaps
    from sparkrdma_tpu.obs.trace import collect_spans

    t_build0 = time.perf_counter()
    if spans is None:
        spans = collect_spans()
    path = extract(spans, job_span.start, job_span.end,
                   exclude={job_span.span_id})
    # gap segments get their dominant sampled frames BEFORE attribution
    # folds segments into dicts (no-op without a live process profiler)
    annotate_gaps(path)
    verdict = attribute(path)
    # feedback seam: attribution-driven controllers (the wave
    # self-tuner, shuffle/autotune.py) read the latest verdict here
    publish_breakdown(verdict)
    reg = get_registry()
    reg.counter("critpath.builds", role=role).inc()
    reg.histogram("critpath.build_ms", role=role).observe(
        (time.perf_counter() - t_build0) * 1e3
    )
    reg.gauge("critpath.coverage_pct").set(int(round(verdict.coverage * 100)))
    return verdict
