"""Distributed transformer train step — dp x sp x tp in one jit.

A compact demonstration that the framework's mesh vocabulary composes
into a real training step (the thing the multi-chip dry-run validates):

- **dp**: batch sharded over the ``dp`` axis; gradients psum across it,
- **sp**: sequence sharded over the ``sp`` axis; exact ring attention
  (kv blocks hop neighbour-to-neighbour with an online softmax — the
  same schedule as :mod:`sparkrdma_tpu.ops.ring_attention`),
- **tp**: the MLP hidden dimension Megatron-sharded over the ``tp``
  axis; activations stay replicated on tp, the second matmul's partial
  sums reduce with one psum.

Everything — forward, ring hops, tp reduction, loss, backward (via
jax.value_and_grad inside shard_map), cross-shard gradient reduction,
SGD update — runs inside ONE jitted SPMD program, compile-once.

Weights: attention projections replicated (their grads psum over
dp+sp; tp shards compute identical copies); W1 [D, H/tp] and
W2 [H/tp, D] are tp-local (their grads psum over dp+sp only).
"""

from __future__ import annotations

import functools
import math
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from sparkrdma_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.ops.ulysses_attention import ulysses_shard_attention

NEG_INF = -1e30


@jax.custom_vjp
def _tp_copy(x):
    """Megatron's "f" operator: identity forward, all-reduce backward.

    The column-parallel matmul consumes a tp-replicated activation;
    each tp shard's backward produces only its slice's contribution to
    dx, so the cotangent must psum over tp here — otherwise every
    parameter upstream of the MLP receives a partial gradient."""
    return x


def _tp_copy_fwd(x):
    return x, None


def _tp_copy_bwd(_, ct):
    return (jax.lax.psum(ct, "tp"),)


_tp_copy.defvjp(_tp_copy_fwd, _tp_copy_bwd)


@jax.custom_vjp
def _tp_psum(x):
    """Megatron's "g" operator: all-reduce forward, identity backward.

    Conjugate of :func:`_tp_copy`. A bare ``lax.psum`` cannot be used
    here: under ``shard_map(check_vma=False)`` psum transposes to psum,
    so the row-parallel matmul's cotangent would arrive multiplied by
    the tp group size (the replicated downstream cotangent gets summed
    over tp), scaling the w1/w2 gradients by exactly ``tp``. The
    correct adjoint of "replicated ct through an all-reduce" is the
    identity — each tp shard already holds the full cotangent."""
    return jax.lax.psum(x, "tp")


def _tp_psum_fwd(x):
    return jax.lax.psum(x, "tp"), None


def _tp_psum_bwd(_, ct):
    return (ct,)


_tp_psum.defvjp(_tp_psum_fwd, _tp_psum_bwd)


def make_training_mesh(devices=None) -> Mesh:
    """(dp, sp, tp) mesh over 8+ devices (2x2x2 at 8)."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if n % 8 == 0:
        shape = (n // 4, 2, 2)
    elif n % 4 == 0:
        shape = (n // 4, 2, 2)
    elif n % 2 == 0:
        shape = (n // 2, 2, 1)
    else:
        shape = (1, 1, 1)
        devices = devices[:1]
    k = shape[0] * shape[1] * shape[2]
    return Mesh(np.array(devices[:k]).reshape(shape), ("dp", "sp", "tp"))


def init_params(d_model: int, n_heads: int, d_hidden: int, tp: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    s = 0.02

    def w(*shape):
        return (rng.normal(size=shape) * s).astype(np.float32)

    return {
        "wq": w(d_model, d_model),
        "wk": w(d_model, d_model),
        "wv": w(d_model, d_model),
        "wo": w(d_model, d_model),
        "w1": w(d_model, d_hidden),  # sharded on dim 1 over tp
        "w2": w(d_hidden, d_model),  # sharded on dim 0 over tp
    }


class TransformerStep:
    """One-layer attention+MLP block with an SGD train step.

    ``attn`` selects the sequence-parallel schedule:

    - ``"ring"`` (default): kv blocks hop neighbour-to-neighbour over
      the sp axis with an online-softmax accumulation — O(s/sp) memory,
      jnp-level math, differentiated by autodiff through ppermute.
    - ``"ulysses"``: two ``all_to_all``s re-shard seq<->heads and the
      full-sequence attention per head group runs through the Pallas
      flash kernel — trainable thanks to the kernel's custom VJP, so
      the backward also never materializes [Sq, Sk]. Requires
      ``n_heads % sp == 0``.
    """

    def __init__(self, mesh: Optional[Mesh] = None, n_heads: int = 4,
                 lr: float = 0.1, attn: str = "ring"):
        if attn not in ("ring", "ulysses"):
            raise ValueError(f"unknown attn schedule {attn!r}")
        self.mesh = mesh if mesh is not None else make_training_mesh()
        if attn == "ulysses" and n_heads % self.mesh.shape["sp"] != 0:
            raise ValueError(
                f"ulysses needs n_heads ({n_heads}) divisible by the sp "
                f"axis ({self.mesh.shape['sp']})"
            )
        self.n_heads = n_heads
        self.lr = lr
        self.attn = attn
        self._cache: Dict = {}

    # ------------------------------------------------------------------
    def _build(self, b, s, d, h):
        mesh = self.mesh
        sp = mesh.shape["sp"]
        heads = self.n_heads
        lr = self.lr
        dhead = d // heads

        x_spec = P("dp", "sp", None)
        rep = P()
        w1_spec = P(None, "tp")
        w2_spec = P("tp", None)
        pspecs = {
            "wq": rep, "wk": rep, "wv": rep, "wo": rep,
            "w1": w1_spec, "w2": w2_spec,
        }

        def ring_attn(q, k, v):
            # q/k/v: [b_loc, s_loc, H, dh]; ring over the sp axis
            perm = [(i, (i + 1) % sp) for i in range(sp)]
            bl, sl = q.shape[0], q.shape[1]
            m = jnp.full((bl, heads, sl), NEG_INF, jnp.float32)
            num = jnp.zeros((bl, sl, heads, dhead), jnp.float32)
            den = jnp.zeros((bl, heads, sl), jnp.float32)
            scale = 1.0 / math.sqrt(dhead)
            kb, vb = k, v
            for hop in range(sp):
                sc = jnp.einsum("bqhd,bkhd->bhqk", q, kb).astype(jnp.float32) * scale
                m_new = jnp.maximum(m, sc.max(-1))
                corr = jnp.exp(m - m_new)
                p = jnp.exp(sc - m_new[..., None])
                num = num * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
                    "bhqk,bkhd->bqhd", p, vb.astype(jnp.float32)
                )
                den = den * corr + p.sum(-1)
                m = m_new
                if hop != sp - 1:
                    kb = jax.lax.ppermute(kb, "sp", perm)
                    vb = jax.lax.ppermute(vb, "sp", perm)
            return (num / den.transpose(0, 2, 1)[..., None]).astype(q.dtype)

        def ulysses_attn(q, k, v):
            # one shared shard-level schedule (ops/ulysses_attention):
            # seq-gather / head-scatter, full-seq flash per head group,
            # inverse exchange — gradients flow through all_to_all (its
            # own transpose) and the flash kernel's custom VJP
            return ulysses_shard_attention(q, k, v, "sp", sp, causal=False)

        attn_fn = ring_attn if self.attn == "ring" else ulysses_attn

        def forward_local(params, x):
            bl, sl, _ = x.shape
            def qkv(w):
                return (x @ w).reshape(bl, sl, heads, dhead)

            attn = attn_fn(qkv(params["wq"]), qkv(params["wk"]), qkv(params["wv"]))
            x = x + attn.reshape(bl, sl, d) @ params["wo"]
            # Megatron MLP: column-parallel w1, row-parallel w2; the
            # _tp_copy/psum pair is the f/g conjugate operator pair
            hcol = jax.nn.gelu(_tp_copy(x) @ params["w1"])  # [bl, sl, H/tp]
            mlp = _tp_psum(hcol @ params["w2"])
            return x + mlp

        # global element count is static: every (dp, sp) shard holds an
        # equal tile of the [b, s, d] batch
        n_shards = mesh.shape["dp"] * mesh.shape["sp"]

        def train_shard(params, x, y):
            # The differentiated function must return the LOCAL loss
            # contribution (no dp/sp psum inside): under
            # check_vma=False psum transposes to psum, so a psum'd loss
            # seeds every shard with the full group cotangent and the
            # explicit psum(grads) below would then double-count by a
            # factor of dp*sp. Sum-reduce local grads/losses AFTER the
            # backward instead.
            def loss_fn(p):
                out = forward_local(p, x)
                return ((out - y) ** 2).sum()

            gcount = jnp.asarray(x.size * n_shards, jnp.float32)
            sq, grads = jax.value_and_grad(loss_fn)(params)
            loss = jax.lax.psum(sq, ("dp", "sp")) / gcount
            # cross-shard reduction: every param's grad sums over dp+sp;
            # tp-sharded params keep their local slice, replicated params
            # computed identical grads on every tp shard (x replicated on
            # tp), so no tp reduction is needed for either kind
            grads = jax.tree.map(
                lambda g: jax.lax.psum(g, ("dp", "sp")) / gcount, grads
            )
            new = jax.tree.map(lambda p, g: p - lr * g, params, grads)
            return loss, new

        fn = shard_map(
            train_shard,
            mesh=mesh,
            in_specs=(pspecs, x_spec, x_spec),
            out_specs=(P(), pspecs),
            check_vma=False,
        )
        return jax.jit(fn)

    # ------------------------------------------------------------------
    def place(self, params, x, y):
        mesh = self.mesh
        def put(a, spec):
            return jax.device_put(a, NamedSharding(mesh, spec))

        pl = {
            "wq": put(params["wq"], P()),
            "wk": put(params["wk"], P()),
            "wv": put(params["wv"], P()),
            "wo": put(params["wo"], P()),
            "w1": put(params["w1"], P(None, "tp")),
            "w2": put(params["w2"], P("tp", None)),
        }
        return pl, put(x, P("dp", "sp", None)), put(y, P("dp", "sp", None))

    def _get_step_fn(self, b, s, d, h):
        key = (b, s, d, h)
        fn = self._cache.get(key)
        if fn is None:
            fn = self._build(b, s, d, h)
            self._cache[key] = fn
        return fn

    def step(self, params, x, y):
        """(loss, new_params) — one SGD step, fully sharded."""
        b, s, d = x.shape
        return self._get_step_fn(b, s, d, params["w1"].shape[1])(params, x, y)

    def run_steps(self, params, x, y, n_steps: int):
        """(final_loss, new_params) after ``n_steps`` SGD steps with the
        WHOLE loop inside one executable (DESIGN.md §4: compile-once is
        the SVC pattern — even inter-step collective scheduling is
        compiled, and a K-step run costs one dispatch)."""
        b, s, d = x.shape
        h = params["w1"].shape[1]
        key = (b, s, d, h, "loop")
        loop = self._cache.get(key)
        if loop is None:
            step_fn = self._get_step_fn(b, s, d, h)

            @functools.partial(jax.jit, static_argnums=(3,))
            def loop(params, x, y, n):
                def body(_, carry):
                    _, p = carry
                    return step_fn(p, x, y)

                return jax.lax.fori_loop(
                    0, n, body, (jnp.float32(0.0), params)
                )

            self._cache[key] = loop
        return loop(params, x, y, n_steps)


def reference_step(params, x, y, n_heads: int, lr: float):
    """Single-device implementation of the identical math."""
    d = x.shape[-1]
    dhead = d // n_heads

    def forward(p, x):
        b, s, _ = x.shape
        def qkv(w):
            return (x @ w).reshape(b, s, n_heads, dhead)

        q, k, v = qkv(p["wq"]), qkv(p["wk"]), qkv(p["wv"])
        sc = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / math.sqrt(dhead)
        att = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(sc, -1), v)
        x = x + att.reshape(b, s, d) @ p["wo"]
        return x + jax.nn.gelu(x @ p["w1"]) @ p["w2"]

    def loss_fn(p):
        out = forward(p, x)
        return ((out - y) ** 2).mean()

    loss, grads = jax.value_and_grad(loss_fn)(params)
    return loss, jax.tree.map(lambda p, g: p - lr * g, params, grads)
