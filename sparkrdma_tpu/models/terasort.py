"""Device-resident distributed TeraSort — the framework's flagship workload.

The reference's headline benchmark is HiBench TeraSort-175GB, 1.41x
over stock Spark sort shuffle (README.md:7-19, BASELINE.md). Its
pipeline is: map tasks range-partition records -> all-to-all shuffle
over one-sided RDMA READ -> reduce tasks merge-sort their range
(SURVEY.md §3.3-3.4). The TPU-native pipeline keeps the same three
stages but runs them *where the bytes live*:

  partition (radix on top key bits, on-device)
    -> exchange (ExchangeProgram: lax.all_to_all over ICI/DCN)
    -> merge (masked sort of the received slab, on-device)

all inside ONE jitted SPMD program per (mesh, shard size, capacity) —
compile-once / execute-many, the reference's SVC pattern. Output:
shard i of the mesh holds the globally i-th sorted key range, sorted
— i.e. a total global sort.

Static-shape handling (SURVEY.md §7.3(2)): each peer bucket holds
``capacity = ceil(N/E) * capacity_factor`` keys; the step returns an
``overflowed`` flag instead of silently corrupting, and the host
retries with the next capacity class — exactly how the registered
pool re-rounds sizes.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from sparkrdma_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.ops.sort import (
    device_sort,
    merge_received,
    split_sorted,
    split_sorted_edges,
)
from sparkrdma_tpu.parallel.mesh import make_mesh, shard_spec

KEY_BITS = 32
SENTINEL = jnp.uint32(0xFFFFFFFF)


class MapShardSorter:
    """Device sort + range-partition of ONE map shard — the map plane's
    compute kernel (pipelined map plane, DESIGN.md).

    The e2e map side was losing to the host baseline by running
    ``np.sort`` per shard while the device sort this framework owns
    runs ~9x host speed (BENCH_r05 ``device_sort_gbps``); this class
    moves that O(N log N) step onto the chip: pad the shard with the
    key-space sentinel, one ``device_sort`` (the measured optimum,
    ops/sort.py), then a device-side ``searchsorted`` against the
    reducer range edges — the shard lands back on host already sorted
    AND cut at every reducer boundary, so staging is pure slicing.

    Compile-once/execute-many: shards pad up to a power-of-two size
    class, so jit's dispatch cache holds ONE executable per
    (size class, num edges) — the SVC pattern every model here follows.
    Edges ride as a device ARGUMENT (not a static), so different
    reducer counts reuse nothing but different edge VALUES recompile
    nothing.
    """

    def __init__(self, device=None):
        self._device = device

        @jax.jit
        def _step(padded, edges, n_valid):
            s = device_sort(padded)
            # sentinels sort to the tail; clamp every cut to the valid
            # count so an edge above the max real key can't spill a
            # reducer's bound into the padding
            cuts = jnp.minimum(
                jnp.searchsorted(s, edges).astype(jnp.int32), n_valid
            )
            return s, cuts

        self._step = _step

    @staticmethod
    def _size_class(n: int) -> int:
        return max(1024, 1 << (n - 1).bit_length())

    def warm(self, n: int, num_edges: int) -> None:
        """Compile the (size class, edges) executable ahead of the
        timed path — the JVM-startup analogue the ledger excludes."""
        cap = self._size_class(n)
        jax.block_until_ready(
            self._step(
                jnp.full((cap,), SENTINEL, jnp.uint32),
                jnp.zeros((num_edges,), jnp.uint32),
                jnp.int32(0),
            )[0]
        )

    def sort_partition(
        self, keys: np.ndarray, edges: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Sort ``keys`` (uint32) and cut at ``edges`` (ascending reducer
        range boundaries, len = num_reducers - 1).

        Returns ``(sorted_keys [n], bounds [num_reducers + 1])`` with
        reducer r's keys at ``sorted_keys[bounds[r]:bounds[r + 1]]``.
        """
        n = len(keys)
        cap = self._size_class(n)
        padded = np.full((cap,), np.uint32(SENTINEL), dtype=np.uint32)
        padded[:n] = keys
        dev = jnp.asarray(padded)
        if self._device is not None:
            dev = jax.device_put(dev, self._device)
        s, cuts = self._step(
            dev, jnp.asarray(edges, jnp.uint32), jnp.int32(n)
        )
        local = np.asarray(s)[:n]
        bounds = np.concatenate(
            [[0], np.asarray(cuts, dtype=np.int64), [n]]
        )
        return local, bounds

    def sort_columnar_partition(
        self, frame, edges: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray]:
        """:meth:`sort_partition` taken straight off a columnar block
        (DESIGN.md §25): column 0 of ``frame`` is the uint32 key column,
        decoded as an ``np.frombuffer`` view aliasing the landed bytes —
        the view feeds the size-class pad copy directly, so consuming a
        fetched shuffle block on-device costs header validation plus
        the one HBM DMA. No pickle, no per-record tuples."""
        from sparkrdma_tpu.shuffle import columnar

        keys = columnar.decode_columns(frame)[0]
        if keys.dtype != np.uint32:
            raise TypeError(
                f"columnar key column is {keys.dtype}, expected uint32"
            )
        return self.sort_partition(keys, edges)


class TeraSorter:
    """Compile-once global sorter over a device mesh.

    ``sort_sharded`` maps [E, n_local] uint32 keys (sharded over the
    mesh) to [E, P*capacity] sorted rows plus per-shard valid counts;
    row i's valid prefix is globally the i-th key range.
    """

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        capacity_factor: float = 2.0,
    ):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.num_shards = math.prod(self.mesh.shape.values())
        if self.num_shards & (self.num_shards - 1):
            raise ValueError("TeraSorter requires a power-of-two shard count")
        self.capacity_factor = capacity_factor
        self._step_cache = {}

    # ------------------------------------------------------------------
    def _build_step(self, n_local: int, capacity: int, adaptive: bool = False):
        e = self.num_shards
        axes = tuple(self.mesh.axis_names)
        spec = shard_spec(self.mesh)

        def shard_fn(keys, edges=None):  # keys: [n_local] uint32 shard
            if e == 1:
                # single-shard short circuit: no split, no exchange — the
                # reference's invariant #2 (local partitions never loop
                # through the network, RdmaShuffleFetcherIterator.scala:328-339).
                # device_sort == lax.sort, the measured optimum for this
                # chip (ops/sort.py module doc, DESIGN.md §6) — the same
                # delegation the reference makes to Spark's sort writers.
                merged = device_sort(keys)
                total = jnp.asarray([keys.shape[0]], jnp.int32)
                return merged, total, jnp.zeros((), jnp.int32)
            # local sort FIRST: destinations are key ranges, so sorted
            # keys are grouped by destination and the send slab falls out
            # of range-edge slices — measured ~25x cheaper than the
            # argsort/scatter pack at 32M keys (benchmarks/sort_study.py)
            local = device_sort(keys)
            if adaptive:
                # sampled quantile edges ride as DATA (replicated over
                # the mesh): the adaptive planner's cuts balance the
                # receive counts under skew, and a re-plan changes only
                # values — the executable is reused (ops/sort.py
                # split_sorted_edges, shuffle/planner.py plan_edges)
                slab, counts, overflowed = split_sorted_edges(
                    local, edges, capacity, fill=int(SENTINEL)
                )
            else:
                slab, counts, overflowed = split_sorted(
                    local, e, capacity, KEY_BITS, fill=int(SENTINEL)
                )
            # one all_to_all delivers every peer's bucket — the one-sided
            # READ plane collapsed into a single XLA collective
            recv = jax.lax.all_to_all(slab, axes, split_axis=0, concat_axis=0, tiled=True)
            rcounts = jax.lax.all_to_all(counts, axes, split_axis=0, concat_axis=0, tiled=True)
            merged, total = merge_received(recv, rcounts, int(SENTINEL))
            # any shard overflowing must abort the round everywhere
            overflowed = jax.lax.pmax(overflowed.astype(jnp.int32), axes)
            return merged, total[None], overflowed

        # the non-adaptive step keeps its historic single-argument
        # signature (bench.py / graft entry call step(n)(keys)); only
        # the adaptive variant threads the replicated edges array
        in_specs = (spec, P()) if adaptive else (spec,)
        fn = shard_map(
            shard_fn,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=(spec, spec, P()),
            check_vma=False,
        )
        return jax.jit(fn)

    def step(
        self,
        n_local: int,
        capacity: Optional[int] = None,
        adaptive: bool = False,
    ):
        """The jitted SPMD sort step for [E*n_local] global keys."""
        if capacity is None:
            capacity = self.default_capacity(n_local)
        key = (n_local, capacity, adaptive)
        fn = self._step_cache.get(key)
        if fn is None:
            fn = self._build_step(n_local, capacity, adaptive)
            self._step_cache[key] = fn
        return fn

    def default_capacity(self, n_local: int) -> int:
        cap = int(math.ceil(n_local / self.num_shards) * self.capacity_factor)
        return max(8, cap)

    # ------------------------------------------------------------------
    def sort(
        self,
        keys: np.ndarray,
        adaptive: bool = False,
        sample_size: int = 4096,
    ) -> np.ndarray:
        """Host-facing total sort of uint32 keys (pads to shard multiple).

        Retries with doubled capacity on bucket overflow (skewed data),
        mirroring the pool's size-class re-rounding. With ``adaptive``
        the shard range edges come from a host-side key sample
        (shuffle/planner.py ``plan_edges``) instead of static top bits,
        and the capacity class is sized from the sampled shard shares —
        under zipf skew this replaces several overflow-retry executions
        at doubled capacity with ONE right-sized run."""
        n = len(keys)
        e = self.num_shards
        n_local = int(math.ceil(n / e))
        padded = np.full((e * n_local,), np.uint32(SENTINEL), dtype=np.uint32)
        padded[:n] = keys
        sharding = NamedSharding(self.mesh, shard_spec(self.mesh))
        dev = jax.device_put(padded, sharding)

        use_adaptive = adaptive and e > 1 and n > 0
        if use_adaptive:
            from sparkrdma_tpu.shuffle.planner import (
                capacity_from_sample,
                plan_edges,
            )

            sample = keys[:: max(1, n // max(1, sample_size))][:sample_size]
            edges_np = plan_edges(sample, e)
            # + e covers the injected SENTINEL padding (< e keys, all
            # routed to the last shard); clamp to n_local (a sender
            # holds no more)
            capacity = min(
                n_local, capacity_from_sample(sample, e, n_local,
                                              edges=edges_np) + e,
            )
        else:
            edges_np = np.zeros((max(0, e - 1),), dtype=np.uint32)
            capacity = self.default_capacity(n_local)
        edges = jnp.asarray(edges_np, jnp.uint32)

        for _ in range(8):
            fn = self.step(n_local, capacity, adaptive=use_adaptive)
            merged, totals, overflowed = (
                fn(dev, edges) if use_adaptive else fn(dev)
            )
            if not bool(overflowed):
                break
            # n_local is a hard ceiling: one sender holds n_local keys,
            # so no per-destination run can exceed it
            capacity = min(n_local, capacity * 2)
        else:
            raise RuntimeError("terasort bucket overflow after 8 capacity doublings")

        merged = np.asarray(merged).reshape(e, -1)
        totals = np.asarray(totals).reshape(-1)
        out = np.concatenate([merged[i, : totals[i]] for i in range(e)])
        # drop the padding sentinels we injected (they sort to the tail)
        return out[:n] if n < len(out) else out
