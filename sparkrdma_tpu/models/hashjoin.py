"""Device-resident distributed hash join — the shuffle-heavy join workload.

BASELINE.md workload #3 (TPC-DS q64/q72: shuffle-heavy hash joins). The
Spark plan repartitions both tables by join key and hash-joins each
partition; here both sides radix-partition on the key's top bits, ride
ONE all_to_all each, and the local join is a sort + ``searchsorted``
probe — dense vector ops instead of a hash table, which is the
TPU-shaped way to probe (binary search over a sorted build side
vectorizes; chasing hash buckets does not).

Join shape: build side has UNIQUE keys (the dimension-table case those
TPC-DS queries hit); every probe row matches at most one build row, so
the output is exactly probe-sized — static shapes end to end. Probe
rows with no match return ``miss_value`` (left-outer semantics; filter
client-side for inner).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from sparkrdma_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.models.terasort import KEY_BITS, SENTINEL
from sparkrdma_tpu.ops.sort import pack_by_partition, radix_partition
from sparkrdma_tpu.parallel.mesh import make_mesh, shard_spec


class HashJoin:
    """Compile-once distributed join over a device mesh."""

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        capacity_factor: float = 2.0,
        miss_value: int = -1,
    ):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.num_shards = math.prod(self.mesh.shape.values())
        if self.num_shards & (self.num_shards - 1):
            raise ValueError("HashJoin requires a power-of-two shard count")
        self.capacity_factor = capacity_factor
        self.miss_value = miss_value
        self._cache = {}

    # ------------------------------------------------------------------
    def _build(self, nb_local: int, np_local: int, cap_b: int, cap_p: int):
        e = self.num_shards
        axes = tuple(self.mesh.axis_names)
        spec = shard_spec(self.mesh)
        miss = self.miss_value

        def a2a(x):
            return jax.lax.all_to_all(x, axes, split_axis=0, concat_axis=0, tiled=True)

        def shard_fn(bk, bv, pk, pv):
            # bk/bv: [nb_local] build keys/values; pk/pv: [np_local] probe
            # 1) repartition both sides by key range (two exchanges)
            def scatter(keys, vals, cap):
                dest = radix_partition(keys, e, KEY_BITS)
                kslab, counts, overflow = pack_by_partition(
                    keys, dest, e, cap, fill=int(SENTINEL)
                )
                vslab, _, _ = pack_by_partition(vals, dest, e, cap, fill=miss)
                return a2a(kslab), a2a(vslab), a2a(counts), overflow

            bk2, bv2, bcnt, ovf_b = scatter(bk, bv, cap_b)
            pk2, pv2, pcnt, ovf_p = scatter(pk, pv, cap_p)
            overflow = jax.lax.pmax(
                (ovf_b | ovf_p).astype(jnp.int32), axes
            )

            # 2) local join: sort the build side, binary-search the probes
            bmask = (
                jnp.arange(cap_b)[None, :] < bcnt[:, None]
            ).reshape(-1)
            bkeys = jnp.where(bmask, bk2.reshape(-1), SENTINEL)
            order = jnp.argsort(bkeys)
            bkeys_s = bkeys[order]
            bvals_s = bv2.reshape(-1)[order]

            pmask = (
                jnp.arange(cap_p)[None, :] < pcnt[:, None]
            ).reshape(-1)
            pkeys = pk2.reshape(-1)
            pos = jnp.searchsorted(bkeys_s, pkeys)
            pos = jnp.minimum(pos, bkeys_s.shape[0] - 1)
            hit = (bkeys_s[pos] == pkeys) & pmask
            joined = jnp.where(hit, bvals_s[pos], miss)
            # [E, cap_p] rows aligned with pk2/pv2 for the caller to
            # re-associate via the returned counts
            return (
                pk2,
                pv2,
                joined.reshape(e, cap_p),
                pcnt,
                overflow,
            )

        fn = shard_map(
            shard_fn,
            mesh=self.mesh,
            in_specs=(spec, spec, spec, spec),
            out_specs=(spec, spec, spec, spec, P()),
            check_vma=False,
        )
        return jax.jit(fn)

    # ------------------------------------------------------------------
    def join(
        self,
        build_keys: np.ndarray,
        build_vals: np.ndarray,
        probe_keys: np.ndarray,
        probe_vals: np.ndarray,
    ) -> np.ndarray:
        """Left-outer join; returns [m, 3] (probe_key, probe_val,
        build_val-or-miss) rows, one per probe row (order not preserved).
        Retries with doubled bucket capacity on skew overflow."""
        e = self.num_shards

        def shard_pad(x, fill):
            n = len(x)
            n_local = int(math.ceil(n / e))
            dtype = np.uint32 if fill == int(SENTINEL) else np.int32
            out = np.full((e * n_local,), fill, dtype=dtype)
            out[:n] = x
            return out, n_local

        bk, nb = shard_pad(build_keys.astype(np.uint32), int(SENTINEL))
        bv, _ = shard_pad(build_vals.astype(np.int32), self.miss_value)
        pk, npl = shard_pad(probe_keys.astype(np.uint32), int(SENTINEL))
        pv, _ = shard_pad(probe_vals.astype(np.int32), self.miss_value)

        sharding = NamedSharding(self.mesh, shard_spec(self.mesh))
        args = [jax.device_put(x, sharding) for x in (bk, bv, pk, pv)]

        cap_b = max(8, int(math.ceil(nb / e) * self.capacity_factor))
        cap_p = max(8, int(math.ceil(npl / e) * self.capacity_factor))
        for _ in range(8):
            key = (nb, npl, cap_b, cap_p)
            fn = self._cache.get(key)
            if fn is None:
                fn = self._build(nb, npl, cap_b, cap_p)
                self._cache[key] = fn
            pk2, pv2, joined, pcnt, overflow = fn(*args)
            if not bool(overflow):
                break
            cap_b *= 2
            cap_p *= 2
        else:
            raise RuntimeError("join bucket overflow after 8 capacity doublings")

        pk2 = np.asarray(pk2).reshape(e, e, -1)
        pv2 = np.asarray(pv2).reshape(e, e, -1)
        joined = np.asarray(joined).reshape(e, e, -1)
        pcnt = np.asarray(pcnt).reshape(e, e)
        rows = []
        for d in range(e):
            for s in range(e):
                c = pcnt[d, s]
                for j in range(c):
                    k = pk2[d, s, j]
                    if k == int(SENTINEL):
                        continue  # padding rows injected by shard_pad
                    rows.append((k, pv2[d, s, j], joined[d, s, j]))
        return np.array(rows, dtype=np.int64)
