"""Device-resident ALS matrix factorization — the iterative wide shuffle.

BASELINE.md workload #4 (MLlib ALS on MovieLens-20M). In Spark each
half-iteration is a wide shuffle carrying the other side's factor
blocks to every partition; here that exchange is one ``all_gather``
over the mesh per half-iteration (factors ride ICI), and the per-row
normal-equation solves are batched dense ops on the MXU
(``vmap``-batched Cholesky-style solves over static padded rating
lists).

Layout: users and items block-sharded over the mesh. Ratings are
preprocessed host-side into padded per-row lists
``[n_rows_local, max_nnz]`` of (col, rating), -1 padded — the same
static-shape bucketing discipline as the exchange plane. The whole
alternating loop runs inside ONE jit (compile-once / iterate-many).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from sparkrdma_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding

from sparkrdma_tpu.parallel.mesh import make_mesh, shard_spec


def _pad_rows(rows, cap):
    out_idx = np.full((len(rows), cap), -1, dtype=np.int32)
    out_val = np.zeros((len(rows), cap), dtype=np.float32)
    for i, lst in enumerate(rows):
        k = min(len(lst), cap)
        if k:
            arr = np.asarray(lst[:k])
            out_idx[i, :k] = arr[:, 0]
            out_val[i, :k] = arr[:, 1]
    return out_idx, out_val


class ALS:
    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        rank: int = 8,
        reg: float = 0.1,
    ):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.num_shards = math.prod(self.mesh.shape.values())
        self.rank = rank
        self.reg = reg
        self._cache = {}

    # ------------------------------------------------------------------
    def prepare(self, ratings: np.ndarray, n_users: int, n_items: int):
        """ratings: [m, 3] (user, item, rating). Returns padded per-user
        and per-item lists sharded over the mesh."""
        e = self.num_shards
        nu = int(math.ceil(n_users / e))
        ni = int(math.ceil(n_items / e))
        by_user = [[] for _ in range(e * nu)]
        by_item = [[] for _ in range(e * ni)]
        for u, i, r in ratings:
            u, i = int(u), int(i)
            by_user[u].append((i, float(r)))
            by_item[i].append((u, float(r)))
        cap_u = max(1, max(len(x) for x in by_user))
        cap_i = max(1, max(len(x) for x in by_item))
        u_idx, u_val = _pad_rows(by_user, cap_u)
        i_idx, i_val = _pad_rows(by_item, cap_i)
        return (u_idx, u_val, i_idx, i_val, nu, ni)

    # ------------------------------------------------------------------
    def _build(self, nu, ni, cap_u, cap_i, iters):
        axes = tuple(self.mesh.axis_names)
        spec2 = shard_spec(self.mesh)
        k = self.rank
        reg = self.reg

        def solve_side(own_idx, own_val, other_all):
            # own_idx/val: [n_local, cap]; other_all: [N_other, k]
            def per_row(idx, val):
                valid = (idx >= 0).astype(jnp.float32)  # [cap]
                f = other_all[jnp.maximum(idx, 0)]      # [cap, k]
                f = f * valid[:, None]
                a = f.T @ f + reg * jnp.maximum(valid.sum(), 1.0) * jnp.eye(k)
                b = f.T @ (val * valid)
                return jnp.linalg.solve(a, b)

            return jax.vmap(per_row)(own_idx, own_val)

        def shard_fn(u_idx, u_val, i_idx, i_val, u0, v0):
            def one_iter(_, carry):
                u, v = carry
                # the wide shuffle: every shard needs the other side's
                # factors — one all_gather per half-iteration
                v_all = jax.lax.all_gather(v, axes, tiled=True)  # [N_items, k]
                u_new = solve_side(u_idx, u_val, v_all)
                u_all = jax.lax.all_gather(u_new, axes, tiled=True)
                v_new = solve_side(i_idx, i_val, u_all)
                return u_new, v_new

            return jax.lax.fori_loop(0, iters, one_iter, (u0, v0))

        fn = shard_map(
            shard_fn,
            mesh=self.mesh,
            in_specs=(spec2, spec2, spec2, spec2, spec2, spec2),
            out_specs=(spec2, spec2),
            check_vma=False,
        )
        return jax.jit(fn)

    # ------------------------------------------------------------------
    def fit(
        self, ratings: np.ndarray, n_users: int, n_items: int, iters: int = 10,
        seed: int = 0,
    ) -> Tuple[np.ndarray, np.ndarray]:
        u_idx, u_val, i_idx, i_val, nu, ni = self.prepare(ratings, n_users, n_items)
        e = self.num_shards
        key = (nu, ni, u_idx.shape[1], i_idx.shape[1], iters)
        fn = self._cache.get(key)
        if fn is None:
            fn = self._build(nu, ni, u_idx.shape[1], i_idx.shape[1], iters)
            self._cache[key] = fn
        rng = np.random.default_rng(seed)
        u0 = (rng.normal(size=(e * nu, self.rank)) * 0.1).astype(np.float32)
        v0 = (rng.normal(size=(e * ni, self.rank)) * 0.1).astype(np.float32)
        sharding = NamedSharding(self.mesh, shard_spec(self.mesh))
        args = [
            jax.device_put(x, sharding)
            for x in (u_idx, u_val, i_idx, i_val, u0, v0)
        ]
        u, v = fn(*args)
        return np.asarray(u)[:n_users], np.asarray(v)[:n_items]


def rmse(u: np.ndarray, v: np.ndarray, ratings: np.ndarray) -> float:
    pred = (u[ratings[:, 0].astype(int)] * v[ratings[:, 1].astype(int)]).sum(axis=1)
    return float(np.sqrt(np.mean((pred - ratings[:, 2]) ** 2)))


def reference_als(
    ratings: np.ndarray, n_users: int, n_items: int, rank=8, reg=0.1,
    iters=10, seed=0, u0: Optional[np.ndarray] = None,
    v0: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Dense numpy ALS (same math, fp64) for correctness checks."""
    rng = np.random.default_rng(seed)
    u = u0.copy() if u0 is not None else rng.normal(size=(n_users, rank)) * 0.1
    v = v0.copy() if v0 is not None else rng.normal(size=(n_items, rank)) * 0.1
    by_user = [[] for _ in range(n_users)]
    by_item = [[] for _ in range(n_items)]
    for a, b, r in ratings:
        by_user[int(a)].append((int(b), r))
        by_item[int(b)].append((int(a), r))

    def solve(rows, other):
        out = np.zeros((len(rows), rank))
        for i, lst in enumerate(rows):
            if not lst:
                continue
            idx = np.array([x[0] for x in lst])
            val = np.array([x[1] for x in lst])
            f = other[idx]
            a = f.T @ f + reg * len(lst) * np.eye(rank)
            out[i] = np.linalg.solve(a, f.T @ val)
        return out

    for _ in range(iters):
        u = solve(by_user, v)
        v = solve(by_item, u)
    return u.astype(np.float32), v.astype(np.float32)
