"""Benchmark workload models (the reference's HiBench role, SURVEY.md §6).

The reference published exactly one number — TeraSort wall-clock
(README.md:7-19) — with no benchmark code in-repo. This package IS
that missing benchmark code for the TPU framework: fully-jittable
distributed workloads built on the device exchange plane.
"""

from sparkrdma_tpu.models.als import ALS
from sparkrdma_tpu.models.hashjoin import HashJoin
from sparkrdma_tpu.models.pagerank import PageRank
from sparkrdma_tpu.models.terasort import MapShardSorter, TeraSorter

__all__ = ["ALS", "HashJoin", "MapShardSorter", "PageRank", "TeraSorter"]
