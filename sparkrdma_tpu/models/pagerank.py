"""Device-resident distributed PageRank — the multi-round all-to-all workload.

BASELINE.md workload #5 (GraphX PageRank on twitter-2010: "multi-round
all-to-all"). The reference would run this as one Spark shuffle per
iteration; here every iteration is a single jitted SPMD step whose
exchange is one ``lax.all_to_all`` over the mesh — the same collective
the shuffle read path rides, exercised iteratively.

Layout: vertices dense-sharded over the mesh ([E, n_local] ranks).
Edges are preprocessed host-side into per-(src-shard → dst-shard)
padded blocks, so each shard scatter-adds its out-contributions into E
destination-shard vectors (static shapes), exchanges them, and sums
what it receives:

  contrib[d] = Σ_{(s→t) edges to shard d} rank[s] / outdeg[s]
  rank' = (1-α)/N + α · (Σ_src received contrib + dangling share)

The whole power iteration runs in ONE jit (``lax.fori_loop`` with the
collective inside) — compile-once / iterate-many.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from sparkrdma_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding

from sparkrdma_tpu.parallel.mesh import make_mesh, shard_spec


class PageRank:
    def __init__(self, mesh: Optional[Mesh] = None, damping: float = 0.85):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.num_shards = math.prod(self.mesh.shape.values())
        self.damping = damping
        self._cache = {}

    # ------------------------------------------------------------------
    def prepare(self, edges: np.ndarray, num_vertices: int):
        """Host-side preprocessing: pad per-(src,dst)-shard edge blocks.

        ``edges``: [m, 2] int array of (src, dst). Vertices are
        block-partitioned: vertex v lives on shard v // n_local.
        Returns arrays ready for :meth:`run`.
        """
        e = self.num_shards
        n_local = int(math.ceil(num_vertices / e))
        src, dst = edges[:, 0], edges[:, 1]
        outdeg = np.bincount(src, minlength=num_vertices).astype(np.float32)
        s_shard, d_shard = src // n_local, dst // n_local
        # bucket edges by (src shard, dst shard)
        cap = 0
        buckets = {}
        for i in range(e):
            for j in range(e):
                sel = (s_shard == i) & (d_shard == j)
                blk = edges[sel]
                buckets[(i, j)] = blk
                cap = max(cap, len(blk))
        cap = max(cap, 1)
        # padded local-index blocks: [E_src, E_dst, cap, 2], -1 = padding
        packed = np.full((e, e, cap, 2), -1, dtype=np.int32)
        for (i, j), blk in buckets.items():
            if len(blk):
                packed[i, j, : len(blk), 0] = blk[:, 0] % n_local
                packed[i, j, : len(blk), 1] = blk[:, 1] % n_local
        deg = np.zeros((e * n_local,), dtype=np.float32)
        deg[:num_vertices] = outdeg
        return packed, deg, n_local

    # ------------------------------------------------------------------
    def _build(self, n_local: int, cap: int, iters: int, num_vertices: int):
        axes = tuple(self.mesh.axis_names)
        spec = shard_spec(self.mesh)
        alpha = self.damping

        def shard_fn(rank, deg, valid, blocks):
            # rank/deg/valid: [n_local]; blocks: [E_dst, cap, 2] local
            # indices. ``valid`` masks the padding slots that exist only
            # because num_vertices does not divide the shard count —
            # they must hold zero rank and shed no dangling mass.
            safe_deg = jnp.maximum(deg, 1.0)

            def one_iter(_, r):
                outc = jnp.where(deg > 0, r / safe_deg, 0.0)
                # dangling mass is redistributed uniformly (standard PR)
                dangling = jax.lax.psum(
                    jnp.where((deg == 0) & (valid > 0), r, 0.0).sum(), axes
                )

                def contrib_for(blk):
                    s_idx, d_idx = blk[:, 0], blk[:, 1]
                    valid = s_idx >= 0
                    vals = jnp.where(valid, outc[jnp.maximum(s_idx, 0)], 0.0)
                    return jnp.zeros((n_local,), jnp.float32).at[
                        jnp.maximum(d_idx, 0)
                    ].add(vals, mode="drop")

                contribs = jax.vmap(contrib_for)(blocks)  # [E_dst, n_local]
                # one all_to_all per iteration: row d -> shard d
                recv = jax.lax.all_to_all(
                    contribs, axes, split_axis=0, concat_axis=0, tiled=True
                )
                inflow = recv.sum(axis=0)
                r_new = (1.0 - alpha) / num_vertices + alpha * (
                    inflow + dangling / num_vertices
                )
                return jnp.where(valid > 0, r_new, 0.0)

            return jax.lax.fori_loop(0, iters, one_iter, rank)

        fn = shard_map(
            shard_fn,
            mesh=self.mesh,
            in_specs=(spec, spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )
        return jax.jit(fn)

    # ------------------------------------------------------------------
    def run(
        self, edges: np.ndarray, num_vertices: int, iters: int = 20
    ) -> np.ndarray:
        packed, deg, n_local = self.prepare(edges, num_vertices)
        e = self.num_shards
        cap = packed.shape[2]
        key = (n_local, cap, iters, num_vertices)
        fn = self._cache.get(key)
        if fn is None:
            fn = self._build(n_local, cap, iters, num_vertices)
            self._cache[key] = fn
        sharding = NamedSharding(self.mesh, shard_spec(self.mesh))
        r0 = np.zeros((e * n_local,), dtype=np.float32)
        r0[:num_vertices] = 1.0 / num_vertices
        valid = np.zeros((e * n_local,), dtype=np.float32)
        valid[:num_vertices] = 1.0
        rank0 = jax.device_put(r0, sharding)
        deg_d = jax.device_put(deg, sharding)
        valid_d = jax.device_put(valid, sharding)
        blocks = jax.device_put(
            packed.reshape(e * e, cap, 2),
            NamedSharding(self.mesh, shard_spec(self.mesh)),
        )
        out = fn(rank0, deg_d, valid_d, blocks)
        return np.asarray(out)[:num_vertices]


def reference_pagerank(
    edges: np.ndarray, num_vertices: int, iters: int = 20, damping: float = 0.85
) -> np.ndarray:
    """Dense numpy power iteration for correctness checks."""
    rank = np.full((num_vertices,), 1.0 / num_vertices, dtype=np.float64)
    outdeg = np.bincount(edges[:, 0], minlength=num_vertices).astype(np.float64)
    for _ in range(iters):
        contrib = np.zeros(num_vertices, dtype=np.float64)
        outc = np.divide(rank, outdeg, out=np.zeros_like(rank), where=outdeg > 0)
        np.add.at(contrib, edges[:, 1], outc[edges[:, 0]])
        dangling = rank[outdeg == 0].sum()
        rank = (1 - damping) / num_vertices + damping * (
            contrib + dangling / num_vertices
        )
    return rank.astype(np.float32)
