"""Device compute plane: exchange programs, HBM arenas, sort kernels.

This package is the TPU-native replacement for the reference's verbs
data plane (RdmaChannel.java one-sided READ machinery + RdmaBufferManager
registered-memory pools): compile-once XLA exchange programs over a
device mesh, size-classed HBM slab pools, and the on-device partition /
sort kernels that make shuffle *compute* live where the bytes live.
"""

from sparkrdma_tpu.ops.exchange import ExchangeProgram, pack_blocks, unpack_blocks
from sparkrdma_tpu.ops.hbm_arena import DeviceBuffer, DeviceBufferManager
from sparkrdma_tpu.ops.pallas_attention import flash_attention
from sparkrdma_tpu.ops.ring_attention import RingAttention
from sparkrdma_tpu.ops.ulysses_attention import UlyssesAttention

__all__ = [
    "flash_attention",
    "ExchangeProgram",
    "pack_blocks",
    "unpack_blocks",
    "DeviceBuffer",
    "DeviceBufferManager",
    "RingAttention",
    "UlyssesAttention",
]
