"""On-device partition / pack / merge kernels for the shuffle compute path.

The reference's write path serializes records into per-partition
blocks (sort-shuffle files or registered chunks, SURVEY.md §3.3); the
read path re-aggregates blocks per source (§3.4). On TPU the same
stages become dense vector ops that XLA fuses:

- ``radix_partition``: dest-partition assignment from the key's top
  bits (the range partitioner of TeraSort),
- ``pack_by_partition``: stable counting-sort layout into a
  [num_partitions, capacity] bucketed send slab + counts — static
  shapes with a length prefix per row, overflow *detected* rather than
  avoided (host re-runs with the next bucket class, like the pool's
  power-of-two re-rounding),
- ``merge_received``: mask + sort of the post-exchange slab.

All functions are jit-safe (static shapes, no data-dependent Python
control flow).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def radix_partition(keys: jax.Array, num_partitions: int, key_bits: int = 32) -> jax.Array:
    """Destination partition per key from its top log2(P) bits.

    ``num_partitions`` must be a power of two (TeraSort's uniform key
    space makes top-bit ranges perfectly balanced)."""
    if num_partitions & (num_partitions - 1):
        raise ValueError("num_partitions must be a power of two")
    shift = key_bits - (num_partitions.bit_length() - 1)
    if shift >= key_bits:
        return jnp.zeros(keys.shape, dtype=jnp.int32)
    return (keys >> shift).astype(jnp.int32)


def pack_by_partition(
    values: jax.Array, dest: jax.Array, num_partitions: int, capacity: int,
    fill: int = 0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Stable counting-sort scatter of ``values`` into fixed rows.

    Returns ``(slab [P, capacity], counts [P], overflowed scalar bool)``.
    Rows hold each partition's values in input order, padded with
    ``fill``; entries beyond a row's count are padding. If any
    partition exceeds ``capacity`` its surplus is clamped into the last
    slot and ``overflowed`` is set — callers must check it and retry
    with a larger bucket class (static shapes forbid growing in-kernel).
    """
    n = values.shape[0]
    counts = jnp.bincount(dest, length=num_partitions).astype(jnp.int32)
    overflowed = jnp.any(counts > capacity)
    # stable sort by destination gives contiguous per-partition runs
    order = jnp.argsort(dest, stable=True)
    sorted_vals = values[order]
    sorted_dest = dest[order]
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)[:-1].astype(jnp.int32)]
    )
    # rank within the run = global sorted position - run start
    pos = jnp.arange(n, dtype=jnp.int32) - starts[sorted_dest]
    pos = jnp.minimum(pos, capacity - 1)  # clamp overflow into last slot
    slab = jnp.full((num_partitions, capacity), fill, dtype=values.dtype)
    slab = slab.at[sorted_dest, pos].set(sorted_vals, mode="drop")
    return slab, jnp.minimum(counts, capacity), overflowed


def merge_received(
    slab: jax.Array, counts: jax.Array, sentinel: int
) -> Tuple[jax.Array, jax.Array]:
    """Mask padding to ``sentinel`` and sort the flattened slab.

    Returns ``(sorted flat values, total valid count)``; valid entries
    occupy the prefix when ``sentinel`` is the dtype max."""
    p, cap = slab.shape
    valid = jnp.arange(cap, dtype=jnp.int32)[None, :] < counts[:, None]
    flat = jnp.where(valid, slab, jnp.asarray(sentinel, slab.dtype)).reshape(-1)
    return jnp.sort(flat), counts.sum()
