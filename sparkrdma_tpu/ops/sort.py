"""On-device partition / pack / merge kernels for the shuffle compute path.

The reference's write path serializes records into per-partition
blocks (sort-shuffle files or registered chunks, SURVEY.md §3.3); the
read path re-aggregates blocks per source (§3.4). On TPU the same
stages become dense vector ops that XLA fuses:

- ``device_sort``: the framework's exact device sort — ``lax.sort``,
  chosen by measurement (see below), the primitive under every other
  op here,
- ``radix_partition``: dest-partition assignment from the key's top
  bits (the range partitioner of TeraSort),
- ``split_sorted``: partition an already-sorted key array into a
  [num_partitions, capacity] bucketed send slab by slicing at the
  radix range boundaries — the fast path when keys are sortable by
  their destination (TeraSort), measured ~25x cheaper than the
  scatter-based general pack at 32M keys,
- ``pack_by_partition``: stable counting-sort layout into the same
  slab shape for arbitrary (dest, value) pairs (hash joins) — static
  shapes with a length prefix per row, overflow *detected* rather than
  avoided (host re-runs with the next bucket class, like the pool's
  power-of-two re-rounding),
- ``merge_received``: mask + sort of the post-exchange slab.

Why ``lax.sort`` and not a bespoke kernel (measured on a v5e chip,
reproduce with ``benchmarks/sort_study.py``; full table in
docs/DESIGN.md §6): a flat 32M-u32 ``lax.sort`` runs at ~83 ms — the
VPU comparator roofline for a ~310-stage bitonic network, executing at
~0.25 ms/stage. Short-row sorts are far cheaper per pass (3.9 ms for
[131072, 256]), but completing them into a total sort needs ~290 merge
stages that cost ~1.4-3.6 ms EACH when composed from jnp reshape +
min/max (XLA fuses its own sort stages ~5-14x better than anything
expressible at the jnp level), and scatter-based radix passes run at
0.06-0.55 GB/s. Every expressible decomposition we measured or bounded
costs 3-6x the flat sort. This mirrors the reference exactly: SparkRDMA
never replaced Spark's sort machinery — it delegated to Spark's own
sort writers (RdmaWrapperShuffleWriter.scala:85-101) and accelerated
the byte plane underneath. We delegate to XLA's sort and do the same.

All functions are jit-safe (static shapes, no data-dependent Python
control flow).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def device_put_columns(frame, device=None):
    """Pickle-free device staging of one columnar block (DESIGN.md §25).

    ``frame`` is a columnar frame's bytes/memoryview as landed by the
    fetch path (shuffle/columnar.py). Its fixed-width columns decode as
    ``np.frombuffer`` views ALIASING the landed buffer — zero host
    copies — and each view stages to the device as one contiguous DMA
    (``jax.device_put`` / ``jnp.asarray``). No pickle decode, no
    per-record tuples, no per-block ``bytes()`` materialization: the
    whole host-side cost of consuming a shuffle block on-device is the
    header validation. Returns one ``jax.Array`` per column.
    """
    from sparkrdma_tpu.shuffle import columnar

    cols = columnar.decode_columns(frame)
    if device is None:
        return [jnp.asarray(c) for c in cols]
    return [jax.device_put(c, device) for c in cols]


def device_sort(x: jax.Array) -> jax.Array:
    """The framework's exact device sort (ascending, any shape's last axis
    or flat 1-D).

    Implementation: ``jnp.sort`` (XLA's fused bitonic-network lowering),
    selected by measurement over row-wise decompositions, jnp-composed
    merge trees, Pallas compare-exchange kernels, and scatter-based
    radix passes — see the module docstring and docs/DESIGN.md §6. The
    reference delegates sorting to Spark's sort writers the same way
    (RdmaWrapperShuffleWriter.scala:85-101); the transport planes are
    where this framework spends its own silicon.
    """
    return jnp.sort(x)


def radix_partition(keys: jax.Array, num_partitions: int, key_bits: int = 32) -> jax.Array:
    """Destination partition per key from its top log2(P) bits.

    ``num_partitions`` must be a power of two (TeraSort's uniform key
    space makes top-bit ranges perfectly balanced)."""
    if num_partitions & (num_partitions - 1):
        raise ValueError("num_partitions must be a power of two")
    shift = key_bits - (num_partitions.bit_length() - 1)
    if shift >= key_bits:
        return jnp.zeros(keys.shape, dtype=jnp.int32)
    return (keys >> shift).astype(jnp.int32)


def pack_by_partition(
    values: jax.Array, dest: jax.Array, num_partitions: int, capacity: int,
    fill: int = 0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Stable counting-sort scatter of ``values`` into fixed rows.

    Returns ``(slab [P, capacity], counts [P], overflowed scalar bool)``.
    Rows hold each partition's values in input order, padded with
    ``fill``; entries beyond a row's count are padding. If any
    partition exceeds ``capacity`` its surplus is clamped into the last
    slot and ``overflowed`` is set — callers must check it and retry
    with a larger bucket class (static shapes forbid growing in-kernel).
    """
    n = values.shape[0]
    counts = jnp.bincount(dest, length=num_partitions).astype(jnp.int32)
    overflowed = jnp.any(counts > capacity)
    # stable sort by destination gives contiguous per-partition runs
    order = jnp.argsort(dest, stable=True)
    sorted_vals = values[order]
    sorted_dest = dest[order]
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)[:-1].astype(jnp.int32)]
    )
    # rank within the run = global sorted position - run start
    pos = jnp.arange(n, dtype=jnp.int32) - starts[sorted_dest]
    pos = jnp.minimum(pos, capacity - 1)  # clamp overflow into last slot
    slab = jnp.full((num_partitions, capacity), fill, dtype=values.dtype)
    slab = slab.at[sorted_dest, pos].set(sorted_vals, mode="drop")
    return slab, jnp.minimum(counts, capacity), overflowed


def split_sorted(
    sorted_keys: jax.Array, num_partitions: int, capacity: int,
    key_bits: int = 32, fill: int = 0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Bucketed send slab from an ALREADY-SORTED key array.

    TeraSort's destination is a key-range (top bits), so locally-sorted
    keys are already grouped by destination: the per-partition runs are
    contiguous and found with a searchsorted against the range edges —
    no argsort, no scatter. Each run is laid into its slab row with one
    masked gather at a dynamic offset. Measured (v5e, 32M keys,
    benchmarks/sort_study.py): the scatter-based general pack costs
    ~2.1 s/step; local sort (83 ms) + this split is ~25x cheaper — the
    packing strategy the SPMD TeraSort step uses.

    Returns ``(slab [P, capacity], counts [P], overflowed scalar
    bool)``; semantics identical to :func:`pack_by_partition` (rows
    padded with ``fill``; surplus clamped; caller retries a larger
    capacity class on overflow).
    """
    if num_partitions & (num_partitions - 1):
        raise ValueError("num_partitions must be a power of two")
    n = sorted_keys.shape[0]
    p = num_partitions
    shift = key_bits - (p.bit_length() - 1)
    # range edges: partition e owns keys in [e << shift, (e+1) << shift);
    # computed as static Python ints (uint64 is unavailable under the
    # default x64-disabled config, and e << shift fits the key dtype)
    edges = jnp.asarray([e << shift for e in range(1, p)], sorted_keys.dtype)
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.searchsorted(sorted_keys, edges).astype(jnp.int32)]
    )
    ends = jnp.concatenate([starts[1:], jnp.asarray([n], jnp.int32)])
    counts = ends - starts
    overflowed = jnp.any(counts > capacity)
    # row e = keys[starts[e] : starts[e]+capacity]: one dynamic_slice per
    # partition (contiguous, static size — never the slow gather path);
    # pad the tail so a run near the end can't clamp-shift its window
    padded = jnp.concatenate(
        [sorted_keys, jnp.full((capacity,), fill, sorted_keys.dtype)]
    )
    rows = [
        jax.lax.dynamic_slice(padded, (starts[e],), (capacity,))
        for e in range(p)
    ]
    slab = jnp.stack(rows, axis=0)
    valid = jnp.arange(capacity, dtype=jnp.int32)[None, :] < counts[:, None]
    slab = jnp.where(valid, slab, jnp.asarray(fill, sorted_keys.dtype))
    return slab, jnp.minimum(counts, capacity), overflowed


def split_sorted_edges(
    sorted_keys: jax.Array, edges: jax.Array, capacity: int, fill: int = 0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """:func:`split_sorted` with the range edges as a TRACED argument.

    ``edges`` is an ascending ``[P-1]`` array: partition ``e`` owns
    keys in ``[edges[e-1], edges[e])``. The static variant derives its
    edges from the key's top bits, which balances only a uniform key
    space; this one takes sampled quantile edges from the adaptive
    planner (shuffle/planner.py ``plan_edges``) so a zipf-skewed run
    balances its receive counts instead of overflowing one shard's
    capacity class. Because ``edges`` is data, not structure, the same
    compiled step serves every re-plan — no recompile when the sample
    shifts the cuts. ``P`` comes from ``edges.shape[0] + 1`` (static)
    and need not be a power of two. Same return contract as
    :func:`split_sorted`."""
    n = sorted_keys.shape[0]
    p = edges.shape[0] + 1
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.searchsorted(sorted_keys, edges.astype(sorted_keys.dtype))
         .astype(jnp.int32)]
    )
    ends = jnp.concatenate([starts[1:], jnp.asarray([n], jnp.int32)])
    counts = ends - starts
    overflowed = jnp.any(counts > capacity)
    padded = jnp.concatenate(
        [sorted_keys, jnp.full((capacity,), fill, sorted_keys.dtype)]
    )
    rows = [
        jax.lax.dynamic_slice(padded, (starts[e],), (capacity,))
        for e in range(p)
    ]
    slab = jnp.stack(rows, axis=0)
    valid = jnp.arange(capacity, dtype=jnp.int32)[None, :] < counts[:, None]
    slab = jnp.where(valid, slab, jnp.asarray(fill, sorted_keys.dtype))
    return slab, jnp.minimum(counts, capacity), overflowed


def merge_received(
    slab: jax.Array, counts: jax.Array, sentinel: int
) -> Tuple[jax.Array, jax.Array]:
    """Mask padding to ``sentinel`` and sort the flattened slab.

    Returns ``(sorted flat values, total valid count)``; valid entries
    occupy the prefix when ``sentinel`` is the dtype max."""
    p, cap = slab.shape
    valid = jnp.arange(cap, dtype=jnp.int32)[None, :] < counts[:, None]
    flat = jnp.where(valid, slab, jnp.asarray(sentinel, slab.dtype)).reshape(-1)
    return device_sort(flat), counts.sum()
