"""On-device partition / pack / merge kernels for the shuffle compute path.

The reference's write path serializes records into per-partition
blocks (sort-shuffle files or registered chunks, SURVEY.md §3.3); the
read path re-aggregates blocks per source (§3.4). On TPU the same
stages become dense vector ops that XLA fuses:

- ``radix_partition``: dest-partition assignment from the key's top
  bits (the range partitioner of TeraSort),
- ``pack_by_partition``: stable counting-sort layout into a
  [num_partitions, capacity] bucketed send slab + counts — static
  shapes with a length prefix per row, overflow *detected* rather than
  avoided (host re-runs with the next bucket class, like the pool's
  power-of-two re-rounding),
- ``merge_received``: mask + sort of the post-exchange slab.

All functions are jit-safe (static shapes, no data-dependent Python
control flow).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def radix_partition(keys: jax.Array, num_partitions: int, key_bits: int = 32) -> jax.Array:
    """Destination partition per key from its top log2(P) bits.

    ``num_partitions`` must be a power of two (TeraSort's uniform key
    space makes top-bit ranges perfectly balanced)."""
    if num_partitions & (num_partitions - 1):
        raise ValueError("num_partitions must be a power of two")
    shift = key_bits - (num_partitions.bit_length() - 1)
    if shift >= key_bits:
        return jnp.zeros(keys.shape, dtype=jnp.int32)
    return (keys >> shift).astype(jnp.int32)


def pack_by_partition(
    values: jax.Array, dest: jax.Array, num_partitions: int, capacity: int,
    fill: int = 0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Stable counting-sort scatter of ``values`` into fixed rows.

    Returns ``(slab [P, capacity], counts [P], overflowed scalar bool)``.
    Rows hold each partition's values in input order, padded with
    ``fill``; entries beyond a row's count are padding. If any
    partition exceeds ``capacity`` its surplus is clamped into the last
    slot and ``overflowed`` is set — callers must check it and retry
    with a larger bucket class (static shapes forbid growing in-kernel).
    """
    n = values.shape[0]
    counts = jnp.bincount(dest, length=num_partitions).astype(jnp.int32)
    overflowed = jnp.any(counts > capacity)
    # stable sort by destination gives contiguous per-partition runs
    order = jnp.argsort(dest, stable=True)
    sorted_vals = values[order]
    sorted_dest = dest[order]
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)[:-1].astype(jnp.int32)]
    )
    # rank within the run = global sorted position - run start
    pos = jnp.arange(n, dtype=jnp.int32) - starts[sorted_dest]
    pos = jnp.minimum(pos, capacity - 1)  # clamp overflow into last slot
    slab = jnp.full((num_partitions, capacity), fill, dtype=values.dtype)
    slab = slab.at[sorted_dest, pos].set(sorted_vals, mode="drop")
    return slab, jnp.minimum(counts, capacity), overflowed


def _bitonic_merge_rows(v: jax.Array) -> jax.Array:
    """Bitonic merge of each row of ``v`` ([R, L], every row a bitonic
    sequence, L a power of two) into ascending order: log2(L) fully
    vectorized compare-exchange stages along the lane dimension."""
    rows, length = v.shape
    d = length // 2
    while d >= 1:
        w = v.reshape(rows, length // (2 * d), 2, d)
        lo = jnp.minimum(w[:, :, 0, :], w[:, :, 1, :])
        hi = jnp.maximum(w[:, :, 0, :], w[:, :, 1, :])
        v = jnp.stack([lo, hi], axis=2).reshape(rows, length)
        d //= 2
    return v


def bitonic_merge_sort(x: jax.Array, row_len: int = 4096) -> jax.Array:
    """Total sort of a flat array: sorted rows + pairwise bitonic merges.

    TPU-measured motivation (docs/DESIGN.md §6): one flat ``jnp.sort``
    of 32M keys costs ~10x more than the same data sorted as rows along
    the lane axis, and scatter-based radix passes are 3-6x slower than
    sorting itself — so the winning decomposition is (1) sort [R, L]
    rows in one cheap pass, then (2) log2(R) rounds of pairwise bitonic
    merges, each a short chain of vectorized min/max at halving strides.
    Comparator stages: log2(L)^2/2 + sum_{k} log2(2^k L) vs the flat
    sort's log2(n)^2/2 — ~2.6x fewer at n=32M, all in layouts XLA tiles
    well.

    Handles any length by padding to a power-of-two multiple of
    ``row_len`` with the dtype's max (pad keys sort to the tail and are
    sliced off). Unsigned integer dtypes only; ``row_len`` must be a
    power of two."""
    if row_len <= 0 or row_len & (row_len - 1):
        raise ValueError(f"row_len must be a power of two, got {row_len}")
    (n,) = x.shape
    if n <= row_len or n & (n - 1):
        target = max(row_len, 1 << (n - 1).bit_length())
        if target != n:
            pad_val = jnp.asarray(jnp.iinfo(x.dtype).max, x.dtype)
            x = jnp.concatenate([x, jnp.full((target - n,), pad_val, x.dtype)])
    m = x.shape[0]
    if m <= row_len:
        return jnp.sort(x)[:n]
    v = jnp.sort(x.reshape(m // row_len, row_len), axis=1)
    while v.shape[0] > 1:
        # adjacent row pairs -> one bitonic row: ascending ++ descending
        asc = v[0::2]
        desc = jnp.flip(v[1::2], axis=1)
        v = _bitonic_merge_rows(jnp.concatenate([asc, desc], axis=1))
    return v[0, :n]


def merge_received(
    slab: jax.Array, counts: jax.Array, sentinel: int
) -> Tuple[jax.Array, jax.Array]:
    """Mask padding to ``sentinel`` and sort the flattened slab.

    Returns ``(sorted flat values, total valid count)``; valid entries
    occupy the prefix when ``sentinel`` is the dtype max."""
    p, cap = slab.shape
    valid = jnp.arange(cap, dtype=jnp.int32)[None, :] < counts[:, None]
    flat = jnp.where(valid, slab, jnp.asarray(sentinel, slab.dtype)).reshape(-1)
    return jnp.sort(flat), counts.sum()
