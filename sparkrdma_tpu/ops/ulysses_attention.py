"""Ulysses-style sequence parallelism — the all-to-all SP schedule.

The second of the two sequence-parallel schedules (the first,
:mod:`ring_attention`, streams kv blocks around the ring). Ulysses
re-shards with two all-to-alls instead: heads are scattered and
sequence gathered, so each device computes FULL-sequence attention for
its subset of heads, then the output is re-sharded back to sequence.
One dense exchange each way — the same ``lax.all_to_all`` the shuffle
read path rides — versus the ring's E-1 neighbour hops; Ulysses wins
when head count ≥ shard count and the interconnect is all-to-all
capable (ICI), the ring when sequence is extreme or only neighbour
bandwidth is available.

Requires ``num_heads % num_shards == 0``. The per-device full-sequence
attention uses the Pallas flash kernel on TPU (interpreter off-TPU).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from sparkrdma_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.ops.pallas_attention import flash_attention
from sparkrdma_tpu.parallel.mesh import make_mesh


def ulysses_shard_attention(q, k, v, axis: str, num_shards: int,
                            causal: bool = False, use_flash: bool = True):
    """The shard-local Ulysses schedule, for use INSIDE shard_map:
    seq-gather / head-scatter ([B, s, H, D] -> [B, s*E, H/E, D]) via
    one tiled ``all_to_all``, full-sequence attention per head group
    (the Pallas flash kernel — differentiable through its custom VJP),
    and the inverse exchange. Both :class:`UlyssesAttention` and the
    training step's sp schedule call this one implementation."""
    if num_shards > 1:
        q, k, v = (
            jax.lax.all_to_all(t, axis, split_axis=2, concat_axis=1,
                               tiled=True)
            for t in (q, k, v)
        )
    if use_flash:
        out = flash_attention(q, k, v, causal=causal)
    else:
        from sparkrdma_tpu.ops.ring_attention import reference_attention

        out = reference_attention(q, k, v, causal=causal)
    if num_shards > 1:
        out = jax.lax.all_to_all(out, axis, split_axis=1, concat_axis=2,
                                 tiled=True)
    return out


class UlyssesAttention:
    """Compile-once all-to-all sequence-parallel attention."""

    def __init__(self, mesh: Optional[Mesh] = None, axis: Optional[str] = None):
        self.mesh = mesh if mesh is not None else make_mesh()
        if axis is None:
            axis = self.mesh.axis_names[-1]
        self.axis = axis
        self.num_shards = self.mesh.shape[axis]
        self._cache = {}

    def _build(self, shape, dtype, causal: bool, use_flash: bool):
        e = self.num_shards
        axis = self.axis
        spec = P(None, axis, None, None)  # sharded on sequence

        def shard_fn(q, k, v):
            return ulysses_shard_attention(
                q, k, v, axis, e, causal=causal, use_flash=use_flash
            )

        fn = shard_map(
            shard_fn,
            mesh=self.mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )
        return jax.jit(fn)

    def __call__(self, q, k, v, causal: bool = False, use_flash: bool = True):
        b, s, h, d = q.shape
        if h % self.num_shards:
            raise ValueError(
                f"num_heads {h} must divide by shard count {self.num_shards}"
            )
        key = (q.shape, jnp.dtype(q.dtype).name, causal, use_flash)
        fn = self._cache.get(key)
        if fn is None:
            fn = self._build(q.shape, q.dtype, causal, use_flash)
            self._cache[key] = fn
        sharding = NamedSharding(self.mesh, P(None, self.axis, None, None))
        q = jax.device_put(q, sharding)
        k = jax.device_put(k, sharding)
        v = jax.device_put(v, sharding)
        return fn(q, k, v)
