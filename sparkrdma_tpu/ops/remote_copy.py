"""HBM→HBM one-sided block pull — the device fetch plane's data mover.

This is the truest analogue of the reference's IBV_WR_RDMA_READ
(RdmaChannel.java:360-393): the destination device *pulls* a source
device's HBM slab over the interconnect with no host CPU in the data
path. Two movers are provided behind one call:

- ``pallas_neighbor_pull``: a Pallas ``make_async_remote_copy`` kernel
  over ICI (SNIPPETS.md [1]-[3] pattern) — each device DMAs its
  left-neighbor's slab into local HBM, start/wait on explicit DMA
  semaphores, ``memory_space=ANY`` so the compiler keeps the refs in
  HBM. Compiled once per (mesh size, shape, dtype) and wrapped in
  ``shard_map`` exactly as the guide prescribes. TPU meshes only.
- ``emulated_pull``: ``jax.device_put`` of the source array onto the
  destination device — the same copy expressed through XLA's transfer
  engine. On a CPU mesh (``JAX_PLATFORMS=cpu``) this is the ONLY
  mover, which is what makes the whole plane testable in tier-1; on
  TPU it is also the fallback for single-device processes where no
  ICI ring exists.

The planner (shuffle/device_fetch.py) decides per block whether either
mover applies; this module only moves bytes.
"""

from __future__ import annotations

import functools
import logging
from typing import Optional

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)


def mesh_device_count() -> int:
    return jax.local_device_count()


def is_tpu_mesh() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def emulated_pull(src_array, dst_device):
    """Pull ``src_array`` onto ``dst_device`` via the transfer engine.

    One DMA on TPU (HBM→HBM over ICI when src/dst share a slice); a
    plain buffer copy on the CPU backend. Blocks until the bytes are
    resident so the caller may adopt the result into its arena and
    immediately recycle/unpin the source."""
    try:
        src_devices = src_array.devices()
    except Exception:
        src_devices = set()
    if dst_device in src_devices:
        # src already lives on dst_device: device_put would be a no-op
        # (or an alias of the same buffer). The caller is about to
        # unpin the source arena slab — whose later spill DELETES that
        # buffer — so the pull must own an independent copy; force one
        # through host memory. This is the single-device/CPU-mesh case,
        # never the cross-chip ICI one.
        import numpy as np

        pulled = jax.device_put(np.asarray(src_array), dst_device)
    else:
        pulled = jax.device_put(src_array, dst_device)
    jax.block_until_ready(pulled)
    return pulled


@functools.lru_cache(maxsize=64)
def _neighbor_pull_program(axis_size: int, shape, dtype_str: str):
    """Jitted shard_map'd Pallas program: every device pulls its RIGHT
    neighbor's shard into its own output ref (a rotate-left collective
    built from one-sided remote DMA, SNIPPETS.md [3]).

    Cached per (mesh size, block shape, dtype) like the exchange
    program cache — stateful-verb-call reuse, not per-block compiles."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from sparkrdma_tpu.utils.jax_compat import shard_map

    dtype = jnp.dtype(dtype_str)

    def kernel(src_ref, dst_ref, send_sem, recv_sem):
        my_id = jax.lax.axis_index("x")
        left = jax.lax.rem(my_id + axis_size - 1, axis_size)
        # one-sided semantics: the copy is *initiated* toward the left
        # neighbor, so each device's dst_ref receives its right
        # neighbor's shard — the reduce task's "pull" once the mesh
        # rotation places source data one hop right
        op = pltpu.make_async_remote_copy(
            src_ref=src_ref,
            dst_ref=dst_ref,
            send_sem=send_sem,
            recv_sem=recv_sem,
            device_id=(left,),
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        op.start()
        op.wait()

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        in_specs=[pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
        scratch_shapes=([pltpu.SemaphoreType.DMA] * 2),
    )

    pull = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(shape, dtype),
        grid_spec=grid_spec,
    )

    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(jax.devices()[:axis_size], ("x",))
    f = shard_map(
        pull, mesh=mesh, in_specs=P("x"), out_specs=P("x"), check_rep=False
    )
    return jax.jit(f)


def pallas_neighbor_pull(sharded_blocks):
    """Run the ICI neighbor pull over a [axis_size, ...] sharded array.

    Returns the rotated array (row i now holds row (i+1) % n's bytes).
    Raises on non-TPU platforms — callers planner-gate on
    ``is_tpu_mesh()`` and use ``emulated_pull`` otherwise."""
    if not is_tpu_mesh():
        raise RuntimeError("pallas_neighbor_pull requires a TPU mesh")
    n = sharded_blocks.shape[0]
    per_dev = (sharded_blocks.shape[0] // n,) + tuple(sharded_blocks.shape[1:])
    prog = _neighbor_pull_program(
        n, per_dev, str(sharded_blocks.dtype)
    )
    return prog(sharded_blocks)


@functools.lru_cache(maxsize=64)
def _wave_pull_program(axis_size: int, rows: int, bucket_elems: int,
                       dtype_str: str):
    """Jitted shard_map'd Pallas program moving a whole fetch WAVE in
    one kernel epoch: ``rows`` one-sided remote DMAs started together,
    waited together — the batched multi-block pull the per-block
    ``_neighbor_pull_program`` is the building block for. Row *i*'s
    source device rides in a scalar-prefetch lane (the WR list's
    per-entry rkey analogue), so one executable serves every wave of
    the same (rows, bucket) class regardless of which peers it names.

    Cached per (mesh size, bucketed rows, bucket elems, dtype) — the
    shuffle-schedule compiler buckets both axes so ragged stages reuse
    these executables (DESIGN.md §22)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from sparkrdma_tpu.utils.jax_compat import shard_map

    dtype = jnp.dtype(dtype_str)

    def kernel(src_ids, src_ref, dst_ref, send_sem, recv_sem):
        def start(i, _):
            op = pltpu.make_async_remote_copy(
                src_ref=src_ref.at[i],
                dst_ref=dst_ref.at[i],
                send_sem=send_sem.at[i],
                recv_sem=recv_sem.at[i],
                device_id=(src_ids[i],),
                device_id_type=pltpu.DeviceIdType.MESH,
            )
            op.start()
            return _

        def wait(i, _):
            op = pltpu.make_async_remote_copy(
                src_ref=src_ref.at[i],
                dst_ref=dst_ref.at[i],
                send_sem=send_sem.at[i],
                recv_sem=recv_sem.at[i],
                device_id=(src_ids[i],),
                device_id_type=pltpu.DeviceIdType.MESH,
            )
            op.wait()
            return _

        # every DMA in flight before the first wait: the epoch's wall
        # is max(row latency), not sum — the whole point of the wave
        jax.lax.fori_loop(0, rows, start, 0)
        jax.lax.fori_loop(0, rows, wait, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        in_specs=[pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
        scratch_shapes=(
            [pltpu.SemaphoreType.DMA((rows,))] * 2
        ),
    )

    pull = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows, bucket_elems), dtype),
        grid_spec=grid_spec,
    )

    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(jax.devices()[:axis_size], ("x",))
    f = shard_map(
        pull, mesh=mesh, in_specs=(P(), P("x")), out_specs=P("x"),
        check_rep=False,
    )
    return jax.jit(f)


def pallas_wave_pull(src_ids, stacked_sharded):
    """Run one wave's batched remote pull over a sharded [n*rows, b]
    array; ``src_ids`` is the int32 per-row source-device lane. TPU
    meshes only — the schedule compiler gates on ``is_tpu_mesh()`` and
    uses :func:`emulated_wave_pull` otherwise."""
    if not is_tpu_mesh():
        raise RuntimeError("pallas_wave_pull requires a TPU mesh")
    n = mesh_device_count()
    rows = stacked_sharded.shape[0] // n
    prog = _wave_pull_program(
        n, rows, stacked_sharded.shape[1], str(stacked_sharded.dtype)
    )
    return prog(src_ids, stacked_sharded)


@functools.lru_cache(maxsize=1)
def _same_device_copy_program():
    """Jitted buffer copy for the same-device pull case: unlike
    ``device_put`` (which may alias, see ``emulated_pull``) the jit
    output is always a fresh buffer, and unlike the forced host round
    trip it stays on-device AND dispatches asynchronously — the issue
    half of the pipelined emulated mover. One jit object; XLA caches
    one tiny executable per slab class."""
    return jax.jit(jnp.copy)


def emulated_row_pull_start(src_array, dst_device):
    """START one row's pull without waiting — the emulated analogue of
    ``make_async_remote_copy(...).start()``. Returns the in-flight
    array; the wave's consume half waits on it (``emulated_wave_wait``)
    before adopting. Same-device sources go through a jitted copy (an
    independent buffer the source arena's later spill cannot delete);
    cross-device sources ride the transfer engine."""
    try:
        src_devices = src_array.devices()
    except Exception:
        src_devices = set()
    if dst_device in src_devices:
        return _same_device_copy_program()(src_array)
    return jax.device_put(src_array, dst_device)


def emulated_wave_issue(stacked_host, dst_device):
    """ISSUE an assembled [rows, bucket] stack toward the destination
    without waiting: the transfer engine reads the host assembly while
    the caller moves on to the next wave (or consumes the previous
    one). The recv-semaphore wait lives in ``emulated_wave_wait``."""
    return jax.device_put(stacked_host, dst_device)


def emulated_wave_wait(inflight):
    """Wait for issued transfers to land — the emulated recv-semaphore
    wait. Accepts a single array or any pytree/list of them (one wave's
    row pulls wait together, like the kernel's wait-all loop)."""
    jax.block_until_ready(inflight)
    return inflight


def emulated_wave_pull(stacked_host, dst_device):
    """Off-TPU wave mover: land an assembled [rows, bucket] stack on
    the destination in ONE transfer-engine dispatch — the emulated
    counterpart of one batched-DMA kernel epoch. Kept as the
    issue+wait composition; the pipelined schedule compiler calls the
    halves separately so wave N+1's issue overlaps wave N's merge."""
    return emulated_wave_wait(emulated_wave_issue(stacked_host, dst_device))


@functools.lru_cache(maxsize=64)
def _pipelined_wave_pull_program(axis_size: int, depth: int, rows: int,
                                 bucket_elems: int, dtype_str: str):
    """Depth-aware double-buffered wave program: ``depth`` waves of
    ``rows`` one-sided remote DMAs each, with wave d+1's DMAs STARTED
    before wave d's wait loop runs — so the interconnect always has a
    wave in flight while the previous one drains. One DMA-semaphore
    array per in-flight wave (send and recv), exactly the per-lane
    scratch shape of ``_wave_pull_program`` replicated per pipeline
    slot, so wave d's waits never consume wave d+1's completions.

    The caller groups consecutive same-(rows, bucket) waves up to the
    ``collective.pipelineDepth`` knob; ragged neighbors fall back to
    the single-wave program. Cached per (mesh size, depth, rows class,
    bucket class, dtype) like every other wave executable."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from sparkrdma_tpu.utils.jax_compat import shard_map

    dtype = jnp.dtype(dtype_str)

    def kernel(src_ids, src_ref, dst_ref, *sems):
        send_sems, recv_sems = sems[:depth], sems[depth:]

        def _op(d, i):
            return pltpu.make_async_remote_copy(
                src_ref=src_ref.at[d, i],
                dst_ref=dst_ref.at[d, i],
                send_sem=send_sems[d].at[i],
                recv_sem=recv_sems[d].at[i],
                device_id=(src_ids[d, i],),
                device_id_type=pltpu.DeviceIdType.MESH,
            )

        def start_wave(d):
            jax.lax.fori_loop(
                0, rows, lambda i, _: (_op(d, i).start(), _)[1], 0
            )

        def wait_wave(d):
            jax.lax.fori_loop(
                0, rows, lambda i, _: (_op(d, i).wait(), _)[1], 0
            )

        # the pipeline: wave d+1 is airborne before wave d drains, so
        # the drain epoch of every wave but the last overlaps a wave's
        # worth of in-flight DMA (depth is a Python constant — this
        # unrolls at trace time)
        start_wave(0)
        for d in range(1, depth):
            start_wave(d)
            wait_wave(d - 1)
        wait_wave(depth - 1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        in_specs=[pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
        scratch_shapes=(
            [pltpu.SemaphoreType.DMA((rows,))] * (2 * depth)
        ),
    )

    pull = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((depth, rows, bucket_elems), dtype),
        grid_spec=grid_spec,
    )

    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(jax.devices()[:axis_size], ("x",))
    f = shard_map(
        pull, mesh=mesh, in_specs=(P(), P("x")), out_specs=P("x"),
        check_rep=False,
    )
    return jax.jit(f)


def pallas_pipelined_wave_pull(src_ids, stacked_sharded, depth: int):
    """Run ``depth`` same-class waves as one double-buffered kernel
    epoch over a sharded [n*depth, rows, b] array; ``src_ids`` is the
    [depth, rows] int32 source-device lane. TPU meshes only — the
    schedule compiler gates on ``is_tpu_mesh()`` and uses the
    emulated issue/wait halves otherwise."""
    if not is_tpu_mesh():
        raise RuntimeError("pallas_pipelined_wave_pull requires a TPU mesh")
    n = mesh_device_count()
    rows = stacked_sharded.shape[1]
    prog = _pipelined_wave_pull_program(
        n, depth, rows, stacked_sharded.shape[2], str(stacked_sharded.dtype)
    )
    return prog(src_ids, stacked_sharded)


def pull_block(src_array, dst_device) -> Optional[object]:
    """Best-effort single-block pull used by the planner.

    Today both the TPU and emulated paths route through the transfer
    engine (``emulated_pull``); the ring-scheduled Pallas program above
    is used by the bench's device A/B and is the building block for
    batched multi-block pulls (one program invocation moving a whole
    fetch window). Returns None on any failure — the planner treats
    that as one more reason to fall back to host fetch."""
    try:
        return emulated_pull(src_array, dst_device)
    except Exception:
        logger.exception("device pull failed; falling back to host path")
        return None
