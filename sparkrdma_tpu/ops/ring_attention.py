"""Ring attention — sequence-parallel long-context attention over the mesh.

The framework's sequence/context-parallel capability (build brief:
long-context is first-class; the reference's analogous *mechanism* is
chunked block aggregation for objects larger than one buffer,
SURVEY.md §2.3 / §5.1 #7). Sequence is sharded over the ``exec`` axis;
each device holds one query block and streams every peer's key/value
block through the same neighbour-ring schedule as
:meth:`ExchangeProgram.ring_exchange` — one block in flight per hop,
only ICI-neighbour links used.

Numerics: blockwise online softmax (flash-attention style running
max / denominator), so the result is exact attention — not an
approximation — with O(seq/E) memory per device.

Layout: ``[batch, seq, heads, head_dim]`` global, sharded on ``seq``.
Compile-once per (mesh, shapes, causal) via :class:`RingAttention`.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from sparkrdma_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkrdma_tpu.parallel.mesh import make_mesh

NEG_INF = -1e30


def _block_attn(q, k, v, mask, m_prev, num_prev, den_prev):
    """One blockwise online-softmax accumulation step.

    q: [B, Sq, H, D]; k/v: [B, Sk, H, D]; mask: [Sq, Sk] additive.
    Carries: m (running max) [B, H, Sq], num [B, Sq, H, D], den [B, H, Sq].
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    # scores in fp32 for stable softmax regardless of input dtype
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * scale + mask[None, None, :, :]
    m_new = jnp.maximum(m_prev, s.max(axis=-1))
    # renormalize previous accumulator to the new max
    correction = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[..., None])  # [B, H, Sq, Sk]
    num = num_prev * correction.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v.astype(jnp.float32)
    )
    den = den_prev * correction + p.sum(axis=-1)
    return m_new, num, den


class RingAttention:
    """Compile-once exact ring attention over a 1-D mesh axis."""

    def __init__(self, mesh: Optional[Mesh] = None, axis: Optional[str] = None):
        self.mesh = mesh if mesh is not None else make_mesh()
        if axis is None:
            axis = self.mesh.axis_names[-1]  # exec (ICI) by default
        self.axis = axis
        self.num_shards = self.mesh.shape[axis]
        self._cache = {}

    def _build(self, shape, dtype, causal: bool):
        e = self.num_shards
        axis = self.axis
        # shard sequence (dim 1); replicate everything else
        spec = P(None, axis, None, None)

        def shard_fn(q, k, v):
            b, s_loc, h, d = q.shape
            me = jax.lax.axis_index(axis)
            perm = [(i, (i + 1) % e) for i in range(e)]

            m = jnp.full((b, h, s_loc), NEG_INF, dtype=jnp.float32)
            num = jnp.zeros((b, s_loc, h, d), dtype=jnp.float32)
            den = jnp.zeros((b, h, s_loc), dtype=jnp.float32)

            k_blk, v_blk = k, v
            q_pos = me * s_loc + jnp.arange(s_loc)
            for hop in range(e):
                src = (me - hop) % e  # which shard's kv block we hold now
                if causal:
                    kv_pos = src * s_loc + jnp.arange(s_loc)
                    mask = jnp.where(
                        q_pos[:, None] >= kv_pos[None, :], 0.0, NEG_INF
                    ).astype(jnp.float32)
                else:
                    mask = jnp.zeros((s_loc, s_loc), dtype=jnp.float32)
                m, num, den = _block_attn(q, k_blk, v_blk, mask, m, num, den)
                if hop != e - 1:
                    # one kv block in flight per device per hop — the
                    # ring_exchange schedule (neighbour links only)
                    k_blk = jax.lax.ppermute(k_blk, axis, perm)
                    v_blk = jax.lax.ppermute(v_blk, axis, perm)

            out = num / den.transpose(0, 2, 1)[..., None]
            return out.astype(q.dtype)

        fn = shard_map(
            shard_fn,
            mesh=self.mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )
        return jax.jit(fn)

    def __call__(self, q, k, v, causal: bool = False):
        """Exact attention over globally [B, S, H, D] inputs sharded on S."""
        key = (q.shape, jnp.dtype(q.dtype).name, causal)
        fn = self._cache.get(key)
        if fn is None:
            fn = self._build(q.shape, q.dtype, causal)
            self._cache[key] = fn
        sharding = NamedSharding(self.mesh, P(None, self.axis, None, None))
        q = jax.device_put(q, sharding)
        k = jax.device_put(k, sharding)
        v = jax.device_put(v, sharding)
        return fn(q, k, v)


def reference_attention(q, k, v, causal: bool = False):
    """Dense single-device attention for correctness checks."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        n = q.shape[1]
        mask = jnp.where(
            jnp.arange(n)[:, None] >= jnp.arange(n)[None, :], 0.0, NEG_INF
        )
        s = s + mask[None, None]
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)
