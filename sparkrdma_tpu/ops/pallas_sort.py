"""Pallas bitonic merge kernels — the device sort's hot path.

``ops.sort.bitonic_merge_sort`` decomposes a flat sort into one cheap
row-wise ``jnp.sort`` plus log2(R) rounds of pairwise bitonic merges.
Expressed as plain XLA ops every merge stage round-trips the full array
through HBM (measured 0.11 GB/s on v5e — 8x SLOWER than a flat
``jnp.sort``); the comparator network only wins if consecutive stages
stay in VMEM. That is exactly what these kernels do:

- :func:`merge_block` — one grid program loads a whole 2D-element block
  (<= ~2 MiB), runs EVERY remaining compare-exchange stage
  (d = D .. 1, sublane regime then lane regime) on-chip, and writes the
  block once: log2(2D) stages for a single HBM round trip.
- :func:`apply_stage` — the handful of stages whose distance exceeds
  the VMEM block span, as a free-reshape XLA elementwise pass
  (bandwidth-bound, one read + one write).

Roofline (docs/DESIGN.md §6): a comparison sort of n=32M uint32 needs
~log2(L)^2/2 + sum stages ~= 400 vectorized compare-exchange stages;
the VPU, not HBM, is the binding resource once stages fuse in VMEM.
Scatter-based radix passes are measured 3-6x slower than sorting on
this hardware, so the bitonic decomposition is the right ceiling to
chase. Reference role: the in-memory merge-sort the reference delegates
to Spark's sort shuffle (SURVEY.md §3.3).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
# max elements a merge_block program holds in VMEM (uint32): 2^19 = 2 MiB
MAX_BLOCK_ELEMS = 1 << 19


def _stages_in_registers(w: jax.Array, first_d: int) -> jax.Array:
    """Compare-exchange stages ``first_d .. 1`` on ``w`` ([S, 128],
    row-major flat order). Pure value ops — usable inside a kernel."""
    s = w.shape[0]
    d = first_d
    while d >= LANES:
        dr = d // LANES
        w4 = w.reshape(s // (2 * dr), 2, dr, LANES)
        lo = jnp.minimum(w4[:, 0], w4[:, 1])
        hi = jnp.maximum(w4[:, 0], w4[:, 1])
        w = jnp.concatenate([lo[:, None], hi[:, None]], axis=1).reshape(s, LANES)
        d //= 2
    while d >= 1:
        w4 = w.reshape(s, LANES // (2 * d), 2, d)
        lo = jnp.minimum(w4[:, :, 0], w4[:, :, 1])
        hi = jnp.maximum(w4[:, :, 0], w4[:, :, 1])
        w = jnp.concatenate([lo[:, :, None], hi[:, :, None]], axis=2).reshape(
            s, LANES
        )
        d //= 2
    return w


def _merge_block_kernel(v_ref, out_ref, *, flip: bool, first_d: int):
    w = v_ref[0]  # [S, 128]
    s = w.shape[0]
    if flip:
        # rows are (ascending ++ ascending); reversing the second half
        # (both axes = full sequence reversal) makes the block bitonic
        top = w[: s // 2]
        desc = w[s // 2 :][::-1, ::-1]
        lo = jnp.minimum(top, desc)
        hi = jnp.maximum(top, desc)
        w = jnp.concatenate([lo, hi], axis=0)
        w = _stages_in_registers(w, first_d // 2)
    else:
        w = _stages_in_registers(w, first_d)
    out_ref[0] = w


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def merge_block(
    x: jax.Array, block_elems: int, flip: bool, interpret: bool = False
) -> jax.Array:
    """Apply all remaining bitonic stages inside each ``block_elems``
    block of flat ``x`` (power-of-two sizes).

    ``flip=True``: each block is two sorted ascending runs -> merged.
    ``flip=False``: each block is already bitonic (stages > block span
    were applied by :func:`apply_stage`) -> finished."""
    (n,) = x.shape
    s = block_elems // LANES
    v3 = x.reshape(n // block_elems, s, LANES)
    out = pl.pallas_call(
        functools.partial(
            _merge_block_kernel, flip=flip, first_d=block_elems // 2
        ),
        out_shape=jax.ShapeDtypeStruct(v3.shape, x.dtype),
        grid=(v3.shape[0],),
        in_specs=[
            pl.BlockSpec((1, s, LANES), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec((1, s, LANES), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(v3)
    return out.reshape(n)


def apply_stage(x: jax.Array, d: int) -> jax.Array:
    """One compare-exchange stage at distance ``d`` as a plain XLA
    elementwise pass (for distances too wide for a VMEM block). The
    reshapes are layout-free (row-major splits)."""
    (n,) = x.shape
    w = x.reshape(n // (2 * d), 2, d)
    lo = jnp.minimum(w[:, 0], w[:, 1])
    hi = jnp.maximum(w[:, 0], w[:, 1])
    return jnp.concatenate([lo[:, None], hi[:, None]], axis=1).reshape(n)


def flip_odd_pairs(x: jax.Array, run_len: int) -> jax.Array:
    """Reverse every second ``run_len`` run so (asc, asc) pairs become
    bitonic (asc, desc) — the pre-pass for rounds whose first stage runs
    in :func:`apply_stage` rather than in-kernel."""
    (n,) = x.shape
    w = x.reshape(n // (2 * run_len), 2, run_len)
    return jnp.concatenate([w[:, :1], w[:, 1:, ::-1]], axis=1).reshape(n)


def sort_flat(
    x: jax.Array, row_len: int = 8192, interpret: bool = None
) -> jax.Array:
    """Total ascending sort of a flat power-of-two uint array.

    Pipeline: row-wise ``jnp.sort`` (VMEM-friendly, the measured fast
    direction on TPU) -> per-round pairwise merges. Rounds whose pair
    fits a VMEM block run entirely in one :func:`merge_block` call;
    wider rounds run their wide stages via :func:`apply_stage` and
    finish in one :func:`merge_block` pass."""
    (n,) = x.shape
    if n & (n - 1):
        raise ValueError("sort_flat requires a power-of-two length")
    if row_len & (row_len - 1) or row_len < LANES:
        raise ValueError("row_len must be a power of two >= 128")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if n <= max(row_len, MAX_BLOCK_ELEMS):
        return jnp.sort(x)
    v = jnp.sort(x.reshape(n // row_len, row_len), axis=1).reshape(n)
    length = row_len
    while length < n:
        pair = 2 * length
        if pair <= MAX_BLOCK_ELEMS:
            v = merge_block(v, pair, True, interpret)
        else:
            # wide stages in HBM: flip odd runs, then distances
            # pair/2 .. MAX_BLOCK_ELEMS/2; blocks of MAX_BLOCK_ELEMS are
            # then bitonic and finish on-chip
            v = flip_odd_pairs(v, length)
            d = pair // 2
            while d >= MAX_BLOCK_ELEMS:
                v = apply_stage(v, d)
                d //= 2
            v = merge_block(v, MAX_BLOCK_ELEMS, False, interpret)
        length = pair
    return v
