"""Pallas bitonic sort kernels — the device sort's hot path.

``ops.sort.bitonic_merge_sort`` decomposes a flat sort into one cheap
row-wise ``jnp.sort`` plus log2(R) rounds of pairwise bitonic merges.
Expressed as plain XLA ops every merge stage round-trips the full array
through HBM (measured 0.11 GB/s on v5e — 8x SLOWER than a flat
``jnp.sort``); the comparator network only wins if consecutive stages
stay in VMEM. That is what these kernels do, using the classic
*alternating-direction* bitonic network (Batcher): element ``i`` of a
round with run length ``k`` sorts ascending iff bit ``log2(k)`` of
``i`` is 0 — no sequence reversal anywhere (Pallas TPU has no ``rev``
lowering), just a per-run min/max swap selected by that bit.

- :func:`local_sort_blocks` — one grid program loads a whole block
  (<= ~2 MiB) and runs EVERY round from the pre-sorted row length up to
  the block size on-chip: ~100 compare-exchange stages for a single HBM
  round trip.
- :func:`merge_block` — for rounds wider than a block, the tail stages
  (distance <= block/2) fused into one pass; the run direction is
  uniform per block and derived from ``program_id``.
- :func:`apply_stage` — the few stages whose distance exceeds the VMEM
  block span, as a free-reshape XLA elementwise pass (bandwidth-bound,
  one read + one write).

Roofline (docs/DESIGN.md §6): for n=32M uint32 the pipeline is one XLA
row sort + 1 local-sort pass + 6 merge passes + 21 wide stages ~= 29
full-array HBM round trips ~= 7.8 GB of traffic; at v5e's ~800 GB/s
that bounds the sort at ~13 GB/s — an order of magnitude above the
1.5 GB/s flat ``jnp.sort``. Reference role: the in-memory merge-sort
the reference delegates to Spark's sort shuffle (SURVEY.md §3.3).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
# max elements a kernel program holds in VMEM (uint32): 2^19 = 2 MiB
MAX_BLOCK_ELEMS = 1 << 19


def _roll(w: jax.Array, shift: int, interpret: bool) -> jax.Array:
    return jnp.roll(w, shift, axis=1) if interpret else pltpu.roll(w, shift, 1)


def _ce_stages(
    w: jax.Array, kr: int, first_d: int, row0, interpret: bool
) -> jax.Array:
    """Compare-exchange stages ``first_d .. 1`` on ``w`` ([S, 128] in
    row-major flat order), within runs of ``kr`` rows; the run holding
    global row ``row0 + r`` sorts ascending iff its index is even (i.e.
    ascending iff bit log2(k) of the flat element index is 0 — Batcher's
    alternating-direction network). ``row0`` may be traced (program_id
    arithmetic). Pure value ops — usable inside a kernel.

    Sublane stages (d >= 128) are free row-major reshapes; lane stages
    (d < 128) use cyclic lane rolls with an XOR-partner mask, because
    Mosaic cannot reshape across the lane dimension."""
    s = w.shape[0]
    d = first_d
    while d >= LANES:
        dr = d // LANES
        g = s // (2 * dr)
        gi = jax.lax.broadcasted_iota(jnp.int32, (g, 1), 0)[:, 0]
        asc = ((((row0 + gi * (2 * dr)) // kr) & 1) == 0).reshape(g, 1, 1)
        w4 = w.reshape(g, 2, dr, LANES)
        a, b = w4[:, 0], w4[:, 1]
        lo, hi = jnp.minimum(a, b), jnp.maximum(a, b)
        first = jnp.where(asc, lo, hi)
        second = jnp.where(asc, hi, lo)
        w = jnp.concatenate(
            [first[:, None], second[:, None]], axis=1
        ).reshape(s, LANES)
        d //= 2
    if d >= 1:
        lane = jax.lax.broadcasted_iota(jnp.int32, (s, LANES), 1)
        ri = jax.lax.broadcasted_iota(jnp.int32, (s, 1), 0)
        ascw = (((row0 + ri) // kr) & 1) == 0  # (s, 1)
        while d >= 1:
            # partner of lane l is l ^ d: from l+d when bit d clear
            # (cyclic roll by LANES-d), else from l-d (roll by d)
            up = _roll(w, LANES - d, interpret)
            down = _roll(w, d, interpret)
            low_side = (lane & d) == 0
            partner = jnp.where(low_side, up, down)
            lo = jnp.minimum(w, partner)
            hi = jnp.maximum(w, partner)
            w = jnp.where(low_side == ascw, lo, hi)
            d //= 2
    return w


def _local_sort_kernel(v_ref, out_ref, *, row_len: int, block: int,
                       interpret: bool):
    w = v_ref[0]  # [S, 128]
    s = w.shape[0]
    row0 = pl.program_id(0) * s
    k = 2 * row_len
    while k <= block:
        w = _ce_stages(w, k // LANES, k // 2, row0, interpret)
        k *= 2
    out_ref[0] = w


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def local_sort_blocks(
    x: jax.Array, row_len: int, block: int, interpret: bool = False
) -> jax.Array:
    """All bitonic rounds from run length ``2*row_len`` up to ``block``,
    fused into one HBM round trip. Input: flat ``x`` whose ``row_len``
    runs alternate ascending/descending; output: ``block`` runs
    alternating ascending/descending (run ``b`` ascending iff even)."""
    (n,) = x.shape
    s = block // LANES
    v3 = x.reshape(n // block, s, LANES)
    out = pl.pallas_call(
        functools.partial(_local_sort_kernel, row_len=row_len, block=block,
                          interpret=interpret),
        out_shape=jax.ShapeDtypeStruct(v3.shape, x.dtype),
        grid=(v3.shape[0],),
        in_specs=[
            pl.BlockSpec((1, s, LANES), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec((1, s, LANES), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(v3)
    return out.reshape(n)


def _merge_block_kernel(v_ref, out_ref, *, first_d: int, kr: int,
                        interpret: bool):
    w = v_ref[0]  # [S, 128]
    s = w.shape[0]
    row0 = pl.program_id(0) * s
    out_ref[0] = _ce_stages(w, kr, first_d, row0, interpret)


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def merge_block(
    x: jax.Array, block: int, k: int, interpret: bool = False
) -> jax.Array:
    """Stages ``block/2 .. 1`` of the run-length-``k`` round (``k >
    block``; wider stages were applied by :func:`apply_stage`) inside
    each ``block``-element tile of flat ``x``."""
    (n,) = x.shape
    s = block // LANES
    v3 = x.reshape(n // block, s, LANES)
    out = pl.pallas_call(
        functools.partial(
            _merge_block_kernel, first_d=block // 2, kr=k // LANES,
            interpret=interpret
        ),
        out_shape=jax.ShapeDtypeStruct(v3.shape, x.dtype),
        grid=(v3.shape[0],),
        in_specs=[
            pl.BlockSpec((1, s, LANES), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec((1, s, LANES), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(v3)
    return out.reshape(n)


def apply_stage(x: jax.Array, d: int, k: int) -> jax.Array:
    """One compare-exchange stage at distance ``d`` of the
    run-length-``k`` round, as a plain XLA elementwise pass (for
    distances too wide for a VMEM block). The reshapes are layout-free
    (row-major splits); direction alternates per run."""
    (n,) = x.shape
    w = x.reshape(n // k, k // (2 * d), 2, d)
    a, b = w[:, :, 0], w[:, :, 1]
    lo, hi = jnp.minimum(a, b), jnp.maximum(a, b)
    asc = (jnp.arange(n // k, dtype=jnp.int32) % 2 == 0).reshape(-1, 1, 1)
    first = jnp.where(asc, lo, hi)
    second = jnp.where(asc, hi, lo)
    return jnp.concatenate(
        [first[:, :, None], second[:, :, None]], axis=2
    ).reshape(n)


def presort_rows(x: jax.Array, row_len: int) -> jax.Array:
    """Sort each ``row_len`` run, directions alternating asc/desc.

    Descending is done by bit-flipping odd rows around an ascending
    sort (``~x = -x-1`` reverses signed order, and all-ones XOR
    reverses unsigned order) — elementwise, no lane reversal (which
    would be a relayout on TPU)."""
    (n,) = x.shape
    r = n // row_len
    ones = ~jnp.zeros((), x.dtype)
    mask = jnp.where((jnp.arange(r) & 1) == 1, ones, jnp.zeros((), x.dtype))
    mask = mask[:, None]
    return (jnp.sort(x.reshape(r, row_len) ^ mask, axis=1) ^ mask).reshape(n)


def sort_flat(
    x: jax.Array, row_len: int = 8192, interpret: bool = None
) -> jax.Array:
    """Total ascending sort of a flat power-of-two uint array.

    Pipeline: alternating-direction row pre-sort (XLA ``jnp.sort``, the
    measured fast direction on TPU) -> one :func:`local_sort_blocks`
    pass fusing every round that fits a VMEM block -> per wider round,
    its wide stages via :func:`apply_stage` and the in-block tail via
    :func:`merge_block`. The final round (k = n) has every direction
    bit 0, so the output is fully ascending."""
    (n,) = x.shape
    if n & (n - 1):
        raise ValueError("sort_flat requires a power-of-two length")
    if row_len & (row_len - 1) or row_len < 2 * LANES:
        raise ValueError("row_len must be a power of two >= 256")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block = MAX_BLOCK_ELEMS
    if n <= max(row_len, block):
        return jnp.sort(x)
    # Mosaic has no unsigned vector min/max (arith.minui); bias uint32
    # into int32 order-preservingly (flip the sign bit) at the boundary
    unsigned = jnp.issubdtype(x.dtype, jnp.unsignedinteger)
    if unsigned:
        in_dtype = x.dtype
        x = jax.lax.bitcast_convert_type(
            x ^ jnp.asarray(1 << 31, x.dtype), jnp.int32
        )
    v = presort_rows(x, row_len)
    v = local_sort_blocks(v, row_len, block, interpret)
    k = 2 * block
    while k <= n:
        d = k // 2
        while d >= block:
            v = apply_stage(v, d, k)
            d //= 2
        v = merge_block(v, block, k, interpret)
        k *= 2
    if unsigned:
        v = jax.lax.bitcast_convert_type(v, in_dtype) ^ jnp.asarray(
            1 << 31, in_dtype
        )
    return v
