"""The resident exchange program — device-side all-to-all block transfer.

TPU-native analogue of the reference's one-sided-READ data plane
(IBV_WR_RDMA_READ WR lists, RdmaChannel.java:360-393). The verbs
semantics are asynchronous, peer-passive, arbitrary-offset pulls;
XLA collectives are synchronous SPMD with static shapes. Following
SURVEY.md §7.3(1-2), the gap is bridged with:

- **bucketed static shapes**: every peer-to-peer block rides in a
  fixed-size bucket of ``block_bytes``; actual lengths travel alongside
  as an int32 "length prefix" lane (the rkey/length analogue). Buckets
  round to the conf's ``exchange.bucketMin``..``bucketMax`` power-of-two
  classes, exactly like the registered-buffer pool's size classes
  (RdmaBufferManager.java:103-118).
- **compile-once, execute-many**: one jitted SPMD program per
  (mesh, num rows, bucket) — the reference's stateful-verb-call
  pattern (pre-serialized WR lists executed repeatedly,
  RdmaChannel.java:185-192) becomes an XLA executable cache.
- **ICI before DCN**: on a multi-slice ``(dcn, exec)`` mesh the
  all-to-all runs over the flattened (dcn, exec) axes so XLA routes
  intra-slice traffic on ICI and only cross-slice rows on DCN.

Two transfer schedules are provided:

- ``exchange``: single ``lax.all_to_all`` — XLA's native schedule,
  best for dense all-to-all (the TeraSort repartition).
- ``ring_exchange``: E-1 ``lax.ppermute`` steps moving one peer-block
  per step around the ring — the staged, flow-controlled schedule
  (analogue of ``maxBytesInFlight`` throttled fetches,
  RdmaShuffleFetcherIterator.scala:279-284), and the building block
  shared with ring-attention-style long-sequence exchange.
"""

from __future__ import annotations

import math
import time
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding

from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.parallel.mesh import shard_spec
from sparkrdma_tpu.utils.jax_compat import shard_map

MIN_BUCKET = 1024


def round_bucket(nbytes: int, lo: int = MIN_BUCKET, hi: int = 1 << 31) -> int:
    """Round a block size up to its power-of-two bucket class.

    Mirror of the registered-buffer pool's size classing
    (RdmaBufferManager.java:103-118: power-of-two rounding, 16 KiB min —
    buckets here may be smaller because device lanes are cheap).
    """
    n = max(lo, min(hi, nbytes))
    return 1 << max(n - 1, 1).bit_length() if n > lo else lo


def round_rows(rows: int, lo: int = 1) -> int:
    """Round a row count up to its power-of-two bucket class — the
    leading-axis twin of :func:`round_bucket`. Ragged stage sizes
    (distinct per-peer row counts, distinct wave populations) pad up to
    the class and reuse one cached executable instead of recompiling
    per distinct count; pad rows travel with a zero length prefix and
    are sliced off after the exchange."""
    n = max(lo, rows)
    return 1 << max(n - 1, 1).bit_length() if n > lo else lo


def pack_blocks(
    blocks: Sequence[bytes], block_bytes: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side: pack one peer-block per row into a [E, block_bytes] send
    buffer plus its length-prefix vector. Blocks longer than the bucket
    are a caller bug (callers split at ``shuffleReadBlockSize`` first,
    like AggregatedPartitionGroup packing)."""
    e = len(blocks)
    out = np.zeros((e, block_bytes), dtype=np.uint8)
    counts = np.zeros((e,), dtype=np.int32)
    for i, b in enumerate(blocks):
        if len(b) > block_bytes:
            raise ValueError(f"block {i} ({len(b)}B) exceeds bucket {block_bytes}B")
        out[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
        counts[i] = len(b)
    return out, counts


def unpack_blocks(recv: np.ndarray, counts: np.ndarray) -> List[bytes]:
    """Host-side inverse of pack_blocks on the received side."""
    return [recv[i, : int(counts[i])].tobytes() for i in range(recv.shape[0])]


class ExchangeProgram:
    """Compile-once all-to-all exchange over a mesh.

    Global layout: ``send`` is [E*rows, block] sharded on dim 0 over all
    mesh axes; each device's local [rows, block] slab holds one
    outgoing block per peer-row (rows == E for a plain all-to-all;
    multiples of E for multi-block rounds). ``counts`` is the int32
    length-prefix array of the same leading shape.

    After the exchange, device *i*'s local row *j* holds what device
    *j* staged for device *i* — the device analogue of "reduce task
    pulls its partition from every map output" (SURVEY.md §3.4).
    """

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        # Collective axis order MUST match the sharding's global shard
        # order (dcn-major, exec-minor) so that send-row j lands on the
        # device holding global shard j. XLA still routes the intra-slice
        # component over ICI; the order here is index math, not routing.
        self.axes = tuple(mesh.axis_names)
        self.num_shards = math.prod(mesh.shape[a] for a in self.axes)
        self._all_to_all_cache = {}
        self._ring_cache = {}
        # transfer accounting (reference: pool/read stats at stop,
        # RdmaBufferManager.java:131-141, RdmaShuffleReaderStats).
        # Aggregates for back-compat; per-schedule detail in
        # ``self.stats`` counts BOTH directions plus wall time per
        # step, so schedule comparisons (a2a vs ring) can cite real
        # transfer counters, not send-side capacity alone.
        self.exchanges = 0
        self.bytes_moved = 0
        self.stats = {
            label: {
                "exchanges": 0,
                "bytes_sent": 0,            # bucket capacity dispatched
                "bytes_received": 0,        # bucket capacity landed
                "bytes_received_valid": 0,  # sum of recv length prefixes
                "time_s": 0.0,              # wall incl. device sync
            }
            for label in ("a2a", "ring")
        }

    def _account(self, label: str, send, recv, rcounts, t0: float):
        """Block on the step's outputs and record both directions.

        Blocking is what makes the wall time a *step* time (dispatch
        alone is meaningless through an async runtime); callers of the
        host-level entry points consume the results immediately, so
        the sync costs them nothing extra. The valid-byte count reads
        the int32 length-prefix lane only (tiny), never the payload.

        On multi-host meshes ALL byte counters are per-process: capacity
        comes from this process's addressable shards, not the global
        array size — ``send.size`` spans every host, and charging the
        whole global slab to each process would over-report aggregate
        traffic by ``num_processes ×``."""

        def _cap_bytes(arr) -> int:
            itemsize = jnp.dtype(arr.dtype).itemsize
            if getattr(arr, "is_fully_addressable", True):
                return arr.size * itemsize
            return sum(s.data.size for s in arr.addressable_shards) * itemsize

        recv = jax.block_until_ready(recv)
        rcounts = jax.block_until_ready(rcounts)
        dt = time.perf_counter() - t0
        cap = _cap_bytes(send)
        if getattr(rcounts, "is_fully_addressable", True):
            valid = int(np.asarray(rcounts).sum())
        else:  # multi-host: only this process's shards are readable
            valid = int(
                sum(np.asarray(s.data).sum() for s in rcounts.addressable_shards)
            )
        recv_cap = _cap_bytes(recv)
        s = self.stats[label]
        s["exchanges"] += 1
        s["bytes_sent"] += cap
        # measured from the landed array, independently of the send side
        s["bytes_received"] += recv_cap
        s["bytes_received_valid"] += valid
        s["time_s"] += dt
        self.exchanges += 1
        self.bytes_moved += cap
        reg = get_registry()
        reg.counter("exchange.exchanges", schedule=label).inc()
        reg.counter("exchange.bytes_sent", schedule=label).inc(cap)
        reg.counter("exchange.bytes_received", schedule=label).inc(recv_cap)
        reg.counter("exchange.bytes_received_valid", schedule=label).inc(valid)
        reg.histogram("exchange.time_ms", schedule=label).observe(dt * 1e3)
        return recv, rcounts

    def _placed(self, send, counts):
        """Lay host arrays out over the mesh; pass device arrays through.

        A non-fully-addressable ``jax.Array`` is the multi-host path:
        no single process can materialize (or device_put) the full
        global slab, so the caller builds it from process-local shards
        (``jax.make_array_from_process_local_data``) and this must not
        touch it. Fully-addressable arrays still go through device_put
        so a committed single-device array (any prior jit's output)
        gets re-placed onto the mesh instead of crashing the shard_map
        with an incompatible-devices error."""
        sharding = NamedSharding(self.mesh, shard_spec(self.mesh))
        if not (isinstance(send, jax.Array) and not send.is_fully_addressable):
            send = jax.device_put(send, sharding)
        if not (isinstance(counts, jax.Array) and not counts.is_fully_addressable):
            counts = jax.device_put(counts, sharding)
        return send, counts

    # -- schedule 1: XLA-native dense all-to-all ---------------------------
    def _build_all_to_all(self, rows: int, block: int, dtype) -> "jax.stages.Wrapped":
        axes = self.axes
        spec = shard_spec(self.mesh)
        cspec = spec

        def shard_fn(send, counts):
            # send: [rows, block]; row j is the block bound for peer j.
            # tiled all_to_all: row j goes to device j, received rows
            # concatenate in peer order — one-sided semantics, no peer code.
            recv = jax.lax.all_to_all(
                send, axes, split_axis=0, concat_axis=0, tiled=True
            )
            rcounts = jax.lax.all_to_all(
                counts, axes, split_axis=0, concat_axis=0, tiled=True
            )
            return recv, rcounts

        fn = shard_map(
            shard_fn,
            mesh=self.mesh,
            in_specs=(spec, cspec),
            out_specs=(spec, cspec),
            check_vma=False,
        )
        return jax.jit(fn)

    def program_for(self, rows: int, block: int, dtype) -> "jax.stages.Wrapped":
        """The cached compile-once executable for a shape class — the
        SVC handle (pre-serialized WR list) callers may embed inside
        larger jitted programs (TeraSort steps, benches)."""
        key = ("a2a", rows, (block,), jnp.dtype(dtype).name)
        fn = self._all_to_all_cache.get(key)
        if fn is None:
            fn = self._build_all_to_all(rows, block, dtype)
            self._all_to_all_cache[key] = fn
        return fn

    def exchange(self, send, counts):
        """Dense exchange; returns (recv, recv_counts) with identical shapes.

        ``send``: [E*rows_per_shard, block] (any dtype), sharded or
        shardable over the mesh; ``counts``: [E*rows_per_shard] int32.

        Rows-per-peer are bucketed to power-of-two classes
        (:func:`round_rows`) the same way block bytes are: a ragged
        stage whose shards stage 3 then 5 then 4 blocks per peer
        compiles TWO executables (classes 4 and 8), not three — pad
        rows ride with a zero length prefix and are sliced off before
        returning, so results are byte-identical to the exact-shape
        program. Bucketing applies only to fully-addressable inputs
        whose rows divide evenly by E; the multi-host path (caller
        builds non-addressable global arrays from process-local
        shards) keeps exact shapes — padding there would need a
        cross-process layout agreement this entry point cannot make.
        """
        e = self.num_shards
        rows = send.shape[0] // e
        addressable = not (
            isinstance(send, jax.Array) and not send.is_fully_addressable
        )
        rpp = rows // e if (addressable and rows % e == 0 and rows > 0) else 0
        pad = 0
        if rpp > 0:
            rb = round_rows(rpp)
            pad = rb - rpp
            if pad:
                block = send.shape[1]
                s = np.asarray(send).reshape(e, e, rpp, block)
                c = np.asarray(counts).reshape(e, e, rpp)
                s = np.pad(s, ((0, 0), (0, 0), (0, pad), (0, 0)))
                c = np.pad(c, ((0, 0), (0, 0), (0, pad)))
                send = s.reshape(e * e * rb, block)
                counts = c.reshape(-1)
                rows = e * rb
        fn = self.program_for(rows, send.shape[1], send.dtype)
        send, counts = self._placed(send, counts)
        t0 = time.perf_counter()
        recv, rcounts = fn(send, counts)
        recv, rcounts = self._account("a2a", send, recv, rcounts, t0)
        if pad:
            # receivers see each peer's chunk padded at its tail; strip
            # the pad rows so callers get the exact-shape result back
            rb = rpp + pad
            block = recv.shape[1]
            r = np.asarray(recv).reshape(e, e, rb, block)[:, :, :rpp]
            rc = np.asarray(rcounts).reshape(e, e, rb)[:, :, :rpp]
            recv = r.reshape(e * e * rpp, block)
            rcounts = rc.reshape(-1)
        return recv, rcounts

    # -- schedule 2: staged ring (ppermute) --------------------------------
    def _build_ring(self, block: int, dtype) -> "jax.stages.Wrapped":
        if len(self.axes) != 1:
            raise NotImplementedError("ring schedule requires a 1-D mesh")
        axis = self.axes[0]
        e = self.num_shards
        spec = shard_spec(self.mesh)

        def shard_fn(send, counts):
            # send: [E, block]; deliver row j to device j by rotating the
            # slab around the ring, peeling off the arriving row each hop
            # — only neighbour links are ever used (the topology ring
            # attention shares), and each device has a bounded amount in
            # flight per step (the maxBytesInFlight-style staging).
            me = jax.lax.axis_index(axis)
            recv0 = send[me]  # my own row short-circuits locally
            rcount0 = counts[me]
            perm_fwd = [(i, (i + 1) % e) for i in range(e)]

            slab = send
            ccnt = counts
            outs = []
            couts = []
            for k in range(1, e):
                slab = jax.lax.ppermute(slab, axis, perm_fwd)
                ccnt = jax.lax.ppermute(ccnt, axis, perm_fwd)
                # after k hops the slab on me originated at device me-k;
                # its row `me` is the block that device staged for me.
                outs.append(slab[me])
                couts.append(ccnt[me])

            # reassemble receive slab in peer order: row j came from peer j
            # = me - k mod e at hop k. Scatter hop results to peer rows.
            recv = jnp.zeros_like(send)
            rcounts = jnp.zeros_like(counts)
            recv = recv.at[me].set(recv0)
            rcounts = rcounts.at[me].set(rcount0)
            for k in range(1, e):
                src = (me - k) % e
                recv = recv.at[src].set(outs[k - 1])
                rcounts = rcounts.at[src].set(couts[k - 1])
            return recv, rcounts

        fn = shard_map(
            shard_fn,
            mesh=self.mesh,
            in_specs=(spec, spec),
            out_specs=(spec, spec),
            check_vma=False,
        )
        return jax.jit(fn)

    def ring_exchange(self, send, counts):
        """Staged exchange: E-1 ppermute hops, one bucket in flight each.

        Semantically identical to ``exchange``; schedule differs (ring
        neighbours only — the pattern ring attention shares)."""
        key = ("ring", send.shape[1:], jnp.dtype(send.dtype).name)
        fn = self._ring_cache.get(key)
        if fn is None:
            fn = self._build_ring(send.shape[1], send.dtype)
            self._ring_cache[key] = fn
        send, counts = self._placed(send, counts)
        t0 = time.perf_counter()
        recv, rcounts = fn(send, counts)
        return self._account("ring", send, recv, rcounts, t0)
