"""Pallas flash attention — the on-chip kernel for the attention hot op.

Single-device exact attention with O(block) memory, written as a TPU
Pallas kernel (guide: /opt/skills/guides/pallas_guide.md). The grid is
(batch, heads, q-blocks, k-blocks) with the k axis minor, so the
running online-softmax statistics (max, denominator, accumulator) live
in VMEM scratch across the k sweep — init at the first k block,
finalize into the output at the last. This is the same blockwise
recurrence :mod:`sparkrdma_tpu.ops.ring_attention` runs *across
devices*; here it runs across VMEM tiles within one chip, keeping the
[Sq, Sk] score matrix out of HBM entirely.

Falls back to interpreter mode off-TPU (used by the CPU test mesh), so
the same code path is exercised everywhere.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
            *, scale, causal, block_q, block_k, num_kv_blocks, seq_len,
            precision):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # causal: a kv block strictly above the diagonal band contributes
    # nothing — skip its two MXU passes entirely (the block-sparsity
    # that makes flash ~2x on causal, measured in bench.py)
    live = (ik * block_k <= iq * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)  # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)  # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)  # [bk, d]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        ) * scale  # [bq, bk]

        # mask padded kv rows (seq padded up to a block multiple) and, if
        # causal, future positions — all from static block indices
        kv_pos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = kv_pos < seq_len
        if causal:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            mask = mask & (q_pos >= kv_pos)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[...][:, 0]          # [bq] (value slice, lanes equal)
        l_prev = l_ref[...][:, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])    # [bq, bk]
        l_new = l_prev * corr + p.sum(axis=-1)
        acc_ref[...] = acc_ref[...] * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(ik == num_kv_blocks - 1)
    def _finalize():
        l = l_ref[...][:, 0]
        m = m_ref[...][:, 0]
        # fully-masked rows (query padding) have l == 0; emit zeros,
        # and pin their logsumexp to +inf-ish so the backward's
        # exp(s - lse) is exactly 0 there (m + log 0 would be nan)
        denom = jnp.where(l > 0, l, 1.0)
        o_ref[0, 0] = (acc_ref[...] / denom[:, None]).astype(o_ref.dtype)
        if lse_ref is not None:  # static: training variant only
            lse = jnp.where(l > 0, m + jnp.log(denom), -NEG_INF)
            # row-statistic layout: one [8, L] tile per q block with
            # L = max(block_q, 128) — 8 replicated sublanes and a
            # 128-divisible lane slot keep Mosaic's (8, 128) block
            # alignment even for small clamped blocks (a bare
            # [1, block_q] block fails lowering when block_q < 128)
            L = lse_ref.shape[-1]
            if L > block_q:
                lse = jnp.pad(lse, (0, L - block_q))
            lse_ref[0, 0] = jnp.broadcast_to(
                lse[None, :].astype(jnp.float32), lse_ref.shape[2:]
            )


def _kernel_no_lse(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, **kw):
    _kernel(q_ref, k_ref, v_ref, o_ref, None, m_ref, l_ref, acc_ref, **kw)


def _resolve_blocks(s: int, block_q: int, block_k: int):
    """Clamp blocks for short sequences to the next power of two <= s
    (>= 8): power-of-two blocks keep Mosaic-friendly (8, 128)-tile
    alignment, where a raw s clamp (e.g. 300) would build unaligned
    block shapes and iotas. The padded length must divide by BOTH
    block sizes, or kv blocks past s_pad//block_k would silently never
    be visited."""
    if s < block_q:
        block_q = max(8, 1 << (s.bit_length() - 1))
    if s < block_k:
        block_k = max(8, 1 << (s.bit_length() - 1))
    lcm = math.lcm(block_q, block_k)
    s_pad = int(math.ceil(s / lcm)) * lcm
    return block_q, block_k, s_pad


def _prep(x, s, s_pad):
    x = jnp.transpose(x, (0, 2, 1, 3))  # [B, H, S, D]
    if s_pad != s:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, s_pad - s), (0, 0)))
    return x


def _fwd_impl(q, k, v, causal, block_q, block_k, interpret, precision,
              want_lse):
    """[B, S, H, D] -> (out [B, S, H, D], lse or None).

    ``lse`` (training only, ``want_lse=True``) is [B, H, 8, nq * L]
    f32 with L = max(block_q, 128): one lane slot of L per q block,
    value in the first block_q lanes of sublane-replicated rows (see
    the layout note in ``_kernel``). Inference skips the output
    entirely."""
    b, s, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    block_q, block_k, s_pad = _resolve_blocks(s, block_q, block_k)
    qt, kt, vt = (_prep(x, s, s_pad) for x in (q, k, v))
    nq = s_pad // block_q
    nk = s_pad // block_k

    kernel = functools.partial(
        _kernel if want_lse else _kernel_no_lse,
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        num_kv_blocks=nk,
        seq_len=s,
        precision=precision,
    )
    L = max(block_q, 128)
    if causal:
        # above-diagonal kv blocks are skipped by the kernel; clamp their
        # index to the last live block so the pipeline re-addresses the
        # already-resident tile instead of DMAing a dead one from HBM
        def kv_index(bi, hi, qi, ki):
            last_live = (qi * block_q + block_q - 1) // block_k
            return (bi, hi, jnp.minimum(ki, last_live), 0)
    else:
        def kv_index(bi, hi, qi, ki):
            return (bi, hi, ki, 0)

    out_specs = [
        pl.BlockSpec(
            (1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
        ),
    ]
    out_shape = [jax.ShapeDtypeStruct((b, h, s_pad, d), q.dtype)]
    if want_lse:
        out_specs.append(
            pl.BlockSpec(
                (1, 1, 8, L), lambda bi, hi, qi, ki: (bi, hi, 0, qi)
            )
        )
        out_shape.append(
            jax.ShapeDtypeStruct((b, h, 8, nq * L), jnp.float32)
        )
    res = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running denominator
            pltpu.VMEM((block_q, d), jnp.float32),    # output accumulator
        ],
        interpret=interpret,
    )(qt, kt, vt)
    out, lse = res if want_lse else (res[0], None)
    out = jnp.transpose(out[:, :, :s, :], (0, 2, 1, 3))
    return out, lse


# ---------------------------------------------------------------------------
# backward (FlashAttention-2 shape): two kernels re-materialize the
# probability tiles from (q, k, lse) so the [Sq, Sk] matrices never
# exist in HBM in the backward either. delta = rowsum(dout * out) is
# precomputed at the jnp level (elementwise; XLA fuses it).
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dq_ref,
               acc_ref, *, scale, causal, block_q, block_k,
               num_kv_blocks, seq_len, precision):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    live = (ik * block_k <= iq * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)      # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)      # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)      # [bk, d]
        do = do_ref[0, 0].astype(jnp.float32)    # [bq, d]
        lse = lse_ref[0, 0, 0][:block_q]         # [bq] (row 0, L-slot)
        dlt = dlt_ref[0, 0, 0][:block_q]         # [bq]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        ) * scale
        kv_pos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = kv_pos < seq_len
        if causal:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            mask = mask & (q_pos >= kv_pos)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])            # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )                                         # [bq, bk]
        ds = p * (dp - dlt[:, None])
        acc_ref[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        ) * scale

    @pl.when(ik == num_kv_blocks - 1)
    def _finalize():
        dq_ref[0, 0] = acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                block_q, block_k, num_q_blocks, seq_len, precision):
    ik = pl.program_id(2)
    iq = pl.program_id(3)

    @pl.when(iq == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # causal: q blocks strictly above the diagonal see none of this kv
    # block — the transpose of the forward's skip
    live = (iq * block_q + block_q - 1 >= ik * block_k) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)      # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)      # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)      # [bk, d]
        do = do_ref[0, 0].astype(jnp.float32)    # [bq, d]
        lse = lse_ref[0, 0, 0][:block_q]         # [bq] (row 0, L-slot)
        dlt = dlt_ref[0, 0, 0][:block_q]         # [bq]

        # transposed orientation: rows = kv positions
        s_t = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        ) * scale                                 # [bk, bq]
        q_pos = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, block_q), 1
        )
        kv_pos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, block_q), 0
        )
        mask = (q_pos < seq_len) & (kv_pos < seq_len)
        if causal:
            mask = mask & (q_pos >= kv_pos)
        s_t = jnp.where(mask, s_t, NEG_INF)
        p_t = jnp.exp(s_t - lse[None, :])         # [bk, bq]
        dv_acc[...] += jax.lax.dot_general(
            p_t, do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        dp_t = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )                                          # [bk, bq]
        ds_t = p_t * (dp_t - dlt[None, :])
        dk_acc[...] += jax.lax.dot_general(
            ds_t, q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        ) * scale

    @pl.when(iq == num_q_blocks - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_impl(q, k, v, out, lse, do, causal, block_q, block_k,
              interpret, precision):
    b, s, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    block_q, block_k, s_pad = _resolve_blocks(s, block_q, block_k)
    nk = s_pad // block_k

    nq = s_pad // block_q
    L = max(block_q, 128)
    # delta[b,h,q] = rowsum(dout * out) — elementwise, jnp-level;
    # laid out like lse: one [8, L] lane slot per q block
    delta = jnp.einsum(
        "bshd,bshd->bhs", do.astype(jnp.float32), out.astype(jnp.float32)
    )
    if s_pad != s:
        delta = jnp.pad(delta, ((0, 0), (0, 0), (0, s_pad - s)))
    if L > block_q:
        delta = delta.reshape(b, h, nq, block_q)
        delta = jnp.pad(delta, ((0, 0), (0, 0), (0, 0), (0, L - block_q)))
        delta = delta.reshape(b, h, nq * L)
    delta = jnp.broadcast_to(delta[:, :, None, :], (b, h, 8, nq * L))

    qt, kt, vt, dot = (_prep(x, s, s_pad) for x in (q, k, v, do))

    q_spec = pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    row_spec = pl.BlockSpec((1, 1, 8, L), lambda bi, hi, qi, ki: (bi, hi, 0, qi))
    if causal:
        def kv_index(bi, hi, qi, ki):
            last_live = (qi * block_q + block_q - 1) // block_k
            return (bi, hi, jnp.minimum(ki, last_live), 0)
    else:
        def kv_index(bi, hi, qi, ki):
            return (bi, hi, ki, 0)
    kv_spec = pl.BlockSpec((1, 1, block_k, d), kv_index)

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, num_kv_blocks=nk, seq_len=s,
            precision=precision,
        ),
        grid=(b, h, nq, nk),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, s_pad, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    # kv-major grid: q minor so dk/dv accumulators live across the sweep
    if causal:
        def q_index(bi, hi, ki, qi):
            first_live = (ki * block_k) // block_q
            return (bi, hi, jnp.maximum(qi, first_live), 0)

        def row_index(bi, hi, ki, qi):
            first_live = (ki * block_k) // block_q
            return (bi, hi, 0, jnp.maximum(qi, first_live))
    else:
        def q_index(bi, hi, ki, qi):
            return (bi, hi, qi, 0)

        def row_index(bi, hi, ki, qi):
            return (bi, hi, 0, qi)

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, num_q_blocks=nq, seq_len=s,
            precision=precision,
        ),
        grid=(b, h, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), q_index),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_q, d), q_index),
            pl.BlockSpec((1, 1, 8, L), row_index),
            pl.BlockSpec((1, 1, 8, L), row_index),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s_pad, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, s_pad, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    unprep = lambda x: jnp.transpose(x[:, :, :s, :], (0, 2, 1, 3))
    return unprep(dq), unprep(dk), unprep(dv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, block_q, block_k, interpret, precision):
    out, _ = _fwd_impl(q, k, v, causal, block_q, block_k, interpret,
                       precision, want_lse=False)
    return out


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret, precision):
    out, lse = _fwd_impl(q, k, v, causal, block_q, block_k, interpret,
                         precision, want_lse=True)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, precision, res, do):
    q, k, v, out, lse = res
    return _bwd_impl(q, k, v, out, lse, do, causal, block_q, block_k,
                     interpret, precision)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q, k, v,
    causal: bool = False,
    block_q: int = 512,
    block_k: int = 512,
    interpret: Optional[bool] = None,
    precision=None,
):
    """Exact attention over [B, S, H, D] inputs via a Pallas TPU kernel.

    Differentiable: a custom VJP re-materializes probability tiles from
    (q, k, logsumexp) in two Pallas kernels (dq with a kv-minor sweep,
    dk/dv with a q-minor sweep), so neither direction ever holds an
    [Sq, Sk] matrix in HBM — long-context TRAINING runs at flash
    memory cost (the forward additionally saves one f32 logsumexp row
    per query, [B, H, S]).

    ``interpret=None`` auto-selects interpreter mode off-TPU.
    ``precision=None`` uses HIGHEST for fp32 inputs (the MXU otherwise
    decomposes fp32 matmuls into bf16 passes, ~1e-2 score error) and
    the default for bf16 inputs.

    Block defaults are measured, not guessed (v5e, B4 S2048 H8 D128
    bf16 causal, bench.py methodology): 128x128 blocks run ~5x slower
    than 512x512 — small blocks pay the VMEM scratch read-modify-write
    per (q,k) tile without amortizing it over MXU work. 1024x1024 is
    faster still where S and VMEM allow; bench.py uses it for the
    headline number."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if precision is None:
        precision = (
            jax.lax.Precision.HIGHEST
            if q.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT
        )
    return _flash(q, k, v, causal, block_q, block_k, interpret, precision)
