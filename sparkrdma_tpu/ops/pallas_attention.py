"""Pallas flash attention — the on-chip kernel for the attention hot op.

Single-device exact attention with O(block) memory, written as a TPU
Pallas kernel (guide: /opt/skills/guides/pallas_guide.md). The grid is
(batch, heads, q-blocks, k-blocks) with the k axis minor, so the
running online-softmax statistics (max, denominator, accumulator) live
in VMEM scratch across the k sweep — init at the first k block,
finalize into the output at the last. This is the same blockwise
recurrence :mod:`sparkrdma_tpu.ops.ring_attention` runs *across
devices*; here it runs across VMEM tiles within one chip, keeping the
[Sq, Sk] score matrix out of HBM entirely.

Falls back to interpreter mode off-TPU (used by the CPU test mesh), so
the same code path is exercised everywhere.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
            *, scale, causal, block_q, block_k, num_kv_blocks, seq_len,
            precision):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # causal: a kv block strictly above the diagonal band contributes
    # nothing — skip its two MXU passes entirely (the block-sparsity
    # that makes flash ~2x on causal, measured in bench.py)
    live = (ik * block_k <= iq * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)  # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)  # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)  # [bk, d]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        ) * scale  # [bq, bk]

        # mask padded kv rows (seq padded up to a block multiple) and, if
        # causal, future positions — all from static block indices
        kv_pos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = kv_pos < seq_len
        if causal:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            mask = mask & (q_pos >= kv_pos)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[...][:, 0]          # [bq] (value slice, lanes equal)
        l_prev = l_ref[...][:, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])    # [bq, bk]
        l_new = l_prev * corr + p.sum(axis=-1)
        acc_ref[...] = acc_ref[...] * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        )
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(ik == num_kv_blocks - 1)
    def _finalize():
        l = l_ref[...][:, 0]
        # fully-masked rows (query padding) have l == 0; emit zeros
        denom = jnp.where(l > 0, l, 1.0)
        o_ref[0, 0] = (acc_ref[...] / denom[:, None]).astype(o_ref.dtype)


def flash_attention(
    q, k, v,
    causal: bool = False,
    block_q: int = 512,
    block_k: int = 512,
    interpret: Optional[bool] = None,
    precision=None,
):
    """Exact attention over [B, S, H, D] inputs via a Pallas TPU kernel.

    ``interpret=None`` auto-selects interpreter mode off-TPU.
    ``precision=None`` uses HIGHEST for fp32 inputs (the MXU otherwise
    decomposes fp32 matmuls into bf16 passes, ~1e-2 score error) and
    the default for bf16 inputs.

    Block defaults are measured, not guessed (v5e, B4 S2048 H8 D128
    bf16 causal, bench.py methodology): 128x128 blocks run ~5x slower
    than 512x512 — small blocks pay the VMEM scratch read-modify-write
    per (q,k) tile without amortizing it over MXU work. 1024x1024 is
    faster still where S and VMEM allow; bench.py uses it for the
    headline number."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if precision is None:
        precision = (
            jax.lax.Precision.HIGHEST
            if q.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT
        )
    b, s, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    # clamp blocks for short sequences to the next power of two <= s
    # (>= 8): power-of-two blocks keep Mosaic-friendly (8, 128)-tile
    # alignment, where a raw s clamp (e.g. 300) would build unaligned
    # block shapes and iotas
    if s < block_q:
        block_q = max(8, 1 << (s.bit_length() - 1))
    if s < block_k:
        block_k = max(8, 1 << (s.bit_length() - 1))
    # the padded length must divide by BOTH block sizes, or kv blocks
    # past s_pad//block_k would silently never be visited
    lcm = math.lcm(block_q, block_k)
    s_pad = int(math.ceil(s / lcm)) * lcm

    def prep(x):
        x = jnp.transpose(x, (0, 2, 1, 3))  # [B, H, S, D]
        if s_pad != s:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, s_pad - s), (0, 0)))
        return x

    qt, kt, vt = prep(q), prep(k), prep(v)
    nq = s_pad // block_q
    nk = s_pad // block_k

    kernel = functools.partial(
        _kernel,
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        num_kv_blocks=nk,
        seq_len=s,
        precision=precision,
    )
    if causal:
        # above-diagonal kv blocks are skipped by the kernel; clamp their
        # index to the last live block so the pipeline re-addresses the
        # already-resident tile instead of DMAing a dead one from HBM
        def kv_index(bi, hi, qi, ki):
            last_live = (qi * block_q + block_q - 1) // block_k
            return (bi, hi, jnp.minimum(ki, last_live), 0)
    else:
        def kv_index(bi, hi, qi, ki):
            return (bi, hi, ki, 0)

    out = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, s_pad, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running denominator
            pltpu.VMEM((block_q, d), jnp.float32),    # output accumulator
        ],
        interpret=interpret,
    )(qt, kt, vt)
    out = out[:, :, :s, :]
    return jnp.transpose(out, (0, 2, 1, 3))  # back to [B, S, H, D]
