"""HBM slab pool + handle table — the device registered-memory plane.

Device analogue of the host registered-buffer pool
(RdmaBufferManager.java): size-classed stacks of uint8 slabs resident
in device HBM, power-of-two rounding with a 16 KiB floor
(RdmaBufferManager.java:103-118), per-class allocation statistics
printed at shutdown (:131-141), and an optional preallocation pass
(:84-91).

The rkey/address concept (RdmaBlockLocation's ``(address, length,
mkey)``, RdmaPartitionLocation.scala:25) maps to ``(device ordinal,
handle, offset, length)``: the handle table resolves a handle to a
live ``jax.Array`` slab, so any framework component — the fetcher
staging received blocks, the exchange program sourcing send slabs —
can name device memory without holding the array itself.

``jax.Array`` is immutable, so "writing into a slab" means staging a
new array and retiring the old one under the same handle; pooling here
buys *budget accounting* and handle stability rather than malloc reuse
(XLA's allocator handles that). The budget mirrors the reference's
executor-wide in-memory cap (``shuffleWriteMaxInMemoryStoragePerExecutor``,
RdmaShuffleBlockResolver.scala:38-47) via ``hbm.maxBytes``.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

MIN_BLOCK_SIZE = 16 * 1024  # RdmaBufferManager.java MIN_BLOCK_SIZE analogue


def _size_class(nbytes: int) -> int:
    """Round up to a power of two, floored at MIN_BLOCK_SIZE."""
    n = max(nbytes, MIN_BLOCK_SIZE)
    return 1 << (n - 1).bit_length()


class DeviceBuffer:
    """One pooled HBM slab plus the live view of its contents.

    ``length`` is the caller-requested byte length; ``capacity`` the
    size-class slab length actually resident. ``array`` always has
    shape [capacity] dtype uint8.
    """

    __slots__ = ("handle", "capacity", "length", "array", "_manager")

    def __init__(self, handle: int, capacity: int, array, manager):
        self.handle = handle
        self.capacity = capacity
        self.length = 0
        self.array = array
        self._manager = manager

    @property
    def device(self):
        return next(iter(self.array.devices()))

    def stage(self, data: bytes) -> "DeviceBuffer":
        """Host -> HBM: replace the slab contents (pads to capacity)."""
        if len(data) > self.capacity:
            raise ValueError(f"{len(data)}B exceeds slab capacity {self.capacity}B")
        host = np.zeros((self.capacity,), dtype=np.uint8)
        host[: len(data)] = np.frombuffer(data, dtype=np.uint8)
        old = self.array
        self.array = jax.device_put(host, self.device)
        old.delete()
        self.length = len(data)
        return self

    def put_array(self, arr) -> "DeviceBuffer":
        """Adopt a device-resident uint8 array as the slab contents."""
        if arr.dtype != jnp.uint8 or arr.ndim != 1:
            raise ValueError("slab contents must be 1-D uint8")
        if arr.shape[0] > self.capacity:
            raise ValueError("array exceeds slab capacity")
        self.length = arr.shape[0]
        old = self.array
        if arr.shape[0] < self.capacity:
            arr = jnp.zeros((self.capacity,), dtype=jnp.uint8).at[: arr.shape[0]].set(arr)
        self.array = arr
        old.delete()
        return self

    def read(self, offset: int = 0, length: Optional[int] = None) -> bytes:
        """HBM -> host readback of ``[offset, offset+length)``."""
        if length is None:
            length = self.length - offset
        if offset < 0 or length < 0 or offset + length > self.capacity:
            raise ValueError("read out of slab bounds")
        return np.asarray(self.array[offset : offset + length]).tobytes()

    def free(self) -> None:
        self._manager.put(self)


class _AllocatorStack:
    """Lock-guarded per-size-class free stack with a cumulative
    allocation counter (reference AllocatorStack,
    RdmaBufferManager.java:31-71)."""

    __slots__ = ("size", "stack", "total_alloc", "total_gets")

    def __init__(self, size: int):
        self.size = size
        self.stack: List[DeviceBuffer] = []
        self.total_alloc = 0
        self.total_gets = 0


class DeviceBufferManager:
    """Size-classed pool of HBM slabs for one device."""

    def __init__(self, device=None, max_bytes: int = 0, prealloc: int = 0,
                 prealloc_size: int = 0):
        if device is None:
            device = jax.devices()[0]
        self.device = device
        self.max_bytes = max_bytes  # 0 = unbounded
        self._stacks: Dict[int, _AllocatorStack] = {}
        self._handles: Dict[int, DeviceBuffer] = {}
        self._next_handle = 1
        self._in_use_bytes = 0
        self._lock = threading.Lock()
        self._stopped = False
        # optional warm-up (reference maxAggPrealloc, RdmaBufferManager.java:84-91)
        if prealloc > 0 and prealloc_size > 0:
            bufs = [self.get(prealloc_size) for _ in range(prealloc)]
            for b in bufs:
                b.free()

    # ------------------------------------------------------------------
    def get(self, nbytes: int) -> DeviceBuffer:
        """Allocate (or reuse) a slab whose class covers ``nbytes``."""
        cls = _size_class(nbytes)
        with self._lock:
            if self._stopped:
                raise RuntimeError("DeviceBufferManager is stopped")
            stack = self._stacks.setdefault(cls, _AllocatorStack(cls))
            stack.total_gets += 1
            if stack.stack:
                buf = stack.stack.pop()
                buf.length = nbytes
                self._in_use_bytes += cls
                self._handles[buf.handle] = buf
                return buf
            if self.max_bytes and self._in_use_bytes + cls > self.max_bytes:
                raise MemoryError(
                    f"HBM shuffle budget exceeded: in-use {self._in_use_bytes}B "
                    f"+ {cls}B > cap {self.max_bytes}B"
                )
            handle = self._next_handle
            self._next_handle += 1
            stack.total_alloc += 1
            self._in_use_bytes += cls
        arr = jax.device_put(jnp.zeros((cls,), dtype=jnp.uint8), self.device)
        buf = DeviceBuffer(handle, cls, arr, self)
        buf.length = nbytes
        with self._lock:
            self._handles[handle] = buf
        return buf

    def put(self, buf: DeviceBuffer) -> None:
        """Return a slab to its class stack (RdmaBufferManager.java:120-127)."""
        with self._lock:
            if self._handles.pop(buf.handle, None) is None:
                return  # double-free tolerated, like onFailure reentry
            self._in_use_bytes -= buf.capacity
            if self._stopped:
                buf.array.delete()
                return
            self._stacks[buf.capacity].stack.append(buf)
        buf.length = 0

    def resolve(self, handle: int) -> DeviceBuffer:
        """Handle table lookup — the mkey/rkey resolution analogue."""
        with self._lock:
            buf = self._handles.get(handle)
        if buf is None:
            raise KeyError(f"no live device buffer for handle {handle}")
        return buf

    def stage_bytes(self, data: bytes) -> DeviceBuffer:
        """Pool + stage in one step (host bytes -> registered HBM slab)."""
        return self.get(len(data)).stage(data)

    # ------------------------------------------------------------------
    @property
    def in_use_bytes(self) -> int:
        with self._lock:
            return self._in_use_bytes

    def stats(self) -> Dict[int, Dict[str, int]]:
        with self._lock:
            return {
                size: {
                    "total_alloc": s.total_alloc,
                    "total_gets": s.total_gets,
                    "pooled": len(s.stack),
                }
                for size, s in self._stacks.items()
            }

    def stop(self) -> None:
        """Free everything; log per-class stats (RdmaBufferManager.java:131-141)."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            stacks = list(self._stacks.values())
            leaked = list(self._handles.values())
        for s in stacks:
            if s.total_alloc:
                logger.info(
                    "hbm pool class %dB: allocated %d, gets %d, pooled %d",
                    s.size, s.total_alloc, s.total_gets, len(s.stack),
                )
            for buf in s.stack:
                buf.array.delete()
            s.stack.clear()
        for buf in leaked:
            logger.warning("hbm slab handle %d leaked (freeing)", buf.handle)
            buf.array.delete()
