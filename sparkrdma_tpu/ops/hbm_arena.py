"""HBM slab pool + handle table — the device registered-memory plane.

Device analogue of the host registered-buffer pool
(RdmaBufferManager.java): size-classed stacks of uint8 slabs resident
in device HBM, power-of-two rounding with a 16 KiB floor
(RdmaBufferManager.java:103-118), per-class allocation statistics
printed at shutdown (:131-141), and an optional preallocation pass
(:84-91).

The rkey/address concept (RdmaBlockLocation's ``(address, length,
mkey)``, RdmaPartitionLocation.scala:25) maps to ``(device ordinal,
handle, offset, length)``: the handle table resolves a handle to a
live ``jax.Array`` slab, so any framework component — the fetcher
staging received blocks, the exchange program sourcing send slabs —
can name device memory without holding the array itself.

``jax.Array`` is immutable, so "writing into a slab" means staging a
new array and retiring the old one under the same handle; pooling here
buys *budget accounting* and handle stability rather than malloc reuse
(XLA's allocator handles that). The budget mirrors the reference's
executor-wide in-memory cap (``shuffleWriteMaxInMemoryStoragePerExecutor``,
RdmaShuffleBlockResolver.scala:38-47) via ``hbm.maxBytes``.
"""

from __future__ import annotations

import contextlib
import logging
import os
import tempfile
import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from sparkrdma_tpu.analysis.lockorder import named_lock
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.tenancy import current_tenant, tenant_scope
from sparkrdma_tpu.tenancy import quota as _quota

logger = logging.getLogger(__name__)

_M_POOL_HITS = get_registry().counter("hbm.pool_hits")
_M_POOL_MISSES = get_registry().counter("hbm.pool_misses")
_M_SPILL_VICTIMS = get_registry().counter("hbm.spill_victims")
_M_DISK_SPILLS = get_registry().counter("hbm.disk_spills")
# summed across managers; the gauge's high-water mark is the figure of
# interest for sizing hbm.maxBytes
_G_IN_USE = get_registry().gauge("hbm.in_use_bytes")

MIN_BLOCK_SIZE = 16 * 1024  # RdmaBufferManager.java MIN_BLOCK_SIZE analogue


def _size_class(nbytes: int) -> int:
    """Round up to a power of two, floored at MIN_BLOCK_SIZE."""
    n = max(nbytes, MIN_BLOCK_SIZE)
    return 1 << (n - 1).bit_length()


class DeviceBuffer:
    """One pooled HBM slab plus the live view of its contents.

    ``length`` is the caller-requested byte length; ``capacity`` the
    size-class slab length actually resident. ``array`` always has
    shape [capacity] while device-resident; under budget pressure a
    buffer descends the tiered store of SURVEY.md §7.3(4) —
    HBM -> host RAM -> disk — and transparently climbs back on next
    device use. A shuffle far larger than HBM (the reference's 175 GB
    bar vs 16 GiB/chip) therefore degrades in steps, never OOMs.
    """

    __slots__ = (
        "handle", "capacity", "length", "array", "_manager", "_host",
        "_disk", "_tier_lock", "last_use", "tenant", "_quota_tag",
    )

    def __init__(self, handle: int, capacity: int, array, manager):
        self.handle = handle
        self.capacity = capacity
        self.length = 0
        self.array = array
        self._manager = manager
        self.tenant = None  # owning tenant id (spill-victim preference)
        self._quota_tag = None  # (broker, tenant, cls) while charged
        self._host: Optional[np.ndarray] = None  # set while in host tier
        self._disk = None  # (path, dtype_str, count) while in disk tier
        # serializes TIER MOVES of this buffer (manager-initiated
        # cascade victims race caller-initiated restores/frees).
        # Ordering rules that keep this deadlock-free:
        #  - buffer lock OUTER, manager._lock inner;
        #  - a thread holds at most one UNPINNED buffer's lock, and
        #    only for a self-contained move (no other buffer locks
        #    taken inside);
        #  - cascades run with NO buffer lock held;
        #  - victim picks (the only cross-thread acquisition) never
        #    target pinned buffers, and every climber pins itself.
        #  allow_self_nest: a climber legitimately holds its own tier
        #  lock while spilling an unpinned victim (_make_room /
        #  _cascade_host_tier) — safe because the climber is pinned and
        #  victim picks exclude pinned handles, so the inner lock can
        #  never belong to a thread's own outer buffer
        self._tier_lock = named_lock("hbm.buffer", allow_self_nest=True)
        self.last_use = 0

    @property
    def spilled(self) -> bool:
        return self._host is not None or self._disk is not None

    @property
    def on_disk(self) -> bool:
        return self._disk is not None

    @property
    def device(self):
        if self.array is not None:
            return next(iter(self.array.devices()))
        return self._manager.device

    def spill_to_host(self) -> None:
        """HBM -> host RAM; releases device budget, keeps the handle.
        May cascade another buffer host -> disk under the host cap.
        The cascade MUST run after this buffer's lock is released: it
        can legally pick this very buffer (freshly host-resident, LRU)
        and would self-deadlock on the non-reentrant tier lock."""
        with self._tier_lock:
            if self.array is None:
                return  # raced: someone else already moved it
            with self._manager._lock:
                if self.handle not in self._manager._handles:
                    # raced a free(): the victim pick happened before
                    # put() removed this buffer from the handle table,
                    # and put() then returned it (array intact) to the
                    # pool stack. Spilling a POOLED slab would release
                    # its device budget a second time — the only
                    # negative-budget race the threaded stress ever
                    # produced. (Pool reuse re-inserts the same handle,
                    # so a re-gotten buffer spills normally again.)
                    return
            self._host = np.asarray(self.array)
            self.array.delete()
            self.array = None
            self._manager._on_spill_accounting(self)
        self._manager._cascade_host_tier()

    def spill_to_disk(self) -> None:
        """Host RAM -> disk; releases host budget, keeps the handle.
        Acts only on a host-tier resident (cascade victims); a raced
        buffer that climbed away in the meantime is left alone."""
        with self._tier_lock:
            if self._host is None:
                return
            path = self._manager._disk_path(self.handle)
            self._host.tofile(path)
            self._disk = (path, str(self._host.dtype), self._host.shape[0])
            self._host = None
            self._manager._on_disk_spill(self)

    def _ensure_host_locked(self) -> None:
        """Disk -> host RAM (the climb's first step; tier lock held).
        Budget is rolled back if the spill file cannot be read, so a
        failed climb never inflates the host tier forever."""
        if self._disk is None:
            return
        path, dtype_str, count = self._disk
        self._manager._reserve_host(self)
        try:
            host = np.fromfile(path, dtype=np.dtype(dtype_str), count=count)
            if host.shape[0] != count:
                raise IOError(f"spill file truncated: {path}")
        except BaseException:
            self._manager._unreserve_host(self)
            raise
        os.unlink(path)
        self._host = host
        self._disk = None

    def _climb_locked(self) -> None:
        """To device residency; tier lock held, self pinned."""
        if self.array is not None:
            return
        if self._host is None and self._disk is None:
            # freed out from under a concurrent climb (put() won the
            # tier lock first and tore the tiers down) — restoring
            # nothing must charge nothing, or the budget counters
            # corrupt silently (a prefetch racing free() hits this)
            return
        self._ensure_host_locked()
        self._manager._reserve_for_restore(self)
        host, self._host = self._host, None
        self.array = jax.device_put(host, self._manager.device)

    def ensure_device(self) -> "DeviceBuffer":
        """Restore a spilled buffer to HBM from whichever tier holds it
        (may spill others to fit; never a buffer pinned via
        ``DeviceBufferManager.pinned_on_device``). The buffer pins
        ITSELF for the climb: the room-making its restore triggers
        (device victims spilling to host, host cascade to disk) must
        never pick the climber mid-ascent."""
        if self.array is not None:
            return self
        m = self._manager
        m._pin(self.handle)
        try:
            with self._tier_lock:
                self._climb_locked()
        finally:
            m._unpin(self.handle)
        return self

    def stage(self, data: bytes) -> "DeviceBuffer":
        """Host -> HBM: replace the slab contents (pads to capacity).
        Pinned + tier-locked: a concurrent spill can neither delete
        the array mid-swap nor demote the slab while its budget is
        accounted device-resident."""
        if len(data) > self.capacity:
            raise ValueError(f"{len(data)}B exceeds slab capacity {self.capacity}B")
        m = self._manager
        m._pin(self.handle)
        try:
            with self._tier_lock:
                self._climb_locked()
                host = np.zeros((self.capacity,), dtype=np.uint8)
                host[: len(data)] = np.frombuffer(data, dtype=np.uint8)
                old = self.array
                self.array = jax.device_put(host, self.device)
                old.delete()
                self.length = len(data)
        finally:
            m._unpin(self.handle)
        m._touch(self)
        return self

    def put_array(self, arr) -> "DeviceBuffer":
        """Adopt a device-resident 1-D array as the slab contents.

        Any dtype is allowed (``length`` stays in BYTES): staging keys
        as uint32 lets downstream programs consume the slab directly —
        assembling words from a uint8 slab on-device costs a
        [..., 4]-minor reshape whose TPU tiled layout pads 4 -> 128
        (measured: a 32 GiB allocation for a 1 GiB merge input)."""
        if arr.ndim != 1:
            raise ValueError("slab contents must be 1-D")
        if arr.nbytes > self.capacity:
            raise ValueError("array exceeds slab capacity")
        m = self._manager
        m._pin(self.handle)
        try:
            with self._tier_lock:
                self._climb_locked()
                self.length = arr.nbytes
                old = self.array
                if arr.nbytes < self.capacity:
                    n = self.capacity // arr.dtype.itemsize
                    arr = jnp.zeros((n,), dtype=arr.dtype).at[: arr.shape[0]].set(arr)
                self.array = arr
                old.delete()
        finally:
            m._unpin(self.handle)
        m._touch(self)
        return self

    def read(self, offset: int = 0, length: Optional[int] = None) -> bytes:
        """Readback of BYTES ``[offset, offset+length)`` from whichever
        tier holds the slab, regardless of the staged dtype. Tier-locked
        so a concurrent spill cannot move (or delete) the bytes between
        the tier check and the copy."""
        if length is None:
            length = self.length - offset
        if offset < 0 or length < 0 or offset + length > self.capacity:
            raise ValueError("read out of slab bounds")
        with self._tier_lock:
            if self._disk is not None:
                path, dtype_str, count = self._disk
                mm = np.memmap(path, dtype=np.dtype(dtype_str), mode="r",
                               shape=(count,))
                return mm.view(np.uint8)[offset : offset + length].tobytes()
            if self._host is not None:
                return self._host.view(np.uint8)[
                    offset : offset + length
                ].tobytes()
            self._manager._touch(self)
            # slice on-device in whole elements (keeps the transfer
            # small), trim to byte bounds host-side
            k = np.dtype(self.array.dtype).itemsize
            lo = offset // k
            hi = -(-(offset + length) // k)
            chunk = np.asarray(self.array[lo:hi]).view(np.uint8)
            start = offset - lo * k
            return chunk[start : start + length].tobytes()

    def free(self) -> None:
        self._manager.put(self)


class _AllocatorStack:
    """Lock-guarded per-size-class free stack with a cumulative
    allocation counter (reference AllocatorStack,
    RdmaBufferManager.java:31-71)."""

    __slots__ = ("size", "stack", "total_alloc", "total_gets")

    def __init__(self, size: int):
        self.size = size
        self.stack: List[DeviceBuffer] = []
        self.total_alloc = 0
        self.total_gets = 0


class DeviceBufferManager:
    """Size-classed pool of HBM slabs for one device."""

    def __init__(self, device=None, max_bytes: int = 0, prealloc: int = 0,
                 prealloc_size: int = 0, max_host_bytes: int = 0,
                 spill_dir: Optional[str] = None):
        if device is None:
            device = jax.devices()[0]
        self.device = device
        self.max_bytes = max_bytes  # 0 = unbounded
        # host-RAM tier cap; overflow cascades to disk (§7.3(4) tier 3)
        self.max_host_bytes = max_host_bytes
        self._spill_dir = spill_dir
        self._run_token = os.urandom(4).hex()
        self._stacks: Dict[int, _AllocatorStack] = {}
        self._handles: Dict[int, DeviceBuffer] = {}
        self._next_handle = 1
        self._in_use_bytes = 0
        self._host_bytes = 0
        self._use_clock = 0
        self._spill_count = 0
        self._disk_spill_count = 0
        self._pins: Dict[int, int] = {}  # handle -> pin refcount
        self._pin_threads: Dict[int, List[int]] = {}  # handle -> owner idents
        # budget reserved by get() for slabs not yet in the handle
        # table: invisible to victim picks, but a reason to WAIT
        self._allocating = 0
        # waiters in _make_room blocked on pinned residents; notified on
        # any pin drop or budget release
        self._evict_cond = threading.Condition(named_lock("hbm.evict"))
        self._lock = named_lock("hbm.manager")
        self._stopped = False
        # optional warm-up (reference maxAggPrealloc, RdmaBufferManager.java:84-91)
        if prealloc > 0 and prealloc_size > 0:
            bufs = [self.get(prealloc_size) for _ in range(prealloc)]
            for b in bufs:
                b.free()

    # ------------------------------------------------------------------
    # HBM <-> host tiering (SURVEY.md §7.3-4). Tier moves synchronize on
    # buffer state loosely: concurrent spill/restore of the SAME buffer
    # is the caller's race to avoid; budget arithmetic itself is locked.
    def _touch(self, buf: DeviceBuffer) -> None:
        with self._lock:
            self._use_clock += 1
            buf.last_use = self._use_clock

    def _disk_path(self, handle: int) -> str:
        # pid + per-manager random token: two executor processes on one
        # host (the deployment model) must never collide on a spill
        # name — id(self) alone is just a heap address both can share
        d = self._spill_dir or tempfile.gettempdir()
        return f"{d}/hbm-spill-{os.getpid()}-{self._run_token}-{handle}.bin"

    def _pin(self, handle: int) -> None:
        with self._lock:
            self._pins[handle] = self._pins.get(handle, 0) + 1
            self._pin_threads.setdefault(handle, []).append(
                threading.get_ident()
            )

    def _unpin(self, handle: int) -> None:
        with self._lock:
            c = self._pins.get(handle, 0) - 1
            if c > 0:
                self._pins[handle] = c
            else:
                self._pins.pop(handle, None)
            owners = self._pin_threads.get(handle)
            if owners:
                try:
                    owners.remove(threading.get_ident())
                except ValueError:
                    pass
                if not owners:
                    self._pin_threads.pop(handle, None)
        with self._evict_cond:
            self._evict_cond.notify_all()

    def _on_spill_accounting(self, buf: DeviceBuffer) -> None:
        """Device -> host budget transfer. Safe under the mover's tier
        lock — the follow-up cascade is the CALLER's duty, outside it."""
        with self._lock:
            self._in_use_bytes -= buf.capacity
            self._host_bytes += buf.capacity
            self._spill_count += 1
        _G_IN_USE.add(-buf.capacity)
        _M_SPILL_VICTIMS.inc()
        with self._evict_cond:
            self._evict_cond.notify_all()

    def _on_disk_spill(self, buf: DeviceBuffer) -> None:
        with self._lock:
            self._host_bytes -= buf.capacity
            self._disk_spill_count += 1
        _M_DISK_SPILLS.inc()

    def _pick_host_victim(self, exclude_handle: int) -> Optional[DeviceBuffer]:
        with self._lock:
            candidates = [
                b
                for b in self._handles.values()
                if b.handle != exclude_handle
                and b.handle not in self._pins
                and b._host is not None
            ]
            if not candidates:
                return None
            return min(candidates, key=lambda b: b.last_use)

    def _cascade_host_tier(self, exclude_handle: int = -1) -> None:
        """Push LRU host-tier residents to disk while over the host cap."""
        while True:
            with self._lock:
                if not self.max_host_bytes or self._host_bytes <= self.max_host_bytes:
                    return
            victim = self._pick_host_victim(exclude_handle)
            if victim is None:
                return  # everything host-resident is excluded/pinned
            victim.spill_to_disk()

    def _reserve_host(self, buf: DeviceBuffer) -> None:
        """Account a disk -> host climb (cascading others down first;
        safe under the climber's tier lock — the climber is pinned, so
        no victim pick can wait on it)."""
        with self._lock:
            self._host_bytes += buf.capacity
        self._cascade_host_tier(exclude_handle=buf.handle)

    def _unreserve_host(self, buf: DeviceBuffer) -> None:
        """Roll back a failed disk -> host climb."""
        with self._lock:
            self._host_bytes -= buf.capacity

    def _pick_spill_victim(self, pinned) -> Optional[DeviceBuffer]:
        with self._lock:
            candidates = [
                b
                for b in self._handles.values()
                if b.handle not in pinned
                and b.handle not in self._pins
                and not b.spilled
                and b.array is not None
            ]
            if not candidates:
                return None
            broker = _quota.broker("hbm")
            if broker is not None:
                # an over-quota tenant's slabs go first: its own hoard
                # pays for the pressure it created, LRU breaks ties
                return min(
                    candidates,
                    key=lambda b: (
                        not (b.tenant and broker.over_quota(b.tenant)),
                        b.last_use,
                    ),
                )
            return min(candidates, key=lambda b: b.last_use)

    def _make_room(self, cls: int, pinned=frozenset()) -> None:
        """Spill LRU device-resident buffers (never a ``pinned`` handle)
        until ``cls`` bytes fit.

        When every resident slab is pinned by OTHER threads (concurrent
        climbers mid-restore), those pins are transient — wait for one
        to drop instead of failing a healthy pool. Raise immediately
        when only this thread's own pins block the way (waiting would
        self-deadlock), or after a deadline (wedged pin holder)."""
        me = threading.get_ident()
        deadline = time.monotonic() + 30.0
        while True:
            with self._lock:
                if not self.max_bytes or self._in_use_bytes + cls <= self.max_bytes:
                    return
            victim = self._pick_spill_victim(pinned)
            if victim is not None:
                victim.spill_to_host()
                continue
            with self._lock:
                # Any pin held by another thread counts as transient
                # contention worth waiting on — including a climber
                # mid-restore whose budget is already charged
                # (_reserve_for_restore) while its ``array`` is still
                # None until jax.device_put returns (seconds for large
                # slabs). Requiring device residency here raised
                # MemoryError on a healthy pool during that window.
                foreign_pins = any(
                    self._handles.get(h) is not None
                    and any(t != me for t in self._pin_threads.get(h, ()))
                    for h in self._pins
                ) or self._allocating > 0
                in_use = self._in_use_bytes
            if not foreign_pins or time.monotonic() > deadline:
                raise MemoryError(
                    f"HBM shuffle budget exceeded: in-use {in_use}B + {cls}B "
                    f"> cap {self.max_bytes}B and nothing left to spill"
                )
            with self._evict_cond:
                self._evict_cond.wait(0.05)

    def _reserve_for_restore(self, buf: DeviceBuffer) -> None:
        self._make_room(buf.capacity, {buf.handle})
        with self._lock:
            self._in_use_bytes += buf.capacity
            self._host_bytes -= buf.capacity  # leaving the host tier
            self._use_clock += 1
            buf.last_use = self._use_clock
        _G_IN_USE.add(buf.capacity)

    @contextlib.contextmanager
    def pinned_on_device(self, bufs):
        """Context manager: pin a WORKING SET device-resident.

        Inside the ``with`` body every buffer in ``bufs`` is
        device-resident and can never be picked as a spill victim —
        not while restoring other members, and not by CONCURRENT pool
        operations on other threads (pins are refcounted manager
        state, not a call-local exclude list). Direct ``.array``
        access is therefore safe exactly for the duration of the
        block, and only there: on exit the pins drop and any later
        pool op may spill the set again.

        Raises MemoryError up front if the set itself cannot fit the
        budget — loud, instead of thrash-spilling the set against
        itself (which would leave some ``.array`` None)."""
        bufs = list(bufs)
        if self.max_bytes:
            need = sum(b.capacity for b in bufs)
            if need > self.max_bytes:
                raise MemoryError(
                    f"working set of {need}B cannot fit HBM budget "
                    f"{self.max_bytes}B; consume in smaller batches"
                )
        handles = [b.handle for b in bufs]
        for h in handles:
            self._pin(h)
        try:
            for b in bufs:
                b.ensure_device()
                # freshen EVERY member: a long-resident member must not
                # linger as global LRU once the pins drop
                self._touch(b)
            yield
        finally:
            for h in handles:
                self._unpin(h)

    @contextlib.contextmanager
    def pinned_if_resident(self, handle: int):
        """Pin ``handle`` for the block iff it is live AND still
        device-resident; yield the buffer, or None otherwise.

        The device fetch plane's eviction-race guard: unlike
        ``pinned_on_device`` this NEVER climbs a spilled buffer back —
        a source shard the arena already demoted must degrade to the
        host fetch path, not trigger a restore (which could thrash the
        publisher's budget) and never error. While the body runs the
        pin keeps spill victim picks away, so ``.array`` stays valid
        for the duration of the pull."""
        try:
            buf = self.resolve(handle)
        except KeyError:
            yield None
            return
        self._pin(handle)
        try:
            # re-check residency under the pin: a spill that won the
            # race before the pin landed leaves array None / tiers set
            if buf.array is None or buf.spilled:
                yield None
            else:
                with self._lock:
                    live = self._handles.get(handle) is buf
                yield buf if live else None
        finally:
            self._unpin(handle)

    def ensure_device_all(self, bufs) -> None:
        """Restore a working set to HBM without the set victimizing
        itself. NOTE: protection ends when this returns — consumers
        that touch ``.array`` directly should hold
        ``pinned_on_device(bufs)`` across the access instead."""
        with self.pinned_on_device(bufs):
            pass

    def prefetch(self, bufs) -> threading.Event:
        """Start climbing ``bufs`` back toward HBM on a background
        thread — the "prefetch back to HBM on fetch" of SURVEY
        §7.3(4), overlapping tier restores with whatever the caller
        computes next. Returns an Event set when the pass finishes
        (success or not). The climb uses the same pinned restore as
        ``ensure_device_all``; consumers still wrap their access in
        ``pinned_on_device`` (a fast no-op once prefetched). Best
        effort: under budget pressure later traffic may re-spill."""
        bufs = list(bufs)
        done = threading.Event()
        # the climb re-spills victims and re-charges restores under the
        # CALLER's tenant, so the background thread must re-enter its
        # scope — otherwise the work bills the default tenant
        tenant = current_tenant()

        def run():
            with tenant_scope(tenant):
                try:
                    self.ensure_device_all(bufs)
                except Exception:
                    logger.exception("hbm prefetch pass failed")
                finally:
                    done.set()

        threading.Thread(target=run, daemon=True, name="hbm-prefetch").start()
        return done

    def get(self, nbytes: int) -> DeviceBuffer:
        """Allocate (or reuse) a slab whose class covers ``nbytes``.

        Under budget pressure, least-recently-used live slabs spill to
        host RAM first; MemoryError only when nothing is spillable.
        When an hbm quota broker is installed, the tenant's charge
        gates the allocation — an over-quota tenant blocks here, on
        its own worker thread, until its earlier slabs are put back
        (capacity is charged for the get→put lifetime, so spilling a
        slab to host does NOT un-block its tenant)."""
        broker = _quota.broker("hbm")
        if broker is None:
            return self._get_slab(nbytes, None)
        tenant = current_tenant()
        cls = _size_class(nbytes)
        broker.charge(tenant, cls)
        try:
            buf = self._get_slab(nbytes, tenant)
        except BaseException:
            broker.release(tenant, cls)
            raise
        buf._quota_tag = (broker, tenant, cls)
        return buf

    def _get_slab(self, nbytes: int, tenant) -> DeviceBuffer:
        cls = _size_class(nbytes)
        with self._lock:
            if self._stopped:
                raise RuntimeError("DeviceBufferManager is stopped")
            stack = self._stacks.setdefault(cls, _AllocatorStack(cls))
            stack.total_gets += 1
            pooled = stack.stack.pop() if stack.stack else None
            if pooled is not None:
                pooled.length = nbytes
                pooled.tenant = tenant
                pooled._quota_tag = None
                self._in_use_bytes += cls
                self._handles[pooled.handle] = pooled
                self._use_clock += 1
                pooled.last_use = self._use_clock
        if pooled is not None:
            _M_POOL_HITS.inc()
            _G_IN_USE.add(cls)
            # the pooled slab re-enters the budget: spill LRU others if
            # that pushed us over the cap
            self._make_room(0, {pooled.handle})
            return pooled
        _M_POOL_MISSES.inc()
        self._make_room(cls)
        with self._lock:
            handle = self._next_handle
            self._next_handle += 1
            stack.total_alloc += 1
            self._in_use_bytes += cls
            # budget held for a slab not yet visible in the handle
            # table: concurrent _make_room callers must WAIT for it to
            # materialize, not conclude the pool is unspillable
            self._allocating += 1
        _G_IN_USE.add(cls)
        try:
            arr = jax.device_put(jnp.zeros((cls,), dtype=jnp.uint8), self.device)
            buf = DeviceBuffer(handle, cls, arr, self)
            buf.length = nbytes
            buf.tenant = tenant
            with self._lock:
                self._handles[handle] = buf
                self._use_clock += 1
                buf.last_use = self._use_clock
        finally:
            with self._lock:
                self._allocating -= 1
            with self._evict_cond:
                self._evict_cond.notify_all()
        return buf

    def put(self, buf: DeviceBuffer) -> None:
        """Return a slab to its class stack (RdmaBufferManager.java:120-127).

        Takes the buffer's tier lock so a manager-initiated cascade
        mid-move on this buffer finishes (or sees it gone) before the
        tiers are torn down."""
        with buf._tier_lock:
            with self._lock:
                if self._handles.pop(buf.handle, None) is None:
                    return  # double-free tolerated, like onFailure reentry
                # freeing while pinned is a caller bug; don't let the
                # stale pin shield a recycled slab from eviction forever
                self._pins.pop(buf.handle, None)
                self._pin_threads.pop(buf.handle, None)
                if buf.spilled:
                    # spilled slabs released their device budget already
                    # and have no device array to pool — drop whichever
                    # lower tier holds the bytes
                    if buf._host is not None:
                        self._host_bytes -= buf.capacity
                        buf._host = None
                    disk, buf._disk = buf._disk, None
                else:
                    disk = None
            tag, buf._quota_tag = buf._quota_tag, None
            if tag is not None:
                # held-capacity quota retires with the slab, whatever
                # tier the bytes ended up in
                tag[0].release(tag[1], tag[2])
            if disk is not None:
                try:
                    os.unlink(disk[0])
                except OSError:
                    pass
            if buf.array is None:
                return
            with self._lock:
                self._in_use_bytes -= buf.capacity
                stopped = self._stopped
                if stopped:
                    buf.array.delete()
                else:
                    self._stacks[buf.capacity].stack.append(buf)
            _G_IN_USE.add(-buf.capacity)
            with self._evict_cond:
                self._evict_cond.notify_all()
            if not stopped:
                buf.length = 0

    def resolve(self, handle: int) -> DeviceBuffer:
        """Handle table lookup — the mkey/rkey resolution analogue."""
        with self._lock:
            buf = self._handles.get(handle)
        if buf is None:
            raise KeyError(f"no live device buffer for handle {handle}")
        return buf

    def stage_bytes(self, data: bytes) -> DeviceBuffer:
        """Pool + stage in one step (host bytes -> registered HBM slab)."""
        return self.get(len(data)).stage(data)

    def stage_view(self, view, valid_len: Optional[int] = None,
                   dtype=np.uint8) -> DeviceBuffer:
        """Pool + stage from a buffer-protocol object WITHOUT the host
        round trip ``stage_bytes`` pays: the device transfer reads the
        source memory directly (one DMA), and no pad program ever
        compiles — the transfer is exactly one slab class long
        (SURVEY.md §7.3(3): the copy count at the host<->HBM seam is
        the difference between matching and missing the wire rate).

        ``valid_len`` (default: the whole view) is the byte length of
        the real contents. When the source is at least a slab class
        long — always true for pooled registered buffers, whose
        power-of-two classes match the device pool's — the tail past
        ``valid_len`` rides along as this process's own pooled bytes
        and is masked by ``length`` downstream; that removes the
        per-(length, capacity) jitted pad `put_array` would otherwise
        build (measured: each novel shape pair cost a multi-second
        Mosaic compile in the fetch path).

        ``dtype`` reinterprets the bytes host-side (free) so the slab
        lands typed — e.g. uint32 keys a device merge consumes
        directly (see ``put_array`` on why on-device byte->word
        assembly is ruinous on TPU)."""
        src = np.frombuffer(view, dtype=np.uint8)
        n = src.nbytes if valid_len is None else valid_len
        buf = self.get(n)
        if src.nbytes >= buf.capacity:
            typed = src[: buf.capacity].view(dtype)
            if buf.device.platform == "cpu":
                # the CPU backend's device_put may ALIAS host memory
                # zero-copy — but the source is a pooled registered
                # buffer the caller recycles immediately, so a later
                # fetch would overwrite these "device" bytes in place
                # (caught by the overlapped e2e on the CPU mesh; TPU
                # always DMAs a real copy)
                typed = typed.copy()
            arr = jax.device_put(typed, buf.device)
        else:
            # short source (not from a pooled class): pad host-side —
            # one memcpy, still compile-free
            host = np.zeros((buf.capacity,), dtype=np.uint8)
            host[: src.nbytes] = src
            arr = jax.device_put(host.view(dtype), buf.device)
        buf = buf.put_array(arr)
        buf.length = n
        # device_put may read the source asynchronously; callers recycle
        # the source buffer (a pooled registered region) immediately, so
        # the transfer must be complete before this returns
        jax.block_until_ready(buf.array)
        return buf

    # ------------------------------------------------------------------
    @property
    def in_use_bytes(self) -> int:
        with self._lock:
            return self._in_use_bytes

    @property
    def spill_count(self) -> int:
        with self._lock:
            return self._spill_count

    @property
    def disk_spill_count(self) -> int:
        with self._lock:
            return self._disk_spill_count

    @property
    def host_bytes(self) -> int:
        with self._lock:
            return self._host_bytes

    def stats(self) -> Dict[int, Dict[str, int]]:
        with self._lock:
            return {
                size: {
                    "total_alloc": s.total_alloc,
                    "total_gets": s.total_gets,
                    "pooled": len(s.stack),
                }
                for size, s in self._stacks.items()
            }

    def stop(self) -> None:
        """Free everything; log per-class stats (RdmaBufferManager.java:131-141)."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            stacks = list(self._stacks.values())
            leaked = list(self._handles.values())
        for s in stacks:
            if s.total_alloc:
                logger.info(
                    "hbm pool class %dB: allocated %d, gets %d, pooled %d",
                    s.size, s.total_alloc, s.total_gets, len(s.stack),
                )
            for buf in s.stack:
                buf.array.delete()
            s.stack.clear()
        for buf in leaked:
            logger.warning("hbm slab handle %d leaked (freeing)", buf.handle)
            if buf.array is not None:
                buf.array.delete()
            buf._host = None
            if buf._disk is not None:
                try:
                    os.unlink(buf._disk[0])
                except OSError:
                    pass
                buf._disk = None
