"""Wire types for block/partition locations and manager identity.

TPU-native analogue of RdmaPartitionLocation.scala (reference:
/root/reference/src/main/scala/org/apache/spark/shuffle/rdma/
RdmaPartitionLocation.scala:25-147).

A *block location* is the one-sided-read handle triple: in the reference
it is ``(address: Long, length: Int, mKey: Int)`` — a raw virtual address
plus the RDMA memory-region key. Here ``address`` is an offset within a
registered buffer and ``mkey`` is the process-wide registry handle of
that buffer (see sparkrdma_tpu.memory.buffer). The passive peer resolves
``(mkey, address, length)`` without involving its application layer,
exactly like an RDMA NIC resolves ``(rkey, addr, len)``.

Serialization is fixed-width big-endian, mirroring the reference's
DataOutputStream layout so sizes are predictable for RPC segmentation.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from io import BytesIO
from typing import BinaryIO, List

_BLOCK = struct.Struct(">QII")  # address(8) length(4) mkey(4)


@dataclass(frozen=True)
class BlockLocation:
    """(address, length, mkey) — reference RdmaBlockLocation, :25.

    ``checksum``/``checksum_algo`` are the resilience layer's integrity
    tag over the staged bytes (utils/checksum.py), computed at publish
    time. They are NOT part of the legacy 16-byte serialization below —
    they travel in the PublishPartitionLocations frame's trailing
    checksum extension (rpc.py) so legacy parsers
    (examples/foreign_client.c) keep working. algo 0 = no checksum.

    ``device_coords``/``arena_handle``/``arena_offset`` are the device
    fetch plane's HBM-side address of the same bytes: the publisher's
    mesh device id, its HBM-arena slab handle (ops/hbm_arena.py) and
    byte offset within it. Like the checksum tag they ride a trailing
    frame extension (rpc.py), never the legacy 16-byte form. An
    ``arena_handle`` of 0 means no device copy exists (arena handles
    start at 1); the host triple above is always the durable fallback.

    ``merged_cover`` marks a *merged* location (push-based merge plane,
    shuffle/merge.py): the block is one sequential segment holding the
    concatenated payloads of ``merged_cover`` original per-map blocks
    of its partition. 0 = a plain per-map block. Readers choose
    merged-else-original: a merged location substitutes for ALL the
    partition's originals only when ``merged_cover`` equals their
    count, and the originals always remain the durable fallback. Rides
    a trailing frame extension (rpc.py), never the legacy 16-byte form.

    ``block_format`` names the payload encoding of the staged bytes:
    0 = pickle frame stream (the universal default), 1 = every frame
    in the block is fixed-width columnar (shuffle/columnar.py) — the
    collective compiler may admit such blocks into DMA waves and the
    reduce side decodes them as memoryview column slices. Rides the
    trailing format extension (rpc.py), never the legacy 16-byte form:
    legacy frames stay byte-identical when every block is pickle.

    ``replica_of``/``source_map`` are the elastic layer's lineage tag
    (sparkrdma_tpu/elastic/): ``source_map`` names the map task that
    produced the bytes (-1 = unattributed, e.g. chunked-agg finalize
    segments), ``replica_of`` names the executor whose primary copy
    these bytes duplicate ("" = a primary). Replica locations never
    enter fetch replies directly — the driver diverts them into its
    replica registry and promotes them only when the primary's
    executor is lost. Both ride a trailing frame extension (rpc.py),
    never the legacy 16-byte form.
    """

    address: int
    length: int
    mkey: int
    checksum: int = 0
    checksum_algo: int = 0
    device_coords: int = -1
    arena_handle: int = 0
    arena_offset: int = 0
    merged_cover: int = 0
    replica_of: str = ""
    source_map: int = -1
    block_format: int = 0

    SERIALIZED_SIZE = _BLOCK.size

    FORMAT_PICKLE = 0
    FORMAT_COLUMNAR = 1

    @property
    def is_columnar(self) -> bool:
        """True when the staged payload is the columnar block format."""
        return self.block_format == self.FORMAT_COLUMNAR

    @property
    def has_device(self) -> bool:
        """True when a device-resident copy is advertised."""
        return self.arena_handle != 0

    @property
    def is_merged(self) -> bool:
        """True when this is a merged segment (covers >= 1 originals)."""
        return self.merged_cover != 0

    @property
    def is_replica(self) -> bool:
        """True when this duplicates another executor's primary copy."""
        return bool(self.replica_of)

    def write(self, out: BinaryIO) -> None:
        out.write(_BLOCK.pack(self.address, self.length, self.mkey))

    @classmethod
    def read(cls, inp: BinaryIO) -> "BlockLocation":
        addr, length, mkey = _BLOCK.unpack(inp.read(_BLOCK.size))
        return cls(addr, length, mkey)


def _write_str(out: BinaryIO, s: str) -> None:
    b = s.encode("utf-8")
    out.write(struct.pack(">H", len(b)))
    out.write(b)


def _read_str(inp: BinaryIO) -> str:
    (n,) = struct.unpack(">H", inp.read(2))
    return inp.read(n).decode("utf-8")


@dataclass(frozen=True)
class ShuffleManagerId:
    """Identity of one shuffle endpoint (host, port, executor_id).

    Reference RdmaShuffleManagerId(host, port, blockManagerId), :61-147.
    Equality/hash are on ``executor_id`` alone, mirroring the reference's
    equality on blockManagerId (:128-137) so a restarted endpoint with a
    new port replaces rather than duplicates its registry entries.
    """

    host: str
    port: int
    executor_id: str

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, ShuffleManagerId)
            and self.executor_id == other.executor_id
        )

    def __hash__(self) -> int:
        return hash(self.executor_id)

    def serialized_size(self) -> int:
        return 2 + len(self.host.encode()) + 4 + 2 + len(self.executor_id.encode())

    def write(self, out: BinaryIO) -> None:
        _write_str(out, self.host)
        out.write(struct.pack(">I", self.port))
        _write_str(out, self.executor_id)

    @classmethod
    def read(cls, inp: BinaryIO) -> "ShuffleManagerId":
        host = _read_str(inp)
        (port,) = struct.unpack(">I", inp.read(4))
        executor_id = _read_str(inp)
        return cls(host, port, executor_id)

    def to_bytes(self) -> bytes:
        buf = BytesIO()
        self.write(buf)
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, data: bytes) -> "ShuffleManagerId":
        return cls.read(BytesIO(data))


@dataclass(frozen=True)
class PartitionLocation:
    """One reducer-visible block of one partition on one endpoint.

    Reference RdmaPartitionLocation(rdmaShuffleManagerId, partitionId,
    rdmaBlockLocation), :27-59.
    """

    manager_id: ShuffleManagerId
    partition_id: int
    block: BlockLocation

    def serialized_size(self) -> int:
        return self.manager_id.serialized_size() + 4 + BlockLocation.SERIALIZED_SIZE

    def write(self, out: BinaryIO) -> None:
        self.manager_id.write(out)
        out.write(struct.pack(">i", self.partition_id))
        self.block.write(out)

    @classmethod
    def read(cls, inp: BinaryIO) -> "PartitionLocation":
        mgr = ShuffleManagerId.read(inp)
        (pid,) = struct.unpack(">i", inp.read(4))
        block = BlockLocation.read(inp)
        return cls(mgr, pid, block)


def write_locations(out: BinaryIO, locs: List[PartitionLocation]) -> None:
    out.write(struct.pack(">I", len(locs)))
    for loc in locs:
        loc.write(out)


def read_locations(inp: BinaryIO) -> List[PartitionLocation]:
    (n,) = struct.unpack(">I", inp.read(4))
    return [PartitionLocation.read(inp) for _ in range(n)]
