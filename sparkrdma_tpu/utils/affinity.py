"""CPU-vector allocation for completion threads.

Reference behavior: RdmaNode shuffles the configured ``cpuList`` and
round-robins each channel's CQ thread onto a CPU vector
(RdmaNode.java:221-277); RdmaThread pins itself via
``NativeAffinity.setAffinity`` (RdmaThread.java:44-46). Here the pin is
``os.sched_setaffinity`` on the completion thread. An empty ``cpuList``
means no pinning (the scheduler decides) — the right default on small
hosts.
"""

from __future__ import annotations

import logging
import os
import random
import threading
from typing import List, Optional

logger = logging.getLogger(__name__)


def parse_cpu_list(spec: str) -> List[int]:
    """Parse "0-3,7,9-10" into [0,1,2,3,7,9,10]; invalid entries dropped."""
    cpus: List[int] = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            if "-" in part:
                lo, hi = part.split("-", 1)
                cpus.extend(range(int(lo), int(hi) + 1))
            else:
                cpus.append(int(part))
        except ValueError:
            logger.warning("ignoring invalid cpuList entry %r", part)
    avail = None
    try:
        avail = os.sched_getaffinity(0)
    except (AttributeError, OSError):
        pass
    if avail is not None:
        cpus = [c for c in cpus if c in avail]
    return cpus


class CpuVectorAllocator:
    """Round-robin CPU vectors from a shuffled cpuList (reference
    shuffles before round-robin, RdmaNode.java:233)."""

    def __init__(self, cpu_list: str, seed: Optional[int] = None):
        self._cpus = parse_cpu_list(cpu_list)
        if self._cpus:
            random.Random(seed).shuffle(self._cpus)
        self._next = 0
        self._lock = threading.Lock()

    def next_vector(self) -> Optional[int]:
        with self._lock:
            if not self._cpus:
                return None
            cpu = self._cpus[self._next % len(self._cpus)]
            self._next += 1
            return cpu


def pin_current_thread(cpu: Optional[int]) -> bool:
    """Pin the calling thread to one CPU; False if unsupported/declined."""
    if cpu is None:
        return False
    try:
        os.sched_setaffinity(0, {cpu})
        return True
    except (AttributeError, OSError) as e:
        logger.debug("could not pin thread to cpu %d: %s", cpu, e)
        return False
