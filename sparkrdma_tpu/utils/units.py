"""Byte-size string parsing, Spark-conf style ("4k", "8m", "25g").

Reference semantics: SparkConf.getSizeAsBytes as used by
RdmaShuffleConf.scala:47-58 (values are suffixed byte strings; bare
integers are bytes).
"""

from __future__ import annotations

_SUFFIXES = {
    "": 1,
    "b": 1,
    "k": 1 << 10,
    "kb": 1 << 10,
    "m": 1 << 20,
    "mb": 1 << 20,
    "g": 1 << 30,
    "gb": 1 << 30,
    "t": 1 << 40,
    "tb": 1 << 40,
}


def parse_bytes(value) -> int:
    """Parse a byte-size value: int passes through, strings accept k/m/g/t suffixes."""
    if isinstance(value, (int, float)):
        return int(value)
    s = str(value).strip().lower()
    i = len(s)
    while i > 0 and not s[i - 1].isdigit():
        i -= 1
    num, suffix = s[:i], s[i:].strip()
    if not num or suffix not in _SUFFIXES:
        raise ValueError(f"cannot parse byte size: {value!r}")
    return int(num) * _SUFFIXES[suffix]


def format_bytes(n: int) -> str:
    for unit, div in (("g", 1 << 30), ("m", 1 << 20), ("k", 1 << 10)):
        if n >= div and n % div == 0:
            return f"{n // div}{unit}"
    return str(n)
