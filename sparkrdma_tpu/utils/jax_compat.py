"""Version-skew shims for the jax surface the kernels depend on.

``shard_map`` moved between jax releases: new jax exposes it as
``jax.shard_map`` with a ``check_vma`` kwarg, while the 0.4.x line
ships it as ``jax.experimental.shard_map.shard_map`` with the
equivalent kwarg spelled ``check_rep``. Every in-repo kernel imports
``shard_map`` from here so both families work unmodified.
"""

from __future__ import annotations

try:  # new jax (>= 0.5): top-level export, check_vma kwarg
    from jax import shard_map as _shard_map

    _KWARG = "check_vma"
except ImportError:  # jax 0.4.x: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _KWARG = "check_rep"

_ALIASES = ("check_vma", "check_rep")


def shard_map(f, *args, **kwargs):
    """Call the installed shard_map, translating the replication-check
    kwarg to whichever spelling this jax version accepts."""
    for alias in _ALIASES:
        if alias in kwargs and alias != _KWARG:
            kwargs[_KWARG] = kwargs.pop(alias)
    return _shard_map(f, *args, **kwargs)
