"""Block integrity checksums for the resilient fetch path.

The wire carries an *algo-tagged* checksum per block (docs/RESILIENCE.md)
so publisher and fetcher may disagree on available implementations
without breaking: a fetcher that cannot compute the publisher's algo
treats the block as unverifiable and passes it through, exactly like a
legacy frame with no checksum at all.

Algorithms:
  0 — none (legacy frames / checksums disabled)
  1 — crc32c (Castagnoli; hardware-accelerated ``crc32c`` package)
  2 — crc32 (zlib; always available)

crc32c is the reference-grade choice (what RDMA NICs and Spark's own
shuffle integrity use); without the native package we fall back to
zlib's C crc32 rather than a pure-Python table walk, which would cost
seconds per 8 MiB block.
"""

from __future__ import annotations

import zlib
from typing import Tuple

ALGO_NONE = 0
ALGO_CRC32C = 1
ALGO_CRC32 = 2

try:  # optional accelerator; never a hard dependency
    import crc32c as _crc32c_mod  # type: ignore

    _HAVE_CRC32C = True
except ImportError:
    _crc32c_mod = None
    _HAVE_CRC32C = False

DEFAULT_ALGO = ALGO_CRC32C if _HAVE_CRC32C else ALGO_CRC32


def compute(data, algo: int = None) -> Tuple[int, int]:
    """Checksum ``data`` (any buffer) -> (algo, crc32 value).

    ``algo=None`` picks the best available implementation; an explicitly
    requested but unavailable algo degrades to (ALGO_NONE, 0) rather
    than raising — integrity is best-effort by design.
    """
    if algo is None:
        algo = DEFAULT_ALGO
    if algo == ALGO_CRC32C and _HAVE_CRC32C:
        return ALGO_CRC32C, _crc32c_mod.crc32c(bytes(data)) & 0xFFFFFFFF
    if algo == ALGO_CRC32:
        return ALGO_CRC32, zlib.crc32(data) & 0xFFFFFFFF
    return ALGO_NONE, 0


def verify(data, checksum: int, algo: int) -> bool:
    """True if ``data`` matches, or if the block is unverifiable.

    Unverifiable = no checksum attached (ALGO_NONE), or an algo this
    process cannot compute. Both pass: the checksum extension must
    never make mixed-version clusters worse than no checksums at all.
    """
    if algo == ALGO_NONE:
        return True
    if algo == ALGO_CRC32C:
        if not _HAVE_CRC32C:
            return True
        return (_crc32c_mod.crc32c(bytes(data)) & 0xFFFFFFFF) == checksum
    if algo == ALGO_CRC32:
        return (zlib.crc32(data) & 0xFFFFFFFF) == checksum
    return True  # unknown future algo: unverifiable
