"""TpuShuffleConf — all framework tunables, range-clamped.

TPU-native analogue of RdmaShuffleConf.scala (reference: /root/reference/
src/main/scala/org/apache/spark/shuffle/rdma/RdmaShuffleConf.scala:47-126).
Every getter clamps out-of-range values back to the default, silently,
exactly like the reference's ``getConfKey`` helpers (:47-58). Keys are
prefixed ``tpu.shuffle.`` (reference prefix: ``spark.shuffle.rdma.``).

The defaults reproduce the reference's tuned 100GbE operating point
(queue depths 2048/4096, 4 KiB RPC segments, 8 MiB blocks, 128 MiB
in-flight cap, 25 GiB in-memory budget), plus TPU-only knobs for the
device exchange plane (bucket sizes, mesh axes).
"""

from __future__ import annotations

import enum
import os
from typing import Dict, Optional

from sparkrdma_tpu.utils.units import parse_bytes


class ShuffleWriterMethod(enum.Enum):
    """Reference: ShuffleWriterMethod enum, RdmaShuffleConf.scala:24-28."""

    WRAPPER = "wrapper"
    CHUNKED_PARTITION_AGG = "chunkedpartitionagg"

    @classmethod
    def parse(cls, s: str) -> "ShuffleWriterMethod":
        s = s.strip().lower()
        for m in cls:
            if m.value == s:
                return m
        raise ValueError(
            f"unknown shuffle writer method {s!r}; "
            f"expected one of {[m.value for m in cls]}"
        )


PREFIX = "tpu.shuffle."

# -- declared-knobs registry ----------------------------------------------
# Every tpu.shuffle.* key the framework understands, by suffix. This is
# the single source of truth the knob-registry analysis pass resolves
# reads against (sparkrdma_tpu/analysis/knobs.py): a literal key that
# is not here — in library code, tests, or benches — fails the lint, so
# typo'd knobs die in CI instead of silently falling back to defaults.
# Keep entries in the same order as the property getters below.
DECLARED_KNOBS: Dict[str, str] = {
    "recvQueueDepth": "receive queue depth (transport)",
    "sendQueueDepth": "send queue depth (transport)",
    "recvWrSize": "RPC segment size in bytes",
    "cpuList": "worker thread placement list",
    "shuffleWriteMethod": "writer strategy (wrapper|chunkedpartitionagg)",
    "shuffleWriteChunkSize": "chunked-agg chunk size",
    "shuffleWriteFlushSize": "wrapper writer flush size",
    "shuffleWriteBlockSize": "writer block size",
    "shuffleWriteMaxInMemoryStoragePerExecutor": "in-memory write budget",
    "shuffleReadBlockSize": "reader block size",
    "maxBytesInFlight": "reader in-flight byte cap",
    "maxAggBlock": "aggregation block size",
    "maxAggPrealloc": "preallocated agg buffers per executor",
    "collectShuffleReadStats": "collect reader fetch-time stats",
    "fetchTimeNumBuckets": "reader stats: histogram buckets",
    "fetchTimeBucketSizeInMs": "reader stats: bucket width",
    "obs.traceEnabled": "record spans in the per-role tracers",
    "obs.traceMaxSpans": "retained spans per tracer",
    "obs.critpath.enabled": "per-job critical-path TimeBreakdown",
    "obs.telemetry.enabled": "heartbeat loops + driver TelemetryHub",
    "obs.telemetry.intervalMs": "heartbeat period / ring bucket width",
    "obs.telemetry.ringSize": "windows retained per executor",
    "obs.telemetry.httpPort": "OpenMetrics scrape port (0 = off)",
    "obs.telemetry.stragglerZ": "robust z threshold for stragglers",
    "obs.telemetry.flightWindows": "ring windows per flight record",
    "obs.telemetry.flightDir": "flight-record output directory",
    "obs.telemetry.openmetricsFile": "periodic OpenMetrics file egress",
    "obs.profile.enabled": "always-on wall-clock sampling profiler",
    "obs.profile.hz": "profiler sampling rate (samples/s per thread)",
    "obs.profile.maxFrames": "deepest stack recorded per sample",
    "obs.profile.windowMs": "recent-sample window (flight records, "
                            "gap-frame annotation)",
    "obs.slo.enabled": "SLO burn-rate engine on the telemetry hub",
    "obs.slo.evalIntervalMs": "min period between SLO evaluations",
    "obs.slo.taskP99Ms": "p99 task-latency objective target (0 = off)",
    "obs.slo.queueWaitP99Ms": "p99 admission-wait objective (0 = off)",
    "obs.slo.errorRatio": "fetch error-ratio budget (bad/total)",
    "obs.slo.throughputFloorMBps": "write-throughput floor (0 = off)",
    "obs.slo.fastWindows": "fast-burn horizon in ring windows",
    "obs.slo.slowWindows": "slow-burn horizon in ring windows",
    "obs.slo.fastBurn": "burn-rate multiple that pages",
    "obs.slo.slowBurn": "burn-rate multiple that warns",
    "obs.journal.enabled": "HLC-ordered cluster event journal",
    "obs.journal.ringSize": "events retained per process journal",
    "obs.journal.flightEvents": "merged events per flight record",
    "obs.capacity.enabled": "USE-method capacity plane on the hub",
    "obs.capacity.evalIntervalMs": "min period between USE evaluations",
    "driverHost": "driver RPC host",
    "driverPort": "driver RPC port (0 = ephemeral, written back)",
    "executorPort": "executor listener port (0 = ephemeral)",
    "portMaxRetries": "bind retries above the base port",
    "connectTimeoutMs": "connection establishment timeout",
    "teardownListenTimeoutMs": "listener teardown join timeout",
    "maxConnectionAttempts": "connect attempts per channel",
    "partitionLocationFetchTimeoutMs": "driver location-fetch timeout",
    "resilience.checksums": "crc32c publish/verify per block",
    "resilience.maxFetchAttempts": "total attempts per group READ",
    "resilience.retryBackoffMs": "retry backoff base",
    "resilience.retryBackoffMaxMs": "retry backoff ceiling",
    "resilience.fetchDeadlineMs": "wall budget per group (0 = none)",
    "resilience.circuitFailureThreshold": "failures that open a breaker",
    "resilience.circuitOpenMs": "open-circuit fail-fast window",
    "faultPlan": "fault-injection plan spec (testing/faults.py)",
    "faultPlanSeed": "fault-plan RNG seed",
    "map.parallelism": "bounded map-task pool size",
    "map.pipelineDepth": "map pipeline inter-stage queue bound",
    "map.deviceSort": "sort + range-partition map shards on-device",
    "map.incrementalPublish": "publish sealed writer blocks early",
    "reduce.parallelism": "reduce decode-pool size",
    "reduce.pipelineDepth": "reduce pipeline inter-stage queue bound",
    "reduce.doubleBufferStaging": "overlap staging and device merge",
    "block.format": "block payload encoding: auto|columnar|pickle",
    "block.columnarBatchRows": "records per columnar frame batch",
    "push.enabled": "push-based merge of sealed blocks",
    "push.maxBufferBytes": "merge-endpoint buffered push budget",
    "publish.checksumWorkers": "publish checksum pool size (0 = inline)",
    "planner.enabled": "adaptive reduce-partition planner",
    "planner.hotFactor": "hot-partition isolation threshold",
    "planner.sampleSize": "keys sampled per shard for planning",
    "reader.sortSpillThreshold": "external-sorter in-memory record cap",
    "transport": "host data plane: auto|python|native",
    "fileFastPath": "native same-host READ_FILE fast path",
    "forceSendfile": "serve file regions via sendfile to loopback",
    "fileWorkers": "native same-host file-task workers",
    "mappedFetch": "zero-copy mmap delivery on native transport",
    "native.readBackend": "submission-plane backend: auto|iouring|pread|mapped",
    "native.consumeWorkers": "completion-consume lanes on the native CQ",
    "exchange.bucketMin": "smallest padded exchange bucket",
    "exchange.bucketMax": "largest padded exchange bucket",
    "hbm.slabBytes": "HBM staging slab size",
    "hbm.maxBytes": "HBM shuffle-staging budget",
    "hbm.hostSpillMaxBytes": "host-RAM cap for spilled slabs",
    "hbm.spillDir": "disk-tier spill directory",
    "deviceFetch.enabled": "HBM->HBM device fetch plane",
    "deviceFetch.minBlockBytes": "device-plane minimum block size",
    "collective.enabled": "whole-stage collective shuffle compiler",
    "collective.minBlocks": "device blocks needed to engage the compiler",
    "collective.schedule": "collective schedule: auto|ring|a2a",
    "collective.waveBytes": "max payload bytes per DMA wave",
    "collective.fusedMerge": "allow fetch+merge fusion in one epoch",
    "collective.laneBalance": "planner balances DMA lanes, not just bytes",
    "collective.pipelineDepth": "in-flight DMA waves in the double-buffered pipeline",
    "collective.autoTune": "attribution-driven per-stage waveBytes self-tuning",
    "tenancy.enabled": "multi-tenant serving layer",
    "tenancy.maxConcurrentJobs": "admission in-flight job cap",
    "tenancy.admitTimeoutMs": "admission queue deadline",
    "tenancy.weights": "fair-share weights, e.g. alice:4,bob:1",
    "tenancy.defaultWeight": "weight for unnamed tenants",
    "tenancy.quantumMs": "DRR credit per round (ms per unit weight)",
    "tenancy.mempoolQuotaBytes": "per-tenant mempool byte quota (0 = off)",
    "tenancy.hbmQuotaBytes": "per-tenant HBM byte quota (0 = off)",
    "tenancy.pageCacheQuotaBytes": "per-tenant mapped-fetch byte quota (0 = off)",
    "tenancy.quotaBlockMaxMs": "max quota backpressure stall",
    "elastic.replicas": "map-output replicas pushed to peers (0 = off)",
    "elastic.speculation": "clone straggler tasks onto healthy peers",
    "elastic.speculationCheckMs": "straggler poll period while reducing",
    "elastic.maxRecoveries": "executor-loss recoveries per stage",
    "metastore.peers": "logical metadata peers the registry shards over",
    "metastore.vnodes": "virtual nodes per metadata peer on the hash ring",
    "metastore.rangeSize": "consecutive partitions sharing one shard key",
    "metastore.leaseTtlMs": "shard lease time-to-live",
    "metastore.replicas": "follower copies per metadata shard (0 = off)",
    "metastore.maxWriteAttempts": "epoch-fenced write attempts before failing",
    "metastore.retryBackoffMs": "base backoff between stale-epoch retries",
}

# Knob families with a free segment (``<seg>`` = one dot-free token),
# e.g. per-tenant quota overrides scanned by tenancy/quota.py.
PATTERN_KNOBS = (
    "tenancy.quota.<seg>.mempoolBytes",
    "tenancy.quota.<seg>.hbmBytes",
    "tenancy.quota.<seg>.pageCacheBytes",
    "obs.slo.tenant.<seg>.taskP99Ms",
)


class TpuShuffleConf:
    """Dict-backed configuration with clamped typed getters.

    Construct from any mapping of ``tpu.shuffle.*`` keys. Unknown keys are
    kept (so higher layers can define their own), typed getters clamp to
    [min, max] with silent fallback to the default — reference behavior at
    RdmaShuffleConf.scala:47-58.
    """

    def __init__(self, conf: Optional[Dict[str, object]] = None):
        self._conf: Dict[str, str] = {}
        if conf:
            for k, v in conf.items():
                self._conf[str(k)] = str(v)

    # -- raw access -------------------------------------------------------
    def set(self, key: str, value: object) -> "TpuShuffleConf":
        self._conf[key] = str(value)
        return self

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self._conf.get(key, default)

    def contains(self, key: str) -> bool:
        return key in self._conf

    def to_dict(self) -> Dict[str, str]:
        return dict(self._conf)

    def unknown_keys(self) -> list:
        """``tpu.shuffle.*`` keys present but not declared — the
        runtime complement of the knob-registry lint: surface typo'd
        keys in a live conf instead of silently using defaults."""
        import re

        pats = [
            re.compile(
                "^" + re.escape(p).replace(re.escape("<seg>"), r"[^.]+") + "$"
            )
            for p in PATTERN_KNOBS
        ]
        out = []
        for key in self._conf:
            if not key.startswith(PREFIX):
                continue
            suffix = key[len(PREFIX):]
            if suffix in DECLARED_KNOBS:
                continue
            if any(p.match(suffix) for p in pats):
                continue
            out.append(key)
        return sorted(out)

    # -- clamped typed getters (RdmaShuffleConf.scala:47-58) --------------
    def _int(self, key: str, default: int, lo: int, hi: int) -> int:
        raw = self._conf.get(PREFIX + key)
        if raw is None:
            return default
        try:
            v = int(raw)
        except ValueError:
            return default
        return v if lo <= v <= hi else default

    def _bytes(self, key: str, default: str, lo: int, hi: int) -> int:
        raw = self._conf.get(PREFIX + key, default)
        try:
            v = parse_bytes(raw)
        except ValueError:
            v = parse_bytes(default)
        if not (lo <= v <= hi):
            v = parse_bytes(default)
        return v

    def _float(self, key: str, default: float, lo: float, hi: float) -> float:
        raw = self._conf.get(PREFIX + key)
        if raw is None:
            return default
        try:
            v = float(raw)
        except ValueError:
            return default
        return v if lo <= v <= hi else default

    def _bool(self, key: str, default: bool) -> bool:
        raw = self._conf.get(PREFIX + key)
        if raw is None:
            return default
        return raw.strip().lower() in ("1", "true", "yes", "on")

    # -- transport queue shape (RdmaShuffleConf.scala:72-74) --------------
    @property
    def recv_queue_depth(self) -> int:
        return self._int("recvQueueDepth", 2048, 256, 65535)

    @property
    def send_queue_depth(self) -> int:
        return self._int("sendQueueDepth", 4096, 256, 65535)

    @property
    def recv_wr_size(self) -> int:
        """RPC segment size in bytes (reference default 4 KiB)."""
        return int(self._bytes("recvWrSize", "4k", 2048, 1 << 20))

    # -- worker thread placement (RdmaShuffleConf.scala:79) ---------------
    @property
    def cpu_list(self) -> str:
        return self._conf.get(PREFIX + "cpuList", "")

    # -- writer strategy (RdmaShuffleConf.scala:84-93) --------------------
    @property
    def shuffle_writer_method(self) -> ShuffleWriterMethod:
        raw = self._conf.get(PREFIX + "shuffleWriteMethod", "wrapper")
        try:
            return ShuffleWriterMethod.parse(raw)
        except ValueError:
            return ShuffleWriterMethod.WRAPPER

    @property
    def shuffle_write_chunk_size(self) -> int:
        return self._bytes("shuffleWriteChunkSize", "128k", 4096, 1 << 30)

    @property
    def shuffle_write_flush_size(self) -> int:
        return self._bytes("shuffleWriteFlushSize", "256k", 4096, 1 << 30)

    @property
    def shuffle_write_block_size(self) -> int:
        return self._bytes("shuffleWriteBlockSize", "8m", 65536, 1 << 31)

    @property
    def shuffle_write_max_inmemory_per_executor(self) -> int:
        return self._bytes(
            "shuffleWriteMaxInMemoryStoragePerExecutor", "25g", 0, 1 << 44
        )

    # -- read path (RdmaShuffleConf.scala:99-104) -------------------------
    @property
    def shuffle_read_block_size(self) -> int:
        return self._bytes("shuffleReadBlockSize", "8m", 65536, 1 << 31)

    @property
    def max_bytes_in_flight(self) -> int:
        return self._bytes("maxBytesInFlight", "128m", 65536, 1 << 40)

    @property
    def max_agg_block(self) -> int:
        return self._bytes("maxAggBlock", "2m", 65536, 1 << 31)

    @property
    def max_agg_prealloc(self) -> int:
        return self._int("maxAggPrealloc", 0, 0, 1 << 20)

    # -- reader stats (RdmaShuffleConf.scala:106-113) ---------------------
    @property
    def collect_shuffle_read_stats(self) -> bool:
        return self._bool("collectShuffleReadStats", False)

    @property
    def fetch_time_num_buckets(self) -> int:
        return self._int("fetchTimeNumBuckets", 5, 1, 1000)

    @property
    def fetch_time_bucket_size_ms(self) -> int:
        return self._int("fetchTimeBucketSizeInMs", 300, 1, 1 << 30)

    # -- observability (obs/: metrics registry + span tracer) -------------
    @property
    def trace_enabled(self) -> bool:
        """Record spans in the per-role tracers (obs/trace.py). Metrics
        counters are always on; only span recording is gated."""
        return self._bool("obs.traceEnabled", True)

    @property
    def trace_max_spans(self) -> int:
        """Bound on retained spans per tracer (oldest evicted first)."""
        return self._int("obs.traceMaxSpans", 20000, 100, 1 << 24)

    @property
    def critpath_enabled(self) -> bool:
        """Build the per-job critical-path TimeBreakdown after every
        ``run_job`` (obs/critpath.py / obs/attr.py). Requires span
        recording; a no-op when ``obs.traceEnabled`` is false."""
        return self._bool("obs.critpath.enabled", True)

    # -- cluster telemetry plane (obs/telemetry.py) -----------------------
    @property
    def telemetry_enabled(self) -> bool:
        """Run the executor heartbeat loops + driver TelemetryHub."""
        return self._bool("obs.telemetry.enabled", True)

    @property
    def telemetry_interval_ms(self) -> int:
        """Heartbeat period; also the hub's ring-buffer wall-bucket width."""
        return self._int("obs.telemetry.intervalMs", 1000, 10, 600000)

    @property
    def telemetry_ring_size(self) -> int:
        """Windows retained per executor on the driver (bounded memory)."""
        return self._int("obs.telemetry.ringSize", 128, 8, 65536)

    @property
    def telemetry_http_port(self) -> int:
        """OpenMetrics scrape port on the driver; 0 disables the server."""
        return self._int("obs.telemetry.httpPort", 0, 0, 65535)

    @property
    def telemetry_straggler_z(self) -> float:
        """Robust z-score threshold for the straggler/skew detector."""
        return float(self._int("obs.telemetry.stragglerZ", 3, 1, 1000))

    @property
    def telemetry_flight_windows(self) -> int:
        """Ring windows per executor dumped into a flight record."""
        return self._int("obs.telemetry.flightWindows", 16, 1, 65536)

    @property
    def telemetry_flight_dir(self) -> str:
        """Directory for flight-record JSONs; "" = system temp dir."""
        return str(self.get(PREFIX + "obs.telemetry.flightDir", "") or "")

    @property
    def telemetry_openmetrics_file(self) -> str:
        """If set, the hub rewrites this file with the OpenMetrics
        exposition once per interval (scrape-less egress)."""
        return str(self.get(PREFIX + "obs.telemetry.openmetricsFile", "") or "")

    # -- continuous profiling plane (obs/profiler.py) ---------------------
    @property
    def profile_enabled(self) -> bool:
        """Wall-clock sampling profiler (one timer thread per process)."""
        return self._bool("obs.profile.enabled", True)

    @property
    def profile_hz(self) -> int:
        """Sampling rate. 19 Hz default: high enough to attribute
        ≥100 ms gaps, low enough for the ≤2% overhead gate, and prime
        so it can't phase-lock with periodic workload timers."""
        return self._int("obs.profile.hz", 19, 1, 997)

    @property
    def profile_max_frames(self) -> int:
        """Deepest stack recorded per sample (leaf-most frames kept)."""
        return self._int("obs.profile.maxFrames", 48, 4, 512)

    @property
    def profile_window_ms(self) -> int:
        """Trailing window served to flight records and critical-path
        gap-frame annotation."""
        return self._int("obs.profile.windowMs", 2000, 100, 600000)

    # -- SLO engine + automated diagnosis (obs/slo.py, obs/diagnose.py) ---
    @property
    def slo_enabled(self) -> bool:
        """Evaluate declared objectives on the driver TelemetryHub."""
        return self._bool("obs.slo.enabled", True)

    @property
    def slo_eval_interval_ms(self) -> int:
        """Minimum period between SLO evaluation passes (the engine
        rides the heartbeat ingest path on this cadence)."""
        return self._int("obs.slo.evalIntervalMs", 2000, 100, 600000)

    @property
    def slo_task_p99_ms(self) -> int:
        """p99 task-latency objective target in ms; 0 leaves the
        objective uninstalled (no false pages on unknown workloads)."""
        return self._int("obs.slo.taskP99Ms", 0, 0, 600000)

    @property
    def slo_queue_wait_p99_ms(self) -> int:
        """p99 admission queue-wait objective target in ms; 0 = off."""
        return self._int("obs.slo.queueWaitP99Ms", 0, 0, 600000)

    @property
    def slo_error_ratio(self) -> float:
        """Error budget for the fetch error-ratio objective
        (bad READs / total READs)."""
        return self._float("obs.slo.errorRatio", 0.02, 1e-6, 1.0)

    @property
    def slo_throughput_floor_mbps(self) -> float:
        """Active-window write-throughput floor in MB/s; 0 = off."""
        return self._float("obs.slo.throughputFloorMBps", 0.0, 0.0, 1e9)

    @property
    def slo_fast_windows(self) -> int:
        """Fast-burn (page) horizon in ring windows."""
        return self._int("obs.slo.fastWindows", 8, 1, 65536)

    @property
    def slo_slow_windows(self) -> int:
        """Slow-burn (warn) horizon in ring windows."""
        return self._int("obs.slo.slowWindows", 32, 1, 65536)

    @property
    def slo_fast_burn(self) -> float:
        """Burn-rate multiple of the error budget that pages."""
        return self._float("obs.slo.fastBurn", 8.0, 1.0, 1e6)

    @property
    def slo_slow_burn(self) -> float:
        """Burn-rate multiple of the error budget that warns."""
        return self._float("obs.slo.slowBurn", 2.0, 1.0, 1e6)

    def slo_tenant_task_p99_ms(self, tenant: str) -> int:
        """Per-tenant p99 task-latency target; falls back to the global
        ``obs.slo.taskP99Ms`` (0 = no objective for that tenant)."""
        return self._int(f"obs.slo.tenant.{tenant}.taskP99Ms",
                         self.slo_task_p99_ms, 0, 600000)

    # -- cluster event journal + capacity plane (obs/journal.py,
    #    obs/capacity.py; docs/OBSERVABILITY.md)
    @property
    def journal_enabled(self) -> bool:
        """HLC-ordered cluster event journal; off leaves every
        ``journal.emit`` call site a single None check."""
        return self._bool("obs.journal.enabled", True)

    @property
    def journal_ring_size(self) -> int:
        """Events retained per process journal (hub merge keeps 4x)."""
        return self._int("obs.journal.ringSize", 512, 8, 65536)

    @property
    def journal_flight_events(self) -> int:
        """Merged journal events attached to each flight record."""
        return self._int("obs.journal.flightEvents", 64, 1, 4096)

    @property
    def capacity_enabled(self) -> bool:
        """USE-method capacity accounting on the telemetry hub."""
        return self._bool("obs.capacity.enabled", True)

    @property
    def capacity_eval_interval_ms(self) -> int:
        """Minimum period between hub-side USE evaluations."""
        return self._int("obs.capacity.evalIntervalMs", 2000, 10, 3600000)

    # -- endpoints / connection management (RdmaShuffleConf.scala:118-126)
    @property
    def driver_host(self) -> str:
        return self._conf.get(PREFIX + "driverHost", "127.0.0.1")

    @property
    def driver_port(self) -> int:
        return self._int("driverPort", 0, 0, 65535)

    def set_driver_port(self, port: int) -> None:
        """Write back the negotiated listener port so executors inherit it.

        Reference: the single mutable key, RdmaShuffleConf.scala:67 /
        RdmaShuffleManager.scala:183-184.
        """
        self._conf[PREFIX + "driverPort"] = str(port)

    @property
    def executor_port(self) -> int:
        return self._int("executorPort", 0, 0, 65535)

    @property
    def port_max_retries(self) -> int:
        return self._int("portMaxRetries", 16, 1, 1024)

    @property
    def connect_timeout_ms(self) -> int:
        """CM-event analogue timeout (reference rdmaCmEventTimeout 20s)."""
        return self._int("connectTimeoutMs", 20000, 100, 1 << 30)

    @property
    def teardown_timeout_ms(self) -> int:
        return self._int("teardownListenTimeoutMs", 50, 1, 1 << 30)

    @property
    def max_connection_attempts(self) -> int:
        return self._int("maxConnectionAttempts", 5, 1, 100)

    @property
    def fetch_location_timeout_ms(self) -> int:
        """Timeout for driver location fetches (fetcher iterator wrapper)."""
        return self._int("partitionLocationFetchTimeoutMs", 30000, 100, 1 << 30)

    # -- resilience (retry / checksums / circuit breaker; docs/RESILIENCE.md)
    @property
    def resilience_checksums(self) -> bool:
        """Compute per-block crc32c at publish time and validate on
        fetch (utils/checksum.py). Mismatch = retryable fault."""
        return self._bool("resilience.checksums", True)

    @property
    def max_fetch_attempts(self) -> int:
        """Total attempts per group READ before FetchFailedError:
        initial, same-source retry, re-resolve failover, split."""
        return self._int("resilience.maxFetchAttempts", 4, 1, 100)

    @property
    def retry_backoff_ms(self) -> int:
        """Base of the exponential retry backoff (deterministic jitter)."""
        return self._int("resilience.retryBackoffMs", 50, 1, 1 << 20)

    @property
    def retry_backoff_max_ms(self) -> int:
        return self._int("resilience.retryBackoffMaxMs", 2000, 1, 1 << 24)

    @property
    def fetch_deadline_ms(self) -> int:
        """Wall budget per group across ALL its retries; 0 = unbounded."""
        return self._int("resilience.fetchDeadlineMs", 0, 0, 1 << 30)

    @property
    def circuit_failure_threshold(self) -> int:
        """Consecutive failures that open a peer's circuit breaker."""
        return self._int("resilience.circuitFailureThreshold", 3, 1, 1 << 16)

    @property
    def circuit_open_ms(self) -> int:
        """How long an open circuit fails fast before a half-open probe."""
        return self._int("resilience.circuitOpenMs", 5000, 1, 1 << 30)

    # -- fault injection (testing/faults.py) ------------------------------
    @property
    def fault_plan(self) -> str:
        """Fault-plan spec installed at manager init (empty = none);
        grammar in testing/faults.py. Chaos runs set this plus
        ``faultPlanSeed`` so failures reproduce exactly."""
        return str(self.get(PREFIX + "faultPlan", "") or "")

    @property
    def fault_plan_seed(self) -> int:
        return self._int("faultPlanSeed", 0, 0, 1 << 31)

    # -- map plane (pipelined device-accelerated producer; DESIGN.md) -----
    @property
    def map_parallelism(self) -> int:
        """Bounded map-task pool size per executor process. Map tasks
        dispatch through this pool instead of a sequential loop, so one
        executor overlaps several shards' sort/stage/publish stages."""
        return self._int("map.parallelism", 2, 1, 64)

    @property
    def map_pipeline_depth(self) -> int:
        """Bound on items queued between pipeline stages (sort ->
        stage-into-registered -> publish). Depth 1 still overlaps
        adjacent stages; deeper queues absorb stage-time jitter at the
        cost of holding more shards' staging memory live."""
        return self._int("map.pipelineDepth", 2, 1, 64)

    @property
    def map_device_sort(self) -> bool:
        """Sort + range-partition map shards ON-DEVICE (MapShardSorter:
        device_sort + searchsorted against the reducer edges) instead of
        the host O(N log N) np.sort the map plane was losing on."""
        return self._bool("map.deviceSort", True)

    @property
    def map_incremental_publish(self) -> bool:
        """Chunked-agg incremental publish: sealed (non-tail, immutable)
        writer blocks publish their locations as map tasks commit, so
        location upload overlaps remaining map compute; the map-barrier
        count still rides ONLY the final publish (num_map_outputs=0 on
        incremental segments), so the driver never answers fetches from
        a partial location set."""
        return self._bool("map.incrementalPublish", False)

    # -- reduce plane (pipelined consume; DESIGN.md §16) ------------------
    @property
    def reduce_parallelism(self) -> int:
        """Decode-pool size of the reduce pipeline: workers doing
        checksum verify + decompress + deserialize off the fetch
        thread. 1 degenerates to the serial decode order exactly (the
        sequencer preserves delivery order at ANY parallelism)."""
        return self._int("reduce.parallelism", 2, 1, 64)

    @property
    def reduce_pipeline_depth(self) -> int:
        """Bound on items queued between reduce-pipeline stages (fetch
        -> decode pool -> stage -> merge/deliver). Depth 1 still
        overlaps adjacent stages; deeper queues absorb jitter at the
        cost of holding more fetched groups' memory live."""
        return self._int("reduce.pipelineDepth", 2, 1, 64)

    @property
    def reduce_double_buffer_staging(self) -> bool:
        """Run host->HBM staging and device merge on separate pipeline
        threads so the tunnel transfer of group k+1 rides under the
        merge of group k (double-buffered staging). Off serializes
        stage and merge on one thread."""
        return self._bool("reduce.doubleBufferStaging", True)

    # -- block payload format (shuffle/columnar.py; DESIGN.md §25) --------
    @property
    def block_format(self) -> str:
        """Per-shuffle block payload encoding negotiation: ``pickle``
        is the legacy frame stream (the universal fallback),
        ``columnar`` batches fixed-width numpy tuples into zero-copy
        column-vector frames (per-batch pickle fallback for anything
        the layout cannot carry), ``auto`` sniffs the first record and
        picks. Unknown values fall back to ``auto``."""
        raw = (self._conf.get(PREFIX + "block.format") or "auto").strip().lower()
        return raw if raw in ("auto", "columnar", "pickle") else "auto"

    @property
    def block_columnar_batch_rows(self) -> int:
        """Records accumulated per columnar frame batch: larger batches
        amortize the header and widen the column vectors the collective
        waves DMA; smaller batches bound the writer's batching memory."""
        return self._int("block.columnarBatchRows", 4096, 16, 1 << 22)

    # -- push-based merge plane (shuffle/merge.py; DESIGN.md §18) ---------
    @property
    def push_enabled(self) -> bool:
        """Push sealed chunked-agg writer blocks toward their reducer's
        executor as maps commit; complete pid coverage seals into ONE
        merged segment the reduce path prefers over N per-map fetches.
        Best-effort everywhere: a dropped/late/over-budget push just
        leaves the original per-map locations authoritative."""
        return self._bool("push.enabled", True)

    @property
    def push_max_buffer_bytes(self) -> int:
        """Per-executor budget for buffered pushed-but-unsealed block
        payloads in its MergeEndpoint. A push that would exceed it is
        dropped (its partition falls back to original locations)."""
        return self._bytes("push.maxBufferBytes", "256m", 1 << 16, 1 << 40)

    @property
    def publish_checksum_workers(self) -> int:
        """Shard ``publish_partition_locations``' checksum/validation
        work across a small pool when a publish carries at least
        2x this many locations; 0 computes inline on the publishing
        thread (the pre-PR-7 behavior)."""
        return self._int("publish.checksumWorkers", 4, 0, 32)

    # -- adaptive partition planner (shuffle/planner.py) ------------------
    @property
    def planner_enabled(self) -> bool:
        """Re-plan reduce partition ranges from the map stage's
        per-partition byte statistics before reduce launch: hot
        partitions are isolated (splits), tiny neighbors coalesced —
        contiguous-range rule, so ordering workloads stay correct."""
        return self._bool("planner.enabled", True)

    @property
    def planner_hot_factor(self) -> float:
        """A partition is *hot* (isolated into its own reduce range)
        when its bytes exceed this multiple of the mean reducer load."""
        raw = self._conf.get(PREFIX + "planner.hotFactor")
        try:
            v = float(raw) if raw is not None else 1.5
        except ValueError:
            v = 1.5
        return v if 1.0 <= v <= 100.0 else 1.5

    @property
    def planner_sample_size(self) -> int:
        """Keys sampled per shard for the device planner's quantile
        edges (models/terasort.py adaptive sort)."""
        return self._int("planner.sampleSize", 4096, 64, 1 << 24)

    # -- reduce-side ordering ---------------------------------------------
    @property
    def sort_spill_threshold(self) -> int:
        """Records held in memory before the reader's external sorter
        spills a sorted run to scratch (the ExternalSorter role)."""
        return self._int("reader.sortSpillThreshold", 1 << 20, 1024, 1 << 31)

    # -- transport selection ----------------------------------------------
    @property
    def transport(self) -> str:
        """Host transport data plane: ``auto`` (default), ``python`` or
        ``native`` (C++ epoll loop, sparkrdma_tpu/native/transport.cpp).
        Both speak the same wire format and interoperate. ``auto``
        resolves to native when the toolchain is available — that is the
        only transport with mapped (zero-copy page-cache) delivery, the
        measured-fastest consume path — and python otherwise; setting
        ``transport=python`` is the escape hatch back to the pure-Python
        plane."""
        raw = (self._conf.get(PREFIX + "transport", "auto") or "auto").lower()
        if raw not in ("python", "native", "auto"):
            raw = "auto"
        if raw == "auto":
            from sparkrdma_tpu.native import transport_lib

            return "native" if transport_lib.available() else "python"
        return raw

    @property
    def file_fastpath(self) -> bool:
        """Allow the native client's same-host READ_FILE fast path for
        plain (buffer-destination) READs. Off forces every such READ
        through the streamed socket path — the bench's remote-path
        simulation knob. Mapped READs always probe the file path."""
        return self._bool("fileFastPath", True)

    @property
    def force_sendfile(self) -> bool:
        """Server-side: serve file-backed regions via sendfile even to
        loopback peers. Normally loopback keeps the userspace send
        (measured faster without a DMA NIC); tests and benches of the
        sendfile mechanism itself enable this."""
        return self._bool("forceSendfile", False)

    @property
    def file_workers(self) -> int:
        """Same-host file-task worker threads in the native plane.
        Concurrent read groups overlap their page-cache copies — the
        analogue of the reference striping WR lists over multiple QPs
        (RdmaChannel.java:54-56). Default 2: measured on the bench rig,
        2 workers move ~1.5x one worker even at nproc=1 (kernel-side
        parallelism); more shows no further gain there."""
        return self._int("fileWorkers", 2, 1, 16)

    @property
    def mapped_fetch(self) -> bool:
        """Use mapped delivery (zero-copy page-cache mmap on same-host
        peers) for device-block fetches on the native transport. The
        streamed fallback still lands in one malloc'd blob, so this is
        never slower than the buffer path; off restores pooled
        registered destination buffers."""
        return self._bool("mappedFetch", True)

    @property
    def native_read_backend(self) -> str:
        """Submission-plane backend for same-host file reads in the
        native transport (DESIGN.md §24). ``auto`` probes io_uring at
        runtime and falls back to pread; ``iouring`` requests it
        explicitly (still degrades cleanly on ENOSYS/old kernels);
        ``pread`` is the preadv2-scatter path; ``mapped`` copies
        through mmap+MAP_POPULATE windows. Every backend produces
        byte-identical results."""
        raw = (
            self._conf.get(PREFIX + "native.readBackend", "auto") or "auto"
        ).lower()
        if raw not in ("auto", "iouring", "pread", "mapped"):
            raw = "auto"
        return raw

    @property
    def native_consume_workers(self) -> int:
        """Consume lanes draining the native completion queue: checksum
        verify + decode run in parallel per source-ordered lane
        (completions are routed by channel, so per-source order is
        preserved and the reduce pipeline's sequencer keeps delivery
        byte-identical). Default min(cores-1, 4), floor 1 — a 1-core
        rig degenerates to the old inline consume."""
        cores = os.cpu_count() or 1
        return self._int(
            "native.consumeWorkers", min(max(cores - 1, 1), 4), 1, 16
        )

    # -- TPU device exchange plane (new; no reference analogue) -----------
    @property
    def exchange_bucket_min(self) -> int:
        """Smallest padded block bucket for the static-shape exchange program."""
        return self._bytes("exchange.bucketMin", "64k", 1024, 1 << 31)

    @property
    def exchange_bucket_max(self) -> int:
        return self._bytes("exchange.bucketMax", "8m", 1024, 1 << 33)

    @property
    def hbm_slab_bytes(self) -> int:
        """Size of each HBM staging slab owned by the device buffer manager."""
        return self._bytes("hbm.slabBytes", "64m", 1 << 16, 1 << 33)

    @property
    def hbm_max_bytes(self) -> int:
        """HBM budget for shuffle staging (analogue of the 25g host budget)."""
        return self._bytes("hbm.maxBytes", "2g", 0, 1 << 40)

    @property
    def hbm_host_spill_max_bytes(self) -> int:
        """Host-RAM cap for slabs spilled out of HBM; overflow cascades
        to disk (tier 3 of SURVEY §7.3(4)). 0 = unbounded host tier."""
        return self._bytes("hbm.hostSpillMaxBytes", "0", 0, 1 << 44)

    @property
    def device_fetch_enabled(self) -> bool:
        """Device fetch plane (shuffle/device_fetch.py): publish HBM
        arena coordinates next to the host triple and let reduce tasks
        pull arena-resident blocks HBM->HBM (Pallas remote copy on TPU
        meshes, ``jax.device_put`` emulation elsewhere) instead of
        through host sockets. The host path always remains the
        fallback; disabling only suppresses device locations and
        planner pulls."""
        return self._bool("deviceFetch.enabled", True)

    @property
    def device_fetch_min_block_bytes(self) -> int:
        """Blocks smaller than this skip the device plane: per-pull
        dispatch overhead beats the HBM bandwidth win on tiny blocks,
        and small blocks churn arena slabs (min slab class 16 KiB)."""
        return self._bytes("deviceFetch.minBlockBytes", "16k", 0, 1 << 33)

    @property
    def collective_enabled(self) -> bool:
        """Whole-stage collective shuffle (shuffle/collective.py):
        compile a reduce stage's device-resident location set into
        batched DMA waves instead of per-block planner pulls. Device
        blocks the compiler cannot place (too few, wrong dtype, evicted
        mid-stage) silently degrade to the per-block planner or the
        host triple — results are byte-identical either way."""
        return self._bool("collective.enabled", True)

    @property
    def collective_min_blocks(self) -> int:
        """Device-resident blocks a stage must publish before the
        compiler engages; below this the per-block planner wins (a
        one-block "wave" is pure dispatch overhead)."""
        return self._int("collective.minBlocks", 2, 1, 1 << 20)

    @property
    def collective_schedule(self) -> str:
        """Wave schedule: ``ring`` orders waves lane-major around the
        source ring (one lane in flight — the flow-controlled
        schedule), ``a2a`` interleaves lanes round-robin (dense
        all-to-all), ``auto`` picks a2a when the stage spans more than
        two source lanes."""
        raw = (self.get(PREFIX + "collective.schedule", "auto") or "auto").lower()
        return raw if raw in ("auto", "ring", "a2a") else "auto"

    @property
    def collective_wave_bytes(self) -> int:
        """Payload cap per DMA wave — the device plane's
        maxBytesInFlight analogue: bounds the stacked landing buffer
        and keeps one slow wave from serializing the whole stage."""
        return self._bytes("collective.waveBytes", "64m", 1 << 16, 1 << 33)

    @property
    def collective_fused_merge(self) -> bool:
        """Allow fetch->merge fusion: a partition whose every block
        arrives in one wave lands as ONE merged slab (concatenated in
        deterministic source order) with no intermediate HBM round
        trip. Fusion changes the *shape* of the result (one buffer per
        partition instead of per block), so callers opt in per fetch;
        this knob is the global off-switch."""
        return self._bool("collective.fusedMerge", True)

    @property
    def collective_lane_balance(self) -> bool:
        """Adaptive planner balances per-lane (source executor) DMA
        bytes, not just totals: a partition concentrated in one lane
        costs a longer DMA epoch than the same bytes spread across
        lanes, so reduce-range cuts weigh the max lane load."""
        return self._bool("collective.laneBalance", True)

    @property
    def collective_pipeline_depth(self) -> int:
        """Waves the schedule compiler keeps in flight at once: wave
        N+1's remote DMAs are dispatched while wave N still merges
        (one DMA-semaphore array per in-flight wave). ``1`` disables
        pipelining (issue, wait, adopt, repeat — the pre-pipeline
        behavior); every depth is byte-identical, only the overlap
        changes."""
        return self._int("collective.pipelineDepth", 2, 1, 8)

    @property
    def collective_auto_tune(self) -> bool:
        """Let the compiler's wave controller re-derive the effective
        ``collective.waveBytes`` per (shuffle, stage-shape) from its
        own wave stats plus the job's TimeBreakdown / profiler gap
        frames (shuffle/autotune.py): a stage that ran as one monolithic
        wave is re-cut so the pipeline has waves to overlap, a
        dispatch-bound stage coarsens. The tuned choice is remembered,
        so the second identical stage of a job already runs tuned.
        Never shrinks a wave below the stage's largest partition group
        (fusion needs a partition's rows in ONE wave)."""
        return self._bool("collective.autoTune", True)

    @property
    def hbm_spill_dir(self) -> str:
        """Directory for the disk tier's spill files. Default ("") uses
        the system temp dir — NOTE: on hosts where /tmp is tmpfs that
        is still RAM; point this at real storage when using
        hbm.hostSpillMaxBytes to protect host memory."""
        return str(self.get(PREFIX + "hbm.spillDir", "") or "")

    # -- tenancy (multi-tenant serving; sparkrdma_tpu/tenancy) ------------
    @property
    def tenancy_enabled(self) -> bool:
        """Serve concurrent jobs through the tenancy layer: admission
        control on the driver, deficit-round-robin fair-share dispatch
        on the bounded map/reduce pools, and (when quotas are set)
        per-tenant byte backpressure. With a single (default) tenant
        every mechanism degenerates to the pre-tenancy behavior, so
        this is safe to leave on."""
        return self._bool("tenancy.enabled", True)

    @property
    def tenancy_max_concurrent_jobs(self) -> int:
        """Jobs admitted in-flight before new ones queue (FIFO)."""
        return self._int("tenancy.maxConcurrentJobs", 8, 1, 4096)

    @property
    def tenancy_admit_timeout_ms(self) -> int:
        """Queue-with-deadline: a job still queued after this raises
        AdmissionTimeout instead of camping on the admission queue."""
        return self._int("tenancy.admitTimeoutMs", 30000, 1, 1 << 31)

    @property
    def tenancy_weights(self) -> Dict[str, int]:
        """Fair-share weights, e.g. ``"alice:4,bob:1"``. Tenants not
        named get ``tenancy.defaultWeight``."""
        from sparkrdma_tpu.tenancy import parse_weights

        return parse_weights(str(self.get(PREFIX + "tenancy.weights", "") or ""))

    @property
    def tenancy_default_weight(self) -> int:
        return self._int("tenancy.defaultWeight", 1, 1, 1000)

    @property
    def tenancy_quantum_ms(self) -> int:
        """DRR credit per round in milliseconds of task runtime (per
        unit weight). Smaller = finer cross-tenant interleave."""
        return self._int("tenancy.quantumMs", 20, 1, 60000)

    @property
    def tenancy_mempool_quota_bytes(self) -> int:
        """Per-tenant byte quota on held mempool buffers (0 = off).
        Per-tenant overrides: ``tenancy.quota.<tenant>.mempoolBytes``."""
        return self._bytes("tenancy.mempoolQuotaBytes", "0", 0, 1 << 44)

    @property
    def tenancy_hbm_quota_bytes(self) -> int:
        """Per-tenant byte quota on held HBM-arena capacity (0 = off).
        Per-tenant overrides: ``tenancy.quota.<tenant>.hbmBytes``."""
        return self._bytes("tenancy.hbmQuotaBytes", "0", 0, 1 << 44)

    @property
    def tenancy_pagecache_quota_bytes(self) -> int:
        """Per-tenant byte quota on in-flight zero-copy mapped fetches
        (0 = off). Mapped delivery bypasses the mempool, so without
        this a mapped-heavy tenant's page-cache footprint is invisible
        to the other quotas. Per-tenant overrides:
        ``tenancy.quota.<tenant>.pageCacheBytes``."""
        return self._bytes("tenancy.pageCacheQuotaBytes", "0", 0, 1 << 44)

    @property
    def tenancy_quota_block_max_ms(self) -> int:
        """Upper bound on one quota backpressure stall; past it the
        charge is admitted anyway (tenant.quota_overruns) — the quota
        is backpressure, never a wedge."""
        return self._int("tenancy.quotaBlockMaxMs", 60000, 1, 1 << 31)

    # -- elastic (executor loss, speculation; sparkrdma_tpu/elastic) ------
    @property
    def elastic_replicas(self) -> int:
        """Best-effort copies of each committed map output pushed to
        this many ring peers (elastic/replication.py). 0 disables the
        replication plane; with it on, losing an executor costs zero
        recompute for every map a replica covers."""
        return self._int("elastic.replicas", 0, 0, 16)

    @property
    def elastic_speculation(self) -> bool:
        """Clone in-flight reduce ranges of a telemetry-flagged
        straggler onto a healthy peer; first finisher wins, the loser
        drains through the reader abort latch."""
        return self._bool("elastic.speculation", False)

    @property
    def elastic_speculation_check_ms(self) -> int:
        """How often the cluster driver polls straggler verdicts while
        reduce tasks are in flight."""
        return self._int("elastic.speculationCheckMs", 200, 10, 1 << 31)

    @property
    def elastic_max_recoveries(self) -> int:
        """Executor-loss recovery rounds per stage before the job
        fails. Each round re-runs only the dead executor's unaccounted
        maps on survivors and re-issues its reduce ranges."""
        return self._int("elastic.maxRecoveries", 2, 0, 64)

    # -- metastore (control-plane HA; sparkrdma_tpu/metastore) ------------
    @property
    def metastore_peers(self) -> int:
        """Logical metadata peers the locations registry shards over
        (metastore/shardmap.py). Each peer serves its shards under a
        lease; killing one remaps only its ranges."""
        return self._int("metastore.peers", 4, 1, 64)

    @property
    def metastore_vnodes(self) -> int:
        """Virtual nodes per peer on the consistent-hash ring; more
        vnodes, smoother spread and smaller movement per kill."""
        return self._int("metastore.vnodes", 16, 1, 256)

    @property
    def metastore_range_size(self) -> int:
        """Consecutive partitions sharing one shard key, so a reduce
        task's ``[start, end)`` resolve touches few shards."""
        return self._int("metastore.rangeSize", 8, 1, 4096)

    @property
    def metastore_lease_ttl_ms(self) -> int:
        """Shard lease time-to-live. A lapsed lease takes over under a
        bumped epoch; writes routed under the old one are fenced."""
        return self._int("metastore.leaseTtlMs", 5000, 10, 1 << 31)

    @property
    def metastore_replicas(self) -> int:
        """Follower copies per metadata shard. Writes apply to primary
        + followers; reads serve the primary only. At >= 1 a metadata
        peer's death costs zero metadata loss."""
        return self._int("metastore.replicas", 1, 0, 4)

    @property
    def metastore_max_write_attempts(self) -> int:
        """Stale-epoch publish/resolve attempts (re-route + retry
        through the PR 2 ladder) before surfacing the error."""
        return self._int("metastore.maxWriteAttempts", 4, 1, 64)

    @property
    def metastore_retry_backoff_ms(self) -> int:
        """Base backoff between stale-epoch retries (jittered,
        exponential, capped at 8x)."""
        return self._int("metastore.retryBackoffMs", 2, 1, 1 << 31)
