from sparkrdma_tpu.utils.units import parse_bytes, format_bytes
from sparkrdma_tpu.utils.config import TpuShuffleConf, ShuffleWriterMethod

__all__ = ["parse_bytes", "format_bytes", "TpuShuffleConf", "ShuffleWriterMethod"]
