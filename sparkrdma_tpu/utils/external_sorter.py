"""ExternalSorter — spillable total ordering for the reduce path.

The reference's reader delegates key ordering to Spark's
ExternalSorter, which spills sorted runs to disk under memory pressure
and merge-reads them (RdmaShuffleReader.scala:99-112, spill metrics
:106-108). This is that component for the TPU framework's host engine:
records accumulate in memory up to a threshold, overflow as sorted
pickled runs in scratch files, and the final iterator is a lazy
heap-merge of every run.
"""

from __future__ import annotations

import heapq
import os
import pickle
import tempfile
from typing import Callable, Iterable, Iterator, List, Optional


def _default_key(record):
    return record[0]


class ExternalSorter:
    """Sort arbitrarily many records with bounded memory."""

    def __init__(
        self,
        key: Optional[Callable] = None,
        spill_threshold: int = 1 << 20,
        tmp_dir: Optional[str] = None,
    ):
        self._key = key or _default_key
        self._threshold = max(1, spill_threshold)
        self._tmp_dir = tmp_dir
        self._spill_paths: List[str] = []
        self.spill_count = 0
        self.spilled_records = 0

    # ------------------------------------------------------------------
    def _spill_run(self, run: List) -> None:
        run.sort(key=self._key)
        fd, path = tempfile.mkstemp(prefix="srt_sort_", dir=self._tmp_dir)
        with os.fdopen(fd, "wb") as f:
            for rec in run:
                pickle.dump(rec, f, protocol=pickle.HIGHEST_PROTOCOL)
        self._spill_paths.append(path)
        self.spill_count += 1
        self.spilled_records += len(run)

    def _read_run(self, path: str) -> Iterator:
        try:
            with open(path, "rb") as f:
                while True:
                    try:
                        yield pickle.load(f)
                    except EOFError:
                        break
        finally:
            try:
                os.unlink(path)
            except OSError:
                pass

    # ------------------------------------------------------------------
    def sort(self, records: Iterable) -> Iterator:
        """Consume ``records``; yield them in key order (lazy merge)."""
        run: List = []
        for rec in records:
            run.append(rec)
            if len(run) >= self._threshold:
                self._spill_run(run)
                run = []
        if not self._spill_paths:
            run.sort(key=self._key)
            return iter(run)
        run.sort(key=self._key)
        streams = [self._read_run(p) for p in self._spill_paths]
        streams.append(iter(run))
        self._spill_paths = []
        return heapq.merge(*streams, key=self._key)
