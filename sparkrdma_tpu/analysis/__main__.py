"""``python -m sparkrdma_tpu.analysis`` — run the invariant passes.

Exit status 0 when the tree is clean, 1 when any pass reports an
unsuppressed finding. This is the entry point the CI ``analysis`` job
gates on; docs/ANALYSIS.md documents each pass and the suppression
syntax.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from sparkrdma_tpu.analysis import PASS_IDS, load_tree, repo_root, run_passes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m sparkrdma_tpu.analysis",
        description="project invariant lint (see docs/ANALYSIS.md)",
    )
    ap.add_argument(
        "--root", type=Path, default=None,
        help="checkout root (default: auto-detected from the package)",
    )
    ap.add_argument(
        "--pass", dest="passes", action="append", choices=sorted(PASS_IDS),
        help="run only this pass (repeatable; default: all)",
    )
    ap.add_argument(
        "--dump-metrics", action="store_true",
        help="print observed (name, kind, labelsets) tuples and exit",
    )
    ap.add_argument(
        "--list", action="store_true", help="list pass ids and exit",
    )
    ap.add_argument(
        "--audit-ignores", action="store_true",
        help="list every '# analysis: ignore' suppression with its "
        "reason and exit (malformed suppressions still fail the run)",
    )
    args = ap.parse_args(argv)

    if args.list:
        for pid, desc in sorted(PASS_IDS.items()):
            print(f"{pid:16s} {desc}")
        return 0

    root = args.root or repo_root()
    files = load_tree(root)
    if args.audit_ignores:
        total = 0
        for sf in files:
            for line, ids, reason in sf.suppression_records:
                total += 1
                print(
                    f"{sf.path}:{line}: "
                    f"ignore[{','.join(sorted(ids))}] — {reason}"
                )
        bad = [f for sf in files for f in sf.bad_suppressions]
        for f in bad:
            print(f.render())
        print(
            f"\naudit: {total} suppression(s), {len(bad)} malformed",
            file=sys.stderr,
        )
        return 1 if bad else 0
    if args.dump_metrics:
        from sparkrdma_tpu.analysis import metrics_pass

        for row in metrics_pass.dump(files):
            print(row)
        return 0

    findings = run_passes(files, root, only=args.passes)
    for f in findings:
        print(f.render())
    n_files = len(files)
    if findings:
        print(
            f"\nanalysis: {len(findings)} finding(s) across {n_files} files",
            file=sys.stderr,
        )
        return 1
    print(f"analysis: clean ({n_files} files, {len(PASS_IDS)} passes)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
