"""knob-registry pass — every ``tpu.shuffle.*`` read must be declared.

Candidates are collected from three shapes:

- plain string literals starting with the prefix (tests, benches,
  example configs),
- ``PREFIX + "suffix"`` concatenations (the idiom inside
  ``utils/config.py`` raw reads and the quota per-tenant scan),
- the first argument of ``self._int/_float/_bytes/_bool`` calls inside
  ``utils/config.py`` (the clamped typed getters take bare suffixes).

Each candidate must resolve against ``DECLARED_KNOBS`` /
``PATTERN_KNOBS`` in :mod:`sparkrdma_tpu.utils.config`: exactly, via a
pattern (``<seg>`` matches one dot-free segment), or — when the
candidate ends with ``.`` — as a namespace scan prefix of at least one
declared knob. The inverse is checked too: a declared knob that no
file references is dead weight and is reported.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterable, List, Tuple

from sparkrdma_tpu.analysis import Finding, SourceFile

PASS_ID = "knob-registry"

_GETTERS = {"_int", "_float", "_bytes", "_bool"}


def _pattern_regexes() -> List[re.Pattern]:
    from sparkrdma_tpu.utils.config import PATTERN_KNOBS

    out = []
    for pat in PATTERN_KNOBS:
        out.append(
            re.compile(
                "^"
                + re.escape(pat).replace(re.escape("<seg>"), r"[^.]+")
                + "$"
            )
        )
    return out


def _collect(sf: SourceFile, prefix: str) -> List[Tuple[int, str]]:
    """(line, full-key-or-suffix-candidate) pairs found in one file."""
    found: List[Tuple[int, str]] = []
    in_config = sf.path.endswith("utils/config.py")
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if node.value.startswith(prefix):
                found.append((node.lineno, node.value))
        elif (
            isinstance(node, ast.BinOp)
            and isinstance(node.op, ast.Add)
            and isinstance(node.left, ast.Name)
            and node.left.id == "PREFIX"
            and isinstance(node.right, ast.Constant)
            and isinstance(node.right.value, str)
        ):
            found.append((node.lineno, prefix + node.right.value))
        elif (
            in_config
            and isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _GETTERS
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            found.append((node.lineno, prefix + node.args[0].value))
    return found


def run(files: Iterable[SourceFile], root: Path) -> List[Finding]:
    from sparkrdma_tpu.utils.config import DECLARED_KNOBS, PATTERN_KNOBS, PREFIX

    patterns = _pattern_regexes()
    declared = set(DECLARED_KNOBS)
    referenced: Dict[str, bool] = {k: False for k in declared}
    findings: List[Finding] = []

    def resolve(suffix: str) -> bool:
        if suffix in declared:
            referenced[suffix] = True
            return True
        if suffix.endswith("."):  # namespace scan (e.g. quota override scan)
            hits = [k for k in declared if k.startswith(suffix)]
            for k in hits:
                referenced[k] = True
            return bool(hits) or any(
                pat.startswith(suffix) for pat in PATTERN_KNOBS
            )
        return any(p.match(suffix) for p in patterns)

    key_shape = re.compile(r"^[\w.]*$")
    for sf in files:
        for line, key in _collect(sf, PREFIX):
            suffix = key[len(PREFIX):]
            if not suffix:
                continue  # the PREFIX constant itself
            if not key_shape.match(suffix):
                continue  # prose mentioning the prefix, not a key
            if not resolve(suffix):
                findings.append(
                    Finding(
                        PASS_ID,
                        sf.path,
                        line,
                        f"knob {key!r} is not in DECLARED_KNOBS "
                        "(utils/config.py) — declare it or fix the typo",
                    )
                )

    config_path = next(
        (f.path for f in files if f.path.endswith("utils/config.py")), None
    )
    if config_path is not None:
        for k, seen in sorted(referenced.items()):
            if not seen:
                findings.append(
                    Finding(
                        PASS_ID,
                        config_path,
                        1,
                        f"declared knob {PREFIX + k!r} is never read "
                        "anywhere in the tree",
                    )
                )
    return findings
