"""Invariant analysis suite — project-specific static lint passes.

The framework's correctness rests on a handful of cross-file
invariants that ordinary linters cannot see: every ``tpu.shuffle.*``
knob read must resolve against the declared-knobs table in
``utils/config.py``; every metrics-registry instrument must belong to
a declared family with a consistent label set and an OBSERVABILITY.md
anchor; the wire-extension markers (0xFFFF/0xFFFE/0xFFFD/0xFFFC) and
their struct formats must agree between encoder and parser, with every
marker dispatched from the parser's single peek loop so extensions and
the trace trailer parse in ANY order; and thread
spawns on tenancy-sensitive paths must re-enter ``tenant_scope``.
This package encodes each invariant as an AST pass over the tree and
exposes them behind ``python -m sparkrdma_tpu.analysis`` (gated in
CI) plus a runtime lock-order detector (:mod:`.lockorder`) that tier-1
can run under.

Suppression: a finding is silenced by an inline comment on the same
line (or the line immediately above) of the form::

    # analysis: ignore[<pass-id>]: <reason>

The reason is mandatory — a bare ``ignore[...]`` is itself reported.
``ignore[all]`` silences every pass for that line. See
docs/ANALYSIS.md for the catalogue of passes.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding",
    "SourceFile",
    "PASS_IDS",
    "load_tree",
    "repo_root",
    "run_passes",
]

#: pass-id -> one-line description; the runner modules live next door.
PASS_IDS = {
    "knob-registry": "tpu.shuffle.* reads resolve against DECLARED_KNOBS",
    "metric-families": "registry instruments match a declared metric family",
    "wire-markers": "wire-extension markers/structs agree encoder vs parser",
    "tenant-scope": "thread spawns on tenancy paths re-enter tenant_scope",
}

_SUPPRESS_RE = re.compile(
    r"#\s*analysis:\s*ignore\[([a-z\-,\s]+)\](?::\s*(\S.*))?"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One invariant violation at a source location."""

    pass_id: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_id}] {self.message}"


class SourceFile:
    """A parsed Python source file plus its suppression table."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        # line -> set of suppressed pass ids ("all" suppresses any)
        self.suppressions: Dict[int, Set[str]] = {}
        #: well-formed suppressions as (line, pass_ids, reason) — the
        #: ``--audit-ignores`` inventory
        self.suppression_records: List[Tuple[int, Set[str], str]] = []
        #: malformed suppressions (missing reason) found while parsing
        self.bad_suppressions: List[Finding] = []
        self._scan_suppressions()

    def _scan_suppressions(self) -> None:
        for i, text in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            ids = {p.strip() for p in m.group(1).split(",") if p.strip()}
            if not m.group(2):
                self.bad_suppressions.append(
                    Finding(
                        "suppression",
                        self.path,
                        i,
                        "analysis: ignore[...] requires a ': <reason>'",
                    )
                )
                continue
            unknown = ids - set(PASS_IDS) - {"all"}
            if unknown:
                self.bad_suppressions.append(
                    Finding(
                        "suppression",
                        self.path,
                        i,
                        f"unknown pass id(s) in suppression: {sorted(unknown)}",
                    )
                )
                ids -= unknown
            if ids:
                self.suppression_records.append((i, set(ids), m.group(2)))
            # a comment-only line suppresses the NEXT line too
            target_lines = [i]
            if text.lstrip().startswith("#"):
                target_lines.append(i + 1)
            for ln in target_lines:
                self.suppressions.setdefault(ln, set()).update(ids)

    def suppressed(self, pass_id: str, line: int) -> bool:
        ids = self.suppressions.get(line)
        return bool(ids) and (pass_id in ids or "all" in ids)


def repo_root() -> Path:
    """The checkout root (parent of the ``sparkrdma_tpu`` package)."""
    return Path(__file__).resolve().parents[2]


_SKIP_PARTS = {"__pycache__", ".git", "build", "dist"}


def load_tree(
    root: Optional[Path] = None,
    subdirs: Sequence[str] = ("sparkrdma_tpu", "tests", "bench"),
) -> List[SourceFile]:
    """Parse every analysable .py file under ``root``'s code subdirs."""
    root = root or repo_root()
    files: List[SourceFile] = []
    for sub in subdirs:
        base = root / sub
        if not base.is_dir():
            continue
        for p in sorted(base.rglob("*.py")):
            if _SKIP_PARTS.intersection(p.parts):
                continue
            rel = p.relative_to(root).as_posix()
            try:
                files.append(SourceFile(rel, p.read_text()))
            except SyntaxError as e:
                # a file that does not parse fails the whole run loudly
                raise SyntaxError(f"{rel}: {e}") from e
    return files


def run_passes(
    files: Iterable[SourceFile],
    root: Optional[Path] = None,
    only: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Run the selected passes, returning unsuppressed findings."""
    from sparkrdma_tpu.analysis import knobs, metrics_pass, tenancy_pass, wire

    root = root or repo_root()
    files = list(files)
    runners = {
        "knob-registry": knobs.run,
        "metric-families": metrics_pass.run,
        "wire-markers": wire.run,
        "tenant-scope": tenancy_pass.run,
    }
    selected = list(only) if only else list(runners)
    by_path = {f.path: f for f in files}
    out: List[Finding] = []
    for f in files:
        out.extend(f.bad_suppressions)
    for pid in selected:
        for finding in runners[pid](files, root):
            sf = by_path.get(finding.path)
            if sf is not None and sf.suppressed(finding.pass_id, finding.line):
                continue
            out.append(finding)
    return sorted(out, key=lambda f: (f.path, f.line, f.pass_id))
