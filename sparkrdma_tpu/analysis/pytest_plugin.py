"""pytest plugin: run the suite under the lock-order detector.

Registered by ``tests/conftest.py``; armed only when
``SPARKRDMA_LOCK_ORDER`` is truthy in the environment, so the default
tier-1 run pays nothing. Under the flag, every ``named_lock`` in the
library records acquisition-order edges while the tests exercise the
real concurrency paths, and any violation — order cycle, same-name
nesting, blocking call under a hot lock — fails the session even when
every individual test passed.
"""

from __future__ import annotations

import os

from sparkrdma_tpu.analysis import lockorder

_armed = False


def _flag() -> bool:
    return os.environ.get("SPARKRDMA_LOCK_ORDER", "").strip().lower() in (
        "1", "true", "yes", "on",
    )


def pytest_configure(config):
    global _armed
    if _flag() and not _armed:
        _armed = True
        lockorder.default.reset()
        lockorder.default.enable()


def pytest_terminal_summary(terminalreporter):
    if not _armed:
        return
    det = lockorder.default
    tr = terminalreporter
    if det.violations:
        tr.section("lock-order violations")
        for v in det.violations:
            tr.line(v)
    else:
        edges = sum(len(s) for s in det.edges.values())
        tr.section("lock-order")
        tr.line(
            f"clean: {len(det.edges)} lock names, {edges} order edges, "
            "0 violations"
        )


def pytest_sessionfinish(session, exitstatus):
    if _armed and lockorder.default.violations:
        # mutate the session's exit status so CI fails even when every
        # individual test passed
        session.exitstatus = 1
