"""wire-markers pass — extension markers/structs agree across codec.

The trailing-extension scheme in ``rpc.py`` (checksum 0xFFFF, device
0xFFFE, merged 0xFFFD, elastic 0xFFFC) stays legacy-compatible only
while a set of hand-maintained invariants hold. This pass re-derives
them from the AST of any class that declares ``_<X>_MARKER``
attributes:

- markers are integer literals, pairwise distinct, and >= 0xFF00 (the
  disambiguation against host-count words relies on markers being
  impossible as real list lengths),
- every marker ``X`` has companion ``_<X>_HDR`` and ``_<X>_ITEM``
  ``struct.Struct`` attributes, and all extension headers share one
  format (the parser peeks a single fixed-size header to dispatch),
- each of ``_<X>_MARKER`` / ``_<X>_HDR`` / ``_<X>_ITEM`` is referenced
  in BOTH the encoder (``to_segments``/``to_bytes``) and the parser
  (``from_payload``/``from_bytes``) — an extension wired into one side
  only is a silent wire break,
- a ``_TRACE_EXT`` trailer, when present, must pack strictly fewer
  bytes than the minimum serialized PartitionLocation (28): the parser
  tells "trailing trace ext" from "one more location" by size alone,
- FULL ORDERING: every marker must be dispatched from ONE ``while``
  peek loop in the parser, each marker branch must end in ``continue``
  (re-peek — extensions decode in any on-wire order, including orders
  an older encoder never emits), and when a ``_TRACE_EXT`` trailer
  exists the loop guard must reference it so the trace tail survives
  any number of preceding extensions.

Any ``struct.Struct`` class attribute in ``rpc.py``/``locations.py``
that is used by an encoder method but not a parser method (or vice
versa) is likewise reported.
"""

from __future__ import annotations

import ast
import re
import struct
from pathlib import Path
from typing import Dict, Iterable, List, Optional

from sparkrdma_tpu.analysis import Finding, SourceFile

PASS_ID = "wire-markers"

_MARKER_RE = re.compile(r"^_([A-Z0-9]+)_MARKER$")
_ENCODERS = ("to_segments", "to_bytes")
_PARSERS = ("from_payload", "from_bytes")
#: minimum serialized PartitionLocation: 16-byte block triple + the
#: shortest ShuffleManagerId (two >H-prefixed strings + >i port = 12)
MIN_LOCATION_BYTES = 28


def _struct_fmt(node: ast.AST) -> Optional[str]:
    """The format literal if node is ``struct.Struct("<fmt>")``."""
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "Struct"
        and node.args
        and isinstance(node.args[0], ast.Constant)
        and isinstance(node.args[0].value, str)
    ):
        return node.args[0].value
    return None


def _names_used(fn: ast.AST) -> set:
    used = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Attribute):
            used.add(n.attr)
        elif isinstance(n, ast.Name):
            used.add(n.id)
    return used


def _ordering_findings(
    sf: SourceFile,
    markers: Dict[str, ast.Assign],
    structs: Dict[str, str],
    parsers: List[ast.FunctionDef],
) -> List[Finding]:
    """The any-order invariant: one peek loop dispatches every marker,
    every marker branch re-peeks via ``continue``, and the loop guard
    keeps the trace trailer reachable."""
    findings: List[Finding] = []
    marker_attrs = {f"_{x}_MARKER": x for x in markers}
    whiles = [
        n for p in parsers for n in ast.walk(p) if isinstance(n, ast.While)
    ]
    loop = None
    for w in whiles:
        if set(marker_attrs) <= _names_used(w):
            loop = w
            break
    if loop is None:
        for x, stmt in sorted(markers.items()):
            findings.append(
                Finding(
                    PASS_ID, sf.path, stmt.lineno,
                    f"no single parser peek loop dispatches _{x}_MARKER "
                    "alongside the other markers — extension parse order "
                    "is fixed, not any-order",
                )
            )
        return findings
    for node in ast.walk(loop):
        if not isinstance(node, ast.If):
            continue
        hit = sorted(_names_used(node.test) & set(marker_attrs))
        if not hit or not node.body:
            continue
        if not isinstance(node.body[-1], ast.Continue):
            findings.append(
                Finding(
                    PASS_ID, sf.path, node.lineno,
                    f"marker branch for {'/'.join(hit)} does not end in "
                    "'continue' — the loop stops re-peeking and any "
                    "extension after it parses order-dependently",
                )
            )
    if "_TRACE_EXT" in structs and "_TRACE_EXT" not in _names_used(loop.test):
        findings.append(
            Finding(
                PASS_ID, sf.path, loop.lineno,
                "the marker peek loop's guard does not reserve "
                "_TRACE_EXT's tail — a trace trailer after N extensions "
                "would be consumed as a truncated extension header",
            )
        )
    return findings


def _check_class(sf: SourceFile, cls: ast.ClassDef) -> List[Finding]:
    findings: List[Finding] = []
    markers: Dict[str, ast.Assign] = {}
    structs: Dict[str, str] = {}  # attr name -> format
    struct_lines: Dict[str, int] = {}
    for stmt in cls.body:
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1):
            continue
        tgt = stmt.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        m = _MARKER_RE.match(tgt.id)
        if m:
            markers[m.group(1)] = stmt
        fmt = _struct_fmt(stmt.value)
        if fmt is not None:
            structs[tgt.id] = fmt
            struct_lines[tgt.id] = stmt.lineno

    methods = {
        n.name: n for n in cls.body if isinstance(n, ast.FunctionDef)
    }
    encoders = [methods[n] for n in _ENCODERS if n in methods]
    parsers = [methods[n] for n in _PARSERS if n in methods]
    enc_used = set().union(*(_names_used(f) for f in encoders)) if encoders else set()
    par_used = set().union(*(_names_used(f) for f in parsers)) if parsers else set()

    if markers:
        values: Dict[str, int] = {}
        for x, stmt in markers.items():
            v = stmt.value
            if not (isinstance(v, ast.Constant) and isinstance(v.value, int)):
                findings.append(
                    Finding(
                        PASS_ID, sf.path, stmt.lineno,
                        f"_{x}_MARKER must be an integer literal",
                    )
                )
                continue
            values[x] = v.value
            if v.value < 0xFF00:
                findings.append(
                    Finding(
                        PASS_ID, sf.path, stmt.lineno,
                        f"_{x}_MARKER 0x{v.value:X} < 0xFF00 — collides "
                        "with plausible host-count words",
                    )
                )
        if len(set(values.values())) != len(values):
            findings.append(
                Finding(
                    PASS_ID, sf.path, cls.lineno,
                    f"duplicate extension marker values in {cls.name}: "
                    f"{values}",
                )
            )
        hdr_fmts = set()
        shared_hdr = "_EXT_HDR" in structs
        if shared_hdr:
            hdr_fmts.add(structs["_EXT_HDR"])
        for x, stmt in markers.items():
            if f"_{x}_HDR" in structs:
                hdr_fmts.add(structs[f"_{x}_HDR"])
            elif not shared_hdr:
                findings.append(
                    Finding(
                        PASS_ID, sf.path, stmt.lineno,
                        f"marker _{x}_MARKER has neither a _{x}_HDR "
                        "companion nor a shared _EXT_HDR struct",
                    )
                )
            if f"_{x}_ITEM" not in structs:
                findings.append(
                    Finding(
                        PASS_ID, sf.path, stmt.lineno,
                        f"marker _{x}_MARKER has no companion "
                        f"_{x}_ITEM struct",
                    )
                )
            candidates = [f"_{x}_MARKER"]
            if f"_{x}_ITEM" in structs:
                candidates.append(f"_{x}_ITEM")
            for attr in candidates:
                if encoders and attr not in enc_used:
                    findings.append(
                        Finding(
                            PASS_ID, sf.path, stmt.lineno,
                            f"{attr} is not referenced by the encoder "
                            f"({'/'.join(_ENCODERS)}) — one-sided "
                            "extension wiring",
                        )
                    )
                if parsers and attr not in par_used:
                    findings.append(
                        Finding(
                            PASS_ID, sf.path, stmt.lineno,
                            f"{attr} is not referenced by the parser "
                            f"({'/'.join(_PARSERS)}) — one-sided "
                            "extension wiring",
                        )
                    )
        if len(hdr_fmts) > 1:
            findings.append(
                Finding(
                    PASS_ID, sf.path, cls.lineno,
                    f"extension header formats differ ({sorted(hdr_fmts)}) — "
                    "the parser dispatches on ONE peeked header shape",
                )
            )
        if parsers:
            findings.extend(
                _ordering_findings(sf, markers, structs, parsers)
            )

    if "_TRACE_EXT" in structs:
        try:
            size = struct.calcsize(structs["_TRACE_EXT"])
        except struct.error:
            size = None
        if size is not None and size >= MIN_LOCATION_BYTES:
            findings.append(
                Finding(
                    PASS_ID, sf.path, struct_lines["_TRACE_EXT"],
                    f"_TRACE_EXT packs {size} bytes >= minimum location "
                    f"size {MIN_LOCATION_BYTES}; the tail would parse as "
                    "a location",
                )
            )

    # generic: any codec struct used on one side only
    if encoders and parsers:
        for attr, fmt in structs.items():
            in_enc, in_par = attr in enc_used, attr in par_used
            if in_enc != in_par:
                side = "parser" if in_enc else "encoder"
                findings.append(
                    Finding(
                        PASS_ID, sf.path, struct_lines[attr],
                        f"struct {attr} ({fmt!r}) is never referenced by "
                        f"the {side} side of {cls.name}",
                    )
                )
    return findings


def run(files: Iterable[SourceFile], root: Path) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        if not (
            sf.path.endswith("rpc.py") or sf.path.endswith("locations.py")
        ):
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(_check_class(sf, node))
    return findings
