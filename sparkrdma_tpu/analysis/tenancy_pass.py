"""tenant-scope pass — thread spawns on tenancy paths re-enter scope.

Tenant identity rides a thread-local (``tenancy.current_tenant``), so
every thread or timer spawned on a path that does per-tenant work
(buffer charges, quota gates, fair-share accounting, breaker keys)
must re-establish it — otherwise the child thread silently bills the
default tenant. The accepted shapes, both used across the tree:

- ``threading.Thread(target=tenancy.scoped(tenant, fn))`` — the
  closure re-enters the scope around ``fn``,
- a target function whose own body contains ``with tenant_scope(...)``
  (the retry-timer idiom in ``fetcher.py``).

A spawn in a tenancy-sensitive module matching neither is reported.
Spawns that genuinely do no tenant-attributed work (connection
pre-warm, thread joiners) carry an inline suppression with the reason.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Iterable, List, Optional

from sparkrdma_tpu.analysis import Finding, SourceFile

PASS_ID = "tenant-scope"

#: repo-relative path prefixes where spawned threads do tenant work
SENSITIVE_PREFIXES = (
    "sparkrdma_tpu/shuffle/",
    "sparkrdma_tpu/tenancy/",
    "sparkrdma_tpu/memory/",
    "sparkrdma_tpu/ops/hbm_arena.py",
)

_SCOPE_MARKERS = ("tenant_scope", "scoped")


def _is_spawn(node: ast.Call) -> Optional[str]:
    """'Thread'/'Timer' when node constructs one, else None."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in ("Thread", "Timer"):
        if isinstance(f.value, ast.Name) and f.value.id == "threading":
            return f.attr
    if isinstance(f, ast.Name) and f.id in ("Thread", "Timer"):
        return f.id
    return None


def _target_expr(node: ast.Call, kind: str) -> Optional[ast.AST]:
    for kw in node.keywords:
        if kw.arg == ("function" if kind == "Timer" else "target"):
            return kw.value
    if kind == "Timer" and len(node.args) >= 2:
        return node.args[1]
    if kind == "Thread" and len(node.args) >= 2:
        return node.args[1]
    return None


def _is_scoped_call(expr: ast.AST) -> bool:
    """True for ``tenancy.scoped(...)`` / ``scoped(...)`` closures."""
    if not isinstance(expr, ast.Call):
        return False
    f = expr.func
    if isinstance(f, ast.Attribute) and f.attr == "scoped":
        return True
    return isinstance(f, ast.Name) and f.id == "scoped"


def _re_enters_scope(fn: ast.FunctionDef) -> bool:
    for n in ast.walk(fn):
        if isinstance(n, ast.Call):
            f = n.func
            name = (
                f.attr if isinstance(f, ast.Attribute)
                else f.id if isinstance(f, ast.Name)
                else None
            )
            if name in _SCOPE_MARKERS:
                return True
    return False


def _function_index(tree: ast.AST) -> Dict[str, ast.FunctionDef]:
    """Every function/method in the module, by bare name (last wins)."""
    out: Dict[str, ast.FunctionDef] = {}
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[n.name] = n
    return out


def run(files: Iterable[SourceFile], root: Path) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        if not sf.path.startswith(SENSITIVE_PREFIXES):
            continue
        fns = _function_index(sf.tree)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _is_spawn(node)
            if kind is None:
                continue
            target = _target_expr(node, kind)
            if target is None:
                continue
            if _is_scoped_call(target):
                continue
            # resolve a Name / self.<attr> target to a same-module def
            tgt_name = None
            if isinstance(target, ast.Name):
                tgt_name = target.id
            elif isinstance(target, ast.Attribute):
                tgt_name = target.attr
            fn = fns.get(tgt_name) if tgt_name else None
            if fn is not None and _re_enters_scope(fn):
                continue
            where = f"function {tgt_name!r}" if tgt_name else "its target"
            findings.append(
                Finding(
                    PASS_ID,
                    sf.path,
                    node.lineno,
                    f"threading.{kind} on a tenancy-sensitive path: "
                    f"{where} neither wraps tenancy.scoped(...) nor "
                    "re-enters tenant_scope",
                )
            )
    return findings
