"""metric-families pass — instruments must match a declared family.

Every static call site of the shape ``<obj>.counter("name", k=v, ...)``
(likewise ``gauge``/``histogram``) is checked against
``METRIC_FAMILIES`` in :mod:`sparkrdma_tpu.obs.metrics`:

- the name must be declared,
- the declared kind must match the method used,
- the keyword-argument label keys must equal the declared label set
  exactly (a site that drops or invents a label fragments the family
  across OpenMetrics series),
- and the family name must have an anchor in docs/OBSERVABILITY.md
  (metrics that operators cannot look up are write-only telemetry).

Sites whose name argument is not a string literal (e.g. the fair-share
executor's cached ``getattr(reg, kind)`` helper) are invisible here;
the registry validates those at runtime against the same table.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable, List

from sparkrdma_tpu.analysis import Finding, SourceFile

PASS_ID = "metric-families"

_KINDS = ("counter", "gauge", "histogram")


def run(files: Iterable[SourceFile], root: Path) -> List[Finding]:
    from sparkrdma_tpu.obs.metrics import METRIC_FAMILIES

    findings: List[Finding] = []
    seen_names = set()
    for sf in files:
        # library tree only: tests legitimately mint ad-hoc families to
        # exercise the registry itself
        if not sf.path.startswith("sparkrdma_tpu/"):
            continue
        if sf.path.endswith("obs/metrics.py"):
            continue  # the registry's own method definitions/table
        for node in ast.walk(sf.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _KINDS
            ):
                continue
            if not (
                node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            name = node.args[0].value
            if "." not in name:
                # not a registry family (e.g. collections.Counter("x"))
                continue
            kind = node.func.attr
            seen_names.add(name)
            fam = METRIC_FAMILIES.get(name)
            if fam is None:
                findings.append(
                    Finding(
                        PASS_ID,
                        sf.path,
                        node.lineno,
                        f"metric {name!r} is not in METRIC_FAMILIES "
                        "(obs/metrics.py) — declare the family or fix "
                        "the typo",
                    )
                )
                continue
            decl_kind, decl_labels = fam
            if kind != decl_kind:
                findings.append(
                    Finding(
                        PASS_ID,
                        sf.path,
                        node.lineno,
                        f"metric {name!r} declared as a {decl_kind} but "
                        f"instantiated via .{kind}()",
                    )
                )
            if any(kw.arg is None for kw in node.keywords):
                continue  # **labels splat — runtime validation covers it
            # ``bounds`` is the histogram constructor's bucket spec,
            # not a label
            labels = frozenset(
                kw.arg for kw in node.keywords
                if not (kind == "histogram" and kw.arg == "bounds")
            )
            if labels != decl_labels:
                findings.append(
                    Finding(
                        PASS_ID,
                        sf.path,
                        node.lineno,
                        f"metric {name!r} label set {sorted(labels)} != "
                        f"declared {sorted(decl_labels)}",
                    )
                )

    # doc anchors: every declared family must appear in OBSERVABILITY.md
    doc = root / "docs" / "OBSERVABILITY.md"
    doc_text = doc.read_text() if doc.is_file() else ""
    metrics_path = next(
        (f.path for f in files if f.path.endswith("obs/metrics.py")),
        "sparkrdma_tpu/obs/metrics.py",
    )
    for name in sorted(METRIC_FAMILIES):
        if name not in doc_text:
            findings.append(
                Finding(
                    PASS_ID,
                    metrics_path,
                    1,
                    f"metric family {name!r} has no anchor in "
                    "docs/OBSERVABILITY.md",
                )
            )
    return findings


def dump(files: Iterable[SourceFile]) -> List[str]:
    """Maintenance helper: observed (kind, name, labels) tuples."""
    rows = {}
    for sf in files:
        for node in ast.walk(sf.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _KINDS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and "." in node.args[0].value
            ):
                labels = tuple(
                    sorted(kw.arg for kw in node.keywords if kw.arg)
                )
                rows.setdefault(
                    (node.args[0].value, node.func.attr), set()
                ).add(labels)
    return [
        f"{name} {kind} {sorted(labelsets)}"
        for (name, kind), labelsets in sorted(rows.items())
    ]
