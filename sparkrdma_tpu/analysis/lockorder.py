"""Runtime lock-order detector — cycle and held-lock-blocking checks.

The shuffle stack's deadlock freedom rests on documented acquisition
orders (manager: shuffle lock OUTER / state lock inner; hbm arena:
buffer lock OUTER / manager lock inner) that nothing enforced. This
module provides :func:`named_lock`, a drop-in ``threading.Lock`` /
``RLock`` wrapper that, while a detector is enabled:

- maintains a per-thread stack of held locks,
- records the global acquisition-order graph keyed by lock NAME (two
  per-shuffle locks are the same vertex — order violations between
  instances of one role are exactly the interesting ones),
- flags a cycle in that graph the moment the closing edge is recorded
  (the canonical AB/BA deadlock, caught even when the interleaving
  that would actually deadlock never fires in the run),
- flags nesting two *different instances* under one name (self
  deadlock risk) unless the name opts in via ``allow_self_nest``,
- flags blocking calls (``time.sleep``, ``socket.create_connection``)
  made while holding a lock marked ``hot`` — hot-path locks must
  never be held across I/O.

When no detector is enabled the wrapper costs one attribute load and
one branch per acquire/release; tier-1 runs it permanently. The pytest
plugin (:mod:`.pytest_plugin`) enables the default detector when
``SPARKRDMA_LOCK_ORDER=1`` and fails the session on violations.

``named_lock`` works inside ``threading.Condition`` — the Condition
falls back to the wrapper's plain ``acquire``/``release``, so waits
correctly pop/push the held stack.
"""

from __future__ import annotations

import socket
import threading
import time
import traceback
from typing import Dict, List, Optional, Set, Tuple

from sparkrdma_tpu.analysis.modelcheck import sched as _sched

__all__ = ["LockOrderDetector", "OrderedLock", "named_lock", "default"]


class LockOrderDetector:
    """Acquisition-graph recorder; one global default + test instances."""

    def __init__(self) -> None:
        self.enabled = False
        self._meta = threading.Lock()  # guards edges/violations
        # name -> set of names acquired WHILE name was held
        self.edges: Dict[str, Set[str]] = {}
        self.edge_sites: Dict[Tuple[str, str], str] = {}
        self.violations: List[str] = []
        self._tls = threading.local()

    # -- held stack -------------------------------------------------------
    def _held(self) -> List["OrderedLock"]:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    def held_names(self) -> List[str]:
        return [loc.name for loc in self._held()]

    # -- lifecycle --------------------------------------------------------
    def enable(self) -> None:
        self.enabled = True
        _activate(self)

    def disable(self) -> None:
        self.enabled = False
        _deactivate(self)

    def reset(self) -> None:
        with self._meta:
            self.edges.clear()
            self.edge_sites.clear()
            self.violations.clear()

    # -- recording --------------------------------------------------------
    def _site(self) -> str:
        # two frames above the wrapper: the `with lock:` caller
        for f in reversed(traceback.extract_stack(limit=8)[:-3]):
            if "lockorder" not in f.filename:
                return f"{f.filename}:{f.lineno}"
        return "?"

    def _violate(self, msg: str) -> None:
        with self._meta:
            self.violations.append(msg)

    def on_acquire(self, lock: "OrderedLock") -> None:
        held = self._held()
        if any(h is lock for h in held):
            # re-entrant acquire of the same instance (RLock): no new
            # ordering information
            held.append(lock)
            return
        for h in held:
            if h.name == lock.name:
                if not lock.allow_self_nest:
                    self._violate(
                        f"same-name lock nesting: {lock.name!r} acquired "
                        f"while another {h.name!r} instance is held "
                        f"(thread {threading.current_thread().name}, "
                        f"at {self._site()})"
                    )
                continue
            self._add_edge(h.name, lock.name)
        held.append(lock)

    def on_release(self, lock: "OrderedLock") -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    def on_blocking_call(self, what: str) -> None:
        for h in self._held():
            if h.hot:
                self._violate(
                    f"blocking call {what} while holding hot-path lock "
                    f"{h.name!r} (thread "
                    f"{threading.current_thread().name}, at {self._site()})"
                )

    def _add_edge(self, a: str, b: str) -> None:
        with self._meta:
            succ = self.edges.setdefault(a, set())
            if b in succ:
                return
            succ.add(b)
            self.edge_sites[(a, b)] = self._site()
            path = self._find_path(b, a)
        if path is not None:
            cycle = " -> ".join([a, *path])
            sites = "; ".join(
                f"{x}->{y} at {self.edge_sites.get((x, y), '?')}"
                for x, y in zip([a, *path][:-1], [a, *path][1:])
                if (x, y) in self.edge_sites
            )
            self._violate(
                f"lock-order cycle: {cycle} (edges: {sites})"
            )

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS path src..dst in the edge graph (caller holds _meta)."""
        stack: List[Tuple[str, List[str]]] = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self.edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None


class OrderedLock:
    """Named Lock/RLock wrapper feeding a :class:`LockOrderDetector`."""

    __slots__ = ("name", "hot", "allow_self_nest", "_det", "_lock")

    def __init__(
        self,
        name: str,
        *,
        hot: bool = False,
        recursive: bool = False,
        allow_self_nest: bool = False,
        detector: Optional[LockOrderDetector] = None,
    ):
        self.name = name
        self.hot = hot
        self.allow_self_nest = allow_self_nest or recursive
        self._det = detector or default
        self._lock = threading.RLock() if recursive else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # model-checker seam (analysis/modelcheck/sched.py): one module
        # attr-load + branch when no scheduler is active, mirroring the
        # detector's enabled flag. Non-blocking try-locks never park.
        sim = _sched.active
        if sim is not None and blocking:
            sim.before_lock_acquire(self)
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            if self._det.enabled:
                self._det.on_acquire(self)
            if sim is not None:
                sim.after_lock_acquire(self)
        return ok

    def release(self) -> None:
        if self._det.enabled:
            self._det.on_release(self)
        self._lock.release()
        sim = _sched.active
        if sim is not None:
            sim.after_lock_release(self)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<OrderedLock {self.name!r} hot={self.hot}>"


#: process-wide default detector; library locks bind to it
default = LockOrderDetector()


def named_lock(
    name: str,
    *,
    hot: bool = False,
    recursive: bool = False,
    allow_self_nest: bool = False,
    detector: Optional[LockOrderDetector] = None,
) -> OrderedLock:
    """An instrumented lock. ``name`` keys the acquisition graph; use
    one name per lock ROLE (``manager.shuffle``), not per instance.
    ``hot`` marks locks that must never be held across blocking calls."""
    return OrderedLock(
        name,
        hot=hot,
        recursive=recursive,
        allow_self_nest=allow_self_nest,
        detector=detector,
    )


# -- blocking-call probes --------------------------------------------------
# patched once while any detector is active; each probe fans out to the
# active detectors so test-local instances compose with the default
_active: List[LockOrderDetector] = []
_patch_lock = threading.Lock()
_real_sleep = time.sleep
_real_create_connection = socket.create_connection


def _probed_sleep(secs):
    for det in list(_active):
        det.on_blocking_call("time.sleep")
    return _real_sleep(secs)


def _probed_create_connection(*a, **kw):
    for det in list(_active):
        det.on_blocking_call("socket.create_connection")
    return _real_create_connection(*a, **kw)


def _activate(det: LockOrderDetector) -> None:
    with _patch_lock:
        if det not in _active:
            _active.append(det)
        if time.sleep is not _probed_sleep:
            time.sleep = _probed_sleep
            socket.create_connection = _probed_create_connection


def _deactivate(det: LockOrderDetector) -> None:
    with _patch_lock:
        if det in _active:
            _active.remove(det)
        if not _active and time.sleep is _probed_sleep:
            time.sleep = _real_sleep
            socket.create_connection = _real_create_connection
