"""Protocol model checker — deterministic-schedule interleaving exploration.

The invariant suite (sparkrdma_tpu/analysis/) checks locks, knobs,
metrics, and wire markers; this subpackage checks the level above:
protocol *interleavings*. The real protocol code — merge seal
(shuffle/merge.py), replica promotion (shuffle/manager.py +
elastic/replication.py), speculative reduce (elastic/speculation.py),
quota backpressure (tenancy/quota.py) — runs unmodified under a
cooperative scheduler (:mod:`.sched`) that intercepts the schedule
points PR 9 already named (``OrderedLock`` acquire/release, pipeline
queue handoffs, task-protocol send/recv, timer fires) and explores
thread interleavings systematically (:mod:`.explore`): seeded random
walks for CI, bounded exhaustive search with sleep-set partial-order
reduction for nightly. Invariant oracles (:mod:`.models`) run at every
quiescent point; seeded protocol mutants (:mod:`.mutants`) prove the
oracles have teeth. Failing schedules serialize to replayable JSON
artifacts. See docs/ANALYSIS.md "Model checking".
"""

from sparkrdma_tpu.analysis.modelcheck.sched import (  # noqa: F401
    CooperativeScheduler,
    DeadlockError,
    OracleViolation,
    schedule_point,
)
