"""CLI for the protocol model checker.

CI tier-1 (random-walk smoke, prints the failing seed):

    python -m sparkrdma_tpu.analysis.modelcheck --walks 25

Nightly (bounded exhaustive + sleep-set POR, artifacts on failure):

    python -m sparkrdma_tpu.analysis.modelcheck --exhaustive \\
        --max-schedules 2000 --emit-dir mc-artifacts

Mutation gate (every seeded mutant must be caught):

    python -m sparkrdma_tpu.analysis.modelcheck --mutants

Replay a recorded failing schedule:

    python -m sparkrdma_tpu.analysis.modelcheck --replay artifact.json

Exit status: 0 = clean (or failure reproduced under --replay),
1 = violation found / mutant missed / replay did not reproduce.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

from sparkrdma_tpu.analysis.modelcheck.explore import (
    DEFAULT_MAX_STEPS,
    exhaustive,
    load_artifact,
    random_walk,
    replay_artifact,
    save_artifact,
)
from sparkrdma_tpu.analysis.modelcheck.models import MODELS
from sparkrdma_tpu.analysis.modelcheck.mutants import MUTANTS, run_gate


def _emit(failure: dict, emit_dir: Optional[str]) -> None:
    if not emit_dir:
        return
    os.makedirs(emit_dir, exist_ok=True)
    stamp = f"{failure['model']}-{failure['kind']}-{failure.get('seed')}"
    path = os.path.join(emit_dir, f"{stamp}.json")
    save_artifact(failure, path)
    print(f"  artifact: {path}")


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m sparkrdma_tpu.analysis.modelcheck",
        description="deterministic-schedule model checker for the "
        "shuffle protocol state machines",
    )
    ap.add_argument(
        "--model",
        action="append",
        choices=sorted(MODELS),
        help="protocol model(s) to explore (default: all)",
    )
    ap.add_argument(
        "--walks", type=int, default=25,
        help="random schedules per model (CI smoke; default 25)",
    )
    ap.add_argument("--seed", type=int, default=0, help="base walk seed")
    ap.add_argument(
        "--exhaustive", action="store_true",
        help="bounded exhaustive DFS with sleep-set POR (nightly)",
    )
    ap.add_argument(
        "--max-schedules", type=int, default=2000,
        help="complete-schedule budget for --exhaustive (default 2000)",
    )
    ap.add_argument(
        "--no-por", action="store_true",
        help="disable sleep-set reduction (debugging the reducer)",
    )
    ap.add_argument(
        "--max-steps", type=int, default=DEFAULT_MAX_STEPS,
        help="per-schedule step bound (livelock guard)",
    )
    ap.add_argument(
        "--mutants", action="store_true",
        help="run the mutation-testing gate (every mutant must be caught)",
    )
    ap.add_argument(
        "--replay", metavar="ARTIFACT",
        help="replay one recorded failing-schedule JSON artifact",
    )
    ap.add_argument(
        "--emit-dir", metavar="DIR",
        help="write failing schedules as replayable JSON artifacts here",
    )
    args = ap.parse_args(argv)

    if args.replay:
        artifact = load_artifact(args.replay)
        violation = replay_artifact(artifact, max_steps=args.max_steps)
        if violation is None:
            print(f"replay of {args.replay}: did NOT reproduce")
            return 1
        print(f"replay of {args.replay}: reproduced\n  {violation}")
        return 0

    if args.mutants:
        results = run_gate(
            walks=max(args.walks, 40),
            seed=args.seed,
            max_schedules=args.max_schedules,
        )
        missed = [m for m, r in results.items() if not r["caught"]]
        for name, r in sorted(results.items()):
            status = f"caught ({r['how']})" if r["caught"] else "MISSED"
            print(f"mutant {name:24s} [{r['model']}] {status}")
            if r["violation"]:
                print(f"    {r['violation']}")
        if missed:
            print(f"\nmutation gate RED: {len(missed)} mutant(s) missed: "
                  f"{', '.join(missed)}")
            print(f"({len(MUTANTS)} mutants total)")
            return 1
        print(f"\nmutation gate green: {len(results)} mutants all caught")
        return 0

    models = args.model or sorted(MODELS)
    rc = 0
    for name in models:
        if args.exhaustive:
            outcome = exhaustive(
                name,
                max_schedules=args.max_schedules,
                max_steps=args.max_steps,
                por=not args.no_por,
            )
            tag = "complete" if outcome.get("complete") else "truncated"
            summary = f"{outcome['schedules']} schedules ({tag})"
        else:
            outcome = random_walk(
                name, args.walks, seed=args.seed, max_steps=args.max_steps
            )
            summary = f"{outcome['schedules']} schedules"
        failure = outcome["failure"]
        if failure is None:
            print(f"model {name:20s} clean: {summary}")
            continue
        rc = 1
        print(f"model {name:20s} VIOLATION after {summary}")
        print(f"  {failure['violation']}")
        if failure.get("seed") is not None:
            print(
                f"  reproduce: python -m sparkrdma_tpu.analysis.modelcheck "
                f"--model {name} --walks 1 --seed {failure['seed']}"
            )
        else:
            print(f"  trace: {json.dumps(failure['trace'])}")
        _emit(failure, args.emit_dir)
    return rc


if __name__ == "__main__":
    sys.exit(main())
