"""Seeded protocol mutants — the checker's mutation-testing gate.

Each mutant reintroduces one specific protocol bug by monkeypatching a
real method for the duration of one checker run (:func:`apply_mutant`
is a context manager; :func:`run_gate` drives the full matrix). The
gate is green when EVERY mutant is caught by at least one explored
schedule while the unmutated tree explores its full budget clean —
together those prove the oracles have teeth and aren't tautologies.

Most guards under test are factored as small named predicates in the
protocol code (``MergeEndpoint._dup_locked``,
``TpuShuffleManager._claim_map_owner``,
``SpeculativeReducePhase._already_settled``,
``QuotaBroker._must_block``, ...) precisely so a mutant swaps ONE
decision, not a hand-copied method body that drifts from the original.
The two body copies that remain (partial seal, silent release) keep
their seams so the schedule space stays comparable.
"""

from __future__ import annotations

import contextlib
from typing import Dict, Iterator, List, Optional, Tuple

from sparkrdma_tpu.analysis.modelcheck.sched import schedule_point

#: mutant name -> (model expected to catch it, description)
MUTANTS: Dict[str, Tuple[str, str]] = {
    "merge-skip-dedup": (
        "merge_seal",
        "drop the (source, seq) redelivery dedup: duplicate pushes "
        "double-count the buffer ledger",
    ),
    "merge-seal-partial": (
        "merge_seal",
        "seal on partial coverage: merged segment misses blocks yet "
        "advertises full cover",
    ),
    "merge-ledger-leak": (
        "merge_seal",
        "abandon a partition without refunding its buffered bytes",
    ),
    "merge-sealed-reentry": (
        "merge_seal",
        "accept pushes for sealed/abandoned partitions (late re-entry)",
    ),
    "promo-unshared-lock": (
        "replica_promotion",
        "per-call shuffle locks: publish/loss critical sections no "
        "longer exclude each other",
    ),
    "promo-skip-owner-dedup": (
        "replica_promotion",
        "claim map ownership unconditionally: a losing speculative "
        "publish double-serves its map",
    ),
    "replica-no-divert": (
        "replica_promotion",
        "serve replica publishes as primaries while the primary lives",
    ),
    "spec-double-settle": (
        "speculation",
        "drop the late-loser guard: a loser crossing the line "
        "overwrites the settled winner",
    ),
    "spec-skip-cancel": (
        "speculation",
        "never drain the losing attempt (no cancel_reduce)",
    ),
    "quota-global-usage": (
        "quota_stall",
        "block on GLOBAL usage instead of per-tenant: one tenant at "
        "quota blocks everyone",
    ),
    "quota-silent-release": (
        "quota_stall",
        "release bytes without notifying blocked chargers",
    ),
    "meta-skip-epoch-check": (
        "meta_lease",
        "apply writes without the shard epoch fence: a write routed "
        "under a pre-crash lease lands in the post-crash registry",
    ),
    "meta-tombstone-skip": (
        "meta_lease",
        "ignore per-shard executor tombstones: a swept publisher's "
        "straggling locations double-serve beside promoted replicas",
    ),
    "meta-serve-follower": (
        "meta_lease",
        "resolve from every owner instead of the primary copy only: "
        "one slot answers twice",
    ),
    "meta-lease-serve-expired": (
        "meta_lease",
        "leases never lapse: an expired holder keeps serving without "
        "a takeover epoch bump",
    ),
    "meta-renew-after-expiry": (
        "meta_lease",
        "renew silently resurrects expired/superseded leases instead "
        "of forcing re-acquire through takeover",
    ),
    "meta-adopt-no-bump": (
        "meta_lease",
        "driver-crash wipe advances neither generation nor lease "
        "epochs: a stale re-adoption sweep merges into the new era",
    ),
    "meta-adopt-partial-sweep": (
        "meta_lease",
        "driver-crash wipe clears only one shard: pre-crash entries "
        "survive into the post-crash registry",
    ),
}


def _patch(cls, name: str, fn) -> Tuple:
    orig = cls.__dict__[name]
    setattr(cls, name, fn)
    return (cls, name, orig)


@contextlib.contextmanager
def apply_mutant(name: Optional[str]) -> Iterator[None]:
    """Arm one mutant (or none) for the enclosed checker run."""
    if name is None:
        yield
        return
    if name not in MUTANTS:
        raise KeyError(f"unknown mutant {name!r} (see MUTANTS)")
    patches: List[Tuple] = []
    try:
        patches.extend(_ARMERS[name]())
        yield
    finally:
        for cls, attr, orig in reversed(patches):
            setattr(cls, attr, orig)


# -- the mutants ----------------------------------------------------------
def _arm_merge_skip_dedup() -> List[Tuple]:
    from sparkrdma_tpu.shuffle.merge import MergeEndpoint

    return [
        _patch(
            MergeEndpoint,
            "_dup_locked",
            staticmethod(lambda per, source, seq: False),
        )
    ]


def _arm_merge_seal_partial() -> List[Tuple]:
    from sparkrdma_tpu.shuffle.merge import MergeEndpoint, _natural

    def sealable(self, st):
        # copied from _sealable_locked, coverage check REMOVED: seals
        # whatever arrived, so the merged segment can miss blocks while
        # merged_cover still claims them
        num_maps = max((nm for (_, _, nm) in st.markers.values()), default=0)
        committed = sum(c for (_, c, _) in st.markers.values())
        if num_maps <= 0 or committed < num_maps:
            return []
        out = []
        all_pids = set()
        for counts, _, _ in st.markers.values():
            all_pids.update(p for p, n in counts.items() if n)
        for pid in sorted(all_pids):
            if pid in st.sealed or pid in st.abandoned:
                continue
            need = [
                (src, seq)
                for src, (counts, _, _) in sorted(st.markers.items())
                for seq in range(counts.get(pid, 0))
            ]
            have = st.blocks.get(pid, {})
            need = [k for k in need if k in have]  # BUG: partial cover
            if not need:
                continue
            payloads = st.blocks.pop(pid)
            self._buffered -= sum(len(v) for v in payloads.values())
            st.sealed[pid] = None
            need.sort(key=lambda k: (_natural(k[0]), k[1]))
            out.append((pid, need, payloads))
        return out

    return [_patch(MergeEndpoint, "_sealable_locked", sealable)]


def _arm_merge_ledger_leak() -> List[Tuple]:
    from sparkrdma_tpu.shuffle.merge import MergeEndpoint

    def abandon(self, st, pid):
        st.blocks.pop(pid, None)  # BUG: buffered bytes never refunded
        st.abandoned.add(pid)

    return [_patch(MergeEndpoint, "_abandon_locked", abandon)]


def _arm_merge_sealed_reentry() -> List[Tuple]:
    from sparkrdma_tpu.shuffle.merge import MergeEndpoint

    return [
        _patch(MergeEndpoint, "_closed_locked", lambda self, st, pid: False)
    ]


def _arm_promo_unshared_lock() -> List[Tuple]:
    from sparkrdma_tpu.analysis.lockorder import named_lock
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager

    def shuffle_lock(self, shuffle_id):
        # BUG: fresh lock per call — same park structure, no exclusion
        with self._lock:
            return named_lock("manager.shuffle")

    return [_patch(TpuShuffleManager, "_shuffle_lock", shuffle_lock)]


def _arm_promo_skip_owner_dedup() -> List[Tuple]:
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager

    def claim(self, owner_map, map_id, exec_id):
        schedule_point("proto", "manager.publish.claim")
        owner_map[map_id] = exec_id  # BUG: never checks a prior owner
        return True

    return [_patch(TpuShuffleManager, "_claim_map_owner", claim)]


def _arm_replica_no_divert() -> List[Tuple]:
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager

    return [
        _patch(
            TpuShuffleManager,
            "_is_replica_publish",
            staticmethod(lambda msg: False),
        )
    ]


def _arm_spec_double_settle() -> List[Tuple]:
    from sparkrdma_tpu.elastic.speculation import SpeculativeReducePhase

    return [
        _patch(
            SpeculativeReducePhase,
            "_already_settled",
            lambda self, idx, done, failures: False,
        )
    ]


def _arm_spec_skip_cancel() -> List[Tuple]:
    from sparkrdma_tpu.elastic.speculation import SpeculativeReducePhase

    return [
        _patch(
            SpeculativeReducePhase, "_cancel", lambda self, worker, rng: None
        )
    ]


def _arm_quota_global_usage() -> List[Tuple]:
    from sparkrdma_tpu.tenancy.quota import QuotaBroker

    def must_block(self, tenant, nbytes, quota):
        held = sum(self._usage.values())  # BUG: global, not per-tenant
        return held > 0 and held + nbytes > quota

    return [_patch(QuotaBroker, "_must_block", must_block)]


def _arm_quota_silent_release() -> List[Tuple]:
    from sparkrdma_tpu.tenancy.quota import QuotaBroker

    def release(self, tenant, nbytes):
        schedule_point("proto", "quota.release")
        with self._cond:
            self._usage[tenant] = max(0, self._usage.get(tenant, 0) - nbytes)
            self._g_bytes(tenant).set(self._usage[tenant])
            # BUG: no notify_all — blocked chargers sleep to the deadline

    return [_patch(QuotaBroker, "release", release)]


def _arm_meta_skip_epoch_check() -> List[Tuple]:
    from sparkrdma_tpu.metastore.store import MetaShard

    return [_patch(MetaShard, "_epoch_ok", lambda self, epoch: True)]


def _arm_meta_tombstone_skip() -> List[Tuple]:
    from sparkrdma_tpu.metastore.store import MetaShard

    return [_patch(MetaShard, "_blocked", lambda self, executor_id: False)]


def _arm_meta_serve_follower() -> List[Tuple]:
    from sparkrdma_tpu.metastore.store import ShardedMetaStore

    return [
        _patch(
            ShardedMetaStore,
            "_read_copies",
            staticmethod(lambda owners: list(owners)),
        )
    ]


def _arm_meta_lease_serve_expired() -> List[Tuple]:
    from sparkrdma_tpu.metastore.lease import LeaseTable

    return [
        _patch(
            LeaseTable, "_expired", staticmethod(lambda lease, now: False)
        )
    ]


def _arm_meta_renew_after_expiry() -> List[Tuple]:
    from sparkrdma_tpu.metastore.lease import LeaseTable, StaleEpochError

    def renew(self, peer, epoch):
        lease = self._leases.get(peer)
        if lease is None:
            raise StaleEpochError(peer, epoch, 0)
        # BUG: no aliveness/epoch/expiry fence — a lapsed or superseded
        # holder silently resurrects instead of re-acquiring via takeover
        lease.deadline = self.clock() + self.ttl_s

    return [_patch(LeaseTable, "renew", renew)]


def _arm_meta_adopt_no_bump() -> List[Tuple]:
    from sparkrdma_tpu.metastore.store import ShardedMetaStore

    def wipe(self):
        # copied from wipe, fencing REMOVED: neither the generation nor
        # the lease epochs advance, so a re-adoption sweep fenced at the
        # pre-crash generation merges straight into the new era
        schedule_point("proto", "meta.adopt")
        with self._topology:
            for peer in self._ring.peers:
                shard = self._shards[peer]
                with shard.lock:
                    shard.entries.clear()
            self._reg.gauge("metastore.epoch", role=self.role).set(
                self.generation
            )
            return self.generation

    return [_patch(ShardedMetaStore, "wipe", wipe)]


def _arm_meta_adopt_partial_sweep() -> List[Tuple]:
    from sparkrdma_tpu.metastore.store import ShardedMetaStore

    def wipe(self):
        # copied from wipe, sweep truncated: only the FIRST peer's slice
        # clears, so pre-crash entries survive into the new generation
        schedule_point("proto", "meta.adopt")
        with self._topology:
            self.generation += 1
            self._leases.bump_all()
            for i, peer in enumerate(self._ring.peers):
                epoch = self._leases.epoch(peer)
                shard = self._shards[peer]
                with shard.lock:
                    if i == 0:  # BUG: the other shards keep their entries
                        shard.entries.clear()
                    shard.epoch = epoch
            self._reg.gauge("metastore.epoch", role=self.role).set(
                self.generation
            )
            return self.generation

    return [_patch(ShardedMetaStore, "wipe", wipe)]


_ARMERS = {
    "merge-skip-dedup": _arm_merge_skip_dedup,
    "merge-seal-partial": _arm_merge_seal_partial,
    "merge-ledger-leak": _arm_merge_ledger_leak,
    "merge-sealed-reentry": _arm_merge_sealed_reentry,
    "promo-unshared-lock": _arm_promo_unshared_lock,
    "promo-skip-owner-dedup": _arm_promo_skip_owner_dedup,
    "replica-no-divert": _arm_replica_no_divert,
    "spec-double-settle": _arm_spec_double_settle,
    "spec-skip-cancel": _arm_spec_skip_cancel,
    "quota-global-usage": _arm_quota_global_usage,
    "quota-silent-release": _arm_quota_silent_release,
    "meta-skip-epoch-check": _arm_meta_skip_epoch_check,
    "meta-tombstone-skip": _arm_meta_tombstone_skip,
    "meta-serve-follower": _arm_meta_serve_follower,
    "meta-lease-serve-expired": _arm_meta_lease_serve_expired,
    "meta-renew-after-expiry": _arm_meta_renew_after_expiry,
    "meta-adopt-no-bump": _arm_meta_adopt_no_bump,
    "meta-adopt-partial-sweep": _arm_meta_adopt_partial_sweep,
}


def run_gate(
    walks: int = 60, seed: int = 0, max_schedules: int = 400
) -> Dict[str, Dict[str, object]]:
    """The full mutation matrix: every mutant must be CAUGHT.

    Random walks first (cheap); a mutant the walks miss gets the
    bounded exhaustive pass. Returns {mutant: {"caught": bool, ...}}.
    """
    from sparkrdma_tpu.analysis.modelcheck.explore import (
        exhaustive,
        random_walk,
    )

    results: Dict[str, Dict[str, object]] = {}
    for name, (model, _desc) in MUTANTS.items():
        outcome = random_walk(model, walks, seed=seed, mutant=name)
        how = "random"
        if outcome["failure"] is None:
            outcome = exhaustive(
                model, max_schedules=max_schedules, mutant=name
            )
            how = "exhaustive"
        failure = outcome["failure"]
        results[name] = {
            "caught": failure is not None,
            "how": how if failure is not None else None,
            "model": model,
            "violation": (failure or {}).get("violation"),
            "schedules": outcome["schedules"],
        }
    return results


__all__ = ["MUTANTS", "apply_mutant", "run_gate"]
