"""Exploration strategies over the cooperative scheduler.

Three pickers drive :meth:`CooperativeScheduler.run`:

- :class:`MinPicker` — always the lowest-index runnable thread. This is
  the *serial schedule*: the deterministic reference execution whose
  result bytes every explored schedule must reproduce.
- :class:`RandomPicker` — seeded uniform choice at every step. A seed
  fully determines the schedule, so any failure replays from its seed.
- :class:`FixedPicker` — replays a recorded thread-name trace exactly
  (the artifact/regression-fixture path), raising
  :class:`ReplayDivergence` when the trace names a thread that is not
  currently runnable (model or code drifted since recording).

:func:`random_walk` is the CI entrypoint: serial baseline first (must
be violation-free — it doubles as the byte-identity reference), then N
seeded walks. :func:`exhaustive` is the nightly entrypoint: stateless
DFS with re-execution and Godefroid-style sleep sets, treating two lock
actions on distinct lock instances as independent (everything else is
conservatively dependent — sound, just less reduction).

Failures serialize to JSON artifacts (:func:`save_artifact`) carrying
the model name, seed/trace, and violation text; :func:`replay_artifact`
re-runs one under :class:`FixedPicker`.
"""

from __future__ import annotations

import json
import random
from typing import Dict, List, Optional, Tuple

from sparkrdma_tpu.analysis.modelcheck.models import MODELS
from sparkrdma_tpu.analysis.modelcheck.sched import (
    CooperativeScheduler,
    OracleViolation,
    ReplayDivergence,
    SimThread,
)

DEFAULT_MAX_STEPS = 20000


class MinPicker:
    """The serial schedule: lowest spawn-index runnable thread."""

    def pick(self, step: int, runnable: List[SimThread]) -> SimThread:
        return runnable[0]


class RandomPicker:
    """Seeded uniform schedule; the seed IS the schedule."""

    def __init__(self, seed: int):
        self.seed = seed
        self._rng = random.Random(seed)

    def pick(self, step: int, runnable: List[SimThread]) -> SimThread:
        return runnable[self._rng.randrange(len(runnable))]


class FixedPicker:
    """Replay a recorded thread-name trace; serial past its end."""

    def __init__(self, trace: List[str]):
        self.trace = list(trace)

    def pick(self, step: int, runnable: List[SimThread]) -> SimThread:
        if step < len(self.trace):
            want = self.trace[step]
            for t in runnable:
                if t.name == want:
                    return t
            raise ReplayDivergence(
                f"step {step}: recorded thread {want!r} not runnable "
                f"(runnable: {[t.name for t in runnable]})"
            )
        return runnable[0]


class _FrontierStop(Exception):
    """Internal: prefix consumed; abort the run to inspect the frontier."""


class _PrefixPicker:
    """Follow a fixed prefix, then capture the frontier and stop."""

    def __init__(self, prefix: List[str]):
        self.prefix = prefix
        self.frontier: List[Tuple[str, str, Optional[int]]] = []

    def pick(self, step: int, runnable: List[SimThread]) -> SimThread:
        if step < len(self.prefix):
            want = self.prefix[step]
            for t in runnable:
                if t.name == want:
                    return t
            raise ReplayDivergence(
                f"exhaustive prefix diverged at step {step}: {want!r} not in "
                f"{[t.name for t in runnable]}"
            )
        self.frontier = [
            (t.name, t.pending.kind, t.pending.key) for t in runnable
        ]
        raise _FrontierStop()


def run_schedule(
    model_name: str,
    picker,
    max_steps: int = DEFAULT_MAX_STEPS,
    mutant: Optional[str] = None,
) -> Tuple[bytes, List[str]]:
    """One complete schedule of ``model_name`` under ``picker``.

    Builds a fresh model, runs it to completion with the quiescent
    oracle armed, then runs the final oracles. Returns ``(result_bytes,
    trace)``; raises :class:`OracleViolation` (or Deadlock/Crash/...)
    on the first violation. ``mutant`` arms a seeded protocol mutant
    (:mod:`.mutants`) for the duration of the run.
    """
    from sparkrdma_tpu.analysis.modelcheck.mutants import apply_mutant

    model = MODELS[model_name]()
    sched = CooperativeScheduler()

    def quiescent() -> None:
        violations = model.check()
        if violations:
            raise OracleViolation(
                f"[{model_name}] " + "; ".join(violations)
            )

    with apply_mutant(mutant):
        model.build(sched)
        sched.on_quiescent = quiescent
        try:
            sched.run(picker, max_steps=max_steps)
        except BaseException as e:
            e.mc_trace = list(sched.trace)  # type: ignore[attr-defined]
            raise
        violations = model.final()
        if violations:
            err = OracleViolation(f"[{model_name}] " + "; ".join(violations))
            err.mc_trace = list(sched.trace)  # type: ignore[attr-defined]
            raise err
        return model.result(), list(sched.trace)


def random_walk(
    model_name: str,
    walks: int,
    seed: int = 0,
    max_steps: int = DEFAULT_MAX_STEPS,
    mutant: Optional[str] = None,
) -> Dict[str, object]:
    """Serial baseline + ``walks`` seeded random schedules.

    Returns ``{"schedules": n, "failure": None}`` on success, or the
    first failure as ``{"kind", "seed", "trace", "violation"}`` — the
    caller prints the seed; ``seed`` alone reproduces the schedule.
    """
    try:
        baseline, _ = run_schedule(
            model_name, MinPicker(), max_steps=max_steps, mutant=mutant
        )
    except BaseException as e:
        return {
            "schedules": 0,
            "failure": {
                "model": model_name,
                "kind": "serial",
                "seed": None,
                "trace": getattr(e, "mc_trace", []),
                "violation": f"{type(e).__name__}: {e}",
                "mutant": mutant,
            },
        }
    ran = 1
    for i in range(walks):
        walk_seed = seed + i
        try:
            result, _trace = run_schedule(
                model_name,
                RandomPicker(walk_seed),
                max_steps=max_steps,
                mutant=mutant,
            )
        except BaseException as e:
            return {
                "schedules": ran,
                "failure": {
                    "model": model_name,
                    "kind": "random",
                    "seed": walk_seed,
                    "trace": getattr(e, "mc_trace", []),
                    "violation": f"{type(e).__name__}: {e}",
                    "mutant": mutant,
                },
            }
        ran += 1
        if result != baseline:
            return {
                "schedules": ran,
                "failure": {
                    "model": model_name,
                    "kind": "random",
                    "seed": walk_seed,
                    "trace": _trace,
                    "violation": (
                        "byte-identity: schedule result diverges from the "
                        f"serial schedule ({result!r} != {baseline!r})"
                    ),
                    "mutant": mutant,
                },
            }
    return {"schedules": ran, "failure": None}


def _independent(
    a: Tuple[str, str, Optional[int]], b: Tuple[str, str, Optional[int]]
) -> bool:
    """Conservative independence for sleep sets: only lock actions on
    DISTINCT lock instances commute for sure. Proto seams, waits, and
    timers all touch shared protocol state — treated dependent."""
    _, akind, akey = a
    _, bkind, bkey = b
    if not akind.startswith("lock.") or not bkind.startswith("lock."):
        return False
    return akey is not None and bkey is not None and akey != bkey


def exhaustive(
    model_name: str,
    max_schedules: int = 2000,
    max_steps: int = DEFAULT_MAX_STEPS,
    mutant: Optional[str] = None,
    por: bool = True,
) -> Dict[str, object]:
    """Bounded DFS over all schedules, sleep-set reduced.

    Stateless search with re-execution: a prefix (list of thread names)
    re-runs from scratch to reach its frontier, so protocol state never
    needs checkpointing. ``max_schedules`` bounds COMPLETE schedules
    (budget exhaustion is reported, never silent). Returns the same
    shape as :func:`random_walk` plus ``"complete"`` — True when the
    whole space fit the budget.
    """
    from sparkrdma_tpu.analysis.modelcheck.mutants import apply_mutant

    baseline: List[bytes] = []
    stats = {"schedules": 0, "truncated": False}

    def frontier_of(prefix: List[str]) -> List[Tuple[str, str, Optional[int]]]:
        """Re-execute ``prefix``; return the runnable set just past it
        ([] when the prefix is already a complete schedule)."""
        from sparkrdma_tpu.analysis.modelcheck.models import MODELS as _M

        model = _M[model_name]()
        sched = CooperativeScheduler()
        picker = _PrefixPicker(prefix)
        with apply_mutant(mutant):
            model.build(sched)
            try:
                sched.run(picker, max_steps=max_steps)
            except _FrontierStop:
                return picker.frontier
        return []

    def complete(prefix: List[str]) -> None:
        """Run ``prefix`` as a full schedule with every oracle armed."""
        stats["schedules"] += 1
        result, _ = run_schedule(
            model_name,
            FixedPicker(prefix),
            max_steps=max_steps,
            mutant=mutant,
        )
        if not baseline:
            baseline.append(result)
        elif result != baseline[0]:
            err = OracleViolation(
                "byte-identity: schedule result diverges from the serial "
                f"schedule ({result!r} != {baseline[0]!r})"
            )
            err.mc_trace = list(prefix)  # type: ignore[attr-defined]
            raise err

    def explore(prefix: List[str], sleep: set) -> None:
        if stats["schedules"] >= max_schedules:
            stats["truncated"] = True
            return
        frontier = frontier_of(prefix)
        if not frontier:
            complete(prefix)
            return
        sleep = set(sleep)
        for cand in frontier:
            name = cand[0]
            if cand in sleep:
                continue
            if stats["schedules"] >= max_schedules:
                stats["truncated"] = True
                return
            child_sleep = (
                {c for c in sleep if _independent(c, cand)} if por else set()
            )
            explore(prefix + [name], child_sleep)
            if por:
                sleep.add(cand)

    try:
        # serial first so the byte-identity baseline is the serial result
        complete(_serial_trace(model_name, max_steps, mutant))
        explore([], set())
    except BaseException as e:
        return {
            "schedules": stats["schedules"],
            "complete": False,
            "failure": {
                "model": model_name,
                "kind": "exhaustive",
                "seed": None,
                "trace": getattr(e, "mc_trace", []),
                "violation": f"{type(e).__name__}: {e}",
                "mutant": mutant,
            },
        }
    return {
        "schedules": stats["schedules"],
        "complete": not stats["truncated"],
        "failure": None,
    }


def _serial_trace(
    model_name: str, max_steps: int, mutant: Optional[str]
) -> List[str]:
    _, trace = run_schedule(
        model_name, MinPicker(), max_steps=max_steps, mutant=mutant
    )
    return trace


# -- artifacts ------------------------------------------------------------
def save_artifact(failure: Dict[str, object], path: str) -> None:
    """Write one failing schedule as a replayable JSON artifact."""
    with open(path, "w") as f:
        json.dump(failure, f, indent=2, sort_keys=True)
        f.write("\n")


def load_artifact(path: str) -> Dict[str, object]:
    with open(path) as f:
        return json.load(f)


def replay_artifact(
    artifact: Dict[str, object], max_steps: int = DEFAULT_MAX_STEPS
) -> Optional[str]:
    """Re-run a recorded failing schedule; returns the reproduced
    violation text, or None when the failure no longer reproduces
    (fixed — or the model drifted: ReplayDivergence says which)."""
    model_name = str(artifact["model"])
    mutant = artifact.get("mutant")
    trace = artifact.get("trace") or []
    seed = artifact.get("seed")
    if trace:
        picker = FixedPicker([str(t) for t in trace])
    elif seed is not None:
        picker = RandomPicker(int(seed))  # type: ignore[arg-type]
    else:
        raise ValueError("artifact has neither trace nor seed")
    try:
        run_schedule(
            model_name,
            picker,
            max_steps=max_steps,
            mutant=str(mutant) if mutant else None,
        )
    except ReplayDivergence:
        raise
    except BaseException as e:  # noqa: BLE001 — the violation IS the result
        return f"{type(e).__name__}: {e}"
    return None


def walk_all(
    walks: int, seed: int = 0, mutant: Optional[str] = None
) -> Dict[str, Dict[str, object]]:
    """Random-walk every registered model; {model: outcome}."""
    return {
        name: random_walk(name, walks, seed=seed, mutant=mutant)
        for name in sorted(MODELS)
    }


__all__ = [
    "FixedPicker",
    "MinPicker",
    "RandomPicker",
    "exhaustive",
    "load_artifact",
    "random_walk",
    "replay_artifact",
    "run_schedule",
    "save_artifact",
    "walk_all",
]
