"""Protocol models: the REAL state machines under tiny fixed configs.

Each model wires real protocol objects (``MergeEndpoint``,
``TpuShuffleManager``'s publish/loss mutators, ``SpeculativeReducePhase``,
``QuotaBroker``) to a handful of sim threads representing the concurrent
actors of one documented race, plus the invariant oracles that must hold
at every quiescent point. Configs are deliberately minimal — 2 maps,
2 partitions, 2-5 threads — because exhaustive exploration cost is
exponential in schedule points; the races these protocols can exhibit
(PR 7/8/10 postmortems, docs/RESILIENCE.md) all fit in this window.

A model exposes:

- ``build(sched)`` — construct protocol state, spawn the actor threads;
- ``check()`` — quiescent-point invariants, returning violation strings;
- ``final()`` — end-of-schedule invariants (byte identity, metric
  deltas, counts);
- ``result()`` — canonical bytes for the byte-identity-vs-serial oracle
  (schedule-dependent detail like which executor won must NOT leak in).

Only the driver-side/in-process protocol surfaces run here; the
transport is represented by the call boundary itself (a push/publish
call IS the message arrival — in-process clusters already work this
way, see merge.register_endpoint).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from sparkrdma_tpu.analysis.modelcheck.sched import (
    CooperativeScheduler,
    SimPool,
    schedule_point,
)

MODELS: Dict[str, Callable[[], "ProtocolModel"]] = {}


def register_model(cls):
    MODELS[cls.name] = cls
    return cls


class ProtocolModel:
    """Base: a named scenario over real protocol code."""

    name = ""

    def build(self, sched: CooperativeScheduler) -> None:
        raise NotImplementedError

    def check(self) -> List[str]:
        return []

    def final(self) -> List[str]:
        return []

    def result(self) -> bytes:
        return b""


# ----------------------------------------------------------------------
# shared stubs: the minimum manager surface MergeEndpoint/ReplicaStore
# need — a real ProtectionDomain (so MemoryWriterBlock registration and
# resolve are the real code paths) plus a recording publish sink
# ----------------------------------------------------------------------
class _StubConf:
    driver_port = 0
    push_max_buffer_bytes = 1 << 20


class _StubResolver:
    def reserve_inmemory_bytes(self, n: int) -> bool:
        return True

    def release_inmemory_bytes(self, n: int) -> None:
        pass


class _StubNode:
    def __init__(self):
        from sparkrdma_tpu.memory.registry import ProtectionDomain

        self.pd = ProtectionDomain()


class _SinkManager:
    """Duck-typed manager for endpoint/store objects under test."""

    def __init__(self, executor_id: str = "mc-exec"):
        from sparkrdma_tpu.locations import ShuffleManagerId

        self.conf = _StubConf()
        self.executor_id = executor_id
        self.resolver = _StubResolver()
        self.node = _StubNode()
        self.local_manager_id = ShuffleManagerId("mc", 1, executor_id)
        self.published: List[Tuple[int, int, list, int]] = []
        self._pub_lock = threading.Lock()  # raw: no schedule point inside

    def start_node_if_missing(self) -> None:
        pass

    def publish_partition_locations(
        self, shuffle_id, partition_id, locations, num_map_outputs=0,
        meta_epoch=0,
    ) -> None:
        schedule_point("proto", "sink.publish")
        with self._pub_lock:
            self.published.append(
                (shuffle_id, partition_id, list(locations), num_map_outputs)
            )


def _concat(payloads: Dict[Tuple[str, int], bytes], keys) -> bytes:
    return b"".join(payloads[k] for k in keys)


# ----------------------------------------------------------------------
# model 1: merge seal vs late/duplicate pushes (shuffle/merge.py, PR 7)
# ----------------------------------------------------------------------
@register_model
class MergeSealModel(ProtocolModel):
    """Two sources push toward one MergeEndpoint; one source's windows
    arrive as two concurrent deliveries (the map pool ships windows in
    parallel, so a final marker CAN land before an earlier window);
    a duplicate delivery of the first source's window races everything.
    The byte budget is sized so the serial schedule abandons one
    partition (fallback-to-originals is part of the explored space).

    Oracles: buffer ledger == live payload bytes; sealed/abandoned
    disjoint; sealed partitions hold no buffered blocks; every published
    merged segment's bytes equal the canonical original concatenation
    and its cover equals the partition's original count; final output
    (merged-else-original planning over everything published) is
    byte-identical across schedules.
    """

    name = "merge_seal"
    SID = 7

    # (pid, seq, payload) per source; payload bytes double as originals
    M0 = [(0, 0, b"a00"), (1, 0, b"a10")]
    M1W = [(0, 0, b"b00")]
    M1F = [(0, 1, b"b01"), (1, 0, b"b10")]
    FINAL_M0 = {"counts": {0: 1, 1: 1}, "committed": 1, "num_maps": 2}
    FINAL_M1 = {"counts": {0: 2, 1: 1}, "committed": 1, "num_maps": 2}

    def build(self, sched: CooperativeScheduler) -> None:
        from sparkrdma_tpu.shuffle.merge import MergeEndpoint

        self.manager = _SinkManager()
        # total pushed bytes are 15; 12 forces the serial schedule to
        # abandon whichever partition tips the ledger over
        self.manager.conf.push_max_buffer_bytes = 12
        self.ep = MergeEndpoint(self.manager)
        ep, sid = self.ep, self.SID
        sched.spawn(
            "push_m0", lambda: ep.push_blocks(sid, "m0", self.M0, self.FINAL_M0)
        )
        sched.spawn("push_m1w", lambda: ep.push_blocks(sid, "m1", self.M1W, None))
        sched.spawn(
            "push_m1f", lambda: ep.push_blocks(sid, "m1", self.M1F, self.FINAL_M1)
        )
        # duplicate delivery of m0's window (no final): dedup must drop
        sched.spawn("push_dup", lambda: ep.push_blocks(sid, "m0", self.M0, None))

    # canonical truth: originals per pid in (natural source, seq) order
    def _originals(self) -> Dict[int, Dict[Tuple[str, int], bytes]]:
        out: Dict[int, Dict[Tuple[str, int], bytes]] = {}
        for src, blocks in (("m0", self.M0), ("m1", self.M1W + self.M1F)):
            for pid, seq, payload in blocks:
                out.setdefault(pid, {})[(src, seq)] = payload
        return out

    def check(self) -> List[str]:
        v: List[str] = []
        ep = self.ep
        live = sum(
            len(p)
            for st in ep._shuffles.values()
            for per in st.blocks.values()
            for p in per.values()
        )
        if ep._buffered != live:
            v.append(f"merge ledger drift: buffered={ep._buffered} live={live}")
        if ep._buffered < 0:
            v.append(f"merge ledger negative: {ep._buffered}")
        for st in ep._shuffles.values():
            both = set(st.sealed) & st.abandoned
            if both:
                v.append(f"pids both sealed and abandoned: {sorted(both)}")
            resealed = set(st.sealed) & set(st.blocks)
            if resealed:
                v.append(
                    f"sealed pids still buffering blocks: {sorted(resealed)}"
                )
        return v

    def final(self) -> List[str]:
        v = self.check()
        origs = self._originals()
        pd = self.manager.node.pd
        for _sid, _pid, locs, _n in self.manager.published:
            for loc in locs:
                cover = loc.block.merged_cover
                if not cover:
                    v.append("merge endpoint published a non-merged location")
                    continue
                per = origs.get(loc.partition_id, {})
                if cover != len(per):
                    v.append(
                        f"pid {loc.partition_id}: merged_cover {cover} != "
                        f"{len(per)} originals"
                    )
                want = _concat(per, sorted(per))
                got = bytes(
                    pd.resolve(loc.block.mkey, loc.block.address, loc.block.length)
                )
                if got != want:
                    v.append(
                        f"pid {loc.partition_id}: merged bytes diverge from "
                        f"original concatenation"
                    )
        return v

    def result(self) -> bytes:
        """Planner-visible bytes per pid under merged-else-original."""
        from sparkrdma_tpu.locations import PartitionLocation, ShuffleManagerId
        from sparkrdma_tpu.locations import BlockLocation
        from sparkrdma_tpu.shuffle.merge import plan_reads

        origs = self._originals()
        mid = ShuffleManagerId("mc", 1, "origin")
        locations: List[PartitionLocation] = []
        payload_of: Dict[int, bytes] = {}
        mkey = 1 << 20  # synthetic original mkeys, disjoint from pd's
        for pid, per in sorted(origs.items()):
            for key in sorted(per):
                locations.append(
                    PartitionLocation(mid, pid, BlockLocation(0, len(per[key]), mkey))
                )
                payload_of[mkey] = per[key]
                mkey += 1
        for _sid, _pid, locs, _n in self.manager.published:
            locations.extend(locs)
        selected, _fallbacks = plan_reads(locations)
        pd = self.manager.node.pd
        out: Dict[int, List[bytes]] = {}
        for loc in sorted(
            selected, key=lambda loc: (loc.partition_id, loc.block.merged_cover, loc.block.mkey)
        ):
            if loc.block.merged_cover:
                data = bytes(
                    pd.resolve(loc.block.mkey, loc.block.address, loc.block.length)
                )
            else:
                data = payload_of[loc.block.mkey]
            out.setdefault(loc.partition_id, []).append(data)
        return b"|".join(
            b"%d:%s" % (pid, b"".join(chunks)) for pid, chunks in sorted(out.items())
        )


# ----------------------------------------------------------------------
# model 2: replica promotion vs publish vs speculative re-publish
# (shuffle/manager.py + elastic/replication.py, PR 10)
# ----------------------------------------------------------------------
@register_model
class ReplicaPromotionModel(ProtocolModel):
    """The driver's location registry under a racing executor loss.

    exec-a publishes map 0; exec-b publishes map 1 and holds a replica
    of map 0 (published with the 0xFFFC lineage tag, diverted into the
    replica registry); exec-c re-publishes map 0 (a speculative/
    recompute duplicate); exec-a is lost concurrently. All five actors
    call the REAL ``_handle_publish`` / ``_on_peer_lost`` bodies.

    Oracles: a replica never double-serves while its primary lives
    (no is_replica location in the primary registry before the loss);
    at most one serving location per (pid, map); the barrier stays in
    [0, num_maps], never exceeds the distinct serving maps, and only
    decreases across the loss event.
    """

    name = "replica_promotion"
    SID = 1
    NUM_MAPS = 2

    def _publish_msg(self, exec_id: str, map_id: int, mkey: int):
        from sparkrdma_tpu.locations import (
            BlockLocation,
            PartitionLocation,
            ShuffleManagerId,
        )
        from sparkrdma_tpu.rpc import PublishPartitionLocationsMsg

        mid = ShuffleManagerId("mc", 1, exec_id)
        locs = [
            PartitionLocation(
                mid, pid, BlockLocation(0, 3, mkey + pid, source_map=map_id)
            )
            for pid in (0, 1)
        ]
        return PublishPartitionLocationsMsg(
            self.SID, -1, locs, num_map_outputs=1
        )

    def _replica_msg(self):
        from sparkrdma_tpu.locations import (
            BlockLocation,
            PartitionLocation,
            ShuffleManagerId,
        )
        from sparkrdma_tpu.rpc import PublishPartitionLocationsMsg

        mid = ShuffleManagerId("mc", 1, "exec-b")
        locs = [
            PartitionLocation(
                mid,
                pid,
                BlockLocation(0, 3, 90 + pid, replica_of="exec-a", source_map=0),
            )
            for pid in (0, 1)
        ]
        return PublishPartitionLocationsMsg(self.SID, -1, locs, num_map_outputs=0)

    def build(self, sched: CooperativeScheduler) -> None:
        from sparkrdma_tpu.analysis.lockorder import named_lock
        from sparkrdma_tpu.metastore import ShardedMetaStore
        from sparkrdma_tpu.obs import get_registry
        from sparkrdma_tpu.obs.trace import Tracer
        from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
        from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
        from sparkrdma_tpu.utils.config import TpuShuffleConf

        # storage-only construction: the protocol methods under test
        # (_handle_publish, _on_peer_lost) are pure registry mutators —
        # they need the driver-side dicts and locks, not a transport.
        # The location registry itself is a REAL sharded metastore (the
        # control-plane HA hub): publishes here run the epoch-fenced
        # route/apply path, not a plain dict insert
        m = object.__new__(TpuShuffleManager)
        m.is_driver = True
        m.executor_id = "driver"
        m.tracer = Tracer(role="driver", enabled=False)
        m.registry = get_registry()
        m.telemetry = None
        m._lock = named_lock("manager.state", hot=True)
        m._shuffle_locks = {}
        m.metastore = ShardedMetaStore(TpuShuffleConf({}), role="driver")
        m._registered = {
            self.SID: BaseShuffleHandle(self.SID, self.NUM_MAPS, HashPartitioner(2))
        }
        m._maps_done = {}
        m._maps_by_exec = {}
        m._deferred_fetches = {}
        m._map_owner = {}
        m._replica_locations = {}
        m._manager_ids = {}
        m._lost_executors = set()
        self.m = m
        self.loss_started = False
        self._last_done: Optional[int] = None

        def lose() -> None:
            self.loss_started = True
            m._on_peer_lost("exec-a")

        sched.spawn("pub_a", lambda: m._handle_publish(self._publish_msg("exec-a", 0, 10)))
        sched.spawn("pub_b", lambda: m._handle_publish(self._publish_msg("exec-b", 1, 20)))
        sched.spawn("pub_spec", lambda: m._handle_publish(self._publish_msg("exec-c", 0, 30)))
        sched.spawn("replica", lambda: m._handle_publish(self._replica_msg()))
        sched.spawn("loss", lose)

    def _serving(self) -> Dict[Tuple[int, int], List]:
        by_key: Dict[Tuple[int, int], List] = {}
        for pid, locs in self.m._partition_locations.get(self.SID, {}).items():
            for loc in locs:
                by_key.setdefault((pid, loc.block.source_map), []).append(loc)
        return by_key

    def check(self) -> List[str]:
        v: List[str] = []
        m = self.m
        serving = self._serving()
        done = m._maps_done.get(self.SID, 0)
        if not self.loss_started:
            if any(loc.block.is_replica for locs in serving.values() for loc in locs):
                v.append("replica serving while its primary lives")
        for (pid, map_id), locs in serving.items():
            if len(locs) > 1:
                v.append(
                    f"double-serve: {len(locs)} locations for partition "
                    f"{pid} map {map_id}"
                )
        if not 0 <= done <= self.NUM_MAPS:
            v.append(f"barrier out of range: {done}")
        maps_serving = {k[1] for k in serving}
        if done > len(maps_serving):
            v.append(
                f"barrier {done} exceeds {len(maps_serving)} serving maps"
            )
        if self._last_done is not None and done < self._last_done:
            if not self.loss_started:
                v.append(
                    f"barrier decreased {self._last_done}->{done} without loss"
                )
        self._last_done = done
        return v

    def final(self) -> List[str]:
        v = self.check()
        # replicas in the replica registry must never ALSO serve
        serving_ids = {
            id(loc)
            for locs in self.m._partition_locations.get(self.SID, {}).values()
            for loc in locs
        }
        for locs in self.m._replica_locations.get(self.SID, {}).values():
            for loc in locs:
                if id(loc) in serving_ids:
                    v.append("location in both replica and primary registries")
        return v

    def result(self) -> bytes:
        # canonical: which (pid, map) pairs ended up serving — identical
        # across schedules is NOT required (loss ordering legitimately
        # changes coverage), so the serial-identity oracle gets a
        # constant here and the registry invariants above carry the load
        return b"replica_promotion"


# ----------------------------------------------------------------------
# model 3: speculative reduce first-finisher-wins vs cancel (PR 10)
# ----------------------------------------------------------------------
class _SpecWorker:
    """Task-protocol stub: one executor's reduce/cancel surface."""

    def __init__(self, model: "SpeculationModel", executor_id: str, delay: float):
        self.model = model
        self.executor_id = executor_id
        self.delay = delay

    def request(self, req, timeout_s: Optional[float] = None):
        kind = req["kind"]
        if kind == "reduce":
            with self.model.lock:
                self.model.events.append(("issue", self.executor_id))
            schedule_point("proto", f"reduce:{self.executor_id}")
            time.sleep(self.delay)  # virtual under the scheduler
            with self.model.lock:
                self.model.events.append(("finish", self.executor_id))
            return {"by": self.executor_id, "range": (req["start"], req["end"])}
        if kind == "cancel_reduce":
            with self.model.lock:
                self.model.events.append(("cancel", self.executor_id))
            return True
        raise AssertionError(f"unexpected request {kind}")


class _SpecDriver:
    executor_id = "driver"

    def __init__(self, suspects: Set[str]):
        self._suspects = suspects

    @property
    def health(self):
        return self

    def suspects(self) -> Set[str]:
        return set(self._suspects)


class _SpecConf:
    elastic_speculation = True
    elastic_speculation_check_ms = 100


@register_model
class SpeculationModel(ProtocolModel):
    """One reduce range lands on a flagged executor; the REAL
    SpeculativeReducePhase monitor clones it onto a healthy peer and the
    two attempts race to settle. Attempt scheduling (SimPool), the
    monitor's poll timer, and completion callbacks are all explored.

    Oracles: at most two attempts ever issued and at most one clone
    (exactly one speculation in flight); exactly one winner publishes —
    the first SETTLER wins and atomically cancels everyone else still
    in flight, so the published winner can never be an attempt that was
    cancelled (a cancelled winner means a late loser overwrote the
    settled result); the loser is drained (a cancel reaches it)
    whenever both attempts were issued.
    """

    name = "speculation"

    def build(self, sched: CooperativeScheduler) -> None:
        from sparkrdma_tpu.elastic.speculation import SpeculativeReducePhase

        self.lock = threading.Lock()  # raw: guards the event log only
        self.events: List[Tuple[str, str]] = []
        self.outcome: Optional[Tuple[Dict, Dict]] = None
        # the monitor's first poll fires at virtual 0.1 and clones onto
        # exec-fast, whose 0.5 sleep lands on the SAME virtual deadline
        # as exec-slow's 0.6 — both attempts wake at t=0.6, so the
        # picker explores both settle orders (the late-loser race the
        # first-finisher guard defends against)
        slow = _SpecWorker(self, "exec-slow", delay=0.6)
        fast = _SpecWorker(self, "exec-fast", delay=0.5)
        phase = SpeculativeReducePhase(
            driver=_SpecDriver({"exec-slow"}),
            pool=SimPool(sched, prefix="attempt"),
            conf=_SpecConf(),
            live_workers=lambda: [slow, fast],
            handle=type("H", (), {"shuffle_id": 3})(),
            reduce_fn=None,
            tenant=None,
        )

        def run_phase() -> None:
            self.outcome = phase.run([(0, (0, 2), slow)])

        sched.spawn("phase", run_phase)

    def _counts(self) -> Dict[str, int]:
        with self.lock:
            evs = list(self.events)
        return {
            kind: sum(1 for k, _ in evs if k == kind)
            for kind in ("issue", "finish", "cancel")
        }

    def check(self) -> List[str]:
        v: List[str] = []
        c = self._counts()
        if c["issue"] > 2:
            v.append(f"{c['issue']} attempts issued for one range (max 2)")
        inflight = c["issue"] - c["finish"]
        if inflight > 2:
            v.append(f"{inflight} attempts in flight (max 2)")
        return v

    def final(self) -> List[str]:
        v = self.check()
        c = self._counts()
        if self.outcome is None:
            v.append("phase.run never returned")
            return v
        results, failures = self.outcome
        if failures:
            v.append(f"unexpected failures: {failures}")
        if set(results) != {0}:
            v.append(f"expected exactly range 0 settled, got {sorted(results)}")
            return v
        # either attempt may legally settle first (settle order is the
        # picker's choice), but the first settler cancels every other
        # attempt still in flight before anyone else can run — so a
        # winner that RECEIVED a cancel must have overwritten the
        # settled result after losing
        with self.lock:
            cancelled = {eid for kind, eid in self.events if kind == "cancel"}
        winner = results[0]["by"]
        if winner in cancelled:
            v.append(
                f"winner {winner} was cancelled as a loser: a late loser "
                f"overwrote the settled result"
            )
        if c["issue"] == 2 and c["cancel"] == 0:
            v.append("loser attempt was never drained (no cancel issued)")
        return v

    def result(self) -> bytes:
        if self.outcome is None:
            return b""
        results, _ = self.outcome
        # canonical: the settled range payload minus the executor tag
        # (which executor won is legitimately schedule-dependent)
        return repr(sorted((idx, r["range"]) for idx, r in results.items())).encode()


# ----------------------------------------------------------------------
# model 4: quota backpressure vs frees (tenancy/quota.py, PR 8)
# ----------------------------------------------------------------------
@register_model
class QuotaModel(ProtocolModel):
    """Tenant A fills its quota, blocks on a second charge, and a
    peer thread frees A's bytes; tenant B charges concurrently. The
    REAL QuotaBroker condition-variable protocol runs under virtual
    time (the overrun deadline is a logical timer).

    Oracles: a blocked tenant holds bytes (B, holding zero, is never
    blocked — isolation); no overrun fires while a releaser exists
    (blocked charges are woken by releases, the deadline is a last
    resort); the ledger never goes negative and drains to zero.
    """

    name = "quota_stall"

    def build(self, sched: CooperativeScheduler) -> None:
        from sparkrdma_tpu.obs import get_registry
        from sparkrdma_tpu.tenancy.quota import QuotaBroker

        self.broker = QuotaBroker("modelcheck", 100, block_max_ms=1000)
        self.threads_tenant = {"tA": "A", "tR": "A", "tB": "B"}
        self._overruns = get_registry().counter(
            "tenant.quota_overruns", tenant="A", resource="modelcheck"
        )
        self._overruns0 = self._overruns.value
        self.sched = sched
        charged80 = threading.Event()
        broker = self.broker

        def t_a() -> None:
            broker.charge("A", 80)
            charged80.set()
            broker.charge("A", 50)  # blocks until tR frees (quota 100)
            broker.release("A", 130)

        def t_r() -> None:
            # a peer of tenant A frees the first batch — strictly after
            # it was charged, as any real release pairs with its get
            charged80.wait()
            broker.release("A", 80)

        def t_b() -> None:
            broker.charge("B", 30)
            broker.release("B", 30)

        sched.spawn("tA", t_a)
        sched.spawn("tR", t_r)
        sched.spawn("tB", t_b)

    def check(self) -> List[str]:
        v: List[str] = []
        for t, u in self.broker._usage.items():
            if u < 0:
                v.append(f"negative usage for tenant {t}: {u}")
        # a thread blocked on the broker's condition must hold bytes
        cond_key = id(self.broker._cond)
        for t in self.sched.threads:
            if (
                t.state == "blocked"
                and t.pending.key == cond_key
                and t.name in self.threads_tenant
            ):
                tenant = self.threads_tenant[t.name]
                if self.broker._usage.get(tenant, 0) <= 0:
                    v.append(
                        f"{t.name} blocked on quota while tenant {tenant} "
                        f"holds no bytes (isolation breach)"
                    )
        return v

    def final(self) -> List[str]:
        v = self.check()
        overruns = self._overruns.value - self._overruns0
        if overruns:
            v.append(
                f"{overruns} quota overrun(s) fired although a releaser "
                f"frees the blocked tenant's bytes"
            )
        for t in ("A", "B"):
            u = self.broker._usage.get(t, 0)
            if u != 0:
                v.append(f"ledger not drained for tenant {t}: {u}")
        for t in self.sched.threads:
            if t.name == "tB" and t.block_count:
                v.append(
                    "tenant B (zero held bytes) blocked "
                    f"{t.block_count} time(s) — isolation breach"
                )
        return v

    def result(self) -> bytes:
        return b"quota_stall"


# ----------------------------------------------------------------------
# model 5: sharded metastore under lease fencing, sweep, and driver
# crash (sparkrdma_tpu/metastore, docs/RESILIENCE.md "Control-plane HA")
# ----------------------------------------------------------------------
@register_model
class MetaLeaseModel(ProtocolModel):
    """The REAL ShardedMetaStore under the three control-plane hazards
    at once: a publisher racing its own ``sweep_executor`` tombstone, a
    driver crash (``wipe``: entries gone, leases re-grant under bumped
    epochs, generation advances) racing in-flight epoch-fenced writes,
    and a re-adoption sweep from an OLDER takeover era racing the new
    one (generation fencing). Time is an injected clock the chaos
    thread advances past the lease TTL.

    Threads: pub_a (exec-a's map, swept mid-flight), pub_b (exec-b's
    map, survives), chaos (sweep exec-a -> wipe -> expire leases ->
    generation-fenced adopt re-publish of exec-b), stale_pub (adopt
    sweep fenced at the PRE-wipe generation — must die, not merge),
    reader (epoch-fenced resolves).

    Oracles: no entry predates the wipe (a write routed under a
    pre-crash lease can never land in the post-crash registry); a dead
    shard serves nothing; no tombstoned publisher's location survives
    the final state; the stale-generation sweep leaves no trace; a
    resolve never returns two copies of one (pid, source_map) slot
    (follower double-serve); expired leases cannot renew or serve
    without a takeover epoch bump. ``result()`` is the canonical final
    registry — byte-identical across schedules.
    """

    name = "meta_lease"
    SID = 5

    def _locs(self, exec_id: str, map_id: int, mkey: int):
        from sparkrdma_tpu.locations import (
            BlockLocation,
            PartitionLocation,
            ShuffleManagerId,
        )

        mid = ShuffleManagerId("mc", 1, exec_id)
        return [
            PartitionLocation(
                mid, pid, BlockLocation(0, 3, mkey + pid, source_map=map_id)
            )
            for pid in (0, 1)
        ]

    def build(self, sched: CooperativeScheduler) -> None:
        from sparkrdma_tpu.metastore import ShardedMetaStore, StaleEpochError
        from sparkrdma_tpu.utils.config import TpuShuffleConf

        self.now = [0.0]  # injected clock: ONLY chaos advances it
        conf = TpuShuffleConf({
            "tpu.shuffle.metastore.peers": 3,
            "tpu.shuffle.metastore.vnodes": 4,
            "tpu.shuffle.metastore.rangeSize": 1,
            "tpu.shuffle.metastore.leaseTtlMs": 5000,
            "tpu.shuffle.metastore.replicas": 1,
            "tpu.shuffle.metastore.retryBackoffMs": 1,
        })
        self.store = ShardedMetaStore(
            conf, role="mc-meta", clock=lambda: self.now[0]
        )
        self.StaleEpochError = StaleEpochError
        self.gen0 = self.store.generation  # the pre-crash era
        self.wipe_gen: Optional[int] = None
        self.post_wipe_epochs: Dict[str, int] = {}
        self.reads: List[List] = []
        store = self.store

        def pub(exec_id: str, map_id: int, mkey: int) -> None:
            try:
                store.publish(self.SID, self._locs(exec_id, map_id, mkey))
            except StaleEpochError:
                pass  # retry ladder exhausted: dropped whole, by contract

        def chaos() -> None:
            store.sweep_executor("exec-a", self.SID)
            gen = store.wipe()
            self.wipe_gen = gen
            # record the post-crash epochs, then lapse every lease: any
            # serving past this point must go through takeover
            self.post_wipe_epochs = {
                p: store._leases.epoch(p) for p in store.live_peers()
            }
            self.now[0] += store._leases.ttl_s + 1.0
            try:
                # the re-adoption sweep of the CURRENT era (an executor
                # re-publishing its committed map, generation-fenced)
                store.publish(
                    self.SID, self._locs("exec-b", 1, 20),
                    fence_generation=gen,
                )
            except StaleEpochError:
                pass

        def stale_pub() -> None:
            # an adoption sweep still fenced at the PRE-wipe generation:
            # before the wipe it applies (and is wiped with everything
            # else); after it, it must be rejected whole
            try:
                store.publish(
                    self.SID, self._locs("exec-stale", 2, 40),
                    fence_generation=self.gen0,
                )
            except StaleEpochError:
                pass

        def reader() -> None:
            for _ in range(2):
                try:
                    self.reads.append(store.resolve(self.SID, 0))
                except StaleEpochError:
                    pass

        sched.spawn("pub_a", lambda: pub("exec-a", 0, 10))
        sched.spawn("pub_b", lambda: pub("exec-b", 1, 20))
        sched.spawn("chaos", chaos)
        sched.spawn("stale_pub", stale_pub)
        sched.spawn("reader", reader)

    def _entries(self) -> List[Tuple[str, int, int, int]]:
        """(executor, pid, source_map, gen_applied) across all shards."""
        out = []
        for shard in self.store._shards.values():
            for (sid, pid), bucket in list(shard.entries.items()):
                for loc, gen in list(bucket):
                    out.append(
                        (loc.manager_id.executor_id, pid,
                         loc.block.source_map, gen)
                    )
        return out

    def check(self) -> List[str]:
        v: List[str] = []
        for shard in self.store._shards.values():
            if not shard.alive and shard.entries:
                v.append(f"dead shard {shard.name} still holds entries")
        if self.wipe_gen is not None:
            for exec_id, pid, _sm, gen in self._entries():
                if gen < self.wipe_gen:
                    v.append(
                        f"entry from {exec_id} pid {pid} predates the "
                        f"wipe (applied gen {gen} < {self.wipe_gen}): a "
                        f"pre-crash write landed in the post-crash "
                        f"registry"
                    )
        for locs in self.reads:
            slots: Dict[Tuple[int, int], int] = {}
            for loc in locs:
                k = (loc.partition_id, loc.block.source_map)
                slots[k] = slots.get(k, 0) + 1
            for (pid, sm), n in slots.items():
                if n > 1:
                    v.append(
                        f"double-serve: resolve returned {n} copies of "
                        f"partition {pid} map {sm}"
                    )
        return v

    def final(self) -> List[str]:
        v = self.check()
        entries = self._entries()
        execs = {e for e, _, _, _ in entries}
        if "exec-a" in execs:
            v.append(
                "tombstoned publisher exec-a survives in the registry "
                "(the per-shard sweep check has a window)"
            )
        if "exec-stale" in execs:
            v.append(
                "stale-generation adoption sweep merged into the new era"
            )
        for pid in (0, 1):
            n = sum(
                1 for e, p, _sm, _g in entries
                if e == "exec-b" and p == pid
            )
            # one copy per owner (primary + follower), never more
            if not 1 <= n <= 1 + self.store.replicas:
                v.append(
                    f"re-adoption incomplete or duplicated: exec-b pid "
                    f"{pid} held {n} times (want 1..{1 + self.store.replicas})"
                )
        # expired leases must not serve without a takeover epoch bump
        _, routed = self.store._route(self.SID, 0)
        for peer, epoch in routed:
            before = self.post_wipe_epochs.get(peer)
            if before is not None and epoch <= before:
                v.append(
                    f"peer {peer} serves epoch {epoch} although its "
                    f"lease lapsed at epoch {before}: expired lease "
                    f"served without takeover"
                )
        # a renew carrying a superseded epoch must fence
        leases = self.store._leases
        peer = self.store.live_peers()[0]
        cur = leases.epoch(peer)
        if cur > 1:
            try:
                leases.renew(peer, cur - 1)
                v.append("renew accepted a superseded epoch")
            except self.StaleEpochError:
                pass
        # ... and so must a renew of a lapsed lease (re-acquire via
        # takeover, never silently resurrect)
        self.now[0] += leases.ttl_s + 1.0
        try:
            leases.renew(peer, leases.epoch(peer))
            v.append("renew resurrected an expired lease")
        except self.StaleEpochError:
            pass
        return v

    def result(self) -> bytes:
        # canonical final registry: primary-copy (executor, pid, map)
        # triples — identical across schedules (exec-a swept, stale
        # sweep dead, exec-b re-adopted exactly once per slot)
        ents = self.store.entries_for_shuffle(self.SID)
        return repr(sorted(
            (loc.manager_id.executor_id, pid, loc.block.source_map)
            for pid, locs in sorted(ents.items())
            for loc in locs
        )).encode()
