"""Cooperative scheduler: run real protocol threads one at a time.

Deterministic-simulation testing in the loom/shuttle style: the model's
threads are real ``threading.Thread`` objects running the REAL protocol
code, but only ONE ever runs at a time. Each thread parks at every
*schedule point* — ``OrderedLock`` acquire/release (grafted into
analysis/lockorder.py), pipeline queue handoffs, task-protocol
send/recv, timer fires, and explicit ``proto`` seams in the protocol
bodies — and the scheduler picks which parked thread resumes next. A
seeded picker makes any schedule replayable; an exhaustive picker
enumerates them.

Time is virtual: ``time.sleep``/``Condition.wait(timeout)``/
``Event.wait(timeout)`` park the thread with a logical deadline, and
the clock jumps to the earliest deadline only when nothing is runnable.
No wall-clock waits, so a full schedule runs in microseconds and
timers/backoffs/deadlines fire in a controlled logical order.

Blocking primitives are virtualized only for scheduler-registered
threads: while a scheduler is active, ``threading.Condition`` wait /
notify, ``threading.Event`` wait/set, and ``time`` sleep/monotonic/
perf_counter dispatch to cooperative implementations for sim threads
and to the saved real functions for everything else. The harness's own
handshakes use raw ``threading.Lock`` gates (never Condition/Event —
those are patched) so the machinery cannot intercept itself.

Atomicity rule: code between two schedule points is atomic under this
scheduler. Raw ``threading.Lock`` critical sections are therefore safe
exactly when they contain no schedule point; sections that do must use
``named_lock`` so the scheduler tracks ownership (see the lock
skip-list: hot bookkeeping locks like ``metrics.registry`` are tracked
but never parked on, so metric increments under raw locks stay atomic).

Quiescent points — park points where no sim thread holds any
``OrderedLock`` — are where invariant oracles run: protocol state is
between critical sections, so the oracle sees only states the protocol
itself considers consistent.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

__all__ = [
    "Action",
    "CooperativeScheduler",
    "DeadlockError",
    "ModelCrash",
    "OracleViolation",
    "ReplayDivergence",
    "ScheduleTooLong",
    "SimFuture",
    "SimPool",
    "active",
    "schedule_point",
]


class OracleViolation(AssertionError):
    """An invariant oracle failed at a quiescent point."""


class DeadlockError(AssertionError):
    """No thread runnable and no pending virtual deadline."""


class ScheduleTooLong(AssertionError):
    """Schedule exceeded the per-run step bound (livelock guard)."""


class ReplayDivergence(AssertionError):
    """A replayed choice named a thread that is not runnable."""


class ModelCrash(AssertionError):
    """A sim thread died on an unhandled exception."""


class _Killed(BaseException):
    """Raised inside sim threads to unwind them during drain.

    BaseException so protocol-level ``except Exception`` fallbacks do
    not swallow it.
    """


#: the active scheduler, or None. Interception sites load this ONE
#: module attribute and branch — the disabled cost, exactly like the
#: lock-order detector's ``_det.enabled``.
active: Optional["CooperativeScheduler"] = None


def schedule_point(kind: str, name: str) -> None:
    """Explicit seam in protocol code; no-op unless a scheduler runs."""
    sched = active
    if sched is not None:
        sched.point(kind, name)


class Action:
    """What a parked thread will do next — the exploration alphabet.

    ``key`` identifies the resource for enabledness and independence
    (the lock instance id for lock actions, None otherwise).
    """

    __slots__ = ("kind", "name", "key")

    def __init__(self, kind: str, name: str, key: Optional[int] = None):
        self.kind = kind
        self.name = name
        self.key = key

    def __repr__(self) -> str:
        return f"{self.kind}:{self.name}"


class SimThread:
    """One scheduler-controlled thread. Parks on a raw-Lock gate."""

    def __init__(self, sched: "CooperativeScheduler", name: str, fn: Callable):
        self.sched = sched
        self.name = name
        self.fn = fn
        # held closed except for the instant the scheduler resumes us
        self.gate = threading.Lock()
        self.gate.acquire()
        self.state = "runnable"  # runnable | blocked | finished
        self.pending = Action("start", name)
        self.deadline: Optional[float] = None
        self.notified = False
        self.block_count = 0
        self.exc: Optional[BaseException] = None
        self.thread = threading.Thread(
            target=self._main, name=f"mc:{name}", daemon=True
        )

    def _main(self) -> None:
        self.sched._register(self)
        self.gate.acquire()  # first resume
        try:
            if self.sched._draining:
                raise _Killed()
            self.fn()
        except _Killed:
            pass
        except BaseException as e:  # noqa: BLE001 — surfaced as ModelCrash
            self.exc = e
        finally:
            self.state = "finished"
            self.sched._control.release()


class CooperativeScheduler:
    """Owns the sim threads, the virtual clock, and the step loop."""

    #: lock NAMES whose acquire/release never park (hot bookkeeping
    #: locks acquired under raw locks in protocol code; parking there
    #: would really-block another sim thread). Still ownership-tracked.
    no_park_locks: Set[str] = {"metrics.registry", "quota.table"}

    def __init__(self, trace_actions: bool = False):
        self.threads: List[SimThread] = []
        self._by_ident: Dict[int, SimThread] = {}
        # scheduler waits here; a parking sim thread releases it
        self._control = threading.Lock()
        self._control.acquire()
        self._meta = threading.Lock()  # spawn/waiter tables
        self.now = 0.0
        # id(OrderedLock) -> (owner SimThread, reentry count)
        self.owners: Dict[int, Tuple[SimThread, int]] = {}
        self.lock_names: Dict[int, str] = {}
        # id(waitable) -> FIFO of blocked SimThreads
        self.waiters: Dict[int, List[SimThread]] = {}
        self.trace: List[str] = []
        self.actions: List[str] = [] if trace_actions else None  # type: ignore[assignment]
        self.on_quiescent: Optional[Callable[[], None]] = None
        self._draining = False
        self._started = False
        # True while a sim thread is inside Thread.start() (see spawn)
        self._spawning = False

    # -- setup ----------------------------------------------------------
    def spawn(self, name: str, fn: Callable) -> SimThread:
        t = SimThread(self, name, fn)
        with self._meta:
            self.threads.append(t)
        if self._started:
            # Thread.start() blocks on the child's internal _started
            # Event, which the child's bootstrap sets at a WALL-CLOCK
            # moment. The global Event/Condition patches must not turn
            # that into a schedule point, or whether the spawner parks
            # there is a real race and identical prefixes stop being
            # replayable. Only one sim thread runs at a time, so a
            # plain flag is race-free.
            self._spawning = True
            try:
                t.thread.start()
            finally:
                self._spawning = False
        return t

    def _register(self, t: SimThread) -> None:
        with self._meta:
            self._by_ident[threading.get_ident()] = t

    def _current(self) -> Optional[SimThread]:
        return self._by_ident.get(threading.get_ident())

    # -- park/resume handshake -----------------------------------------
    def _park(
        self,
        t: SimThread,
        action: Action,
        blocked: bool = False,
        deadline: Optional[float] = None,
    ) -> None:
        if self._draining:
            raise _Killed()
        t.pending = action
        t.deadline = deadline
        # NB: ``notified`` is NOT cleared here — a notifier may run while
        # this thread is parked releasing the waitable's lock (cond.wait
        # registers as waiter first), and that early notification must
        # survive until the wait-park checks it. Waiters clear the flag
        # at wait ENTRY instead.
        if blocked:
            t.block_count += 1
        t.state = "blocked" if blocked else "runnable"
        self._control.release()
        t.gate.acquire()
        if self._draining:
            raise _Killed()
        t.state = "running"

    def point(self, kind: str, name: str, key: Optional[int] = None) -> None:
        t = self._current()
        if t is None:
            return
        self._park(t, Action(kind, name, key))

    # -- lock interception (called from OrderedLock) --------------------
    def before_lock_acquire(self, lock) -> None:
        t = self._current()
        if t is None:
            return
        if lock.name in self.no_park_locks:
            return
        self._park(t, Action("lock.acquire", lock.name, key=id(lock)))

    def after_lock_acquire(self, lock) -> None:
        t = self._current()
        if t is None:
            return
        self.lock_names[id(lock)] = lock.name
        owner = self.owners.get(id(lock))
        if owner is not None and owner[0] is not t:
            # a non-sim thread slipped in, or tracking drifted: surface
            raise OracleViolation(
                f"lock {lock.name!r} acquired by {t.name} while scheduler "
                f"thought {owner[0].name} held it"
            )
        self.owners[id(lock)] = (t, (owner[1] + 1) if owner else 1)

    def after_lock_release(self, lock) -> None:
        t = self._current()
        if t is None:
            return
        owner = self.owners.get(id(lock))
        if owner is not None and owner[0] is t:
            if owner[1] > 1:
                self.owners[id(lock)] = (t, owner[1] - 1)
            else:
                del self.owners[id(lock)]
        if lock.name in self.no_park_locks:
            return
        self._park(t, Action("lock.release", lock.name, key=id(lock)))

    # -- cooperative waitables -----------------------------------------
    def _wait_on(self, key: int, name: str, timeout: Optional[float]) -> bool:
        """Block the current sim thread on ``key``; True = notified."""
        t = self._current()
        assert t is not None
        t.notified = False
        with self._meta:
            self.waiters.setdefault(key, []).append(t)
        deadline = self.now + timeout if timeout is not None else None
        self._park(
            t, Action("wait", name, key=key), blocked=True, deadline=deadline
        )
        if not t.notified:
            with self._meta:
                q = self.waiters.get(key, [])
                if t in q:
                    q.remove(t)
        return t.notified

    def _notify_key(self, key: int, n: Optional[int] = None) -> None:
        with self._meta:
            q = self.waiters.get(key, [])
            woken = q[:] if n is None else q[:n]
            del q[: len(woken)]
        for t in woken:
            t.notified = True
            t.state = "runnable"

    # -- the step loop --------------------------------------------------
    def _enabled(self, t: SimThread) -> bool:
        if t.state != "runnable":
            return False
        a = t.pending
        if a.kind == "lock.acquire" and a.key is not None:
            owner = self.owners.get(a.key)
            return owner is None or owner[0] is t
        return True

    def runnable_threads(self) -> List[SimThread]:
        return [
            t
            for t in self.threads
            if t.state != "finished" and self._enabled(t)
        ]

    def run(self, picker, max_steps: int = 20000) -> None:
        """Drive every sim thread to completion under ``picker``.

        ``picker.pick(step, runnable)`` returns the SimThread to resume.
        Raises the first oracle violation / deadlock / crash / replay
        divergence; the caller owns interpretation.
        """
        global active
        if active is not None:
            raise RuntimeError("another CooperativeScheduler is active")
        active = self
        _patch()
        self._started = True
        try:
            for t in list(self.threads):
                t.thread.start()
            step = 0
            while True:
                live = [t for t in self.threads if t.state != "finished"]
                for t in self.threads:
                    if t.exc is not None:
                        raise ModelCrash(
                            f"thread {t.name} crashed: {t.exc!r}"
                        ) from t.exc
                if not live:
                    return
                runnable = [t for t in live if self._enabled(t)]
                if not runnable:
                    deadlines = [
                        t.deadline
                        for t in live
                        if t.state == "blocked" and t.deadline is not None
                    ]
                    if not deadlines:
                        held = {
                            self.lock_names.get(k, str(k)): o[0].name
                            for k, o in self.owners.items()
                        }
                        raise DeadlockError(
                            f"deadlock: {[t.name for t in live]} all blocked, "
                            f"no pending deadline; held locks: {held}"
                        )
                    self.now = max(self.now, min(deadlines))
                    for t in live:
                        if (
                            t.state == "blocked"
                            and t.deadline is not None
                            and t.deadline <= self.now
                        ):
                            t.state = "runnable"  # timed out, not notified
                    continue
                chosen = picker.pick(step, runnable)
                self.trace.append(chosen.name)
                if self.actions is not None:
                    self.actions.append(f"{chosen.name}@{chosen.pending!r}")
                step += 1
                if step > max_steps:
                    raise ScheduleTooLong(
                        f"schedule exceeded {max_steps} steps (livelock?)"
                    )
                chosen.gate.release()
                self._control.acquire()
                if (
                    self.on_quiescent is not None
                    and not self.owners
                    and not self._draining
                ):
                    self.on_quiescent()
        finally:
            self._drain()
            _unpatch()
            active = None

    def _drain(self) -> None:
        """Unwind unfinished sim threads via _Killed, one at a time —
        every parked thread is woken exactly once and releases the
        control lock exactly once on its way out, keeping the handshake
        balanced even while threads unwind through protocol cleanup."""
        self._draining = True
        for _ in range(len(self.threads) + 1000):
            live = [
                t
                for t in self.threads
                if t.state != "finished" and t.thread.ident is not None
            ]
            if not live:
                break
            try:
                live[0].gate.release()
            except RuntimeError:
                pass
            self._control.acquire()
        for t in self.threads:
            if t.thread.is_alive() or t.thread.ident is not None:
                t.thread.join(timeout=5.0)


class SimFuture:
    """Future for :class:`SimPool`; callbacks run on the worker thread,
    exactly like ``concurrent.futures`` — so first-finisher callback
    races are part of the explored schedule space."""

    def __init__(self) -> None:
        self._done = False
        self._result = None
        self._exc: Optional[BaseException] = None
        self._cbs: List[Callable] = []

    def done(self) -> bool:
        return self._done

    def result(self):
        if self._exc is not None:
            raise self._exc
        return self._result

    def exception(self) -> Optional[BaseException]:
        return self._exc

    def add_done_callback(self, cb: Callable) -> None:
        if self._done:
            cb(self)
        else:
            self._cbs.append(cb)

    def _finish(self, result, exc: Optional[BaseException]) -> None:
        self._result = result
        self._exc = exc
        self._done = True
        for cb in self._cbs:
            cb(self)


class SimPool:
    """Executor facade that spawns a sim thread per submit."""

    def __init__(self, sched: CooperativeScheduler, prefix: str = "pool"):
        self._sched = sched
        self._prefix = prefix
        self._n = 0

    def submit(self, fn: Callable, *args, **kwargs) -> SimFuture:
        fut = SimFuture()
        self._n += 1
        name = f"{self._prefix}-{self._n}"

        def run() -> None:
            try:
                result = fn(*args, **kwargs)
            except _Killed:
                raise
            except BaseException as e:  # noqa: BLE001 — future carries it
                fut._finish(None, e)
            else:
                fut._finish(result, None)

        self._sched.spawn(name, run)
        return fut


# ----------------------------------------------------------------------
# blocking-primitive virtualization (installed only while a scheduler
# is active; sim threads get cooperative semantics, everything else the
# saved real functions)
# ----------------------------------------------------------------------
_real_cond_wait = threading.Condition.wait
_real_cond_notify = threading.Condition.notify
_real_cond_notify_all = threading.Condition.notify_all
_real_event_wait = threading.Event.wait
_real_event_set = threading.Event.set
# time.sleep is save/restored at patch time, not import time: the
# lock-order detector patches it too (lockorder._activate), and the
# scheduler must put back whatever was installed when it started
_real_sleep = time.sleep
_real_monotonic = time.monotonic
_real_perf_counter = time.perf_counter
_patched = False


def _sim() -> Optional[SimThread]:
    sched = active
    if sched is None or sched._draining:
        return None
    return sched._current()


def _coop_cond_wait(self, timeout=None):
    t = _sim()
    sched = active
    if t is None or sched is None or sched._spawning:
        return _real_cond_wait(self, timeout)
    # register as waiter BEFORE releasing the lock: a notifier scheduled
    # during the release park must see us (no lost wakeup)
    t.notified = False
    with sched._meta:
        sched.waiters.setdefault(id(self), []).append(t)
    lock = self._lock
    lock.release()
    deadline = sched.now + timeout if timeout is not None else None
    try:
        # the release above is itself a park point — the notification may
        # already have landed while we were parked there; only park as
        # blocked if it hasn't (else we'd clobber our runnable state and
        # sleep to the deadline on a wakeup that already happened)
        if not t.notified:
            sched._park(
                t,
                Action("wait", "cond", key=id(self)),
                blocked=True,
                deadline=deadline,
            )
    finally:
        if not t.notified:
            with sched._meta:
                q = sched.waiters.get(id(self), [])
                if t in q:
                    q.remove(t)
    notified = t.notified
    lock.acquire()
    return notified


def _coop_cond_notify(self, n=1):
    sched = active
    if sched is None or sched._current() is None:
        return _real_cond_notify(self, n)
    sched._notify_key(id(self), n)
    if self._waiters:  # real (non-sim) waiters, if any
        _real_cond_notify(self, n)


def _coop_cond_notify_all(self):
    sched = active
    if sched is None or sched._current() is None:
        return _real_cond_notify_all(self)
    sched._notify_key(id(self), None)
    if self._waiters:
        _real_cond_notify_all(self)


def _coop_event_wait(self, timeout=None):
    t = _sim()
    sched = active
    if t is None or sched is None or sched._spawning:
        return _real_event_wait(self, timeout)
    if self.is_set():
        return True
    sched._wait_on(id(self), "event", timeout)
    return self.is_set()


def _coop_event_set(self):
    _real_event_set(self)
    sched = active
    if sched is not None and not sched._draining:
        sched._notify_key(id(self), None)


def _coop_sleep(secs):
    t = _sim()
    sched = active
    if t is None or sched is None:
        return _real_sleep(secs)
    sched._park(
        t,
        Action("timer", f"sleep:{secs:g}"),
        blocked=True,
        deadline=sched.now + max(0.0, secs),
    )


def _coop_monotonic():
    sched = active
    if sched is None or _sim() is None:
        return _real_monotonic()
    return sched.now


def _coop_perf_counter():
    sched = active
    if sched is None or _sim() is None:
        return _real_perf_counter()
    return sched.now


def _patch() -> None:
    global _patched, _real_sleep
    if _patched:
        return
    _real_sleep = time.sleep
    threading.Condition.wait = _coop_cond_wait  # type: ignore[method-assign]
    threading.Condition.notify = _coop_cond_notify  # type: ignore[method-assign]
    threading.Condition.notify_all = _coop_cond_notify_all  # type: ignore[method-assign]
    threading.Event.wait = _coop_event_wait  # type: ignore[method-assign]
    threading.Event.set = _coop_event_set  # type: ignore[method-assign]
    time.sleep = _coop_sleep
    time.monotonic = _coop_monotonic
    time.perf_counter = _coop_perf_counter
    _patched = True


def _unpatch() -> None:
    global _patched
    if not _patched:
        return
    threading.Condition.wait = _real_cond_wait  # type: ignore[method-assign]
    threading.Condition.notify = _real_cond_notify  # type: ignore[method-assign]
    threading.Condition.notify_all = _real_cond_notify_all  # type: ignore[method-assign]
    threading.Event.wait = _real_event_wait  # type: ignore[method-assign]
    threading.Event.set = _real_event_set  # type: ignore[method-assign]
    time.sleep = _real_sleep
    time.monotonic = _real_monotonic
    time.perf_counter = _real_perf_counter
    _patched = False
