"""sparkrdma_tpu — a TPU-native distributed shuffle framework.

A ground-up re-design of the capabilities of SparkRDMA (Mellanox's RDMA
ShuffleManager plugin for Apache Spark, reference layout documented in
SURVEY.md) for TPU hardware:

- map outputs stage into *registered memory*: host arenas managed by a
  native C++ allocator plus HBM-resident ``jax.Array`` slabs
  (reference: RdmaBuffer.java / RdmaBufferManager.java),
- block locations ``(address, length, mkey)`` are published to a driver
  metadata hub over a small 4-message RPC protocol
  (reference: RdmaRpcMsg.scala / RdmaShuffleManager.scala),
- reducers pull bytes with one-sided READs served by a passive peer IO
  plane on the host path (reference: IBV_WR_RDMA_READ in
  RdmaChannel.java:360-393) and by an XLA ``shard_map``/``all_to_all``
  exchange program over ICI/DCN on the device path,
- everything is flow-controlled, pooled, and size-classed the way the
  reference's 100GbE operating point was tuned.

Layer map (mirrors SURVEY.md §1): ``utils.config`` (L0 config),
``memory`` + ``native`` (L3 registered memory), ``locations`` + ``rpc``
(L4 control plane), ``transport`` (L2), ``shuffle`` (L5/L6 manager,
writers, reader), ``engine`` (the Spark-role host engine), ``parallel``
+ ``ops`` (TPU device exchange plane), ``models`` (benchmark
workloads).
"""

from sparkrdma_tpu.version import __version__

__all__ = ["__version__"]
