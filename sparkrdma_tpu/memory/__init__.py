from sparkrdma_tpu.memory.registry import ProtectionDomain
from sparkrdma_tpu.memory.buffer import TpuBuffer
from sparkrdma_tpu.memory.buffer_manager import TpuBufferManager
from sparkrdma_tpu.memory.registered_buffer import RegisteredBuffer
from sparkrdma_tpu.memory.mapped_file import MappedFile

__all__ = [
    "ProtectionDomain",
    "TpuBuffer",
    "TpuBufferManager",
    "RegisteredBuffer",
    "MappedFile",
]
