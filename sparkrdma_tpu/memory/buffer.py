"""TpuBuffer — one off-heap, optionally-registered allocation.

TPU-native analogue of RdmaBuffer.java (reference: /root/reference/src/
main/java/org/apache/spark/shuffle/rdma/RdmaBuffer.java). The reference
allocates off-JVM-heap memory with ``sun.misc.Unsafe.allocateMemory``
(:55-64), optionally registers it as an RDMA memory region with
LOCAL_WRITE|REMOTE_WRITE|REMOTE_READ access (:81-88), and wraps the raw
address as a DirectByteBuffer (:114-136).

Here the allocation is an anonymous ``mmap`` (page-aligned, outside the
Python object heap) by default: ``mmap.close()`` refuses to free while
exported sub-views (open streams) exist, which makes ``free()``
leak-safe instead of use-after-free under still-open readers. The
native C++ arena (sparkrdma_tpu.native) backs allocations whose
lifetime the framework fully controls (``arena=True`` — staging copies,
bench buffers); its ``free()`` is unconditional, so it must never be
handed to consumer-owned streams. Registration inserts the region into
the endpoint's :class:`~sparkrdma_tpu.memory.registry.ProtectionDomain`,
yielding the ``mkey`` used by remote one-sided READs.
"""

from __future__ import annotations

import mmap
from typing import Optional

from sparkrdma_tpu.memory.registry import ProtectionDomain
from sparkrdma_tpu.native.arena import NativeArena, native_arena_available


class TpuBuffer:
    """A single allocation with optional PD registration."""

    def __init__(
        self,
        pd: Optional[ProtectionDomain],
        length: int,
        register: bool = True,
        arena: bool = False,
    ):
        if length <= 0:
            raise ValueError(f"buffer length must be positive, got {length}")
        self.length = length
        self._arena: Optional[NativeArena] = None
        self._mmap: Optional[mmap.mmap] = None
        if arena and native_arena_available():
            self._arena = NativeArena.shared()
            self._alloc_id, view = self._arena.alloc(length)
        else:
            self._mmap = mmap.mmap(-1, length)
            view = memoryview(self._mmap)
        self._view: Optional[memoryview] = view
        self._pd = pd
        self.mkey = 0
        if register:
            if pd is None:
                raise ValueError("registration requested but no ProtectionDomain")
            self.mkey = pd.register(view)
        self._freed = False

    # -- accessors --------------------------------------------------------
    @property
    def view(self) -> memoryview:
        if self._freed:
            raise ValueError("buffer already freed")
        assert self._view is not None
        return self._view

    @property
    def address(self) -> int:
        """Base offset of this buffer within its own region: always 0.

        The reference exposes the raw virtual address (RdmaBuffer.java:70);
        here addresses in :class:`BlockLocation` are offsets relative to
        the registered region identified by ``mkey``.
        """
        return 0

    def write(self, data, offset: int = 0) -> None:
        """Copy bytes in (reference Unsafe.copyMemory path, :101-112)."""
        n = len(data)
        self.view[offset : offset + n] = bytes(data) if not isinstance(
            data, (bytes, bytearray, memoryview)
        ) else data

    def read(self, offset: int = 0, length: Optional[int] = None) -> bytes:
        if length is None:
            length = self.length - offset
        return bytes(self.view[offset : offset + length])

    # -- lifecycle --------------------------------------------------------
    def free(self) -> None:
        if self._freed:
            return
        self._freed = True
        if self._pd is not None and self.mkey:
            self._pd.deregister(self.mkey)
        view, self._view = self._view, None
        if view is not None:
            view.release()
        if self._arena is not None:
            # arena memory is framework-owned; no consumer views may
            # outlive it (see class docstring), so the free is immediate
            self._arena.free(self._alloc_id)
            self._arena = None
        if self._mmap is not None:
            try:
                self._mmap.close()
            except BufferError:
                # live sub-views (unclosed streams): the mapping stays
                # until they die — leak-safe, never use-after-free
                pass
            self._mmap = None

    def __len__(self) -> int:
        return self.length
