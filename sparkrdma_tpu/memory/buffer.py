"""TpuBuffer — one off-heap, optionally-registered allocation.

TPU-native analogue of RdmaBuffer.java (reference: /root/reference/src/
main/java/org/apache/spark/shuffle/rdma/RdmaBuffer.java). The reference
allocates off-JVM-heap memory with ``sun.misc.Unsafe.allocateMemory``
(:55-64), optionally registers it as an RDMA memory region with
LOCAL_WRITE|REMOTE_WRITE|REMOTE_READ access (:81-88), and wraps the raw
address as a DirectByteBuffer (:114-136).

Here the allocation is an anonymous ``mmap`` (page-aligned, outside the
Python object heap) by default: ``mmap.close()`` refuses to free while
exported sub-views (open streams) exist, which makes ``free()``
leak-safe instead of use-after-free under still-open readers. The
native C++ arena (sparkrdma_tpu.native) backs allocations whose
lifetime the framework fully controls (``arena=True`` — staging copies,
bench buffers); its ``free()`` is unconditional, so it must never be
handed to consumer-owned streams. Registration inserts the region into
the endpoint's :class:`~sparkrdma_tpu.memory.registry.ProtectionDomain`,
yielding the ``mkey`` used by remote one-sided READs.
"""

from __future__ import annotations

import atexit
import mmap
import os
import secrets
import threading
from typing import Optional

from sparkrdma_tpu.memory.registry import ProtectionDomain
from sparkrdma_tpu.native.arena import NativeArena, native_arena_available

# Registered buffers are backed by /dev/shm files when possible so the
# native transport can advertise a (path, offset) same-host fast path
# (peers pread the bytes from page cache instead of streaming them).
# Unguessable names prevent cross-host path collisions: a peer that can
# open the path IS on this host. Files unlink on free() and at normal
# exit (atexit). atexit does NOT run on SIGKILL/OOM, so names embed the
# owning pid and every import sweeps files whose owner is gone — a
# crashed executor's slabs are reclaimed by the next one on the host.
_SHM_DIR = "/dev/shm"
_shm_files: set = set()
_shm_lock = threading.Lock()


def _sweep_shm_files() -> None:
    with _shm_lock:
        leftover = list(_shm_files)
        _shm_files.clear()
    for path in leftover:
        try:
            os.unlink(path)
        except OSError:
            pass


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def sweep_stale_shm_files() -> int:
    """Unlink srt shm files (buffer slabs + native host-proof tokens)
    whose owning process no longer exists. Returns the count removed."""
    removed = 0
    try:
        names = os.listdir(_SHM_DIR)
    except OSError:
        return 0
    for name in names:
        pid = None
        if name.startswith("srt-host-"):
            parts = name.split("-")  # srt-host-<pid>-<hex>
            if len(parts) >= 4 and parts[2].isdigit():
                pid = int(parts[2])
        elif name.startswith("srt-"):
            parts = name.split("-")  # srt-<pid>-<hex>
            if len(parts) >= 3 and parts[1].isdigit():
                pid = int(parts[1])
        if pid is None or pid == os.getpid() or _pid_alive(pid):
            continue
        try:
            os.unlink(os.path.join(_SHM_DIR, name))
            removed += 1
        except OSError:
            pass
    return removed


atexit.register(_sweep_shm_files)
sweep_stale_shm_files()


def _shm_usable() -> bool:
    return os.path.isdir(_SHM_DIR) and os.access(_SHM_DIR, os.W_OK)


class TpuBuffer:
    """A single allocation with optional PD registration."""

    def __init__(
        self,
        pd: Optional[ProtectionDomain],
        length: int,
        register: bool = True,
        arena: bool = False,
    ):
        if length <= 0:
            raise ValueError(f"buffer length must be positive, got {length}")
        if register and pd is None:
            # validate before allocating: a failed constructor must not
            # leave an shm file behind (free() never runs on it)
            raise ValueError("registration requested but no ProtectionDomain")
        self.length = length
        self._arena: Optional[NativeArena] = None
        self._mmap: Optional[mmap.mmap] = None
        self._shm_path: Optional[str] = None
        if arena and native_arena_available():
            self._arena = NativeArena.shared()
            self._alloc_id, view = self._arena.alloc(length)
        elif (
            register
            and getattr(pd, "supports_file_regions", False)
            and _shm_usable()
        ):
            path = os.path.join(
                _SHM_DIR, f"srt-{os.getpid()}-{secrets.token_hex(16)}"
            )
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
            shm_stat = None
            try:
                # posix_fallocate actually reserves tmpfs pages (ENOSPC
                # now) where a sparse ftruncate would SIGBUS on first
                # write past a small container /dev/shm
                os.posix_fallocate(fd, 0, length)
                self._mmap = mmap.mmap(fd, length, mmap.MAP_SHARED)
                # identity of the SAME inode the mapping covers, for the
                # native fast path's registration (never a path re-stat)
                shm_stat = os.fstat(fd)
                os.close(fd)
            except OSError:
                os.close(fd)
                os.unlink(path)
                # fall back to anonymous memory (no fast path, no SIGBUS)
                self._mmap = mmap.mmap(-1, length)
                path = None
            self._shm_path = path
            if path is not None:
                with _shm_lock:
                    _shm_files.add(path)
            view = memoryview(self._mmap)
        else:
            self._mmap = mmap.mmap(-1, length)
            view = memoryview(self._mmap)
        self._view: Optional[memoryview] = view
        self._pd = pd
        self.mkey = 0
        if register:
            # slabs are rewritten in place across pooled reuses; their
            # shm file pages ARE this memory, so the backing is declared
            # mutable (identity = dev/ino; content can't diverge)
            self.mkey = pd.register(
                view, file_path=self._shm_path, file_offset=0,
                file_mutable=True,
                file_stat=shm_stat if self._shm_path else None,
            )
        self._freed = False

    # -- accessors --------------------------------------------------------
    @property
    def view(self) -> memoryview:
        if self._freed:
            raise ValueError("buffer already freed")
        assert self._view is not None
        return self._view

    @property
    def address(self) -> int:
        """Base offset of this buffer within its own region: always 0.

        The reference exposes the raw virtual address (RdmaBuffer.java:70);
        here addresses in :class:`BlockLocation` are offsets relative to
        the registered region identified by ``mkey``.
        """
        return 0

    def write(self, data, offset: int = 0) -> None:
        """Copy bytes in (reference Unsafe.copyMemory path, :101-112)."""
        n = len(data)
        self.view[offset : offset + n] = bytes(data) if not isinstance(
            data, (bytes, bytearray, memoryview)
        ) else data

    def read(self, offset: int = 0, length: Optional[int] = None) -> bytes:
        if length is None:
            length = self.length - offset
        return bytes(self.view[offset : offset + length])

    # -- lifecycle --------------------------------------------------------
    def free(self) -> None:
        if self._freed:
            return
        self._freed = True
        if getattr(self, "_mempool_charge", None) is not None:
            # pool-tagged buffer retired without passing through
            # TpuBufferManager.put — release its accounting here so the
            # tenant quota and in-use gauge never leak (tag is only
            # ever set by the manager, so the module is loaded)
            from sparkrdma_tpu.memory.buffer_manager import release_charge

            release_charge(self)
        if self._pd is not None and self.mkey:
            self._pd.deregister(self.mkey)
        view, self._view = self._view, None
        if view is not None:
            view.release()
        if self._arena is not None:
            # arena memory is framework-owned; no consumer views may
            # outlive it (see class docstring), so the free is immediate
            self._arena.free(self._alloc_id)
            self._arena = None
        if self._mmap is not None:
            try:
                self._mmap.close()
            except BufferError:
                # live sub-views (unclosed streams): the mapping stays
                # until they die — leak-safe, never use-after-free
                pass
            self._mmap = None
        if self._shm_path is not None:
            with _shm_lock:
                _shm_files.discard(self._shm_path)
            try:
                os.unlink(self._shm_path)
            except OSError:
                pass
            self._shm_path = None

    def __len__(self) -> int:
        return self.length
