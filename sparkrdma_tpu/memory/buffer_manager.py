"""TpuBufferManager — size-classed pool of registered buffers.

TPU-native analogue of RdmaBufferManager.java (reference: /root/
reference/src/main/java/org/apache/spark/shuffle/rdma/
RdmaBufferManager.java). Semantics preserved:

- requests round up to the next power of two with a 16 KiB floor
  (reference MIN_BLOCK_SIZE = 16*1024, :26, and getNextPowerOf2,
  :103-118),
- one allocator stack per size class, LIFO reuse (:31-71),
- optional preallocation of ``max_agg_block``-sized buffers on
  executors (:84-91),
- ``put`` returns a buffer to its stack; foreign sizes are freed
  (:120-127),
- ``stop`` prints per-size allocation statistics (:131-141).
"""

from __future__ import annotations

import logging
from collections import deque
from typing import Deque, Dict

from sparkrdma_tpu.analysis.lockorder import named_lock
from sparkrdma_tpu.memory.buffer import TpuBuffer
from sparkrdma_tpu.memory.registry import ProtectionDomain
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.tenancy import current_tenant
from sparkrdma_tpu.tenancy import quota as _quota

logger = logging.getLogger(__name__)

MIN_BLOCK_SIZE = 16 * 1024

# pool counters are process-global (the pools are per-node but share one
# allocation discipline); resolved once at import so get()/put() stay hot
_M_POOL_HITS = get_registry().counter("mempool.hits")
_M_POOL_MISSES = get_registry().counter("mempool.misses")
_M_POOL_RETURNS = get_registry().counter("mempool.returns")
_M_POOL_FREES = get_registry().counter("mempool.frees")
_G_IN_USE = get_registry().gauge("mempool.in_use_bytes")


def release_charge(buf: TpuBuffer) -> None:
    """Retire a buffer's outstanding accounting tag (idempotent)."""
    tag = getattr(buf, "_mempool_charge", None)
    if tag is None:
        return
    buf._mempool_charge = None
    broker, tenant, cls = tag
    _G_IN_USE.add(-cls)
    if broker is not None:
        broker.release(tenant, cls)


def next_power_of_2(n: int) -> int:
    if n <= MIN_BLOCK_SIZE:
        return MIN_BLOCK_SIZE
    return 1 << (n - 1).bit_length()


class _AllocatorStack:
    """LIFO stack of free buffers of one size class (reference :31-71)."""

    def __init__(self, pd: ProtectionDomain, length: int):
        self.pd = pd
        self.length = length
        self.stack: Deque[TpuBuffer] = deque()
        self.total_alloc = 0
        # hot: pop/append only; allocation itself happens outside
        self.lock = named_lock("mempool.stack", hot=True)
        self.closed = False

    def get(self) -> TpuBuffer:
        with self.lock:
            if self.stack:
                _M_POOL_HITS.inc()
                return self.stack.pop()
            self.total_alloc += 1
        _M_POOL_MISSES.inc()
        return TpuBuffer(self.pd, self.length)

    def put(self, buf: TpuBuffer) -> bool:
        """Return buf to the stack; False if the stack is already closed."""
        with self.lock:
            if self.closed:
                return False
            self.stack.append(buf)
            return True

    def close(self) -> None:
        with self.lock:
            self.closed = True
            while self.stack:
                self.stack.pop().free()


class TpuBufferManager:
    """Pool of registered buffers keyed by power-of-two size class."""

    def __init__(
        self,
        pd: ProtectionDomain,
        is_executor: bool = True,
        max_agg_block: int = 2 * 1024 * 1024,
        max_agg_prealloc: int = 0,
    ):
        self.pd = pd
        self._stacks: Dict[int, _AllocatorStack] = {}
        # hot: guards the size-class table only, never held across
        # registration or frees
        self._lock = named_lock("mempool.manager", hot=True)
        self._stopped = False
        # Preallocation of aggregation-block buffers on executors
        # (reference :84-91).
        if is_executor and max_agg_prealloc > 0:
            count = max_agg_prealloc
            stack = self._stack_for(next_power_of_2(max_agg_block))
            pre = [stack.get() for _ in range(count)]
            for buf in pre:
                stack.put(buf)

    def _stack_for(self, length: int) -> _AllocatorStack:
        with self._lock:
            stack = self._stacks.get(length)
            if stack is None:
                stack = _AllocatorStack(self.pd, length)
                self._stacks[length] = stack
            return stack

    def get(self, length: int) -> TpuBuffer:
        """Get a registered buffer of capacity ≥ length (pooled).

        The tenant quota charge gates the allocation: an over-quota
        tenant's worker blocks HERE (backpressure on its own stage/push
        thread) until its earlier buffers are released. The charge tag
        rides the buffer so release (put or free, whichever retires it
        first) is idempotent."""
        if self._stopped:
            raise RuntimeError("buffer manager stopped")
        cls = next_power_of_2(length)
        broker = _quota.broker("mempool")
        tenant = current_tenant() if broker is not None else None
        if broker is not None:
            broker.charge(tenant, cls)
        try:
            buf = self._stack_for(cls).get()
        except BaseException:
            if broker is not None:
                broker.release(tenant, cls)
            raise
        buf._mempool_charge = (broker, tenant, cls)
        _G_IN_USE.add(cls)
        return buf

    def put(self, buf: TpuBuffer) -> None:
        """Return a buffer to the pool (or free, if foreign or unregistered).

        Unregistered scratch buffers (mkey == 0) must never enter the
        registered pool — a consumer would publish mkey 0 and remote
        READs would fail at the peer's PD.
        """
        release_charge(buf)
        with self._lock:
            stack = self._stacks.get(buf.length) if buf.mkey else None
        if stack is None or self._stopped or not stack.put(buf):
            _M_POOL_FREES.inc()
            buf.free()
        else:
            _M_POOL_RETURNS.inc()

    def get_unregistered(self, length: int) -> TpuBuffer:
        """Non-pooled, unregistered scratch allocation (chunk staging).

        Arena-backed: scratch lifetime is framework-controlled, so the
        native arena's unconditional free applies (see TpuBuffer)."""
        return TpuBuffer(None, length, register=False, arena=True)

    def stats(self) -> Dict[int, int]:
        with self._lock:
            return {size: s.total_alloc for size, s in self._stacks.items()}

    def stop(self) -> None:
        """Free all pooled buffers, log per-size-class allocation stats."""
        if self._stopped:
            return
        self._stopped = True
        for size, count in sorted(self.stats().items()):
            if count:
                logger.info(
                    "buffer pool: size class %d bytes — %d buffers allocated", size, count
                )
        with self._lock:
            stacks = list(self._stacks.values())
            self._stacks.clear()
        for stack in stacks:
            stack.close()
