"""ProtectionDomain — the per-endpoint registered-memory handle table.

TPU-native analogue of the verbs protection domain (``IbvPd``) plus
memory-region registration (``IbvPd.regMr``) that the reference obtains
through DiSNI (reference: RdmaNode.java:99-104 allocates the PD;
RdmaBuffer.java:81-88 registers regions against it).

Registering a region yields an ``mkey`` (the rkey/lkey analogue). A
one-sided READ presented to this endpoint as ``(mkey, offset, length)``
is resolved directly against this table by the transport's passive IO
plane — the owning application code is never involved, preserving the
reference's "remote CPU does zero per-byte work" invariant
(SURVEY.md §5.1 #3).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from sparkrdma_tpu.obs import get_registry


class RegionError(KeyError):
    """Access through an unknown or out-of-range (mkey, offset, length)."""


_M_REGISTRATIONS = get_registry().counter("mempool.registrations")
_M_DEREGISTRATIONS = get_registry().counter("mempool.deregistrations")


class ProtectionDomain:
    """Handle table: mkey → registered memoryview."""

    # the pure-Python plane streams every READ; it never consumes
    # file_path hints, so buffers should not bother allocating shm
    # backing for it (NativeProtectionDomain overrides this)
    supports_file_regions = False

    _next_pd_id = 0
    _pd_lock = threading.Lock()

    def __init__(self):
        with ProtectionDomain._pd_lock:
            self.pd_id = ProtectionDomain._next_pd_id
            ProtectionDomain._next_pd_id += 1
        self._lock = threading.Lock()
        self._regions: Dict[int, memoryview] = {}
        self._next_mkey = 1  # 0 reserved as "unregistered"

    def register(
        self,
        view: memoryview,
        file_path: Optional[str] = None,
        file_offset: int = 0,
        file_mutable: bool = False,
        file_stat=None,
    ) -> int:
        """Register a memory region (read-only is fine); returns its mkey.

        ``file_path``/``file_offset``/``file_mutable``/``file_stat``
        describe a file whose bytes mirror the region (shm slab, mapped
        shuffle file). The pure-Python plane streams all READs and
        ignores them; the native plane uses them for the same-host
        pread fast path (transport.cpp srt_reg_file)."""
        del file_path, file_offset, file_mutable, file_stat  # python plane streams
        with self._lock:
            mkey = self._next_mkey
            self._next_mkey += 1
            self._regions[mkey] = view
        _M_REGISTRATIONS.inc()
        return mkey

    def deregister(self, mkey: int) -> None:
        with self._lock:
            removed = self._regions.pop(mkey, None)
        if removed is not None:
            _M_DEREGISTRATIONS.inc()

    def region_length(self, mkey: int) -> int:
        """Total byte length of a registered region (for local
        consumers that want the class-spanning view, not just the
        advertised valid prefix — see DeviceShuffleIO's local
        short-circuit)."""
        with self._lock:
            region = self._regions.get(mkey)
        if region is None:
            raise RegionError(f"mkey {mkey} not registered in pd {self.pd_id}")
        return len(region)

    def resolve(self, mkey: int, offset: int, length: int) -> memoryview:
        """Resolve (mkey, offset, length) → memory, bounds-checked.

        This is the NIC's address-translation step for an incoming READ.
        """
        with self._lock:
            region = self._regions.get(mkey)
        if region is None:
            raise RegionError(f"mkey {mkey} not registered in pd {self.pd_id}")
        if offset < 0 or length < 0 or offset + length > len(region):
            raise RegionError(
                f"READ [{offset}, {offset + length}) out of bounds for "
                f"mkey {mkey} (region size {len(region)})"
            )
        return region[offset : offset + length]

    def region_count(self) -> int:
        with self._lock:
            return len(self._regions)

    def dealloc(self) -> None:
        with self._lock:
            self._regions.clear()
