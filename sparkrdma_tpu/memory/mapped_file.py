"""MappedFile — mmap+register shuffle files for remote one-sided READ.

TPU-native analogue of RdmaMappedFile.java (reference: /root/reference/
src/main/java/org/apache/spark/shuffle/rdma/RdmaMappedFile.java).
Semantics preserved:

- partition-aware **chunked** mapping: consecutive partitions are
  coalesced until the chunk reaches ``block_size`` bytes, each chunk is
  mapped at a 4 KiB-aligned offset and registered as its own region,
  and a per-partition ``(address, length, mkey)`` table is computed
  (reference :135-209),
- a single mapping never exceeds 2 GiB (reference :219-222),
- regions are registered read-only for remote access (reference
  IBV_ACCESS_REMOTE_READ only, :42),
- the backing file is deleted on dispose (reference deleteOnExit +
  dispose, :132, 251-260).
"""

from __future__ import annotations

import mmap
import os
from dataclasses import dataclass
from typing import List, Optional, Sequence

from sparkrdma_tpu.locations import BlockLocation
from sparkrdma_tpu.memory.registry import ProtectionDomain

ALIGN = 4096
MAX_MAPPING = (1 << 31) - ALIGN  # ≤2 GiB per mapping (reference :219-222)


@dataclass
class _FileMapping:
    """One mmap'd, registered chunk of the file (reference RdmaFileMapping)."""

    mm: mmap.mmap
    view: memoryview
    mkey: int
    file_offset: int  # aligned file offset this mapping starts at
    length: int


class MappedFile:
    def __init__(
        self,
        path: str,
        pd: ProtectionDomain,
        block_size: int,
        partition_lengths: Sequence[int],
    ):
        self.path = path
        self._pd = pd
        self._mappings: List[_FileMapping] = []
        # per-partition location (address = offset inside its mapping)
        self._partition_locations: List[Optional[BlockLocation]] = []
        self._partition_mapping: List[Optional[int]] = []  # index into _mappings
        self._disposed = False
        self._fd = os.open(path, os.O_RDONLY)
        try:
            self._map_partitions(block_size, partition_lengths)
        except Exception:
            os.close(self._fd)
            raise

    def _map_partitions(self, block_size: int, partition_lengths: Sequence[int]) -> None:
        file_size = os.fstat(self._fd).st_size
        if sum(partition_lengths) != file_size:
            raise ValueError(
                f"partition lengths sum {sum(partition_lengths)} != file size {file_size}"
            )
        # Coalesce consecutive partitions into ≥block_size chunks
        # (reference :165-209), capped at MAX_MAPPING.
        chunks: List[List[int]] = []  # lists of partition ids
        acc = 0
        current: List[int] = []
        for pid, length in enumerate(partition_lengths):
            if length > MAX_MAPPING:
                # the reference raises for >2 GiB single registrations
                # (RdmaMappedFile.java:219-222); lengths must also fit the
                # 4-byte field in BlockLocation.
                raise ValueError(
                    f"partition {pid} is {length} bytes; single-mapping "
                    f"limit is {MAX_MAPPING}"
                )
            if current and acc + length > MAX_MAPPING:
                chunks.append(current)
                current, acc = [], 0
            current.append(pid)
            acc += length
            if acc >= block_size:
                chunks.append(current)
                current, acc = [], 0
        if current:
            chunks.append(current)

        offsets = [0] * len(partition_lengths)
        off = 0
        for pid, length in enumerate(partition_lengths):
            offsets[pid] = off
            off += length

        self._partition_locations = [None] * len(partition_lengths)
        self._partition_mapping = [None] * len(partition_lengths)

        for chunk in chunks:
            chunk_start = offsets[chunk[0]]
            chunk_end = offsets[chunk[-1]] + partition_lengths[chunk[-1]]
            if chunk_end == chunk_start:
                # all-empty chunk: no mapping needed
                for pid in chunk:
                    self._partition_locations[pid] = BlockLocation(0, 0, 0)
                continue
            aligned_start = chunk_start & ~(ALIGN - 1)
            map_len = chunk_end - aligned_start
            mm = mmap.mmap(
                self._fd, map_len, mmap.MAP_SHARED, mmap.PROT_READ, offset=aligned_start
            )
            view = memoryview(mm)
            # advertise the backing file so same-host peers can pread
            # the chunk from page cache instead of streaming it; the
            # identity comes from fstat of the mapping's own fd so a
            # concurrent same-path rewrite can't be mistaken for it
            mkey = self._pd.register(
                view, file_path=os.path.abspath(self.path),
                file_offset=aligned_start,
                file_stat=os.fstat(self._fd),
            )
            mapping_index = len(self._mappings)
            self._mappings.append(_FileMapping(mm, view, mkey, aligned_start, map_len))
            for pid in chunk:
                addr = offsets[pid] - aligned_start
                self._partition_locations[pid] = BlockLocation(
                    addr, partition_lengths[pid], mkey
                )
                self._partition_mapping[pid] = mapping_index

    # -- accessors (reference :306-327) -----------------------------------
    def partition_count(self) -> int:
        return len(self._partition_locations)

    def get_partition_location(self, pid: int) -> BlockLocation:
        loc = self._partition_locations[pid]
        assert loc is not None
        return loc

    def get_partition_view(self, pid: int) -> memoryview:
        """Local short-circuit read path (no network loop-through)."""
        loc = self.get_partition_location(pid)
        if loc.length == 0:
            return memoryview(b"")
        idx = self._partition_mapping[pid]
        assert idx is not None
        mapping = self._mappings[idx]
        return mapping.view[loc.address : loc.address + loc.length]

    def dispose(self) -> None:
        """Deregister, unmap, close, and delete the backing file."""
        if self._disposed:
            return
        self._disposed = True
        for m in self._mappings:
            self._pd.deregister(m.mkey)
            try:
                m.view.release()
                m.mm.close()
            except BufferError:
                # a partition view from a still-open stream keeps the
                # mapping alive; the OS unmaps when the last view dies
                pass
        self._mappings.clear()
        os.close(self._fd)
        try:
            os.unlink(self.path)
        except OSError:
            pass
