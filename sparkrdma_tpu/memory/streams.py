"""Zero-copy streams over memoryviews.

Analogue of ByteBufferBackedInputStream / ByteBufferBackedOutputStream
(reference: /root/reference/src/main/java/org/apache/spark/shuffle/rdma/
ByteBufferBacked{Input,Output}Stream.java) — minimal stream shims used
by RPC serialization and partition reads, without copying the
underlying registered memory.
"""

from __future__ import annotations

import io
from typing import Optional


class MemoryviewInputStream(io.RawIOBase):
    def __init__(self, view: memoryview, on_close=None):
        self._view: Optional[memoryview] = view
        self._pos = 0
        self._on_close = on_close

    def readable(self) -> bool:
        return True

    def readinto(self, b) -> int:
        if self._view is None:
            raise ValueError("read on closed stream")
        n = min(len(b), len(self._view) - self._pos)
        if n <= 0:
            return 0
        b[:n] = self._view[self._pos : self._pos + n]
        self._pos += n
        return n

    def read(self, size: int = -1) -> bytes:
        if self._view is None:
            raise ValueError("read on closed stream")
        if size is None or size < 0:
            size = len(self._view) - self._pos
        n = min(size, len(self._view) - self._pos)
        out = bytes(self._view[self._pos : self._pos + n])
        self._pos += n
        return out

    def read_view(self, size: int = -1) -> memoryview:
        """Zero-copy ``read``: a memoryview slice of the backing buffer
        instead of a bytes copy. The slice is only guaranteed valid
        until :meth:`close` — the backing registered buffer / mapped
        window recycles then — so consumers must finish decoding
        (decompress / deserialize) before closing the stream.
        """
        if self._view is None:
            raise ValueError("read on closed stream")
        if size is None or size < 0:
            size = len(self._view) - self._pos
        n = min(size, len(self._view) - self._pos)
        out = self._view[self._pos : self._pos + n]
        self._pos += n
        return out

    def close(self) -> None:
        # release the exported view eagerly so the owning buffer/mapping
        # can be freed deterministically at dispose time
        view, self._view = self._view, None
        if view is not None:
            view.release()
        if not self.closed and self._on_close is not None:
            cb, self._on_close = self._on_close, None
            cb()
        super().close()


class MemoryviewOutputStream(io.RawIOBase):
    def __init__(self, view: memoryview):
        self._view = view
        self._pos = 0

    def writable(self) -> bool:
        return True

    def write(self, b) -> int:
        n = len(b)
        if self._pos + n > len(self._view):
            raise ValueError("write past end of buffer")
        self._view[self._pos : self._pos + n] = b
        self._pos += n
        return n

    @property
    def position(self) -> int:
        return self._pos
