"""RegisteredBuffer — ref-counted slicing over one pooled buffer.

TPU-native analogue of RdmaRegisteredBuffer.java (reference: /root/
reference/src/main/java/org/apache/spark/shuffle/rdma/
RdmaRegisteredBuffer.java). Carves sequential slices out of one pooled
:class:`TpuBuffer` with a bump pointer (:79-107); when the refcount
drops to zero the underlying buffer returns to the pool (:52-69).
"""

from __future__ import annotations

import threading
from typing import Optional

from sparkrdma_tpu.memory.buffer import TpuBuffer
from sparkrdma_tpu.memory.buffer_manager import TpuBufferManager


class RegisteredBuffer:
    def __init__(self, manager: TpuBufferManager, length: int):
        self._manager = manager
        self._buffer: Optional[TpuBuffer] = manager.get(length)
        self._lock = threading.Lock()
        self._refcount = 0
        self._block_offset = 0

    @property
    def mkey(self) -> int:
        assert self._buffer is not None
        return self._buffer.mkey

    @property
    def capacity(self) -> int:
        assert self._buffer is not None
        return self._buffer.length

    def retain(self) -> None:
        with self._lock:
            self._refcount += 1

    def release(self) -> None:
        with self._lock:
            self._refcount -= 1
            if self._refcount > 0:
                return
            buf, self._buffer = self._buffer, None
        if buf is not None:
            self._manager.put(buf)

    def ref_count(self) -> int:
        with self._lock:
            return self._refcount

    def slice(self, length: int) -> "BufferSlice":
        """Carve the next `length` bytes; caller holds one reference."""
        with self._lock:
            if self._buffer is None:
                raise ValueError("buffer already released")
            offset = self._block_offset
            if offset + length > self._buffer.length:
                raise ValueError(
                    f"slice of {length} bytes exceeds remaining capacity "
                    f"({self._buffer.length - offset})"
                )
            self._block_offset += length
            view = self._buffer.view[offset : offset + length]
            self._refcount += 1
        return BufferSlice(self, view, offset, length)


class BufferSlice:
    """One carved slice; address/mkey visible for location publication.

    Analogue of RdmaByteBufferManagedBuffer (reference
    RdmaByteBufferManagedBuffer.java — getAddress/getLkey/getLength plus
    retain/release delegation).
    """

    def __init__(self, owner: RegisteredBuffer, view: memoryview, offset: int, length: int):
        self._owner = owner
        self.view = view
        self.address = offset  # offset within the registered region
        self.length = length

    @property
    def mkey(self) -> int:
        return self._owner.mkey

    def retain(self) -> None:
        self._owner.retain()

    def release(self) -> None:
        self._owner.release()
