"""Consistent-hash shard map: ``(shuffle_id, partition range)`` → peers.

The locations registry shards by partition *range* (``range_size``
consecutive partitions share a shard key) so one reduce task's
``[start, end)`` resolve touches few shards, and the ring hashes each
shard key onto the metadata peers with virtual nodes so load spreads
evenly. Two properties the tests pin (tests/test_metastore.py):

- **full cover** — every key maps to exactly one primary (and, with
  replication, a deterministic follower list of distinct peers);
- **minimal movement** — removing a peer only remaps keys that peer
  owned; adding one only steals keys from its ring neighbours. A
  metadata-peer death therefore invalidates only its own ranges.

Deterministic throughout (sha1, no RNG): the modelcheck scheduler can
replay any interleaving byte-for-byte.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import List, Sequence, Tuple


def _point(token: str) -> int:
    """64-bit ring coordinate of a token (stable across processes)."""
    return int.from_bytes(hashlib.sha1(token.encode()).digest()[:8], "big")


class ShardMap:
    """Immutable consistent-hash ring over metadata peer names."""

    def __init__(self, peers: Sequence[str], vnodes: int = 16,
                 range_size: int = 8):
        if not peers:
            raise ValueError("shard map needs at least one peer")
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        if range_size < 1:
            raise ValueError("range_size must be >= 1")
        self.peers: Tuple[str, ...] = tuple(sorted(set(peers)))
        self.vnodes = vnodes
        self.range_size = range_size
        points: List[Tuple[int, str]] = []
        for peer in self.peers:
            for i in range(vnodes):
                points.append((_point(f"{peer}#{i}"), peer))
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [o for _, o in points]

    # -- key space ---------------------------------------------------------
    def shard_key(self, shuffle_id: int, partition_id: int) -> Tuple[int, int]:
        """The ``(shuffle_id, range index)`` bucket a partition lives in."""
        return (shuffle_id, partition_id // self.range_size)

    # -- lookups -----------------------------------------------------------
    def _walk(self, key: Tuple[int, int]) -> List[str]:
        """Distinct peers in ring order starting at the key's point."""
        h = _point(f"{key[0]}:{key[1]}")
        idx = bisect.bisect_right(self._points, h) % len(self._points)
        seen: List[str] = []
        for off in range(len(self._points)):
            owner = self._owners[(idx + off) % len(self._points)]
            if owner not in seen:
                seen.append(owner)
                if len(seen) == len(self.peers):
                    break
        return seen

    def primary(self, shuffle_id: int, partition_id: int) -> str:
        """The peer that serves reads for this partition's shard."""
        return self._walk(self.shard_key(shuffle_id, partition_id))[0]

    def owners(self, shuffle_id: int, partition_id: int,
               replicas: int = 0) -> List[str]:
        """Primary + up to ``replicas`` distinct followers, ring order.
        Writes apply to every owner; reads serve from the primary only
        (store._serving_copy), so replication never double-serves."""
        walk = self._walk(self.shard_key(shuffle_id, partition_id))
        return walk[: 1 + max(0, replicas)]

    # -- membership (immutable: new map per change) ------------------------
    def without_peer(self, peer: str) -> "ShardMap":
        rest = [p for p in self.peers if p != peer]
        return ShardMap(rest, self.vnodes, self.range_size)

    def with_peer(self, peer: str) -> "ShardMap":
        return ShardMap(list(self.peers) + [peer], self.vnodes,
                        self.range_size)
