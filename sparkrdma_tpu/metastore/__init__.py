"""Control-plane HA: sharded, lease-replicated metadata hub.

The driver has always been the metadata hub for every published
``(address, length, mkey)`` partition location (SURVEY §0,
shuffle/manager.py). PR 10 made the *data* plane survive executor
loss; this package removes the matching control-plane single point of
failure (ROADMAP item 1):

- :mod:`~sparkrdma_tpu.metastore.shardmap` — a consistent-hash ring
  that shards the locations registry by ``(shuffle_id, partition
  range)`` across logical metadata peers, with the full-cover and
  minimal-movement properties pinned by tests;
- :mod:`~sparkrdma_tpu.metastore.lease` — the explicit lease/epoch
  protocol: each peer serves its shards under a renewable lease, every
  write carries the epoch it routed against, and a stale epoch is a
  typed rejection (:class:`StaleEpochError`) retried through the PR 2
  retry ladder;
- :mod:`~sparkrdma_tpu.metastore.store` — the sharded store itself:
  epoch-fenced publish/resolve, per-shard executor tombstones (the
  swept-publisher check holds per shard, not per process), follower
  replication with single-primary serving, peer kill with follower
  takeover, and driver-crash ``wipe()`` + generation-fenced
  re-adoption from executors.

See docs/RESILIENCE.md "Control-plane HA" for the state machine and
the chaos bar (driver killed mid-job → the job resumes and completes
byte-identically).
"""

from sparkrdma_tpu.metastore.lease import LeaseTable, ShardLease, StaleEpochError
from sparkrdma_tpu.metastore.shardmap import ShardMap
from sparkrdma_tpu.metastore.store import MetaShard, ShardedMetaStore

__all__ = [
    "LeaseTable",
    "MetaShard",
    "ShardLease",
    "ShardMap",
    "ShardedMetaStore",
    "StaleEpochError",
]
