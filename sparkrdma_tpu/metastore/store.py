"""The sharded, lease-replicated locations store the driver serves from.

Replaces the driver's monolithic ``_partition_locations`` dict
(shuffle/manager.py) as the authoritative registry:

- every ``(shuffle_id, partition range)`` key routes through the
  consistent-hash ring (:mod:`shardmap`) to a primary peer plus
  ``metastore.replicas`` followers; writes apply to every owner,
  reads serve the primary's copy only (:meth:`_read_copies`);
- every write carries the epoch it routed against; the apply-side
  check (:meth:`MetaShard._epoch_ok`) fences writes routed under a
  lease that expired, was revoked, or was taken over in between —
  :class:`StaleEpochError`, retried through the PR 2 retry ladder
  after re-routing;
- executor tombstones live **per shard** (:meth:`MetaShard._blocked`):
  a publish racing ``_on_peer_lost`` either lands before that shard's
  sweep (and is pruned by it) or serializes after it (and sees the
  tombstone) — there is no per-process window (the manager.py:490
  hazard, pinned by the ``meta_lease`` modelcheck model);
- ``kill_peer`` drops a metadata peer: its lease is revoked, the ring
  remaps only its ranges (minimal movement), and the former follower
  — which already holds the copies — becomes primary with zero
  metadata loss;
- ``wipe`` models driver death: every entry is gone, every lease
  re-grants under a bumped epoch, and the **generation** counter
  advances so re-adoption publishes from executors
  (``republish_for_readoption``) are fenced against sweeps started
  under an older takeover (:meth:`_fence_generation`).

Lock order (enforced by the lock-order detector): ``manager.shuffle``
OUTER → ``metastore.topology`` → ``metastore.shard`` leaf. Shard locks
are only ever held for dict mutation; lease transitions run under the
topology lock with shard epochs mirrored in (so the apply path needs
the leaf lock only).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple

from sparkrdma_tpu.analysis.lockorder import named_lock
from sparkrdma_tpu.analysis.modelcheck import schedule_point
from sparkrdma_tpu.locations import PartitionLocation
from sparkrdma_tpu.metastore.lease import LeaseTable, StaleEpochError
from sparkrdma_tpu.metastore.shardmap import ShardMap
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.obs.journal import emit as journal_emit
from sparkrdma_tpu.resilience.retry import RetryPolicy
from sparkrdma_tpu.testing import faults as _faults

Key = Tuple[int, int]  # (shuffle_id, partition_id)


class MetaShard:
    """One metadata peer's slice of the registry."""

    def __init__(self, name: str):
        self.name = name
        self.lock = named_lock("metastore.shard")
        self.epoch = 1  # mirror of the peer's lease epoch (topology-synced)
        self.alive = True
        # (shuffle_id, partition_id) -> [(location, generation applied)]
        self.entries: Dict[Key, List[Tuple[PartitionLocation, int]]] = {}
        # executors swept by _on_peer_lost, per shard: the swept-publisher
        # check holds HERE, not in one process-wide set
        self.tombstones: set = set()

    # -- named decision points (mutation-gate targets) ---------------------
    def _epoch_ok(self, epoch: int) -> bool:
        """May a write routed under ``epoch`` apply here? Only while the
        shard is alive and the epoch is its current one — anything else
        was routed under a lease that no longer holds."""
        return self.alive and epoch == self.epoch

    def _blocked(self, executor_id: str) -> bool:
        """Is this publisher tombstoned on THIS shard? Accepting its
        locations after the sweep would double-serve next to a
        promoted replica."""
        return executor_id in self.tombstones


class ShardedMetaStore:
    """Sharded, epoch-fenced partition-location registry (driver)."""

    def __init__(self, conf, role: str = "driver",
                 clock: Optional[Callable[[], float]] = None):
        self.role = role
        peers = [f"meta-{i}" for i in range(conf.metastore_peers)]
        self.replicas = min(conf.metastore_replicas, len(peers) - 1)
        self._ring = ShardMap(peers, conf.metastore_vnodes,
                              conf.metastore_range_size)
        self._leases = LeaseTable(peers, conf.metastore_lease_ttl_ms / 1000.0,
                                  clock)
        self._shards: Dict[str, MetaShard] = {p: MetaShard(p) for p in peers}
        self.generation = 1
        self.retry = RetryPolicy(
            max_attempts=conf.metastore_max_write_attempts,
            backoff_ms=conf.metastore_retry_backoff_ms,
            backoff_max_ms=conf.metastore_retry_backoff_ms * 8,
            deadline_ms=0,
        )
        # guards ring/lease/generation transitions; shard locks are leaves
        self._topology = named_lock("metastore.topology")
        self._reg = get_registry()
        self._reg.gauge("metastore.shards", role=role).set(len(peers))
        self._reg.gauge("metastore.epoch", role=role).set(self.generation)

    # -- named decision points (mutation-gate targets) ---------------------
    @staticmethod
    def _read_copies(owners: List[Tuple[str, int]]) -> List[Tuple[str, int]]:
        """Owners whose copy a resolve may serve: the primary ONLY.
        Serving a follower's copy beside the primary's is the
        double-serve the replication design must never produce."""
        return owners[:1]

    def _fence_generation(self, carried: int) -> bool:
        """Is a generation-fenced publish stale? Re-adoption sweeps tag
        their publishes with the generation of the takeover that
        started them; a sweep from an older takeover must be rejected
        whole, never retried into the new era."""
        return carried != 0 and carried != self.generation

    # -- routing -----------------------------------------------------------
    def _route(self, shuffle_id: int, partition_id: int
               ) -> Tuple[int, List[Tuple[str, int]]]:
        """Resolve the owner list + epochs a write/read must carry.
        Expired leases take over (epoch bump) HERE — the next apply
        under the old epoch fences."""
        plan = _faults.active()
        while True:
            with self._topology:
                owners = self._ring.owners(shuffle_id, partition_id,
                                           self.replicas)
                routed: List[Tuple[str, int]] = []
                for peer in owners:
                    if not self._leases.live(peer):
                        epoch = self._leases.takeover(peer)
                        self._sync_shard_epoch(peer, epoch)
                        self._reg.counter(
                            "metastore.lease_takeovers", role=self.role
                        ).inc()
                        journal_emit(
                            "meta.takeover", role=self.role,
                            peer=peer, epoch=epoch,
                        )
                    else:
                        epoch = self._leases.epoch(peer)
                    routed.append((peer, epoch))
                gen = self.generation
            if plan is not None:
                killed = [p for p, _ in routed if plan.on_meta(shard=p)]
                if killed:
                    for peer in killed:
                        self.kill_peer(peer)
                    continue  # ranges moved: route again
            return gen, routed

    def _sync_shard_epoch(self, peer: str, epoch: int) -> None:
        shard = self._shards[peer]
        with shard.lock:
            shard.epoch = epoch

    def _renew(self, routed: List[Tuple[str, int]]) -> None:
        with self._topology:
            for peer, epoch in routed:
                try:
                    self._leases.renew(peer, epoch)
                except StaleEpochError:
                    continue  # expired between apply and renew: benign
                self._reg.counter(
                    "metastore.lease_renewals", role=self.role
                ).inc()

    def _stale(self, err: StaleEpochError) -> StaleEpochError:
        self._reg.counter(
            "metastore.stale_epoch_rejects", role=self.role
        ).inc()
        return err

    # -- write path --------------------------------------------------------
    def publish(self, shuffle_id: int, locations: List[PartitionLocation],
                fence_generation: int = 0) -> int:
        """Epoch-fenced scatter of ``locations`` into their shards.

        Returns how many locations were applied; tombstoned publishers'
        locations drop silently (the caller re-checks its lost set for
        barrier accounting). Raises :class:`StaleEpochError` without
        retry when ``fence_generation`` names an older takeover era —
        a stale re-adoption sweep must die, not merge into the new one.
        """
        if fence_generation:
            with self._topology:
                if self._fence_generation(fence_generation):
                    raise self._stale(StaleEpochError(
                        "generation", fence_generation, self.generation))
        applied = 0
        by_key: Dict[Key, List[PartitionLocation]] = {}
        for loc in locations:
            by_key.setdefault((shuffle_id, loc.partition_id), []).append(loc)
        for key, locs in by_key.items():
            applied += self._publish_key(key, locs, fence_generation)
        return applied

    def _publish_key(self, key: Key, locs: List[PartitionLocation],
                     fence_generation: int) -> int:
        attempt = 0
        while True:
            attempt += 1
            gen, routed = self._route(*key)
            if fence_generation and gen != fence_generation:
                raise self._stale(StaleEpochError(
                    "generation", fence_generation, gen))
            schedule_point("proto", "meta.lease")
            try:
                applied = self._apply(key, locs, routed, gen)
            except StaleEpochError as err:
                self._stale(err)
                if not self.retry.allows(attempt + 1):
                    raise
                time.sleep(self.retry.backoff_s(attempt, "meta", *map(str, key)))
                continue
            self._renew(routed)
            return applied

    def _apply(self, key: Key, locs: List[PartitionLocation],
               routed: List[Tuple[str, int]], gen: int) -> int:
        """Apply one key's locations to every owner. Idempotent per
        (owner, location): a retry after a partial apply (one owner
        accepted, the next fenced) never duplicates an entry."""
        applied = 0
        for i, (peer, epoch) in enumerate(routed):
            shard = self._shards[peer]
            with shard.lock:
                if not shard._epoch_ok(epoch):
                    raise StaleEpochError(peer, epoch, shard.epoch)
                bucket = shard.entries.setdefault(key, [])
                for loc in locs:
                    if shard._blocked(loc.manager_id.executor_id):
                        continue
                    if any(have == loc for have, _ in bucket):
                        continue
                    bucket.append((loc, gen))
                    if i == 0:  # count primary copies once, not per replica
                        applied += 1
        return applied

    # -- read path ---------------------------------------------------------
    def resolve(self, shuffle_id: int, partition_id: int
                ) -> List[PartitionLocation]:
        """Epoch-fenced read of one partition's locations (primary copy)."""
        attempt = 0
        while True:
            attempt += 1
            _, routed = self._route(shuffle_id, partition_id)
            schedule_point("proto", "meta.lease")
            out: List[PartitionLocation] = []
            try:
                for peer, epoch in self._read_copies(routed):
                    shard = self._shards[peer]
                    with shard.lock:
                        if not shard._epoch_ok(epoch):
                            raise StaleEpochError(peer, epoch, shard.epoch)
                        bucket = shard.entries.get(
                            (shuffle_id, partition_id), ())
                        out.extend(loc for loc, _ in bucket)
            except StaleEpochError as err:
                self._stale(err)
                if not self.retry.allows(attempt + 1):
                    raise
                time.sleep(self.retry.backoff_s(
                    attempt, "meta", str(shuffle_id), str(partition_id)))
                continue
            return out

    def resolve_range(self, shuffle_id: int, start: int, end: int
                      ) -> List[PartitionLocation]:
        out: List[PartitionLocation] = []
        for pid in range(start, end):
            out.extend(self.resolve(shuffle_id, pid))
        return out

    def entries_for_shuffle(self, shuffle_id: int
                            ) -> Dict[int, List[PartitionLocation]]:
        """Primary-copy view of one shuffle: pid -> locations. Seeded
        partitions appear with empty lists (register parity)."""
        out: Dict[int, List[PartitionLocation]] = {}
        with self._topology:
            ring = self._ring
        for shard in self._shards.values():
            with shard.lock:
                items = [(k, [loc for loc, _ in v])
                         for k, v in shard.entries.items()
                         if k[0] == shuffle_id]
            for (_, pid), locs in items:
                if ring.primary(shuffle_id, pid) != shard.name:
                    continue
                out.setdefault(pid, []).extend(locs)
        return out

    def shuffle_ids(self) -> List[int]:
        sids: set = set()
        for shard in self._shards.values():
            with shard.lock:
                sids.update(k[0] for k in shard.entries)
        return sorted(sids)

    def all_entries(self) -> Dict[int, Dict[int, List[PartitionLocation]]]:
        """Primary-copy view of every shuffle (legacy/test surface —
        the shape ``_partition_locations`` always had)."""
        return {sid: self.entries_for_shuffle(sid)
                for sid in self.shuffle_ids()}

    # -- lifecycle ---------------------------------------------------------
    def ensure_shuffle(self, shuffle_id: int, num_partitions: int) -> None:
        """Seed empty buckets on every owner so resolves of an
        unpublished partition answer [] (register_shuffle parity)."""
        for pid in range(num_partitions):
            _, routed = self._route(shuffle_id, pid)
            for peer, _ in routed:
                shard = self._shards[peer]
                with shard.lock:
                    shard.entries.setdefault((shuffle_id, pid), [])

    def drop_shuffle(self, shuffle_id: int) -> None:
        for shard in self._shards.values():
            with shard.lock:
                for key in [k for k in shard.entries if k[0] == shuffle_id]:
                    del shard.entries[key]

    def sweep_executor(self, executor_id: str,
                       shuffle_id: Optional[int] = None) -> int:
        """Tombstone + prune a dead executor, shard by shard. The
        tombstone and the prune commit atomically per shard: a racing
        publish either lands before the sweep of that shard (pruned
        here) or after it (dropped by :meth:`MetaShard._blocked`)."""
        pruned = 0
        for shard in self._shards.values():
            with shard.lock:
                shard.tombstones.add(executor_id)
                for key, bucket in shard.entries.items():
                    if shuffle_id is not None and key[0] != shuffle_id:
                        continue
                    keep = [(loc, g) for loc, g in bucket
                            if loc.manager_id.executor_id != executor_id]
                    pruned += len(bucket) - len(keep)
                    shard.entries[key] = keep
        return pruned

    def kill_peer(self, peer: str) -> int:
        """Metadata-peer death: revoke its lease, remap only its ranges
        (ring minimal movement), clear its slice. The former follower
        already holds every copy, so reads keep answering — zero
        metadata loss at replication >= 1. Returns the new generation."""
        with self._topology:
            if peer not in self._shards or len(self._ring.peers) <= 1:
                return self.generation
            if peer not in self._ring.peers:
                return self.generation
            self._leases.revoke(peer)
            self._ring = self._ring.without_peer(peer)
            self.generation += 1
            self._reg.gauge("metastore.epoch", role=self.role).set(
                self.generation)
            self._reg.gauge("metastore.shards", role=self.role).set(
                len(self._ring.peers))
            self._reg.counter("metastore.peer_kills", role=self.role).inc()
            journal_emit(
                "meta.peer_kill", role=self.role, peer=peer,
                generation=self.generation,
            )
        shard = self._shards[peer]
        with shard.lock:
            shard.alive = False
            shard.entries.clear()
            # replication below the requested factor now that a peer is
            # gone: surviving writes re-replicate on their next publish
        self.replicas = min(self.replicas, len(self._ring.peers) - 1)
        return self.generation

    def wipe(self) -> int:
        """Driver crash: every entry is gone, every lease re-grants
        under a bumped epoch, generation advances. Recovery is the
        re-adoption sweep (re-publish, not recompute) fenced by the
        returned generation."""
        schedule_point("proto", "meta.adopt")
        with self._topology:
            self.generation += 1
            self._leases.bump_all()
            journal_emit(
                "meta.epoch_bump", role=self.role,
                generation=self.generation,
            )
            for peer in self._ring.peers:
                epoch = self._leases.epoch(peer)
                shard = self._shards[peer]
                with shard.lock:
                    shard.entries.clear()
                    shard.epoch = epoch
                # every lease re-granted under the bumped epoch is a
                # takeover of that peer's slice — journaled per peer so
                # the chaos timeline shows kill -> takeover -> adopt
                journal_emit(
                    "meta.takeover", role=self.role, peer=peer, epoch=epoch,
                )
            self._reg.gauge("metastore.epoch", role=self.role).set(
                self.generation)
            return self.generation

    def live_peers(self) -> List[str]:
        with self._topology:
            return list(self._ring.peers)

    def snapshot(self) -> Dict[str, object]:
        with self._topology:
            leases = self._leases.snapshot()
            peers = list(self._ring.peers)
            gen = self.generation
        entries = 0
        for shard in self._shards.values():
            with shard.lock:
                entries += sum(len(v) for v in shard.entries.values())
        return {
            "generation": gen,
            "peers": peers,
            "replicas": self.replicas,
            "entries": entries,
            "leases": leases,
        }
