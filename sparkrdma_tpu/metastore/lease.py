"""Lease/epoch protocol for metadata shard peers.

Every logical metadata peer serves its shards under a time-bounded
lease tagged with a monotonically increasing **epoch**. The protocol
is three rules, each a small named method so the modelcheck mutation
gate can disarm exactly one decision (analysis/modelcheck/mutants.py):

- a write must carry the epoch it routed against, and the apply-side
  check (:meth:`LeaseTable.check`) rejects any epoch that is not the
  peer's *current* one — :class:`StaleEpochError`, retried through the
  PR 2 retry ladder after re-routing;
- a lease renews only while live (:meth:`LeaseTable.renew`): renewal
  after expiry must go through takeover, never silently resurrect;
- expiry or an explicit revoke **bumps the epoch**
  (:meth:`LeaseTable.takeover`), so every write routed under the old
  lease is fenced the moment the new holder starts serving.

The table never sleeps and never spawns threads: the store drives it
with an injectable clock, so unit tests and the ``meta_lease``
modelcheck model control time explicitly.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Sequence


class StaleEpochError(RuntimeError):
    """A write/resolve carried an epoch the peer no longer serves."""

    def __init__(self, peer: str, carried: int, current: int):
        super().__init__(
            f"stale epoch for {peer}: write carried {carried}, "
            f"peer serves {current}"
        )
        self.peer = peer
        self.carried = carried
        self.current = current


@dataclass
class ShardLease:
    """One peer's serving right: who holds it, which epoch, until when."""

    holder: str
    epoch: int
    deadline: float
    alive: bool = True


class LeaseTable:
    """Peer name → lease. NOT thread-safe by itself: the store calls it
    under its shard/topology locks (docs/RESILIENCE.md lock order)."""

    def __init__(self, peers: Sequence[str], ttl_s: float,
                 clock: Optional[Callable[[], float]] = None):
        self.ttl_s = ttl_s
        self.clock = clock or time.monotonic
        now = self.clock()
        self._leases: Dict[str, ShardLease] = {
            p: ShardLease(holder=p, epoch=1, deadline=now + ttl_s)
            for p in peers
        }

    # -- named decision points (mutation-gate targets) ---------------------
    @staticmethod
    def _expired(lease: ShardLease, now: float) -> bool:
        """Has this lease lapsed? Serving past the deadline is exactly
        the double-serve window the lease exists to close."""
        return now > lease.deadline

    def check(self, peer: str, epoch: int) -> None:
        """Apply-side fence: the carried epoch must be current and the
        lease live. Raises :class:`StaleEpochError` otherwise."""
        lease = self._leases.get(peer)
        if lease is None or not lease.alive:
            raise StaleEpochError(peer, epoch, 0)
        if epoch != lease.epoch:
            raise StaleEpochError(peer, epoch, lease.epoch)

    # -- transitions --------------------------------------------------------
    def epoch(self, peer: str) -> int:
        lease = self._leases.get(peer)
        if lease is None or not lease.alive:
            raise StaleEpochError(peer, 0, 0)
        return lease.epoch

    def live(self, peer: str) -> bool:
        lease = self._leases.get(peer)
        return (
            lease is not None
            and lease.alive
            and not self._expired(lease, self.clock())
        )

    def renew(self, peer: str, epoch: int) -> None:
        """Extend a live lease (the holder touches it on every served
        write). Renewal of an expired or superseded lease raises — the
        old holder must re-acquire through :meth:`takeover`."""
        lease = self._leases.get(peer)
        if lease is None or not lease.alive:
            raise StaleEpochError(peer, epoch, 0)
        if epoch != lease.epoch:
            raise StaleEpochError(peer, epoch, lease.epoch)
        now = self.clock()
        if self._expired(lease, now):
            raise StaleEpochError(peer, epoch, lease.epoch)
        lease.deadline = now + self.ttl_s

    def takeover(self, peer: str, holder: Optional[str] = None) -> int:
        """Grant the shard to ``holder`` (default: the peer itself —
        an in-place restart) under a BUMPED epoch. Every write routed
        under the previous epoch is fenced from this point on."""
        lease = self._leases.get(peer)
        now = self.clock()
        if lease is None:
            lease = ShardLease(holder=holder or peer, epoch=1,
                               deadline=now + self.ttl_s)
            self._leases[peer] = lease
            return lease.epoch
        lease.holder = holder or peer
        lease.epoch += 1
        lease.deadline = now + self.ttl_s
        lease.alive = True
        return lease.epoch

    def revoke(self, peer: str) -> None:
        """Peer death: the lease dies with it. Writes routed to it
        fence immediately; the ring reroutes its ranges elsewhere."""
        lease = self._leases.get(peer)
        if lease is not None:
            lease.alive = False

    def bump_all(self) -> None:
        """Driver crash: a fresh hub serves nothing it didn't re-adopt,
        so every surviving lease re-grants under a new epoch."""
        for peer in list(self._leases):
            self.takeover(peer)

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        return {
            p: {"holder": l.holder, "epoch": l.epoch, "alive": l.alive,
                "live": self.live(p)}
            for p, l in sorted(self._leases.items())
        }
