"""Per-tenant byte quotas with backpressure — mempool, HBM arena, and
mapped-fetch page cache.

A broker tracks *held* bytes per tenant for one resource (capacity is
charged at ``get`` and released at ``put``/``free``, so spilling a
slab to host does not un-block its tenant — the capacity is still
owned). ``charge`` blocks the calling thread — i.e. the offending
tenant's own stage/push worker — while the tenant is at its quota,
and wakes on any of that tenant's releases. Two hard guarantees:

- **progress**: a tenant holding zero bytes is always admitted, even
  for a request larger than its quota (a single oversized buffer must
  not deadlock), and a blocked charge proceeds anyway after
  ``block_max_ms`` (counted under ``tenant.quota_overruns``) — the
  quota is backpressure, never an OOM or a permanent wedge;
- **isolation**: usage is per-tenant, so one tenant at its quota never
  blocks another's allocations.

Brokers are installed process-wide (the mempool/arena are process
singletons per node) from the first tenancy-enabled manager init;
:func:`broker` returns None while unconfigured so the allocation hot
paths pay nothing when quotas are off.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

from sparkrdma_tpu.analysis.lockorder import named_lock
from sparkrdma_tpu.analysis.modelcheck import schedule_point
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.obs.journal import emit as journal_emit

logger = logging.getLogger(__name__)


class QuotaBroker:
    """Byte ledger + backpressure gate for one resource."""

    def __init__(
        self,
        resource: str,
        quota_bytes: int,
        block_max_ms: int = 60000,
        per_tenant: Optional[Dict[str, int]] = None,
    ):
        self.resource = resource
        self._quota = max(0, quota_bytes)  # 0 = unlimited
        self._per_tenant = dict(per_tenant or {})
        self._block_max_s = max(1, block_max_ms) / 1000.0
        self._lock = named_lock(f"quota.{resource}")
        self._cond = threading.Condition(self._lock)
        self._usage: Dict[str, int] = {}
        self._waiting = 0  # threads currently blocked at this quota
        reg = get_registry()
        self._m_blocks = lambda t: reg.counter(
            "tenant.quota_blocks", tenant=t, resource=resource
        )
        self._m_overruns = lambda t: reg.counter(
            "tenant.quota_overruns", tenant=t, resource=resource
        )
        self._h_wait = lambda t: reg.histogram(
            "tenant.quota_wait_ms", tenant=t, resource=resource
        )
        self._g_bytes = lambda t: reg.gauge(
            "tenant.bytes", tenant=t, resource=resource
        )

    def quota_for(self, tenant: str) -> int:
        return self._per_tenant.get(tenant, self._quota)

    def usage(self, tenant: str) -> int:
        with self._lock:
            return self._usage.get(tenant, 0)

    def waiting(self) -> int:
        """Threads blocked at this quota right now — a nonzero value
        means the resource is at 100% utilization regardless of how the
        held-bytes ledger reads between charges (capacity plane)."""
        with self._lock:
            return self._waiting

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        """Per-tenant ``{usage, quota}`` view (capacity plane input)."""
        with self._lock:
            held = dict(self._usage)
        return {
            t: {"usage": u, "quota": self.quota_for(t)}
            for t, u in held.items()
        }

    def over_quota(self, tenant: str) -> bool:
        q = self.quota_for(tenant)
        return q > 0 and self.usage(tenant) > q

    def _must_block(self, tenant: str, nbytes: int, quota: int) -> bool:
        """Backpressure predicate (caller holds the broker lock): block
        only while THIS tenant already holds bytes and the charge would
        overshoot. Per-tenant by design — isolation means one tenant at
        its quota never blocks another — and named so the modelcheck
        mutation gate can swap in the global-usage bug it guards
        against."""
        held = self._usage.get(tenant, 0)
        return held > 0 and held + nbytes > quota

    def charge(self, tenant: str, nbytes: int) -> None:
        """Account nbytes to tenant, blocking at the quota.

        Blocks only while the tenant already holds bytes (progress
        guarantee) and only the offending tenant's thread — other
        tenants charge through the same lock without waiting."""
        schedule_point("proto", "quota.charge")
        quota = self.quota_for(tenant)
        blocked_at: Optional[float] = None
        with self._cond:
            if quota > 0:
                deadline = None
                while self._must_block(tenant, nbytes, quota):
                    now = time.perf_counter()
                    if blocked_at is None:
                        blocked_at = now
                        deadline = now + self._block_max_s
                        self._waiting += 1
                        self._m_blocks(tenant).inc()
                        journal_emit(
                            "quota.block", tenant=tenant,
                            resource=self.resource, bytes=nbytes,
                        )
                    if now >= deadline:
                        self._m_overruns(tenant).inc()
                        journal_emit(
                            "quota.overrun", tenant=tenant,
                            resource=self.resource, bytes=nbytes,
                        )
                        logger.warning(
                            "tenant %s overran its %s quota wait "
                            "(%.0f ms); admitting %d bytes anyway",
                            tenant, self.resource,
                            self._block_max_s * 1e3, nbytes,
                        )
                        break
                    self._cond.wait(deadline - now)
                if blocked_at is not None:
                    self._waiting -= 1
            self._usage[tenant] = self._usage.get(tenant, 0) + nbytes
            self._g_bytes(tenant).set(self._usage[tenant])
        if blocked_at is not None:
            wait_ms = (time.perf_counter() - blocked_at) * 1e3
            self._h_wait(tenant).observe(wait_ms)
            journal_emit(
                "quota.release", tenant=tenant, resource=self.resource,
                bytes=nbytes, wait_ms=round(wait_ms, 1),
            )

    def release(self, tenant: str, nbytes: int) -> None:
        schedule_point("proto", "quota.release")
        with self._cond:
            self._usage[tenant] = max(0, self._usage.get(tenant, 0) - nbytes)
            self._g_bytes(tenant).set(self._usage[tenant])
            self._cond.notify_all()


# -- process-wide broker table -------------------------------------------
_table_lock = named_lock("quota.table")
_brokers: Dict[str, QuotaBroker] = {}


def _per_tenant_overrides(conf, resource_key: str) -> Dict[str, int]:
    """Scan conf for ``tenancy.quota.<tenant>.<resource_key>`` entries."""
    from sparkrdma_tpu.utils.config import PREFIX
    from sparkrdma_tpu.utils.units import parse_bytes

    head = PREFIX + "tenancy.quota."
    tail = "." + resource_key
    out: Dict[str, int] = {}
    for key, raw in conf.to_dict().items():
        if key.startswith(head) and key.endswith(tail):
            tenant = key[len(head) : -len(tail)]
            if not tenant:
                continue
            try:
                out[tenant] = parse_bytes(str(raw))
            except ValueError:
                continue
    return out


def install(conf) -> None:
    """Install the mempool/hbm brokers from conf (idempotent; first
    tenancy-enabled manager in the process wins). A resource with no
    default quota and no per-tenant override gets NO broker, keeping
    the allocation hot paths untouched when quotas are off."""
    specs = {
        "mempool": (conf.tenancy_mempool_quota_bytes, "mempoolBytes"),
        "hbm": (conf.tenancy_hbm_quota_bytes, "hbmBytes"),
        # mapped zero-copy fetches bypass the mempool entirely, so
        # their page-cache footprint gets its own ledger (fetcher.py
        # charges per mapped group, releases on delivery/failure)
        "pagecache": (conf.tenancy_pagecache_quota_bytes, "pageCacheBytes"),
    }
    with _table_lock:
        for resource, (default_quota, key) in specs.items():
            if resource in _brokers:
                continue
            per_tenant = _per_tenant_overrides(conf, key)
            if default_quota <= 0 and not per_tenant:
                continue
            _brokers[resource] = QuotaBroker(
                resource,
                default_quota,
                block_max_ms=conf.tenancy_quota_block_max_ms,
                per_tenant=per_tenant,
            )


def broker(resource: str) -> Optional[QuotaBroker]:
    return _brokers.get(resource)


def charge_pagecache(tenant: str, nbytes: int):
    """THE page-cache charge seam for the read submission plane
    (DESIGN.md §24): every mapped-delivery path — the fetcher's mapped
    group READs and anything else that hands out page-cache windows
    outside the mempool ledger — charges ``tenancy.pageCacheQuotaBytes``
    through this one call site, so the backpressure semantics
    (per-tenant blocking, ``block_max_ms`` overrun escape, isolation)
    cannot drift between paths.

    Charges ``nbytes`` now (blocking at the quota, exactly like
    :meth:`QuotaBroker.charge`) and returns a release-once callable:
    safe to invoke from both the failure-cleanup and the
    last-stream-closed paths — only the first call releases. When no
    ``pagecache`` broker is installed, returns a no-op without
    touching any ledger."""
    b = _brokers.get("pagecache")
    if b is None:
        return lambda: None
    b.charge(tenant, nbytes)
    once = threading.Lock()

    def release() -> None:
        if once.acquire(blocking=False):
            b.release(tenant, nbytes)

    return release


def reset() -> None:
    """Drop installed brokers (tests only)."""
    with _table_lock:
        _brokers.clear()
