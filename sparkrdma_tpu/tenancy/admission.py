"""Per-job admission control — bounded in-flight jobs, FIFO queue
with a deadline beyond the bound.

The driver owns one controller. `run_job` (and the cluster context's
`run_map_reduce`) brackets the whole job — map stage, reduce stage,
and any fetch-failure recompute attempts — in :meth:`admit`, so the
in-flight bound is a bound on *jobs*, not stages. Queued jobs are
served strictly FIFO; a job that waits past its deadline raises
:class:`AdmissionTimeout` so the caller fails fast instead of camping
on the queue forever.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Deque, Iterator, Optional

from sparkrdma_tpu.analysis.lockorder import named_lock
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.obs.journal import emit as journal_emit


class AdmissionTimeout(RuntimeError):
    """Job refused: the admission queue deadline expired."""


class AdmissionClosed(RuntimeError):
    """Job refused: the controller was closed (manager stopping)."""


class _Waiter:
    __slots__ = ("admitted",)

    def __init__(self) -> None:
        self.admitted = False


class AdmissionController:
    """Bounded in-flight job counter with a FIFO overflow queue."""

    def __init__(
        self,
        max_inflight: int,
        queue_timeout_ms: int,
        role: str = "driver",
    ):
        self._max = max(1, max_inflight)
        self._timeout_s = max(1, queue_timeout_ms) / 1000.0
        self._lock = named_lock("admission.state")
        self._cond = threading.Condition(self._lock)
        self._inflight = 0
        self._waiters: Deque[_Waiter] = deque()
        self._closed = False
        reg = get_registry()
        self._m_admitted = lambda t: reg.counter("admission.admitted", tenant=t)
        self._m_queued = lambda t: reg.counter("admission.queue_waits", tenant=t)
        self._m_timeouts = lambda t: reg.counter("admission.timeouts", tenant=t)
        self._m_wait = lambda t: reg.histogram("admission.wait_ms", tenant=t)
        self._g_inflight = reg.gauge("admission.inflight", role=role)
        self._g_queue = reg.gauge("admission.queue_depth", role=role)

    # -- internals --------------------------------------------------------
    def _promote_locked(self) -> None:
        while self._inflight < self._max and self._waiters:
            w = self._waiters.popleft()
            w.admitted = True
            self._inflight += 1
        self._g_queue.set(len(self._waiters))

    # -- API --------------------------------------------------------------
    def acquire(self, tenant: str, timeout_ms: Optional[int] = None) -> None:
        t0 = time.perf_counter()
        timeout_s = self._timeout_s if timeout_ms is None else max(1, timeout_ms) / 1e3
        with self._cond:
            if self._closed:
                raise AdmissionClosed("admission controller closed")
            if self._inflight < self._max and not self._waiters:
                self._inflight += 1
            else:
                w = _Waiter()
                self._waiters.append(w)
                self._g_queue.set(len(self._waiters))
                self._m_queued(tenant).inc()
                journal_emit(
                    "admission.enqueue", tenant=tenant,
                    queue_depth=len(self._waiters), inflight=self._inflight,
                )
                deadline = t0 + timeout_s
                while not w.admitted:
                    if self._closed:
                        if w in self._waiters:
                            self._waiters.remove(w)
                        self._g_queue.set(len(self._waiters))
                        raise AdmissionClosed("admission controller closed")
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        self._waiters.remove(w)
                        self._g_queue.set(len(self._waiters))
                        self._m_timeouts(tenant).inc()
                        journal_emit(
                            "admission.deadline", tenant=tenant,
                            waited_ms=round(timeout_s * 1e3),
                        )
                        raise AdmissionTimeout(
                            f"tenant {tenant!r} job queued past its "
                            f"{timeout_s * 1e3:.0f} ms admission deadline"
                        )
                    self._cond.wait(remaining)
            self._g_inflight.set(self._inflight)
        self._m_admitted(tenant).inc()
        self._m_wait(tenant).observe((time.perf_counter() - t0) * 1e3)

    def release(self) -> None:
        with self._cond:
            self._inflight = max(0, self._inflight - 1)
            self._promote_locked()
            self._g_inflight.set(self._inflight)
            self._cond.notify_all()

    @contextlib.contextmanager
    def admit(self, tenant: str, timeout_ms: Optional[int] = None) -> Iterator[None]:
        """Hold an admission slot for the duration of a job."""
        self.acquire(tenant, timeout_ms)
        try:
            yield
        finally:
            self.release()

    def close(self) -> None:
        """Refuse new jobs and wake queued waiters (they raise
        :class:`AdmissionClosed`). In-flight jobs finish normally."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    @property
    def queued(self) -> int:
        with self._lock:
            return len(self._waiters)
