"""Tenancy — multi-tenant serving primitives for concurrent shuffles.

One `TpuShuffleManager`/`TpuContext` serves N concurrent jobs from
competing tenants. The layer has three independent mechanisms, all
keyed by a thread-local *tenant id* that rides every task the engine
dispatches:

- admission control (:mod:`.admission`) — bounded in-flight jobs with
  a FIFO queue-with-deadline beyond the bound,
- weighted fair-share scheduling (:mod:`.fairshare`) — a
  deficit-round-robin submit queue replacing raw ThreadPoolExecutor
  FIFO on the bounded map/reduce pools, charged by *measured task
  runtime* so a 1000-shard tenant cannot convoy a 10-shard tenant,
- byte quotas (:mod:`.quota`) — per-tenant caps on mempool and HBM
  arena bytes that apply backpressure (block the offending tenant's
  own workers, never OOM, never block other tenants).

The tenant id is context, not identity: `tenant_scope("alice")` tags
everything the current thread does — pool submits, buffer charges,
breaker keys, `obs` labels — until the scope exits. Threads without a
scope belong to ``DEFAULT_TENANT``, and every mechanism degenerates to
the pre-tenancy behavior for that single default tenant (FIFO order,
unscoped breaker keys, no quota), so the layer is safe to leave on.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, Iterator, List, Optional

DEFAULT_TENANT = "default"

_tls = threading.local()

# Thread-ident → tenant side table for cross-thread readers (the
# sampling profiler, obs/profiler.py, reads OTHER threads' tenants
# from its timer thread — a threading.local can't serve that). Plain
# dict ops are atomic under the GIL; entries for the default tenant
# are dropped so idle/finished threads don't accumulate.
_tenant_by_ident: Dict[int, str] = {}


def _publish_ident(tenant: str) -> None:
    ident = threading.get_ident()
    if tenant == DEFAULT_TENANT:
        _tenant_by_ident.pop(ident, None)
    else:
        _tenant_by_ident[ident] = tenant


def current_tenant() -> str:
    """The tenant id owning the current thread's work."""
    return getattr(_tls, "tenant", DEFAULT_TENANT)


def tenant_of_ident(ident: int) -> str:
    """Tenant owning thread ``ident``'s work right now — readable from
    ANY thread (unlike :func:`current_tenant`). Used by the sampling
    profiler to tag wall-clock samples."""
    return _tenant_by_ident.get(ident, DEFAULT_TENANT)


def set_current_tenant(tenant: Optional[str]) -> None:
    t = tenant or DEFAULT_TENANT
    _tls.tenant = t
    _publish_ident(t)


@contextlib.contextmanager
def tenant_scope(tenant: Optional[str]) -> Iterator[str]:
    """Run the enclosed block as ``tenant`` (restores the previous
    scope on exit; None means the default tenant)."""
    prev = getattr(_tls, "tenant", DEFAULT_TENANT)
    t = tenant or DEFAULT_TENANT
    _tls.tenant = t
    _publish_ident(t)
    try:
        yield t
    finally:
        _tls.tenant = prev
        _publish_ident(prev)


def scoped(tenant: Optional[str], fn):
    """Wrap fn to run under ``tenant_scope(tenant)`` — for handing
    work to bare threads/pools that don't inherit thread-locals."""

    def _run(*args, **kwargs):
        with tenant_scope(tenant):
            return fn(*args, **kwargs)

    return _run


def declared_tenants(conf) -> List[str]:
    """Tenant names a configuration declares up front (fair-share
    weight entries), sorted. Per-tenant SLO objectives (obs/slo.py)
    install one objective per declared tenant; tenants that only ever
    appear at runtime ride the global objective instead."""
    return sorted(conf.tenancy_weights)


def parse_weights(spec: str) -> Dict[str, int]:
    """Parse a ``"alice:4,bob:1"`` weight spec (bad entries dropped)."""
    out: Dict[str, int] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        name, _, raw = part.rpartition(":")
        try:
            w = int(raw)
        except ValueError:
            continue
        if name.strip() and w > 0:
            out[name.strip()] = w
    return out


from sparkrdma_tpu.tenancy.admission import (  # noqa: E402
    AdmissionClosed,
    AdmissionController,
    AdmissionTimeout,
)
from sparkrdma_tpu.tenancy.fairshare import FairShareExecutor  # noqa: E402
from sparkrdma_tpu.tenancy import quota  # noqa: E402
from sparkrdma_tpu.tenancy.quota import QuotaBroker  # noqa: E402

__all__ = [
    "DEFAULT_TENANT",
    "current_tenant",
    "tenant_of_ident",
    "set_current_tenant",
    "tenant_scope",
    "scoped",
    "parse_weights",
    "declared_tenants",
    "AdmissionController",
    "AdmissionTimeout",
    "AdmissionClosed",
    "FairShareExecutor",
    "QuotaBroker",
    "quota",
]
