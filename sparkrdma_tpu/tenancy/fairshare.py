"""FairShareExecutor — deficit-round-robin task pool keyed by tenant.

Drop-in for the bounded ``ThreadPoolExecutor``s on the map and reduce
planes (same ``submit``/``shutdown`` surface, returns real
``concurrent.futures.Future``s), replacing FIFO dispatch with weighted
deficit round robin (DRR) over per-tenant submit queues:

- submit order within one tenant is preserved (FIFO per queue),
- dispatch order across tenants follows DRR: each round credits every
  *backlogged* tenant ``quantum × weight`` seconds of deficit, and a
  tenant is served while its deficit is positive,
- the deficit is charged with the task's **measured runtime** on
  completion, not a per-task constant — so fairness is in task-seconds
  and a tenant whose tasks run 100× longer gets 100× fewer of them
  through per round. A 1000-shard tenant queues 1000 tasks but only
  drains its fair share while a 10-shard tenant's queue empties.

Debt is remembered across backlog gaps (a tenant that just burned the
pool on one huge task waits out its debt) but clamped, and credit
never accumulates while idle — the classic DRR anti-hoarding rules.
With a single tenant the whole mechanism degenerates to plain FIFO.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Deque, Dict, Optional, Tuple

from sparkrdma_tpu.analysis.lockorder import named_lock
from sparkrdma_tpu.obs import get_registry, get_tracer
from sparkrdma_tpu.tenancy import current_tenant, tenant_scope

logger = logging.getLogger(__name__)

# positive credit is capped at this many top-up rounds; debt at
# _DEBT_CAP_S seconds (scaled by weight). Both bound how far one
# tenant's history can skew a round without erasing runtime memory.
_CREDIT_CAP_ROUNDS = 2
_DEBT_CAP_S = 2.0

_Item = Tuple[Future, Callable, tuple, dict, str, float]


class FairShareExecutor:
    """Bounded worker pool with weighted per-tenant DRR dispatch."""

    def __init__(
        self,
        max_workers: int,
        weights: Optional[Dict[str, int]] = None,
        default_weight: int = 1,
        quantum_ms: int = 20,
        thread_name_prefix: str = "fair",
        pool: str = "pool",
    ):
        self._weights = dict(weights or {})
        self._default_weight = max(1, default_weight)
        self._quantum = max(1, quantum_ms) / 1000.0
        self._pool_label = pool
        # one graph vertex per pool role; instances of different pools
        # never nest, and the detector would flag it if they did
        self._lock = named_lock("fairshare.state", allow_self_nest=False)
        self._cond = threading.Condition(self._lock)
        self._queues: Dict[str, Deque[_Item]] = {}
        self._deficit: Dict[str, float] = {}
        self._active: Deque[str] = deque()  # backlogged tenants, RR order
        self._pending = 0
        self._shutdown = False
        reg = get_registry()
        self._m_submits: Dict[str, Any] = {}
        self._m_tasks: Dict[str, Any] = {}
        self._h_task: Dict[str, Any] = {}
        self._h_wait: Dict[str, Any] = {}
        self._g_queued: Dict[str, Any] = {}
        self._reg = reg
        self._tracer = get_tracer("fairshare")
        self._threads = [
            threading.Thread(
                target=self._worker,
                name=f"{thread_name_prefix}-{i}",
                daemon=True,
            )
            for i in range(max(1, max_workers))
        ]
        for t in self._threads:
            t.start()

    # -- metric handles (cached per tenant; registry lookups are locked) --
    def _metric(self, cache: Dict[str, Any], kind: str, name: str, tenant: str):
        m = cache.get(tenant)
        if m is None:
            factory = getattr(self._reg, kind)
            m = factory(name, tenant=tenant, pool=self._pool_label)
            cache[tenant] = m
        return m

    def _weight(self, tenant: str) -> int:
        return self._weights.get(tenant, self._default_weight)

    # -- scheduling core --------------------------------------------------
    def _pop_locked(self) -> Optional[_Item]:
        """Pick the next task under DRR, or None on drained shutdown.

        Serves the front-of-rotation tenant while its deficit is
        positive; a full rotation with no positive deficit triggers a
        credit round for every backlogged tenant (idle tenants earn
        nothing). Converges because deficits strictly increase each
        round and debt is clamped."""
        while True:
            if self._pending == 0:
                if self._shutdown:
                    return None
                self._cond.wait()
                continue
            scanned = 0
            while scanned < len(self._active):
                tenant = self._active[0]
                if self._deficit.get(tenant, 0.0) > 0.0:
                    q = self._queues[tenant]
                    item = q.popleft()
                    self._pending -= 1
                    if not q:
                        self._active.popleft()
                    self._metric(
                        self._g_queued, "gauge", "tenant.queued", tenant
                    ).set(len(q))
                    return item
                self._active.rotate(-1)
                scanned += 1
            for tenant in self._active:
                cap = self._quantum * self._weight(tenant) * _CREDIT_CAP_ROUNDS
                self._deficit[tenant] = min(
                    self._deficit.get(tenant, 0.0)
                    + self._quantum * self._weight(tenant),
                    cap,
                )

    def _charge(self, tenant: str, seconds: float) -> None:
        with self._lock:
            floor = -_DEBT_CAP_S * self._weight(tenant)
            self._deficit[tenant] = max(
                self._deficit.get(tenant, 0.0) - seconds, floor
            )

    def _worker(self) -> None:
        while True:
            with self._cond:
                item = self._pop_locked()
            if item is None:
                return
            fut, fn, args, kwargs, tenant, t_submit = item
            if not fut.set_running_or_notify_cancel():
                continue
            t_dispatch = time.perf_counter()
            self._metric(self._h_wait, "histogram", "tenant.wait_ms", tenant).observe(
                (t_dispatch - t_submit) * 1e3
            )
            # queue-wait attribution span (obs/attr.py): the submit→
            # dispatch interval this task spent parked behind DRR
            self._tracer.record(
                "tenant.queue_wait",
                t_submit,
                t_dispatch,
                tenant=tenant,
                pool=self._pool_label,
            )
            t0 = time.perf_counter()
            with tenant_scope(tenant):
                try:
                    result = fn(*args, **kwargs)
                except BaseException as e:  # noqa: BLE001 — future carries it
                    fut.set_exception(e)
                else:
                    fut.set_result(result)
            dt = time.perf_counter() - t0
            self._charge(tenant, dt)
            self._metric(self._m_tasks, "counter", "tenant.tasks", tenant).inc()
            self._metric(self._h_task, "histogram", "tenant.task_ms", tenant).observe(
                dt * 1e3
            )

    # -- executor surface -------------------------------------------------
    def submit(self, fn: Callable, *args, **kwargs) -> Future:
        """Queue fn under the calling thread's tenant; returns a Future."""
        tenant = current_tenant()
        fut: Future = Future()
        with self._cond:
            if self._shutdown:
                raise RuntimeError("cannot schedule new futures after shutdown")
            q = self._queues.get(tenant)
            if q is None:
                q = self._queues[tenant] = deque()
            if not q and tenant not in self._active:
                self._active.append(tenant)
                # fresh backlog starts with one round of credit so a
                # lone tenant never waits out a top-up loop
                self._deficit.setdefault(tenant, 0.0)
                if self._deficit[tenant] <= 0.0 and len(self._active) == 1:
                    self._deficit[tenant] = self._quantum * self._weight(tenant)
            q.append((fut, fn, args, kwargs, tenant, time.perf_counter()))
            self._pending += 1
            self._metric(self._g_queued, "gauge", "tenant.queued", tenant).set(
                len(q)
            )
            self._cond.notify()
        self._metric(self._m_submits, "counter", "tenant.submits", tenant).inc()
        return fut

    def shutdown(self, wait: bool = True, cancel_futures: bool = False) -> None:
        with self._cond:
            self._shutdown = True
            if cancel_futures:
                for q in self._queues.values():
                    while q:
                        q[0][0].cancel()
                        q.popleft()
                        self._pending -= 1
                self._active.clear()
            self._cond.notify_all()
        if wait:
            for t in self._threads:
                t.join()

    def __enter__(self) -> "FairShareExecutor":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(wait=True)
