from sparkrdma_tpu.transport.completion import CompletionListener, FnListener
from sparkrdma_tpu.transport.channel import TpuChannel, ChannelError
from sparkrdma_tpu.transport.node import TpuNode


def create_node(conf, host, is_executor, executor_id, recv_listener=None,
                peer_lost_listener=None):
    """Node factory honoring ``tpu.shuffle.transport`` (python | native).

    Native (C++ epoll data plane) silently falls back to the Python
    transport when the toolchain is unavailable — same wire format."""
    if conf.transport == "native":
        from sparkrdma_tpu.native.transport_lib import available

        if available():
            from sparkrdma_tpu.transport.native_node import NativeTpuNode

            return NativeTpuNode(
                conf, host, is_executor, executor_id,
                recv_listener=recv_listener,
                peer_lost_listener=peer_lost_listener,
            )
    return TpuNode(
        conf, host, is_executor, executor_id,
        recv_listener=recv_listener,
        peer_lost_listener=peer_lost_listener,
    )


def mapped_delivery_enabled(conf, channel) -> bool:
    """True when a fetch should use mapped (zero-copy) delivery: the
    conf allows it and the channel's plane implements it (native
    transport only). Single definition so the record-plane fetcher and
    the device-block fetcher cannot drift."""
    return conf.mapped_fetch and hasattr(channel, "read_mapped_in_queue")


__all__ = [
    "CompletionListener",
    "FnListener",
    "TpuChannel",
    "ChannelError",
    "TpuNode",
    "create_node",
    "mapped_delivery_enabled",
]
