from sparkrdma_tpu.transport.completion import CompletionListener, FnListener
from sparkrdma_tpu.transport.channel import TpuChannel, ChannelError
from sparkrdma_tpu.transport.node import TpuNode

__all__ = ["CompletionListener", "FnListener", "TpuChannel", "ChannelError", "TpuNode"]
