"""TpuChannel — one reliable peer connection with verbs-like semantics.

TPU-native analogue of RdmaChannel.java (reference: /root/reference/src/
main/java/org/apache/spark/shuffle/rdma/RdmaChannel.java). Preserved
semantics:

- two work-request types only: two-sided SEND for RPC segments
  (:395-424) and one-sided READ for data (:360-393); a READ names
  remote ``(mkey, address, length)`` triples and completes once for the
  whole WR list (reference signals only the last WR),
- **send budget**: ``send_queue_depth`` permits; WRs that cannot
  acquire permits go to an overflow queue drained as completions
  reclaim permits, with a one-time oversubscription warning
  (:54-56, 330-358, 589-625),
- a dedicated completion-processing thread per channel (the
  RdmaThread/CQ analogue, RdmaThread.java:44-57) that also serves the
  *passive* side of one-sided READs directly from the endpoint's
  ProtectionDomain — application code never runs per served byte,
- error latching: the first transport error fails every outstanding
  listener exactly once and poisons the channel (:525-529, 576-579,
  659-666); ``on_failure`` may be called multiple times per listener
  and must tolerate it.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from sparkrdma_tpu.memory.registry import ProtectionDomain, RegionError
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.testing import faults as _faults
from sparkrdma_tpu.transport import wire
from sparkrdma_tpu.transport.completion import CompletionListener
from sparkrdma_tpu.utils.config import TpuShuffleConf

logger = logging.getLogger(__name__)


class ChannelError(IOError):
    pass


@dataclass
class _PendingRead:
    """Reference CompletionInfo (RdmaChannel.java:97-108)."""

    listener: CompletionListener
    dst_views: List[memoryview]
    permits: int


@dataclass
class _QueuedWr:
    """Overflow send WR (reference PostRecvWr / sendWrQueue)."""

    kind: str  # "send" | "read"
    permits: int
    payloads: List[bytes] = field(default_factory=list)
    listener: Optional[CompletionListener] = None
    req_id: int = 0
    dst_views: List[memoryview] = field(default_factory=list)
    blocks: List[Tuple[int, int, int]] = field(default_factory=list)


class TpuChannel:
    """One connected peer endpoint over a full-duplex stream."""

    def __init__(
        self,
        conf: TpuShuffleConf,
        pd: ProtectionDomain,
        sock: socket.socket,
        peer_desc: str,
        on_recv=None,
        on_disconnect=None,
        cpu_vector: Optional[int] = None,
        purpose: str = "rpc",
    ):
        self.conf = conf
        self.pd = pd
        self.peer_desc = peer_desc
        self.purpose = purpose
        self._sock = sock
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._on_recv = on_recv
        self._on_disconnect = on_disconnect

        self._write_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._pending_reads: Dict[int, _PendingRead] = {}
        self._next_req_id = 1
        self._send_budget = conf.send_queue_depth
        self._overflow: Deque[_QueuedWr] = deque()
        self._warned_oversubscription = False
        self._error: Optional[Exception] = None
        self._stopped = False
        self._cpu_vector = cpu_vector

        # counters pre-resolved once per channel so the hot verb paths
        # never pay a registry lookup (labels: connection purpose)
        reg = get_registry()
        self._m_sends = reg.counter("transport.sends", purpose=purpose)
        self._m_send_bytes = reg.counter("transport.send_bytes", purpose=purpose)
        self._m_recvs = reg.counter("transport.recvs", purpose=purpose)
        self._m_recv_bytes = reg.counter("transport.recv_bytes", purpose=purpose)
        self._m_reads = reg.counter("transport.reads", purpose=purpose)
        self._m_read_bytes = reg.counter("transport.read_bytes", purpose=purpose)
        self._m_reads_served = reg.counter("transport.reads_served", purpose=purpose)
        self._m_read_bytes_served = reg.counter(
            "transport.read_bytes_served", purpose=purpose
        )
        self._m_completions = reg.counter("transport.completions", purpose=purpose)
        self._m_read_errors = reg.counter("transport.read_errors", purpose=purpose)
        self._m_overflow = reg.counter("transport.send_overflow", purpose=purpose)
        self._m_errors = reg.counter("transport.errors_latched", purpose=purpose)

        self._recv_thread = threading.Thread(
            target=self._process_completions, name=f"cq-{peer_desc}", daemon=True
        )
        self._recv_thread.start()

    # ------------------------------------------------------------------
    # public verb API (reference rdmaSendInQueue / rdmaReadInQueue)
    # ------------------------------------------------------------------
    def send_in_queue(self, listener: CompletionListener, segments: Sequence[bytes]) -> None:
        """Post RPC segments as SEND WRs; one completion for the batch."""
        plan = _faults.active()
        if plan is not None:
            listener, handled = plan.on_send(self, listener, segments)
            if handled:
                return
        payloads = [wire.pack_send(seg) for seg in segments]
        self._m_sends.inc(len(payloads))
        self._m_send_bytes.inc(sum(len(p) for p in payloads))
        wr = _QueuedWr(kind="send", permits=len(payloads), payloads=payloads, listener=listener)
        self._post(wr)

    def read_in_queue(
        self,
        listener: CompletionListener,
        dst_views: List[memoryview],
        blocks: List[Tuple[int, int, int]],
    ) -> None:
        """Post a one-sided READ of remote (mkey, addr, len) blocks.

        ``dst_views`` receive the bytes in order; total destination size
        must equal total block length. Completes once for the whole list
        (reference: only the last WR is signaled, :383-390).
        """
        plan = _faults.active()
        if plan is not None:
            listener, handled = plan.on_read(self, listener, dst_views, blocks)
            if handled:
                return
        total = sum(b[2] for b in blocks)
        if sum(len(v) for v in dst_views) != total:
            raise ValueError("destination size != total remote block length")
        self._m_reads.inc(len(blocks))
        self._m_read_bytes.inc(total)
        wr = _QueuedWr(
            kind="read",
            permits=max(1, len(blocks)),
            listener=listener,
            dst_views=dst_views,
            blocks=blocks,
        )
        self._post(wr)

    # ------------------------------------------------------------------
    # send budget + posting (reference :330-358, 589-625)
    # ------------------------------------------------------------------
    def _post(self, wr: _QueuedWr) -> None:
        with self._state_lock:
            if self._error is not None or self._stopped:
                err = self._error or ChannelError("channel stopped")
                if wr.listener:
                    wr.listener.on_failure(err)
                return
            if self._send_budget >= wr.permits:
                self._send_budget -= wr.permits
            else:
                self._m_overflow.inc()
                if not self._warned_oversubscription:
                    self._warned_oversubscription = True
                    logger.warning(
                        "channel %s send queue oversubscribed; consider raising "
                        "tpu.shuffle.sendQueueDepth (current %d)",
                        self.peer_desc,
                        self.conf.send_queue_depth,
                    )
                self._overflow.append(wr)
                return
        self._execute(wr)

    def _reclaim(self, permits: int) -> None:
        """Return permits; drain overflow WRs that now fit (reference :589-625)."""
        runnable: List[_QueuedWr] = []
        with self._state_lock:
            self._send_budget += permits
            while self._overflow and self._send_budget >= self._overflow[0].permits:
                wr = self._overflow.popleft()
                self._send_budget -= wr.permits
                runnable.append(wr)
        for wr in runnable:
            self._execute(wr)

    def _execute(self, wr: _QueuedWr) -> None:
        req_id = 0
        try:
            if wr.kind == "send":
                with self._write_lock:
                    for p in wr.payloads:
                        self._sock.sendall(p)
                # stream accepted the bytes == send WC
                self._reclaim(wr.permits)
                if wr.listener:
                    wr.listener.on_success(None)
                return
            with self._state_lock:
                req_id = self._next_req_id
                self._next_req_id += 1
                self._pending_reads[req_id] = _PendingRead(
                    wr.listener, wr.dst_views, wr.permits
                )
            with self._write_lock:
                self._sock.sendall(wire.pack_read_req(req_id, wr.blocks))
            # if the error latched between _post's check and the pending
            # registration above, the latch may have missed this WR —
            # flush it ourselves so its listener is never orphaned
            with self._state_lock:
                latched = self._error
                stale = self._pending_reads.pop(req_id, None) if latched else None
            if stale is not None and stale.listener:
                stale.listener.on_failure(latched)
        except OSError as e:
            err = ChannelError(f"send to {self.peer_desc} failed: {e}")
            self._latch_error(err)
            # the latch may have run before our pending registration (or
            # this was a send WR it never saw) — fail this WR directly
            with self._state_lock:
                stale = self._pending_reads.pop(req_id, None)
            listener = stale.listener if stale is not None else wr.listener
            if listener:
                listener.on_failure(err)

    # ------------------------------------------------------------------
    # completion processing (reference exhaustCq/processCompletions)
    # ------------------------------------------------------------------
    def _process_completions(self) -> None:
        # per-channel CQ thread pins to its CPU vector (RdmaThread.java:44-46)
        from sparkrdma_tpu.utils.affinity import pin_current_thread

        pin_current_thread(self._cpu_vector)
        try:
            while True:
                op_raw = self._sock.recv(1)
                if not op_raw:
                    raise ConnectionError("peer closed connection")
                op = op_raw[0]
                if op == wire.OP_SEND:
                    n = struct.unpack(">I", wire.read_exact(self._sock, 4))[0]
                    payload = wire.read_exact(self._sock, n)
                    self._m_recvs.inc()
                    self._m_recv_bytes.inc(n)
                    if self._on_recv is not None:
                        self._on_recv(self, payload)
                elif op == wire.OP_READ_REQ or op == wire.OP_READ_REQ2:
                    # REQ2 (a native file-capable peer) gets the same
                    # streamed READ_RESP: this plane has no file path
                    self._serve_read()
                elif op == wire.OP_READ_RESP:
                    self._complete_read()
                elif op == wire.OP_READ_ERR:
                    self._complete_read_err()
                elif op == wire.OP_GOODBYE:
                    raise ConnectionError("peer disconnected")
                else:
                    raise ChannelError(f"unknown opcode {op} from {self.peer_desc}")
        except (OSError, ChannelError) as e:
            graceful = self._stopped or (
                isinstance(e, ConnectionError) and "disconnected" in str(e)
            )
            self._latch_error(
                ChannelError(f"channel {self.peer_desc}: {e}"), quiet=graceful
            )
            if self._on_disconnect is not None:
                self._on_disconnect(self)

    def _serve_read(self) -> None:
        """Passive one-sided READ service: PD-resolve and stream back.

        Runs on the completion thread — the application layer is never
        involved, preserving SURVEY.md §5.1 invariant #3.
        """
        req_id, blocks = wire.unpack_read_req(self._sock)
        try:
            views = [self.pd.resolve(mkey, addr, length) for mkey, addr, length in blocks]
        except RegionError as e:
            with self._write_lock:
                self._sock.sendall(wire.pack_read_err(req_id, str(e)))
            return
        total = sum(len(v) for v in views)
        self._m_reads_served.inc(len(views))
        self._m_read_bytes_served.inc(total)
        with self._write_lock:
            self._sock.sendall(wire.pack_read_resp_header(req_id, total))
            for v in views:
                self._sock.sendall(v)

    def _complete_read(self) -> None:
        req_id = struct.unpack(">Q", wire.read_exact(self._sock, 8))[0]
        total = struct.unpack(">Q", wire.read_exact(self._sock, 8))[0]
        with self._state_lock:
            pending = self._pending_reads.pop(req_id, None)
        if pending is None:
            # unknown completion: drain the payload to keep framing intact
            wire.read_exact(self._sock, total)
            return
        try:
            for view in pending.dst_views:
                wire.read_into(self._sock, view)
        except Exception as e:
            # the entry was already popped from _pending_reads, so the
            # error latch can no longer see it — fail its listener here
            # before propagating, or the reduce task waits forever
            if pending.listener:
                try:
                    pending.listener.on_failure(
                        ChannelError(f"READ payload from {self.peer_desc} truncated: {e}")
                    )
                except Exception:
                    logger.exception("listener on_failure raised")
            raise
        self._m_completions.inc()
        self._reclaim(pending.permits)
        if pending.listener:
            pending.listener.on_success(total)

    def _complete_read_err(self) -> None:
        req_id = struct.unpack(">Q", wire.read_exact(self._sock, 8))[0]
        n = struct.unpack(">I", wire.read_exact(self._sock, 4))[0]
        msg = wire.read_exact(self._sock, n).decode("utf-8")
        with self._state_lock:
            pending = self._pending_reads.pop(req_id, None)
        if pending is not None:
            self._m_read_errors.inc()
            self._reclaim(pending.permits)
            if pending.listener:
                pending.listener.on_failure(ChannelError(f"remote READ failed: {msg}"))

    # ------------------------------------------------------------------
    # error latching + teardown (reference :525-529, 653-733)
    # ------------------------------------------------------------------
    def _latch_error(self, err: ChannelError, quiet: bool = False) -> None:
        with self._state_lock:
            if self._error is not None:
                return
            self._error = err
            self._m_errors.inc()
            pending = list(self._pending_reads.values())
            self._pending_reads.clear()
            overflow = list(self._overflow)
            self._overflow.clear()
        if not quiet:
            logger.warning("latching channel error: %s", err)
        for p in pending:
            if p.listener:
                try:
                    p.listener.on_failure(err)
                except Exception:
                    logger.exception("listener on_failure raised")
        for wr in overflow:
            if wr.listener:
                try:
                    wr.listener.on_failure(err)
                except Exception:
                    logger.exception("listener on_failure raised")
        try:
            self._sock.close()
        except OSError:
            pass

    @property
    def is_connected(self) -> bool:
        with self._state_lock:
            return self._error is None and not self._stopped

    def stop(self) -> None:
        with self._state_lock:
            if self._stopped:
                return
            self._stopped = True
        try:
            with self._write_lock:
                self._sock.sendall(bytes([wire.OP_GOODBYE]))
        except OSError:
            pass
        self._latch_error(ChannelError("channel stopped"), quiet=True)
        if threading.current_thread() is not self._recv_thread:
            self._recv_thread.join(timeout=self.conf.teardown_timeout_ms / 1000.0)
