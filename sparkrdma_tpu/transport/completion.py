"""Completion callback interface for transport work requests.

Analogue of RdmaCompletionListener (reference: /root/reference/src/main/
java/org/apache/spark/shuffle/rdma/RdmaCompletionListener.java:24-27).
Contract preserved: ``on_failure`` may be invoked more than once (e.g. a
failed WR plus a channel-wide error fan-out) and must tolerate it.
"""

from __future__ import annotations

from typing import Callable, Optional


class CompletionListener:
    def on_success(self, payload=None) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def on_failure(self, exc: Exception) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class FnListener(CompletionListener):
    """Adapter from a pair of callables."""

    def __init__(
        self,
        on_success: Optional[Callable] = None,
        on_failure: Optional[Callable[[Exception], None]] = None,
    ):
        self._ok = on_success
        self._err = on_failure

    def on_success(self, payload=None) -> None:
        if self._ok is not None:
            self._ok(payload)

    def on_failure(self, exc: Exception) -> None:
        if self._err is not None:
            self._err(exc)
