"""TpuNode — the per-process transport endpoint.

TPU-native analogue of RdmaNode.java (reference: /root/reference/src/
main/java/org/apache/spark/shuffle/rdma/RdmaNode.java). Preserved
semantics:

- binds a listener with port retries and a connection backlog
  (:75-97),
- owns the ProtectionDomain and the registered buffer pool (:99-104),
- a listener thread accepts incoming connections (the CM event loop
  analogue, :115-219) including **stale-channel replacement**: a new
  incoming connection from a peer we already track replaces the old
  passive channel (:134-148, 186-195),
- ``get_channel(host, port)`` caches active channels per remote
  address with connect retries and timeout; concurrent connect races
  resolve by keeping the first cached channel (:281-353),
- ``stop()`` tears down all channels then the listener (:369-396).

The reference pins one CQ thread per channel to a CPU vector from
``cpuList`` (:221-277); on this single-core host CPU pinning is a
deliberate no-op, but the per-channel completion-thread model is kept.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from sparkrdma_tpu.analysis.modelcheck import schedule_point
from sparkrdma_tpu.memory.buffer_manager import TpuBufferManager
from sparkrdma_tpu.memory.registry import ProtectionDomain
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.transport import wire
from sparkrdma_tpu.transport.channel import ChannelError, TpuChannel
from sparkrdma_tpu.utils.config import TpuShuffleConf

logger = logging.getLogger(__name__)

RecvCallback = Callable[[TpuChannel, bytes], None]


class TpuNode:
    def __init__(
        self,
        conf: TpuShuffleConf,
        host: str,
        is_executor: bool,
        executor_id: str,
        recv_listener: Optional[RecvCallback] = None,
        peer_lost_listener: Optional[Callable[[str], None]] = None,
    ):
        self.conf = conf
        self.host = host
        self.is_executor = is_executor
        self.executor_id = executor_id
        self._recv_listener = recv_listener
        self._peer_lost_listener = peer_lost_listener

        self.pd = ProtectionDomain()
        self.buffer_manager = TpuBufferManager(
            self.pd,
            is_executor=is_executor,
            max_agg_block=conf.max_agg_block,
            max_agg_prealloc=conf.max_agg_prealloc,
        )

        from sparkrdma_tpu.utils.affinity import CpuVectorAllocator

        self._cpu_vectors = CpuVectorAllocator(conf.cpu_list)
        self._active: Dict[Tuple[str, int, str], TpuChannel] = {}
        # passive channels per (peer executor_id, kind, index): an RPC
        # and a DATA connection from the same peer coexist, and striped
        # data-N connections get distinct index slots
        self._passive: Dict[Tuple[str, int, int], TpuChannel] = {}
        self._lock = threading.Lock()
        self._connect_locks: Dict[Tuple[str, int, str], threading.Lock] = {}
        self._stopped = False

        base_port = conf.executor_port if is_executor else conf.driver_port
        self._listener = self._bind(base_port)
        self.port = self._listener.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"listener-{executor_id}", daemon=True
        )
        self._accept_thread.start()
        logger.info(
            "TpuNode %s listening on %s:%d (%s)",
            executor_id,
            host,
            self.port,
            "executor" if is_executor else "driver",
        )

    # ------------------------------------------------------------------
    def _bind(self, base_port: int) -> socket.socket:
        last_err: Optional[OSError] = None
        for attempt in range(self.conf.port_max_retries):
            port = 0 if base_port == 0 else base_port + attempt
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                s.bind((self.host, port))
                s.listen(128)  # reference backlog 128, RdmaNode.java:86
                return s
            except OSError as e:
                last_err = e
                s.close()
        raise ChannelError(f"could not bind a listener port: {last_err}")

    def _accept_loop(self) -> None:
        while True:
            try:
                sock, addr = self._listener.accept()
            except OSError:
                return  # listener closed
            try:
                op = wire.read_exact(sock, 1)[0]
                if op != wire.OP_HELLO:
                    sock.close()
                    continue
                peer_port, peer_id, kind, index = wire.unpack_hello(sock)
            except OSError:
                sock.close()
                continue
            purpose = "data" if kind == wire.KIND_DATA else "rpc"
            get_registry().counter("transport.accepts", purpose=purpose).inc()
            channel = TpuChannel(
                self.conf,
                self.pd,
                sock,
                peer_desc=f"{peer_id}@{addr[0]}:{peer_port}",
                on_recv=self._recv_listener,
                on_disconnect=self._on_passive_disconnect,
                cpu_vector=self._cpu_vectors.next_vector(),
                purpose=purpose,
            )
            with self._lock:
                if self._stopped:
                    # connection was sitting in the backlog while stop()
                    # snapshotted the passive list — don't leak a live
                    # channel past teardown
                    stale = channel
                    channel = None
                else:
                    # passive channels are per (peer, kind, index): an RPC
                    # and a DATA connection from the same peer coexist
                    # (reference channel roles, RdmaChannel.java:110-154),
                    # and index-distinct data connections stripe
                    # (rdma_channel_conn_count analogue)
                    stale = self._passive.get((peer_id, kind, index))
                    self._passive[(peer_id, kind, index)] = channel
            if stale is not None and stale.is_connected:
                # stale-channel replacement (reference :134-148)
                logger.info("replacing stale passive channel for %s", peer_id)
                stale.stop()

    def _on_passive_disconnect(self, channel: TpuChannel) -> None:
        lost: Optional[str] = None
        with self._lock:
            stopped = self._stopped
            for key, ch in list(self._passive.items()):
                if ch is channel:
                    peer_id = key[0]
                    del self._passive[key]
                    # peer loss is per-peer, not per-channel-flavor: a
                    # dying data channel while the rpc channel is healthy
                    # (or vice versa) must not prune the peer's locations
                    if not any(k[0] == peer_id for k in self._passive):
                        lost = peer_id
                    break
        if lost is not None and not stopped and self._peer_lost_listener is not None:
            # peer-loss detection hook: the reference learns this from CM
            # DISCONNECTED events (RdmaNode.java:186-195) and the driver
            # prunes the peer's locations (RdmaShuffleManager.scala:199-221)
            self._peer_lost_listener(lost)

    # ------------------------------------------------------------------
    def get_channel(
        self,
        host: str,
        port: int,
        must_retry: bool = True,
        purpose: str = "rpc",
    ) -> TpuChannel:
        """Get or create the active channel to (host, port, purpose).

        Reference getRdmaChannel(addr, mustRetry), RdmaNode.java:281-353:
        cached per remote address; connect with attempts × timeout;
        dead cached channels are replaced. ``purpose`` ("rpc" | "data")
        selects the channel flavor (RdmaChannel.java:110-154): control
        messages and bulk READ payloads ride separate connections so an
        8 MiB in-flight READ never head-of-line blocks a location fetch.
        """
        key = (host, port, purpose)
        with self._lock:
            ch = self._active.get(key)
            if ch is not None and ch.is_connected:
                return ch
            connect_lock = self._connect_locks.setdefault(key, threading.Lock())
        # serialize concurrent connects to one peer: a duplicate
        # connection would trigger the peer's stale-channel replacement
        # and kill the live channel from under its users (the reference
        # resolves this race with putIfAbsent, :303-305; serializing
        # avoids creating the duplicate at all)
        with connect_lock:
            with self._lock:
                ch = self._active.get(key)
                if ch is not None and ch.is_connected:
                    return ch
            attempts = self.conf.max_connection_attempts if must_retry else 1
            last_err: Optional[Exception] = None
            ch = None
            for attempt in range(attempts):
                try:
                    ch = self._connect(host, port, purpose)
                    get_registry().counter("transport.connects", purpose=purpose).inc()
                    break
                except OSError as e:
                    last_err = e
                    get_registry().counter(
                        "transport.connect_retries", purpose=purpose
                    ).inc()
                    schedule_point("timer", "transport.backoff")
                    time.sleep(min(0.05 * (2**attempt), 1.0))
            if ch is None:
                raise ChannelError(
                    f"could not connect to {host}:{port} after {attempts} attempts: {last_err}"
                )
            with self._lock:
                self._active[key] = ch
            return ch

    def _connect(self, host: str, port: int, purpose: str = "rpc") -> TpuChannel:
        start = time.monotonic()
        sock = socket.create_connection(
            (host, port), timeout=self.conf.connect_timeout_ms / 1000.0
        )
        sock.settimeout(None)
        sock.sendall(
            wire.pack_hello(
                self.port, self.executor_id,
                wire.kind_of(purpose), wire.index_of(purpose),
            )
        )
        ch = TpuChannel(
            self.conf,
            self.pd,
            sock,
            peer_desc=f"{host}:{port}",
            on_recv=self._recv_listener,
            cpu_vector=self._cpu_vectors.next_vector(),
            purpose=purpose,
        )
        logger.debug(
            "connected to %s:%d in %.1f ms", host, port, (time.monotonic() - start) * 1e3
        )
        return ch

    # ------------------------------------------------------------------
    def stop(self) -> None:
        """Teardown: active channels, then listener, then passive (:369-396)."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            active = list(self._active.values())
            passive = list(self._passive.values())
            self._active.clear()
            self._passive.clear()
        for ch in active:
            ch.stop()
        try:
            self._listener.close()
        except OSError:
            pass
        self._accept_thread.join(timeout=self.conf.teardown_timeout_ms / 1000.0)
        for ch in passive:
            ch.stop()
        self.buffer_manager.stop()
        self.pd.dealloc()
