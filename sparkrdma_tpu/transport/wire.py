"""Low-level socket framing for the host transport.

The host path carries two verb types, mirroring the reference's use of
the NIC (SURVEY.md §2.4): two-sided SEND for RPC segments
(IBV_WR_SEND, RdmaChannel.java:395-424) and one-sided READ for data
(IBV_WR_RDMA_READ, RdmaChannel.java:360-393). A READ request names
``(mkey, address, length)`` triples; the passive side answers from its
ProtectionDomain without touching application code.

Frames (all big-endian):
  SEND      = op(1) payload_len(4) payload
  READ_REQ  = op(1) req_id(8) n(4) then n × [mkey(4) addr(8) len(4)]
  READ_RESP = op(1) req_id(8) total_len(8) payload
  READ_ERR  = op(1) req_id(8) msg_len(4) msg
  HELLO     = op(1) port(4) id_len(2) executor_id   (connection preamble)
  GOODBYE   = op(1)                                  (graceful disconnect)
"""

from __future__ import annotations

import socket
import struct
from typing import List, Tuple

OP_SEND = 1
OP_READ_REQ = 2
OP_READ_RESP = 3
OP_READ_ERR = 4
OP_HELLO = 5
OP_GOODBYE = 6
# READ_REQ2 (native plane): identical layout to READ_REQ, but announces
# the requester can pread the server's backing files directly (same-host
# fast path). A pure-Python server treats it exactly like READ_REQ and
# streams a READ_RESP — never OP_READ_FILE — preserving wire interop.
OP_READ_REQ2 = 9
OP_READ_FILE = 10

_U32 = struct.Struct(">I")
_U64 = struct.Struct(">Q")
_BLOCK = struct.Struct(">IQI")  # mkey(4) addr(8) len(4)


def read_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    remaining = n
    while remaining > 0:
        chunk = sock.recv(min(remaining, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed connection")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks) if len(chunks) != 1 else chunks[0]


def read_into(sock: socket.socket, view: memoryview) -> None:
    remaining = len(view)
    pos = 0
    while remaining > 0:
        n = sock.recv_into(view[pos:], remaining)
        if n == 0:
            raise ConnectionError("peer closed connection")
        pos += n
        remaining -= n


def pack_send(payload: bytes) -> bytes:
    return bytes([OP_SEND]) + _U32.pack(len(payload)) + payload


def pack_read_req(req_id: int, blocks: List[Tuple[int, int, int]]) -> bytes:
    parts = [bytes([OP_READ_REQ]), _U64.pack(req_id), _U32.pack(len(blocks))]
    for mkey, addr, length in blocks:
        parts.append(_BLOCK.pack(mkey, addr, length))
    return b"".join(parts)


def unpack_read_req(sock: socket.socket) -> Tuple[int, List[Tuple[int, int, int]]]:
    req_id = _U64.unpack(read_exact(sock, 8))[0]
    n = _U32.unpack(read_exact(sock, 4))[0]
    raw = read_exact(sock, n * _BLOCK.size)
    blocks = [_BLOCK.unpack_from(raw, i * _BLOCK.size) for i in range(n)]
    return req_id, blocks


def pack_read_resp_header(req_id: int, total_len: int) -> bytes:
    return bytes([OP_READ_RESP]) + _U64.pack(req_id) + _U64.pack(total_len)


def pack_read_err(req_id: int, msg: str) -> bytes:
    b = msg.encode("utf-8")
    return bytes([OP_READ_ERR]) + _U64.pack(req_id) + _U32.pack(len(b)) + b


# channel kinds carried in the HELLO preamble (reference channel roles,
# RdmaChannel.java:110-154: RPC vs DATA flavors per peer). The kind
# rides in the otherwise-unused high byte of the 4-byte port field, so
# legacy encoders (which store 0 there) parse as KIND_RPC.
KIND_RPC = 0
KIND_DATA = 1

def kind_of(purpose: str) -> int:
    """Wire kind for a channel purpose; raises on unknown values so a
    typo'd purpose can't silently create an RPC-tagged data channel.

    ``data`` sub-purposes (``data-0``, ``data-1``, ...) all map to
    KIND_DATA: the channel cache keys on the full purpose string, so
    distinct sub-purposes are distinct CONNECTIONS to the same peer —
    the striping lever (reference: rdma_channel_conn_count QP striping,
    RdmaChannel.java:54-56; here bench.py's 1-vs-M A/B pairs)."""
    if purpose == "rpc":
        return KIND_RPC
    if purpose == "data" or purpose.startswith("data-"):
        return KIND_DATA
    raise ValueError(f"unknown channel purpose {purpose!r} (rpc|data[-N])")


def index_of(purpose: str) -> int:
    """Channel index within a (peer, kind): ``data-N`` sub-purposes
    carry N so the acceptor can keep N striped connections from one
    peer alive side by side instead of stale-replacing them. ``rpc``
    and plain ``data`` are index 0 (the legacy encoding, bit-for-bit)."""
    if purpose.startswith("data-"):
        try:
            return int(purpose[5:]) & 0xFF
        except ValueError:
            pass
    return 0


def pack_hello(port: int, executor_id: str, kind: int = KIND_RPC,
               index: int = 0) -> bytes:
    b = executor_id.encode("utf-8")
    word = (kind << 24) | ((index & 0xFF) << 16) | (port & 0xFFFF)
    return bytes([OP_HELLO]) + _U32.pack(word) + struct.pack(">H", len(b)) + b


def split_hello_word(word: int) -> Tuple[int, int, int]:
    """(port, kind, index) from the 4-byte hello word — the single
    definition of its bit layout, shared with the native plane's ACCEPT
    aux. Byte 2 (bits 23-16) is the striping index, 0 from legacy
    encoders which always stored 0 there."""
    return word & 0xFFFF, (word >> 24) & 0xFF, (word >> 16) & 0xFF


def unpack_hello(sock: socket.socket) -> Tuple[int, str, int, int]:
    word = _U32.unpack(read_exact(sock, 4))[0]
    (n,) = struct.unpack(">H", read_exact(sock, 2))
    port, kind, index = split_hello_word(word)
    return port, read_exact(sock, n).decode("utf-8"), kind, index
