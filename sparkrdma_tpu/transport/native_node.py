"""NativeTpuNode / NativeTpuChannel — host transport over the C++ data plane.

Same public surface as the pure-Python :class:`TpuNode`/:class:`TpuChannel`
(node.py / channel.py) and the same wire format, but every per-byte
operation — frame parsing, the passive one-sided READ service, payload
streaming into destination buffers, socket IO — runs inside
``transport.cpp``'s epoll loop. Python keeps orchestration only:
channel caching, retry policy, listener dispatch (one CQ-poll thread
per node, the RdmaThread analogue pinned to ``srt_poll_cq``).

This is the framework's libdisni equivalent (SURVEY.md §2.2): the
reference's JVM held the same division — Scala/Java orchestration above,
native verbs doing the bytes below. Selected via
``tpu.shuffle.transport = native`` (default ``python``); both transports
interoperate on the wire, so a cluster can mix them.
"""

from __future__ import annotations

import ctypes
import logging
import os
import queue
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sparkrdma_tpu.memory.buffer_manager import TpuBufferManager
from sparkrdma_tpu.native import transport_lib as tl
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.testing import faults as _faults
from sparkrdma_tpu.transport import wire
from sparkrdma_tpu.transport.channel import ChannelError
from sparkrdma_tpu.transport.completion import CompletionListener
from sparkrdma_tpu.utils.config import TpuShuffleConf

logger = logging.getLogger(__name__)


def _addr_of(view) -> int:
    """Raw address of a buffer-protocol object without copying (works
    for read-only buffers too, unlike ctypes.from_buffer)."""
    return np.frombuffer(view, dtype=np.uint8).ctypes.data


class MappedDelivery:
    """Result of a mapped one-sided READ (``read_mapped_in_queue``).

    ``views`` holds one read-only memoryview per requested block, in
    request order. On the same-host fast path the views are mmap'd
    page-cache windows of the peer's backing files — the bytes were
    never copied anywhere; consumers read them in place (stage to the
    device, checksum, parse) and then MUST call :meth:`release` to
    drop the mappings. On the streamed fallback (remote peer, unbacked
    region) the views slice one malloc'd blob that release() frees.
    Either way: views are INVALID after release()."""

    __slots__ = ("views", "mapped", "_free", "_released")

    def __init__(self, views, mapped: bool, free_fn):
        self.views = views
        self.mapped = mapped  # True: zero-copy mmap; False: copied blob
        self._free = free_fn
        self._released = False

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self.views = []
        self._free()

    def __del__(self):  # leak guard: mappings must not outlive the GC
        try:
            self.release()
        except Exception:
            pass


class NativeProtectionDomain:
    """PD over the native region registry.

    ``register`` inserts the region into the C++ registry (so remote
    one-sided READs are served entirely natively) and mirrors it in a
    Python dict so local consumers can still ``resolve`` views."""

    supports_file_regions = True  # file hints feed the same-host pread path

    def __init__(self, node: "NativeTpuNode"):
        self._node = node
        self._mirror: Dict[int, memoryview] = {}
        self._lock = threading.Lock()

    def register(
        self,
        view: memoryview,
        file_path: Optional[str] = None,
        file_offset: int = 0,
        file_mutable: bool = False,
        file_stat: Optional[os.stat_result] = None,
    ) -> int:
        """Register a region; when ``file_path`` names a file whose
        bytes at ``file_offset`` are identical to the region (an shm
        slab or a mapped shuffle file), same-host peers serve READs by
        pread-ing it straight from page cache instead of streaming.

        ``file_stat`` should be the caller's ``os.fstat`` of the SAME
        fd that backs the region's mapping — identity taken from a
        fresh ``os.stat(path)`` (the fallback) can race a concurrent
        rewrite of the path. ``file_mutable`` declares the backing's
        content may change after registration while staying equal to
        the region memory (shm slabs: the file pages ARE the region);
        immutable backings (committed shuffle files) get a full
        (dev, ino, size, mtime_ns) identity check so a task re-attempt
        rewriting the same path can never serve wrong bytes
        (transport.cpp READ_FILE wire doc)."""
        np_handle = self._node._np
        if not np_handle:
            raise RuntimeError("native node stopped; cannot register regions")
        if file_path:
            if file_stat is None:
                try:
                    file_stat = os.stat(file_path)
                except OSError:
                    file_stat = None
            if file_stat is None:
                # unverifiable backing: plain streamed region
                mkey = tl.load().srt_reg(np_handle, _addr_of(view), len(view))
            else:
                size_id = 0 if file_mutable else file_stat.st_size
                mtime_id = 0 if file_mutable else file_stat.st_mtime_ns
                mkey = tl.load().srt_reg_file(
                    np_handle, _addr_of(view), len(view),
                    file_path.encode(), file_offset,
                    file_stat.st_dev, file_stat.st_ino, size_id, mtime_id,
                )
        else:
            mkey = tl.load().srt_reg(np_handle, _addr_of(view), len(view))
        with self._lock:
            self._mirror[mkey] = view
        return mkey

    def deregister(self, mkey: int) -> None:
        np_handle = self._node._np
        if np_handle:
            tl.load().srt_dereg(np_handle, mkey)
        with self._lock:
            self._mirror.pop(mkey, None)

    def region_length(self, mkey: int) -> int:
        from sparkrdma_tpu.memory.registry import RegionError

        with self._lock:
            view = self._mirror.get(mkey)
        if view is None:
            raise RegionError(f"unknown mkey {mkey}")
        return len(view)

    def resolve(self, mkey: int, offset: int, length: int) -> memoryview:
        from sparkrdma_tpu.memory.registry import RegionError

        with self._lock:
            view = self._mirror.get(mkey)
        if view is None:
            raise RegionError(f"unknown mkey {mkey}")
        if offset < 0 or length < 0 or offset + length > len(view):
            raise RegionError(
                f"resolve out of bounds: mkey {mkey} [{offset}, {offset + length}) "
                f"in region of {len(view)}"
            )
        return view[offset : offset + length]

    def region_count(self) -> int:
        with self._lock:
            return len(self._mirror)

    def dealloc(self) -> None:
        with self._lock:
            keys = list(self._mirror.keys())
            self._mirror.clear()
        lib = tl.load()
        if lib is not None and self._node._np:
            for mkey in keys:
                lib.srt_dereg(self._node._np, mkey)


class NativeTpuChannel:
    """Handle to one native connection (id-based).

    Carries the reference's **send-budget** semantics
    (RdmaChannel.java:54-56, 330-358): ``send_queue_depth`` permits per
    channel, one per WR (send segment or read block); WRs that cannot
    acquire permits queue in an overflow deque drained as completions
    reclaim, with a one-time oversubscription warning."""

    def __init__(self, node: "NativeTpuNode", channel_id: int, peer_desc: str,
                 purpose: str = "rpc"):
        self._node = node
        self.channel_id = channel_id
        self.peer_desc = peer_desc
        self.purpose = purpose
        self._dead = threading.Event()
        self._budget = node.conf.send_queue_depth
        self._budget_lock = threading.Lock()
        self._overflow: "list" = []
        self._warned_oversubscription = False
        # same metric names as the pure-Python TpuChannel so registry
        # views stay transport-agnostic; per-byte completions live in
        # the C++ loop, so only the Python-visible verbs are counted
        reg = get_registry()
        self._m_sends = reg.counter("transport.sends", purpose=purpose)
        self._m_send_bytes = reg.counter("transport.send_bytes", purpose=purpose)
        self._m_reads = reg.counter("transport.reads", purpose=purpose)
        self._m_read_bytes = reg.counter("transport.read_bytes", purpose=purpose)
        self._m_overflow = reg.counter("transport.send_overflow", purpose=purpose)

    def _acquire_or_queue(self, permits: int, item) -> bool:
        with self._budget_lock:
            if self._budget >= permits:
                self._budget -= permits
                return True
            if not self._warned_oversubscription:
                self._warned_oversubscription = True
                logger.warning(
                    "channel %s send queue oversubscribed; consider raising "
                    "tpu.shuffle.sendQueueDepth (current %d)",
                    self.peer_desc, self._node.conf.send_queue_depth,
                )
            self._m_overflow.inc()
            self._overflow.append(item)
            return False

    def _reclaim(self, permits: int) -> None:
        runnable = []
        with self._budget_lock:
            self._budget += permits
            while self._overflow:
                p, fn = self._overflow[0]
                if self._budget < p:
                    break
                self._budget -= p
                runnable.append(fn)
                self._overflow.pop(0)
        for fn in runnable:
            fn()

    def _wrap_reclaim(self, listener: Optional[CompletionListener], permits: int):
        from sparkrdma_tpu.transport.completion import FnListener

        def ok(payload):
            self._reclaim(permits)
            if listener:
                listener.on_success(payload)

        def err(e):
            self._reclaim(permits)
            if listener:
                listener.on_failure(e)

        return FnListener(ok, err)

    def _ring_wrap(self, listener: Optional[CompletionListener], nbytes: int):
        """Stamp the READ's submit→complete interval into the node's
        timestamp ring (critical-path attribution, obs/critpath.py):
        the native data plane is otherwise span-dark — completions fire
        on the C++ epoll loop with no Python frame to trace."""
        from sparkrdma_tpu.transport.completion import FnListener

        t0 = time.perf_counter()
        ring = self._node._read_ring

        def ok(payload):
            ring.append((t0, time.perf_counter(), nbytes))
            if listener:
                listener.on_success(payload)

        def err(e):
            if listener:
                listener.on_failure(e)

        return FnListener(ok, err)

    # -- verb API (parity with TpuChannel) -----------------------------
    def send_in_queue(self, listener: CompletionListener, segments: Sequence[bytes]) -> None:
        plan = _faults.active()
        if plan is not None:
            listener, handled = plan.on_send(self, listener, segments)
            if handled:
                return
        segments = [bytes(s) for s in segments]
        self._m_sends.inc(len(segments))
        self._m_send_bytes.inc(sum(len(s) for s in segments))
        permits = max(1, len(segments))
        wrapped = self._wrap_reclaim(listener, permits)
        def post():
            self._node._post_send(self, wrapped, segments)

        if self._acquire_or_queue(permits, (permits, post)):
            post()

    def read_in_queue(
        self,
        listener: CompletionListener,
        dst_views: List[memoryview],
        blocks: List[Tuple[int, int, int]],
    ) -> None:
        plan = _faults.active()
        if plan is not None:
            listener, handled = plan.on_read(self, listener, dst_views, blocks)
            if handled:
                return
        total = sum(b[2] for b in blocks)
        if sum(len(v) for v in dst_views) != total:
            raise ValueError("destination size != total remote block length")
        self._m_reads.inc(len(blocks))
        self._m_read_bytes.inc(total)
        permits = max(1, len(blocks))
        wrapped = self._wrap_reclaim(self._ring_wrap(listener, total), permits)
        def post():
            self._node._post_read(self, wrapped, dst_views, blocks)

        if self._acquire_or_queue(permits, (permits, post)):
            post()

    def read_mapped_in_queue(
        self,
        listener: CompletionListener,
        blocks: List[Tuple[int, int, int]],
    ) -> None:
        """One-sided READ with mapped delivery: no destination buffer.
        ``listener.on_success`` receives a :class:`MappedDelivery` —
        same-host file-backed blocks arrive as zero-copy page-cache
        mappings; anything else falls back to one streamed copy. The
        listener owns the delivery and must release() it."""
        plan = _faults.active()
        if plan is not None:
            # dst_views=None marks the mapped (read-only delivery) flavor
            listener, handled = plan.on_read(self, listener, None, blocks)
            if handled:
                return
        total = sum(b[2] for b in blocks)
        self._m_reads.inc(len(blocks))
        self._m_read_bytes.inc(total)
        permits = max(1, len(blocks))
        wrapped = self._wrap_reclaim(self._ring_wrap(listener, total), permits)
        def post():
            self._node._post_read_mapped(self, wrapped, blocks)

        if self._acquire_or_queue(permits, (permits, post)):
            post()

    @property
    def is_connected(self) -> bool:
        return not self._dead.is_set()

    def stop(self) -> None:
        self._node._close_channel(self)


class NativeTpuNode:
    """Per-process endpoint over the native event loop (TpuNode parity)."""

    def __init__(
        self,
        conf: TpuShuffleConf,
        host: str,
        is_executor: bool,
        executor_id: str,
        recv_listener: Optional[Callable] = None,
        peer_lost_listener: Optional[Callable[[str], None]] = None,
    ):
        lib = tl.load()
        if lib is None:
            raise ChannelError("native transport unavailable (g++ build failed)")
        self._lib = lib
        self.conf = conf
        self.host = host
        self.is_executor = is_executor
        self.executor_id = executor_id
        self._recv_listener = recv_listener
        self._peer_lost_listener = peer_lost_listener

        base_port = conf.executor_port if is_executor else conf.driver_port
        self._np = lib.srt_node_create(
            host.encode(), base_port, conf.port_max_retries
        )
        if not self._np:
            raise ChannelError("could not bind a listener port (native)")
        self.port = lib.srt_node_port(self._np)

        self.pd = NativeProtectionDomain(self)
        self.buffer_manager = TpuBufferManager(
            self.pd,
            is_executor=is_executor,
            max_agg_block=conf.max_agg_block,
            max_agg_prealloc=conf.max_agg_prealloc,
        )

        self._channels: Dict[int, NativeTpuChannel] = {}  # id -> handle
        self._active: Dict[Tuple[str, int, str], NativeTpuChannel] = {}
        # passive channels per (peer executor_id, kind): an RPC and a
        # DATA connection from the same peer coexist (reference channel
        # roles, RdmaChannel.java:110-154)
        self._passive: Dict[Tuple[str, int, int], NativeTpuChannel] = {}
        self._peer_of_channel: Dict[int, str] = {}
        self._connect_locks: Dict[Tuple[str, int, str], threading.Lock] = {}
        self._lock = threading.Lock()

        # outstanding work requests: wr_id -> (listener, keepalive)
        self._wrs: Dict[int, Tuple[CompletionListener, object]] = {}
        self._next_wr = 1
        # READ submit→complete timestamp ring (bounded; appended from
        # completion threads, drained by the fetcher into
        # ``transport.native_read`` spans — obs/critpath.py host-read
        # attribution). deque ops are atomic, so no extra lock.
        self._read_ring: Deque[Tuple[float, float, int]] = deque(maxlen=4096)
        # mapped READs in flight: wr_id -> block lengths (for slicing a
        # streamed-fallback blob back into per-block views)
        self._mapped_wrs: Dict[int, List[int]] = {}

        if not conf.file_fastpath:
            # bench/remote-simulation knob: stream every non-mapped READ
            lib.srt_set_file_fastpath(self._np, 0)
        if conf.file_workers > 1:
            lib.srt_set_file_workers(self._np, conf.file_workers)
        if conf.force_sendfile:
            lib.srt_set_force_sendfile(self._np, 1)
        backend = conf.native_read_backend
        if backend != "auto":
            lib.srt_set_read_backend(self._np, tl.READ_BACKENDS[backend])

        # consume lanes: READ_DONE checksum+decode sharded across
        # threads, routed by channel so per-source completion order is
        # preserved (the reduce pipeline's sequencer restores global
        # order — delivery stays byte-identical). 1 lane degenerates to
        # the old inline consume on the poll thread.
        reg = get_registry()
        self._consume_workers = conf.native_consume_workers
        self._m_consume_busy = reg.counter("transport.consume.busy_ms")
        self._consume_lanes: List["queue.SimpleQueue"] = []
        self._consume_threads: List[threading.Thread] = []
        if self._consume_workers > 1:
            # gauge counts lanes actually running: inline consume
            # (workers == 1) contributes nothing (OBSERVABILITY.md)
            reg.gauge("transport.consume.workers").add(self._consume_workers)
            for i in range(self._consume_workers):
                lane: "queue.SimpleQueue" = queue.SimpleQueue()
                t = threading.Thread(
                    target=self._consume_loop, args=(lane,),
                    name=f"srt-consume-{executor_id}-{i}", daemon=True,
                )
                self._consume_lanes.append(lane)
                self._consume_threads.append(t)
                t.start()

        # submission-plane counter mirror: native atomics -> registry
        # counters, synced as deltas from the poll thread (~1 Hz)
        self._sq_synced = {
            "submits": 0, "batches": 0, "completions": 0,
            "backend_fallbacks": 0,
        }
        self._sq_next_sync = 0.0

        self._stopped = threading.Event()
        self._cq_thread = threading.Thread(
            target=self._poll_loop, name=f"srt-cq-{executor_id}", daemon=True
        )
        self._cq_thread.start()
        logger.info(
            "NativeTpuNode %s listening on %s:%d (%s)",
            executor_id, host, self.port,
            "executor" if is_executor else "driver",
        )

    # ------------------------------------------------------------------
    # verb posting
    # ------------------------------------------------------------------
    def _alloc_wr(self, listener: CompletionListener, keepalive=None) -> int:
        with self._lock:
            wr = self._next_wr
            self._next_wr += 1
            self._wrs[wr] = (listener, keepalive)
        return wr

    def _post_send(self, ch: NativeTpuChannel, listener, segments: Sequence[bytes]) -> None:
        if ch._dead.is_set():
            if listener:
                listener.on_failure(ChannelError(f"channel {ch.peer_desc} is down"))
            return
        wr = self._alloc_wr(listener)
        n = len(segments)
        for i, seg in enumerate(segments):
            seg = bytes(seg)
            # only the last frame of the batch is signalled (the
            # reference signals only the last WR of a list, :383-390)
            self._lib.srt_post_send(
                self._np, ch.channel_id, seg, len(seg),
                wr if i == n - 1 else 0, 1 if i == n - 1 else 0,
            )
        if n == 0:
            self._complete_wr(wr, None, None)

    def _post_read(self, ch, listener, dst_views: List[memoryview], blocks) -> None:
        if ch._dead.is_set():
            if listener:
                listener.on_failure(ChannelError(f"channel {ch.peer_desc} is down"))
            return
        # pair destinations with blocks 1:1 where lengths align (the
        # fetcher always does); otherwise stage contiguously and scatter
        aligned = len(dst_views) == len(blocks) and all(
            len(v) == b[2] for v, b in zip(dst_views, blocks)
        )
        if aligned and len(blocks) > 0:
            remaining = [len(blocks)]
            failed = [False]
            lock = threading.Lock()

            def sub_listener(i):
                def ok(_):
                    with lock:
                        remaining[0] -= 1
                        done = remaining[0] == 0 and not failed[0]
                    if done and listener:
                        listener.on_success(None)

                def err(e):
                    with lock:
                        first = not failed[0]
                        failed[0] = True
                    if first and listener:
                        listener.on_failure(e)

                from sparkrdma_tpu.transport.completion import FnListener

                return FnListener(ok, err)

            for i, (view, block) in enumerate(zip(dst_views, blocks)):
                arr = (ctypes.c_uint64 * 3)(block[0], block[1], block[2])
                wr = self._alloc_wr(sub_listener(i), keepalive=view)
                self._lib.srt_post_read(
                    self._np, ch.channel_id, wr, _addr_of(view), arr, 1
                )
            return
        # general case: one staging buffer, scatter on completion
        total = sum(b[2] for b in blocks)
        staging = np.empty((total,), dtype=np.uint8)

        def scatter(_):
            off = 0
            for view in dst_views:
                n = len(view)
                view[:] = staging[off : off + n].tobytes()
                off += n
            if listener:
                listener.on_success(None)

        from sparkrdma_tpu.transport.completion import FnListener

        wr = self._alloc_wr(
            FnListener(scatter, listener.on_failure if listener else None),
            keepalive=staging,
        )
        flat = (ctypes.c_uint64 * (3 * len(blocks)))()
        for i, b in enumerate(blocks):
            flat[3 * i], flat[3 * i + 1], flat[3 * i + 2] = b
        self._lib.srt_post_read(
            self._np, ch.channel_id, wr, staging.ctypes.data, flat, len(blocks)
        )

    def _post_read_mapped(self, ch, listener, blocks) -> None:
        if ch._dead.is_set():
            if listener:
                listener.on_failure(ChannelError(f"channel {ch.peer_desc} is down"))
            return
        wr = self._alloc_wr(listener)
        with self._lock:
            # remember the block lengths so the completion can slice a
            # streamed-fallback blob back into per-block views
            self._mapped_wrs[wr] = [b[2] for b in blocks]
        flat = (ctypes.c_uint64 * (3 * len(blocks)))()
        for i, b in enumerate(blocks):
            flat[3 * i], flat[3 * i + 1], flat[3 * i + 2] = b
        self._lib.srt_post_read_mapped(
            self._np, ch.channel_id, wr, flat, len(blocks)
        )

    def _mapped_delivery(self, c, lens) -> MappedDelivery:
        """Build the delivery object for a mapped READ completion."""
        lib = self._lib
        if c.aux == 1:
            # n x 32B host-endian records [user_ptr, len, base, map_len]
            n = c.payload_len // 32 if c.payload else 0
            rec = (
                np.ctypeslib.as_array(
                    ctypes.cast(c.payload, ctypes.POINTER(ctypes.c_uint64)),
                    shape=(n * 4,),
                ).reshape(n, 4).copy()
                if n
                else np.zeros((0, 4), np.uint64)
            )
            views = [
                memoryview(
                    (ctypes.c_ubyte * int(r[1])).from_address(int(r[0]))
                ).cast("B").toreadonly()  # writes would SIGSEGV PROT_READ pages
                for r in rec
            ]

            def free():
                for r in rec:
                    lib.srt_unmap(
                        ctypes.c_void_p(int(r[2])), ctypes.c_uint64(int(r[3]))
                    )

            return MappedDelivery(views, True, free)
        # aux == 0: contiguous copied blob; we take ownership (the poll
        # loop's blanket free is skipped by nulling c.payload)
        addr, total = c.payload, c.payload_len
        c.payload = None
        blob = (
            memoryview((ctypes.c_ubyte * total).from_address(addr))
            .cast("B")
            .toreadonly()  # match the mmap path: views are read-only
            if addr
            else memoryview(b"")
        )
        views = []
        off = 0
        for ln in lens:
            views.append(blob[off : off + ln])
            off += ln

        def free_blob(addr=addr):
            if addr:
                lib.srt_free_payload(ctypes.c_void_p(addr))

        return MappedDelivery(views, False, free_blob)

    def _complete_wr(self, wr_id: int, payload, error: Optional[Exception]) -> None:
        with self._lock:
            entry = self._wrs.pop(wr_id, None)
        if entry is None:
            return
        listener, _keep = entry
        if listener is None:
            return
        try:
            if error is None:
                listener.on_success(payload)
            else:
                listener.on_failure(error)
        except Exception:
            logger.exception("completion listener raised")

    # ------------------------------------------------------------------
    # consume lanes (sharded READ_DONE checksum+decode)
    # ------------------------------------------------------------------
    def _consume(self, wr_id: int, payload, error: Optional[Exception]) -> None:
        t0 = time.monotonic()
        try:
            self._complete_wr(wr_id, payload, error)
        finally:
            self._m_consume_busy.inc(int((time.monotonic() - t0) * 1000))

    def _consume_loop(self, lane: "queue.SimpleQueue") -> None:
        while True:
            item = lane.get()
            if item is None:
                return
            self._consume(*item)

    def _sync_sq_metrics(self) -> None:
        """Mirror the native SubmissionPlane atomics into the process
        registry as deltas (multiple nodes sum into one family)."""
        self._sq_next_sync = time.monotonic() + 1.0
        np_handle = self._np
        if not np_handle:
            return
        lib, reg = self._lib, get_registry()
        cur = {
            "submits": lib.srt_stat_sq_submits(np_handle),
            "batches": lib.srt_stat_sq_batches(np_handle),
            "completions": lib.srt_stat_sq_completions(np_handle),
            "backend_fallbacks": lib.srt_stat_sq_backend_fallbacks(np_handle),
        }
        d = cur["submits"] - self._sq_synced["submits"]
        if d > 0:
            reg.counter("transport.sq.submits").inc(d)
        d = cur["batches"] - self._sq_synced["batches"]
        if d > 0:
            reg.counter("transport.sq.batches").inc(d)
        d = cur["completions"] - self._sq_synced["completions"]
        if d > 0:
            reg.counter("transport.sq.completions").inc(d)
        d = cur["backend_fallbacks"] - self._sq_synced["backend_fallbacks"]
        if d > 0:
            reg.counter("transport.sq.backend_fallbacks").inc(d)
        self._sq_synced = cur
        depth = lib.srt_stat_sq_depth_hwm(np_handle)
        gauge = reg.gauge("transport.sq.sqe_depth")
        if depth > gauge.value:
            gauge.set(depth)

    # ------------------------------------------------------------------
    # CQ poll loop (RdmaThread analogue)
    # ------------------------------------------------------------------
    def _poll_loop(self) -> None:
        # the node-wide CQ thread takes the first configured vector
        # (RdmaThread pinning analogue)
        from sparkrdma_tpu.utils.affinity import CpuVectorAllocator, pin_current_thread

        pin_current_thread(CpuVectorAllocator(self.conf.cpu_list).next_vector())
        comps = (tl.SrtComp * 64)()
        while not self._stopped.is_set():
            k = self._lib.srt_poll_cq(self._np, comps, 64, 100)
            for i in range(k):
                c = comps[i]
                try:
                    self._dispatch(c)
                except Exception:
                    logger.exception("error dispatching native completion")
                finally:
                    if c.payload:
                        self._lib.srt_free_payload(c.payload)
            if time.monotonic() >= self._sq_next_sync:
                self._sync_sq_metrics()

    def _dispatch(self, c: tl.SrtComp) -> None:
        if c.kind == tl.COMP_ACCEPT:
            peer_id = (
                ctypes.string_at(c.payload, c.payload_len).decode("utf-8")
                if c.payload
                else ""
            )
            # aux is the raw 32-bit hello word (wire.pack_hello layout)
            peer_port, chan_kind, chan_index = wire.split_hello_word(c.aux)
            purpose = "data" if chan_kind == wire.KIND_DATA else "rpc"
            get_registry().counter("transport.accepts", purpose=purpose).inc()
            ch = NativeTpuChannel(
                self, c.channel, f"{peer_id}:{peer_port}", purpose=purpose
            )
            with self._lock:
                self._channels[c.channel] = ch
                # keyed by (peer, kind, index): index-distinct striped
                # data connections from one peer coexist instead of
                # stale-replacing each other (wire.index_of)
                stale = self._passive.get((peer_id, chan_kind, chan_index))
                self._passive[(peer_id, chan_kind, chan_index)] = ch
                self._peer_of_channel[c.channel] = peer_id
            if stale is not None and stale.is_connected:
                logger.info("replacing stale passive channel for %s", peer_id)
                stale.stop()
            return
        if c.kind == tl.COMP_RECV:
            payload = (
                ctypes.string_at(c.payload, c.payload_len) if c.payload else b""
            )
            with self._lock:
                ch = self._channels.get(c.channel)
            if ch is not None and self._recv_listener is not None:
                self._recv_listener(ch, payload)
            return
        if c.kind == tl.COMP_SEND_DONE:
            err = (
                None
                if c.status == tl.ST_OK
                else ChannelError("send failed (channel down)")
            )
            self._complete_wr(c.wr_id, None, err)
            return
        if c.kind == tl.COMP_READ_DONE:
            with self._lock:
                lens = self._mapped_wrs.pop(c.wr_id, None)
            # materialize the payload/error NOW, on the poll thread:
            # the comps array is reused next batch and c.payload is
            # freed in the poll loop's finally — nothing native may
            # leak into a consume lane
            error: Optional[Exception] = None
            payload = None
            if c.status == tl.ST_OK:
                payload = (
                    self._mapped_delivery(c, lens) if lens is not None else None
                )
            elif c.status == tl.ST_REMOTE_ERR:
                msg = (
                    ctypes.string_at(c.payload, c.payload_len).decode("utf-8")
                    if c.payload
                    else "remote error"
                )
                error = ChannelError(f"remote READ failed: {msg}")
            else:
                error = ChannelError("READ failed (channel down)")
            if self._consume_lanes:
                # shard checksum+decode across the lanes; channel-keyed
                # routing keeps per-source FIFO order (error READ_DONEs
                # posted by a dying channel stay ordered with its data)
                lane = self._consume_lanes[c.channel % len(self._consume_lanes)]
                lane.put((c.wr_id, payload, error))
            else:
                self._consume(c.wr_id, payload, error)
            return
        if c.kind == tl.COMP_CHANNEL_DOWN:
            lost_peer: Optional[str] = None
            with self._lock:
                ch = self._channels.pop(c.channel, None)
                peer = self._peer_of_channel.pop(c.channel, None)
                if peer is not None:
                    was_tracked = False
                    for key, p in list(self._passive.items()):
                        if p is ch:
                            del self._passive[key]
                            was_tracked = True
                    # peer loss is per-peer, not per-channel-flavor: only
                    # signal once the peer has no surviving passive
                    # channel of any kind (reference treats CM DISCONNECT
                    # as peer-scoped, RdmaNode.java:186-195). A stale
                    # channel already replaced out of _passive must not
                    # re-signal a loss the replacement already implied.
                    if was_tracked and not any(k[0] == peer for k in self._passive):
                        lost_peer = peer
                for key, a in list(self._active.items()):
                    if a is ch:
                        del self._active[key]
            if ch is not None:
                ch._dead.set()
            if (
                lost_peer is not None
                and not self._stopped.is_set()
                and self._peer_lost_listener is not None
            ):
                self._peer_lost_listener(lost_peer)
            return

    # ------------------------------------------------------------------
    # channel cache (TpuNode.get_channel parity)
    # ------------------------------------------------------------------
    def get_channel(
        self,
        host: str,
        port: int,
        must_retry: bool = True,
        purpose: str = "rpc",
    ) -> NativeTpuChannel:
        """Cached active channel per (host, port, purpose) — same
        contract as TpuNode.get_channel (node.py): ``purpose``
        ("rpc" | "data") selects the channel flavor so bulk READ
        payloads never head-of-line block control messages
        (RdmaChannel.java:110-154)."""
        key = (host, port, purpose)
        # srt_connect's kind arg carries the composed (kind, index) pair;
        # the C side places it in hello-word bits 31-16 so the acceptor's
        # wire.split_hello_word sees kind in byte 3, index in byte 2
        kind = (wire.kind_of(purpose) << 8) | wire.index_of(purpose)
        with self._lock:
            ch = self._active.get(key)
            if ch is not None and ch.is_connected:
                return ch
            connect_lock = self._connect_locks.setdefault(key, threading.Lock())
        with connect_lock:
            with self._lock:
                ch = self._active.get(key)
                if ch is not None and ch.is_connected:
                    return ch
            attempts = self.conf.max_connection_attempts if must_retry else 1
            cid = 0
            for attempt in range(attempts):
                cid = self._lib.srt_connect(
                    self._np, host.encode(), port, self.port,
                    self.executor_id.encode(), self.conf.connect_timeout_ms,
                    kind,
                )
                if cid:
                    get_registry().counter(
                        "transport.connects", purpose=purpose
                    ).inc()
                    break
                get_registry().counter(
                    "transport.connect_retries", purpose=purpose
                ).inc()
                time.sleep(min(0.05 * (2 ** attempt), 1.0))
            if not cid:
                raise ChannelError(
                    f"could not connect to {host}:{port} after {attempts} attempts"
                )
            ch = NativeTpuChannel(self, cid, f"{host}:{port}", purpose=purpose)
            with self._lock:
                self._channels[cid] = ch
                self._active[key] = ch
            return ch

    def drain_read_ring(self) -> List[Tuple[float, float, int]]:
        """Pop and return every buffered READ ``(t_submit, t_complete,
        nbytes)`` stamp (oldest first). Consumers turn these into
        ``transport.native_read`` spans; the ring is bounded, so stamps
        nobody drains age out instead of accumulating."""
        out: List[Tuple[float, float, int]] = []
        ring = self._read_ring
        while True:
            try:
                out.append(ring.popleft())
            except IndexError:
                return out

    def read_path_stats(self) -> Tuple[int, int]:
        """(file_fast_path_reads, streamed_reads) completed by this
        node's client side — observability for tests and the bench."""
        np_handle = self._np  # capture once: stop() nulls it concurrently
        if not np_handle:
            return (0, 0)
        return (
            self._lib.srt_stat_file_reads(np_handle),
            self._lib.srt_stat_streamed_reads(np_handle),
        )

    def split_parts(self) -> int:
        """Parts created by splitting multi-block pread tasks across
        the worker pool (0 = the split never engaged)."""
        np_handle = self._np
        if not np_handle:
            return 0
        return self._lib.srt_stat_split_parts(np_handle)

    def block_stripes(self) -> int:
        """Sub-ranges created by striping single large blocks' preads
        across the worker pool (0 = the stripe never engaged)."""
        np_handle = self._np
        if not np_handle:
            return 0
        return self._lib.srt_stat_block_stripes(np_handle)

    def sq_stats(self) -> Dict[str, object]:
        """Submission-plane accounting (transport.cpp SubmissionPlane):
        SQ counters, the resolved read backend (`auto` probed), and
        whether io_uring support was compiled in."""
        np_handle = self._np
        if not np_handle:
            return {}
        lib = self._lib
        return {
            "submits": lib.srt_stat_sq_submits(np_handle),
            "batches": lib.srt_stat_sq_batches(np_handle),
            "sqe_depth": lib.srt_stat_sq_depth_hwm(np_handle),
            "completions": lib.srt_stat_sq_completions(np_handle),
            "backend_fallbacks": lib.srt_stat_sq_backend_fallbacks(np_handle),
            "backend": {1: "iouring", 2: "pread", 3: "mapped"}.get(
                lib.srt_read_backend_effective(np_handle), "pread"
            ),
            "uring_compiled": bool(lib.srt_uring_compiled()),
            "consume_workers": self._consume_workers,
        }

    def force_uring_probe_fail(self, on: bool) -> None:
        """Test seam (and the ``read:enosys`` fault kind): make the
        io_uring availability probe behave like an ENOSYS kernel, so
        degradation to pread is exercised deterministically."""
        np_handle = self._np
        if np_handle:
            self._lib.srt_sq_force_probe_fail(np_handle, 1 if on else 0)

    def set_read_backend(self, backend: str) -> None:
        """Switch the submission-plane backend at runtime (normally
        fixed by ``tpu.shuffle.native.readBackend`` at init) — the
        per-backend A/Bs and byte-identity tests flip it between sides
        on one node."""
        np_handle = self._np
        if np_handle:
            self._lib.srt_set_read_backend(
                np_handle, tl.READ_BACKENDS[backend]
            )

    def _close_channel(self, ch: NativeTpuChannel) -> None:
        ch._dead.set()
        if not self._stopped.is_set():
            self._lib.srt_close_channel(self._np, ch.channel_id)

    # ------------------------------------------------------------------
    def stop(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        # srt_node_stop frees the Node, so the poll thread must be OUT
        # of srt_poll_cq first — the loop re-checks _stopped every
        # 100 ms poll timeout, so this join is bounded unless a
        # completion listener wedged
        self._cq_thread.join(timeout=10.0)
        if self._cq_thread.is_alive():
            # a wedged listener: leak the native node rather than free
            # it under the still-running poller (use-after-free)
            logger.error("cq poll thread failed to stop; leaking native node")
            self._np = None
        # drain the consume lanes: the poll thread is out, so every
        # READ_DONE it routed is already queued; sentinels let each lane
        # finish its FIFO before the node tears down underneath it
        for lane in self._consume_lanes:
            lane.put(None)
        for t in self._consume_threads:
            t.join(timeout=10.0)
        if self._consume_threads:
            get_registry().gauge("transport.consume.workers").add(
                -self._consume_workers
            )
        self._sync_sq_metrics()
        with self._lock:
            channels = list(self._channels.values())
            self._channels.clear()
        for ch in channels:
            ch._dead.set()
        # teardown order matters twice over: pooled buffers deregister
        # their regions through the native node (so it must be alive for
        # buffer_manager.stop), and the epoll loop may still be streaming
        # READ payloads into destination buffers referenced only by _wrs
        # keepalives — so the loop must be FULLY joined (srt_node_stop)
        # before those references are dropped
        self.buffer_manager.stop()
        self.pd.dealloc()
        np_handle, self._np = self._np, None
        if np_handle:
            self._lib.srt_node_stop(np_handle)
        # loop is dead now: fail anything still outstanding (latch
        # semantics) and release the keepalives
        with self._lock:
            wrs = list(self._wrs.items())
            self._wrs.clear()
        err = ChannelError("node stopped")
        for _, (listener, _keep) in wrs:
            if listener is not None:
                try:
                    listener.on_failure(err)
                except Exception:
                    logger.exception("listener on_failure raised")
