"""Descriptor staging: ship bulk bytes over the data plane, not the
task protocol.

The engine task protocol (engine/worker.py) is a control plane: one
length-prefixed cloudpickle request per connection. Early cluster-mode
builds of the push/merge plane (shuffle/merge.py) and the replication
plane (elastic/replication.py) embedded their block *payloads* inside
those requests, so shuffle-sized volume rode a pickled control socket —
the exact anti-pattern the reference eliminates by keeping bulk bytes
on one-sided READs (SURVEY.md §2 "Data plane").

This module is the staging seam both planes now share:

- the **sender** registers each payload in its node's ProtectionDomain
  and ships only ``(mkey, length)`` descriptors plus its data-plane
  address through the task request (`stage_payloads`), releasing the
  registrations once the receiver's reply confirms the pull;
- the **receiver** resolves the descriptors with a one-sided READ group
  on a ``purpose="data"`` channel (`pull_payloads`) — the same verb,
  channel flavor, and completion contract the shuffle fetcher uses
  (shuffle/fetcher.py), so injected read faults and transport metrics
  cover pushed and replicated bytes exactly like fetched ones.

The task request that carries the descriptors stays O(#blocks), and
benchmarks/soak.py's ``push_absent_from_rpc_handle_ms`` bar keeps the
driver RPC plane (rpc.handle_ms) strictly control-plane.
"""

from __future__ import annotations

import threading
from typing import List, Sequence, Tuple

from sparkrdma_tpu.transport.channel import ChannelError
from sparkrdma_tpu.transport.completion import FnListener

# one staged transfer must never wedge a worker's task thread: the
# sender's socket timeout is 10 s, so fail the pull first and let the
# best-effort contract (silent push miss / durability miss) apply
PULL_TIMEOUT_S = 8.0


def stage_payloads(
    node, payloads: Sequence[bytes]
) -> Tuple[Tuple[str, int], List[Tuple[int, int]], "_Release"]:
    """Register ``payloads`` in ``node``'s ProtectionDomain.

    Returns ``(data_addr, descs, release)``: the node's data-plane
    address, one ``(mkey, length)`` descriptor per payload, and a
    callable that deregisters them all (idempotent — call it in a
    ``finally`` once the receiver has replied)."""
    mkeys = [node.pd.register(memoryview(p)) for p in payloads]
    descs = [(mkey, len(p)) for mkey, p in zip(mkeys, payloads)]
    return (node.host, node.port), descs, _Release(node.pd, mkeys)


class _Release:
    def __init__(self, pd, mkeys: List[int]):
        self._pd = pd
        self._mkeys = mkeys

    def __call__(self) -> None:
        mkeys, self._mkeys = self._mkeys, []
        for mkey in mkeys:
            self._pd.deregister(mkey)


def pull_payloads(
    node,
    data_addr: Tuple[str, int],
    descs: Sequence[Tuple[int, int]],
    timeout_s: float = PULL_TIMEOUT_S,
) -> List[bytes]:
    """One-sided READ of staged ``(mkey, length)`` descriptors.

    Blocks until the whole group lands (the task-protocol reply that
    follows is the sender's release signal) or raises ChannelError on
    failure/timeout."""
    if not descs:
        return []
    host, port = data_addr
    channel = node.get_channel(host, port, purpose="data")
    bufs = [bytearray(length) for _, length in descs]
    done = threading.Event()
    err: List[Exception] = []

    def on_failure(exc: Exception) -> None:
        if not err:
            err.append(exc)
        done.set()

    channel.read_in_queue(
        FnListener(lambda _=None: done.set(), on_failure),
        [memoryview(b) for b in bufs],
        [(mkey, 0, length) for mkey, length in descs],
    )
    if not done.wait(timeout_s):
        raise ChannelError(
            f"staged pull of {len(descs)} block(s) from {host}:{port} "
            f"timed out after {timeout_s}s"
        )
    if err:
        raise ChannelError(f"staged pull failed: {err[0]}") from err[0]
    return [bytes(b) for b in bufs]
