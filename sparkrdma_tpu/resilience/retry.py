"""RetryPolicy — bounded, deterministic retry/backoff for group READs.

The fetcher's retry ladder (shuffle/fetcher.py) walks one rung per
failed attempt of a group:

  attempt 0   initial READ
  attempt 1   retry the same source (transient channel hiccups)
  attempt 2   re-resolve locations from the driver and failover
              (stale mkeys / respawned writers)
  attempt 3+  split the aggregated group and retry blocks one by one
              (isolates a single poisoned block)
  exhausted   FetchFailedError -> stage recompute (the reference's
              only move, now the LAST resort)

Backoff jitter is deterministic — a hash of (shuffle, partition,
attempt) — so fault-plan tests reproduce byte-identical schedules run
to run, and concurrent reducers still decorrelate.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass


@dataclass(frozen=True)
class RetryPolicy:
    """Knobs live under ``tpu.shuffle.resilience.*`` (utils/config.py)."""

    max_attempts: int = 4
    backoff_ms: int = 50
    backoff_max_ms: int = 2000
    deadline_ms: int = 0  # 0 = unbounded (per-group wall budget)

    @classmethod
    def from_conf(cls, conf) -> "RetryPolicy":
        return cls(
            max_attempts=conf.max_fetch_attempts,
            backoff_ms=conf.retry_backoff_ms,
            backoff_max_ms=conf.retry_backoff_max_ms,
            deadline_ms=conf.fetch_deadline_ms,
        )

    def allows(self, attempt: int) -> bool:
        """True if attempt number ``attempt`` (0-based) may be issued."""
        return attempt < self.max_attempts

    def deadline_s(self) -> float:
        """Per-group wall budget in seconds; +inf when unbounded."""
        return self.deadline_ms / 1000.0 if self.deadline_ms > 0 else float("inf")

    def backoff_s(self, attempt: int, *keys) -> float:
        """Delay before re-issuing after failed attempt ``attempt``.

        Exponential base with deterministic jitter in [0.5, 1.0]× drawn
        from a crc32 of (attempt, *keys) — stable across runs, varied
        across groups.
        """
        base = min(self.backoff_ms * (2 ** attempt), self.backoff_max_ms)
        h = zlib.crc32(repr((attempt,) + keys).encode()) & 0xFFFFFFFF
        return base * (0.5 + 0.5 * (h / 0xFFFFFFFF)) / 1000.0
