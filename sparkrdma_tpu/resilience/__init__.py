"""Resilience layer for the remote-read path (docs/RESILIENCE.md).

The reference degrades EVERY fetch failure to whole-stage recompute
(FetchFailedException -> scheduler re-run; SURVEY.md §5.1 #9). This
package is the strategy the reference lacks:

- :mod:`retry` — RetryPolicy: bounded attempts, exponential backoff
  with deterministic jitter, per-fetch deadline budget.
- :mod:`health` — per-remote-manager circuit breaker so a dead peer
  fails fast instead of burning every reducer's retry budget.

Checksums (utils/checksum.py) and the fault-injection subsystem
(testing/faults.py) complete the picture.
"""

from sparkrdma_tpu.resilience.health import (
    CircuitBreaker,
    CircuitOpenError,
    SourceHealthRegistry,
)
from sparkrdma_tpu.resilience.retry import RetryPolicy

__all__ = [
    "CircuitBreaker",
    "CircuitOpenError",
    "RetryPolicy",
    "SourceHealthRegistry",
]
