"""Source health tracking — per-remote-manager circuit breakers.

A dead peer must fail FAST: without a breaker, every reducer fetching
from it independently burns its full retry budget (attempts × backoff)
before surfacing FetchFailedError, multiplying a single executor loss
into minutes of cluster-wide stall. The breaker is the classic
three-state machine:

  CLOSED     normal operation; consecutive failures count up
  OPEN       >= failure_threshold consecutive failures: every fetch to
             the peer fails immediately (CircuitOpenError) for
             ``open_ms``
  HALF_OPEN  after ``open_ms`` ONE probe fetch is allowed through;
             success closes the circuit, failure re-opens it

State transitions are counted in the process-wide obs registry under
``resilience.circuit_open`` / ``resilience.circuit_close``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional

from sparkrdma_tpu import tenancy
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.obs.journal import emit as journal_emit

logger = logging.getLogger(__name__)

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitOpenError(IOError):
    """Fetch refused because the source's circuit is open (fail-fast).

    Deliberately NOT retryable by the fetcher's ladder: the breaker IS
    the retry governor for a peer presumed dead; the failure surfaces
    straight to FetchFailedError so the engine can recompute the stage
    elsewhere.
    """


class CircuitBreaker:
    """One peer's health state machine. Thread-safe."""

    def __init__(
        self,
        failure_threshold: int = 3,
        open_ms: int = 5000,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._threshold = max(1, failure_threshold)
        self._open_s = open_ms / 1000.0
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_out = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._observe_locked()

    def _observe_locked(self) -> str:
        if self._state == OPEN and self._clock() - self._opened_at >= self._open_s:
            self._state = HALF_OPEN
            self._probe_out = False
        return self._state

    def allow(self) -> bool:
        """May a fetch be issued to this peer right now?

        HALF_OPEN admits exactly one in-flight probe; concurrent
        callers keep failing fast until the probe reports back.
        """
        with self._lock:
            state = self._observe_locked()
            if state == CLOSED:
                return True
            if state == HALF_OPEN and not self._probe_out:
                self._probe_out = True
                return True
            return False

    def record_success(self) -> bool:
        """Report a completed fetch; True if this closed the circuit."""
        with self._lock:
            was_open = self._state != CLOSED
            self._state = CLOSED
            self._consecutive_failures = 0
            self._probe_out = False
            return was_open

    def record_failure(self) -> bool:
        """Report a failed fetch; True if this opened the circuit."""
        with self._lock:
            state = self._observe_locked()
            if state == HALF_OPEN:
                # the probe failed: straight back to OPEN for a full window
                self._state = OPEN
                self._opened_at = self._clock()
                self._probe_out = False
                return True
            self._consecutive_failures += 1
            if state == CLOSED and self._consecutive_failures >= self._threshold:
                self._state = OPEN
                self._opened_at = self._clock()
                return True
            return False


class SourceHealthRegistry:
    """Circuit breakers keyed by remote executor_id, one per manager.

    The breaker keys on executor identity (not host:port) to match
    ShuffleManagerId equality semantics: a respawned executor under the
    same id inherits — and must re-earn — its predecessor's health.

    Tenancy: breakers are additionally scoped per tenant
    (``"<tenant>:<executor_id>"``) so one tenant's fault plan tripping
    a peer's circuit cannot fail-fast ANOTHER tenant's fetches from
    the same peer. The default tenant keeps the bare executor_id key —
    single-tenant deployments see exactly the pre-tenancy keyspace.
    """

    def __init__(self, conf, role: str = ""):
        self._threshold = conf.circuit_failure_threshold
        self._open_ms = conf.circuit_open_ms
        self._role = role
        self._lock = threading.Lock()
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._suspects: Dict[str, dict] = {}
        reg = get_registry()
        self._m_open = reg.counter("resilience.circuit_open", role=role)
        self._m_close = reg.counter("resilience.circuit_close", role=role)
        self._m_advisory = reg.counter(
            "resilience.straggler_advisories", role=role
        )

    @staticmethod
    def _key(executor_id: str, tenant: Optional[str]) -> str:
        t = tenant if tenant is not None else tenancy.current_tenant()
        if t == tenancy.DEFAULT_TENANT:
            return executor_id
        return f"{t}:{executor_id}"

    def get(
        self, executor_id: str, tenant: Optional[str] = None
    ) -> CircuitBreaker:
        key = self._key(executor_id, tenant)
        with self._lock:
            br = self._breakers.get(key)
            if br is None:
                br = CircuitBreaker(self._threshold, self._open_ms)
                self._breakers[key] = br
            return br

    def allow(self, executor_id: str, tenant: Optional[str] = None) -> bool:
        br = self.get(executor_id, tenant)
        was_half_open = br.state == HALF_OPEN
        ok = br.allow()
        if ok and was_half_open:
            journal_emit(
                "circuit.half_open", role=self._role, executor=executor_id,
            )
        return ok

    def record_success(
        self, executor_id: str, tenant: Optional[str] = None
    ) -> None:
        if self.get(executor_id, tenant).record_success():
            self._m_close.inc()
            journal_emit(
                "circuit.close", role=self._role, executor=executor_id,
            )
            logger.info("circuit to %s closed (probe succeeded)", executor_id)

    def record_failure(
        self, executor_id: str, tenant: Optional[str] = None
    ) -> None:
        if self.get(executor_id, tenant).record_failure():
            self._m_open.inc()
            journal_emit(
                "circuit.open", role=self._role, executor=executor_id,
            )
            logger.warning(
                "circuit to %s opened after consecutive failures",
                self._key(executor_id, tenant),
            )

    def states(self) -> Dict[str, str]:
        """Snapshot of every tracked peer's state (metrics_snapshot)."""
        with self._lock:
            items = list(self._breakers.items())
        return {peer: br.state for peer, br in items}

    # -- telemetry advisory path (docs/RESILIENCE.md) ---------------------
    def apply_straggler_report(self, report: Dict) -> None:
        """Advisory signal from the telemetry hub's straggler detector.

        A straggler is SLOW, not DEAD: the report marks the executor as
        a suspect (visible in :meth:`suspects` and counted under
        ``resilience.straggler_advisories``) but never opens its
        circuit — only the breaker's own consecutive fetch failures do
        that. Suspects that fall out of the report are cleared.

        Suspect keys match :meth:`_key` — bare executor id for the
        default tenant, ``<tenant>:<executor>`` otherwise — so a
        straggler verdict derived from one tenant's task metrics never
        smears that executor for other tenants. Reports from older
        hubs without ``suspect_keys`` fall back to the tenant-blind
        ``stragglers`` list.
        """
        flagged = set(
            report.get("suspect_keys") or report.get("stragglers") or ()
        )
        wall_ms = report.get("generated_wall_ms", 0)
        with self._lock:
            new = flagged - set(self._suspects)
            self._suspects = {
                eid: self._suspects.get(eid, {"first_wall_ms": wall_ms})
                for eid in flagged
            }
            for eid in flagged:
                self._suspects[eid]["last_wall_ms"] = wall_ms
        for eid in sorted(new):
            self._m_advisory.inc()
            logger.warning(
                "telemetry advisory: %s flagged as straggler (circuit NOT "
                "opened; advisory only)", eid,
            )

    def suspects(self) -> Dict[str, dict]:
        """Executors currently flagged by the straggler advisory."""
        with self._lock:
            return {eid: dict(info) for eid, info in self._suspects.items()}
