"""Device-mesh plane: mesh construction and sharding helpers.

The reference scales by a full mesh of executor QPs over RoCE
(RdmaNode.java:281-353); the TPU framework scales by a
``jax.sharding.Mesh`` whose axes ride ICI (intra-slice) and DCN
(inter-slice). This package owns mesh construction and the sharding
vocabulary used by the exchange plane (SURVEY.md §2.4, §7.1).
"""

from sparkrdma_tpu.parallel.mesh import (
    exec_axis,
    dcn_axis,
    make_mesh,
    mesh_axis_size,
    shard_spec,
    replicated_spec,
)

__all__ = [
    "exec_axis",
    "dcn_axis",
    "make_mesh",
    "mesh_axis_size",
    "shard_spec",
    "replicated_spec",
]
