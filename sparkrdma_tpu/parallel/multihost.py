"""Multi-host bootstrap — scaling the mesh across processes and slices.

The reference scales out with one RdmaNode per JVM and a full mesh of
RC queue pairs over RoCE (SURVEY.md §2.4). The TPU-native scale-out
needs no per-peer connection state at all: each host process calls
:func:`initialize` (a thin wrapper over ``jax.distributed``), after
which ``jax.devices()`` spans every host and :func:`global_mesh` builds
the framework's ``(dcn, exec)`` mesh over all of them — intra-slice
collectives ride ICI, cross-slice DCN, with XLA owning the transport
(the NCCL/MPI-equivalent role of the reference's verbs layer).

The host control plane (driver hub, location RPC) is transport-
independent and keeps working unchanged across hosts — executors just
pass real hostnames instead of 127.0.0.1.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import jax

from sparkrdma_tpu.parallel.mesh import make_mesh

logger = logging.getLogger(__name__)


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-host JAX runtime (no-op for single-process runs).

    On Cloud TPU all three arguments are auto-detected from the
    metadata environment; pass them explicitly elsewhere
    (``host0:port``, world size, this process's rank)."""
    if num_processes is not None and num_processes <= 1:
        return
    # idempotent like startRdmaNodeIfMissing: skip when the runtime is
    # already up (jax raises on a second initialize). The state object
    # is internal-only (jax._src), so guard the import.
    try:
        from jax._src.distributed import global_state as _state
    except ImportError:
        _state = None
    if _state is not None and getattr(_state, "client", None) is not None:
        logger.debug("jax.distributed already initialized; skipping")
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        # fallback idempotence when global_state isn't inspectable; the
        # live runtime says "should only be called once", older versions
        # said "already initialized"
        msg = str(e).lower()
        if "already" not in msg and "only be called once" not in msg:
            raise
        logger.debug("jax.distributed already initialized: %s", e)


def global_mesh(num_slices: Optional[int] = None):
    """The framework mesh over every device of every host."""
    return make_mesh(jax.devices(), num_slices=num_slices)


def local_device_indices() -> Sequence[int]:
    """Global shard indices owned by this process (for feeding
    per-host input pipelines into a globally-sharded array)."""
    all_devices = list(jax.devices())
    local = set(d.id for d in jax.local_devices())
    return [i for i, d in enumerate(all_devices) if d.id in local]
