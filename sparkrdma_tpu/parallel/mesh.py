"""Mesh construction for the TPU exchange plane.

The reference's communication topology is a lazily-connected full mesh
of RC queue pairs between executors (RdmaNode.java:281-353), with the
driver as a TPU-free metadata hub (SURVEY.md §3.1). The TPU-native
topology is a ``jax.sharding.Mesh``:

- the ``"exec"`` axis is the executor ring — devices within one slice,
  connected by ICI; collectives over it are the analogue of the
  executor<->executor one-sided READ plane,
- the optional ``"dcn"`` axis is the inter-slice dimension — multi-pod
  scale-out where collectives ride DCN, the analogue of routed RoCE
  between racks.

No QP state is kept anywhere: the mesh *is* the membership, and XLA's
collectives are compiled against it once (the SVC compile-once /
execute-many pattern of the reference's stateful verb calls,
RdmaChannel.java:185-192, becomes jit compile-once / call-many).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# Canonical axis names used across the framework.
EXEC_AXIS = "exec"
DCN_AXIS = "dcn"


def exec_axis() -> str:
    return EXEC_AXIS


def dcn_axis() -> str:
    return DCN_AXIS


def _infer_num_slices(devices: Sequence[jax.Device]) -> int:
    """Group devices by slice (DCN domain) when the platform reports one."""
    slice_ids = []
    for d in devices:
        sid = getattr(d, "slice_index", None)
        if sid is None:
            return 1
        slice_ids.append(sid)
    return len(set(slice_ids))


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    num_slices: Optional[int] = None,
) -> Mesh:
    """Build the framework mesh: ``(dcn, exec)`` if multi-slice, else ``(exec,)``.

    ``num_slices`` overrides slice detection (useful for simulating DCN
    topology on a CPU device farm, SURVEY.md §4's
    multi-node-without-a-cluster strategy).
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if num_slices is None:
        num_slices = _infer_num_slices(devices)
    if num_slices <= 1:
        return Mesh(np.array(devices), (EXEC_AXIS,))
    if n % num_slices != 0:
        raise ValueError(
            f"{n} devices do not divide into {num_slices} slices"
        )
    arr = np.array(devices).reshape(num_slices, n // num_slices)
    return Mesh(arr, (DCN_AXIS, EXEC_AXIS))


def mesh_axis_size(mesh: Mesh, axis: str = EXEC_AXIS) -> int:
    return mesh.shape[axis]


def all_exchange_axes(mesh: Mesh) -> Tuple[str, ...]:
    """Every mesh axis, innermost (ICI) first — exchange order matters:
    intra-slice traffic should ride ICI before anything crosses DCN."""
    names = list(mesh.axis_names)
    names.reverse()  # exec (ICI) first, dcn last
    return tuple(names)


def shard_spec(mesh: Mesh) -> PartitionSpec:
    """PartitionSpec sharding dim 0 over every mesh axis (dcn outermost)."""
    if len(mesh.axis_names) == 1:
        return PartitionSpec(EXEC_AXIS)
    return PartitionSpec((DCN_AXIS, EXEC_AXIS))


def replicated_spec() -> PartitionSpec:
    return PartitionSpec()


def sharding_for(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, shard_spec(mesh))
