"""Test/bench support subsystems shipped with the framework.

:mod:`faults` — the seeded, config-driven fault-injection plan hooked
at the transport and RPC seams (docs/RESILIENCE.md). Importing this
package costs nothing at runtime: the hot-path check is a single
module-level ``active()`` None test.
"""

from sparkrdma_tpu.testing import faults

__all__ = ["faults"]
