"""Fault injection as a first-class subsystem (docs/RESILIENCE.md).

Promotes the ad-hoc monkeypatching the fault tests started with into a
seeded, config-driven *fault plan* hooked at four seams:

  - ``read``  — one-sided READ verbs (``TpuChannel.read_in_queue``,
    ``NativeTpuChannel.read_in_queue`` / ``read_mapped_in_queue``)
  - ``send``  — two-sided SEND verbs (RPC segment posts)
  - ``rpc``   — message dispatch (``TpuShuffleManager._receive_listener``)
  - ``stage`` — the reduce pipeline's post-transport stages
    (``DeviceShuffleIO.verify_host_block`` = ``stage=decode``,
    ``DeviceShuffleIO.stage_host_block`` = ``stage=stage``): corrupt a
    block AFTER the wire delivered it intact, proving the decode-stage
    checksum gate catches what the transport-level gates cannot see
  - ``push``  — the push/merge plane (shuffle/merge.py): ``drop`` /
    ``fail`` / ``delay`` fire at the client's send phase (lost push →
    originals stay authoritative); ``corrupt`` fires at the endpoint's
    seal phase AFTER the merged checksum tag (reduce path must detect
    and fall back)
  - ``exec``  — executor-death seam (engine/worker.py task entry):
    ``exec:kill:N[:peer=<id>]`` hard-exits the worker process,
    ``exec:hang:N`` wedges the task thread — the elastic layer's chaos
    rig (docs/RESILIENCE.md "Elasticity")
  - ``driver`` — driver/hub-death seam (engine phase boundaries):
    ``driver:kill:N[:stage=reduce_phase]`` wipes the metadata hub
    mid-job; the job must resume through the re-adoption ladder
    (sparkrdma_tpu/metastore, docs/RESILIENCE.md "Control-plane HA")
  - ``meta``  — metadata-peer-death seam (metastore route time):
    ``meta:kill:N[:shard=meta-K]`` revokes one metadata peer's lease
    and remaps its shard ranges; in-flight writes fence with a stale
    epoch and retry against the former follower
  - ``block`` — block-format seam (shuffle/fetcher.py checksum gate):
    ``block:corrupt_header:N`` flips one byte inside a landed columnar
    frame's header/descriptor span (DESIGN.md §25) BEFORE
    verification — the checksum gate must detect, the retry ladder
    refetch, and the reduce path deliver byte-identical rows. Groups
    with no writable columnar frame burn no budget

Fault kinds: ``fail`` (listener.on_failure with :class:`InjectedFault`),
``delay`` (sleep ``delay_ms`` then proceed), ``corrupt`` (flip one
deterministic byte of the delivered payload — the checksum layer's
adversary), ``drop`` (connection drop for verbs; silent message loss
for sends/rpc), ``kill``/``hang`` (exec seam only: process death /
live-but-stuck), ``enosys`` (read seam only: force the native
submission plane's io_uring probe to report unavailable — DESIGN.md
§24 — then let the read proceed; the bytes must arrive identical via
the pread fallback and ``transport.sq.backend_fallbacks`` must tick).

Plans are spec strings — ``op:kind:count[:k=v[,k=v...]]`` joined with
``;`` — so they travel through conf keys (``tpu.shuffle.faultPlan`` +
``faultPlanSeed``), pytest parametrization, and ``bench.py
--fault-plan`` identically. ``count`` 0 means unlimited. Options:
``after=N`` (skip the first N matching ops), ``delay_ms=N``,
``peer=SUBSTR`` (match on the channel's peer description),
``stage=NAME`` (restrict a ``stage`` rule to one pipeline stage, e.g.
``stage:corrupt:1:stage=decode``), ``shard=NAME`` (restrict a ``meta``
rule to one metadata peer, e.g. ``meta:kill:1:shard=meta-0``).

The plan installs process-globally (:func:`install` /
:func:`uninstall` / the :func:`installed` context manager); the hot
path pays one module-attribute None check when no plan is active.
Everything a plan does is deterministic given (spec, seed).
"""

from __future__ import annotations

import contextlib
import logging
import random
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

OPS = ("read", "send", "rpc", "stage", "push", "exec", "driver", "meta", "block")
KINDS = (
    "fail", "delay", "corrupt", "drop", "kill", "hang", "enosys",
    "corrupt_header",
)


class InjectedFault(IOError):
    """The error surfaced by ``fail``/``drop`` rules."""


@dataclass
class FaultRule:
    """One rule of a plan; see module docstring for the spec grammar."""

    op: str
    kind: str
    count: int = 1  # 0 = unlimited
    after: int = 0
    delay_ms: int = 0
    peer: str = ""
    stage: str = ""  # restrict a "stage" rule to one pipeline stage
    shard: str = ""  # restrict a "meta" rule to one metadata peer

    def __post_init__(self):
        if self.op not in OPS:
            raise ValueError(f"unknown fault op {self.op!r}; expected one of {OPS}")
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; expected one of {KINDS}"
            )

    @classmethod
    def parse(cls, item: str) -> "FaultRule":
        parts = item.strip().split(":")
        if len(parts) < 2:
            raise ValueError(f"fault rule {item!r}: expected op:kind[:count[:opts]]")
        op, kind = parts[0].strip().lower(), parts[1].strip().lower()
        count = int(parts[2]) if len(parts) > 2 and parts[2].strip() else 1
        opts: Dict[str, str] = {}
        if len(parts) > 3 and parts[3].strip():
            for kv in parts[3].split(","):
                k, _, v = kv.partition("=")
                opts[k.strip()] = v.strip()
        return cls(
            op=op,
            kind=kind,
            count=count,
            after=int(opts.pop("after", 0)),
            delay_ms=int(opts.pop("delay_ms", 0)),
            peer=opts.pop("peer", ""),
            stage=opts.pop("stage", ""),
            shard=opts.pop("shard", ""),
        )


class FaultPlan:
    """A seeded set of rules plus its firing bookkeeping. Thread-safe."""

    def __init__(self, rules: Sequence[FaultRule], seed: int = 0, spec: str = ""):
        self.rules: List[FaultRule] = list(rules)
        self.seed = seed
        self.spec = spec or ";".join(
            f"{r.op}:{r.kind}:{r.count}" for r in self.rules
        )
        self._lock = threading.Lock()
        # per-rule: how many matching ops were seen / faults fired
        self._seen = [0] * len(self.rules)
        self._fired = [0] * len(self.rules)
        self.injected: Dict[Tuple[str, str], int] = {}

    @classmethod
    def parse(cls, spec: str, seed: int = 0) -> "FaultPlan":
        rules = [
            FaultRule.parse(item)
            for item in spec.split(";")
            if item.strip()
        ]
        return cls(rules, seed=seed, spec=spec)

    # -- bookkeeping ----------------------------------------------------
    @property
    def total_injected(self) -> int:
        with self._lock:
            return sum(self.injected.values())

    def injected_count(self, op: str = None, kind: str = None) -> int:
        with self._lock:
            return sum(
                n
                for (o, k), n in self.injected.items()
                if (op is None or o == op) and (kind is None or k == kind)
            )

    def _match(
        self, op: str, peer: str, stage: str = "",
        kinds: Sequence[str] = (), shard: str = ""
    ) -> Optional[Tuple[FaultRule, int]]:
        """First applicable rule for this op, or None. Decrements its
        budget and returns (rule, global fire index) when it fires.
        ``kinds`` restricts matching to those fault kinds — seams with
        several phases (push send vs seal) use it so a rule for the
        other phase neither fires nor burns budget here."""
        with self._lock:
            for i, rule in enumerate(self.rules):
                if rule.op != op:
                    continue
                if kinds and rule.kind not in kinds:
                    continue
                if rule.peer and rule.peer not in peer:
                    continue
                if rule.stage and rule.stage != stage:
                    continue
                if rule.shard and rule.shard != shard:
                    continue
                self._seen[i] += 1
                if self._seen[i] <= rule.after:
                    continue
                if rule.count and self._fired[i] >= rule.count:
                    continue
                self._fired[i] += 1
                key = (rule.op, rule.kind)
                self.injected[key] = self.injected.get(key, 0) + 1
                fire_index = sum(self.injected.values())
                return rule, fire_index
            return None

    def _flip_byte(self, view, fire_index: int) -> None:
        """Deterministically corrupt one byte of a writable buffer."""
        if len(view) == 0:
            return
        rng = random.Random((self.seed << 20) ^ fire_index)
        idx = rng.randrange(len(view))
        view[idx] ^= 0xFF

    # -- seam entry points ---------------------------------------------
    def on_read(
        self, channel, listener, dst_views, blocks
    ) -> Tuple[object, bool]:
        """READ-verb seam. Returns (listener, handled); handled=True
        means the fault consumed the verb and the caller must return."""
        hit = self._match("read", getattr(channel, "peer_desc", ""))
        if hit is None:
            return listener, False
        rule, fire_index = hit
        logger.info("fault plan: %s read on %s", rule.kind, channel.peer_desc)
        if rule.kind == "enosys":
            # force the submission plane's io_uring probe to latch
            # unavailable (as if io_uring_setup returned ENOSYS), then
            # let the read proceed: the pread fallback must deliver
            # byte-identical data. Pure-Python channels have no plane
            # to degrade, so the rule is a counted no-op there.
            node = getattr(channel, "_node", None)
            force = getattr(node, "force_uring_probe_fail", None)
            if force is not None:
                force(True)
            return listener, False
        if rule.kind == "fail":
            listener.on_failure(InjectedFault("injected read fault"))
            return listener, True
        if rule.kind == "drop":
            _drop_channel(channel)
            listener.on_failure(InjectedFault("injected connection drop"))
            return listener, True
        if rule.kind == "delay":
            time.sleep(rule.delay_ms / 1000.0)
            return listener, False
        # corrupt: let the READ complete, then flip one byte of the
        # landed payload before the fetcher sees it (checksum adversary)
        if dst_views is None:
            # mapped delivery exposes read-only page-cache windows; the
            # closest honest corruption is a failed delivery
            listener.on_failure(InjectedFault("injected read fault (mapped)"))
            return listener, True
        inner = listener
        views = list(dst_views)

        class _Corrupting:
            def on_success(self_inner, payload):
                for v in views:
                    if len(v):
                        self._flip_byte(v, fire_index)
                        break
                inner.on_success(payload)

            def on_failure(self_inner, e):
                inner.on_failure(e)

        return _Corrupting(), False

    def on_send(self, channel, listener, segments) -> Tuple[object, bool]:
        """SEND-verb seam. Same contract as :meth:`on_read`."""
        hit = self._match("send", getattr(channel, "peer_desc", ""))
        if hit is None:
            return listener, False
        rule, _ = hit
        logger.info("fault plan: %s send on %s", rule.kind, channel.peer_desc)
        if rule.kind in ("fail", "corrupt"):
            listener.on_failure(InjectedFault("injected send fault"))
            return listener, True
        if rule.kind == "drop":
            # the message is silently lost: success to the sender, the
            # receiver never sees it (lost-datagram semantics)
            listener.on_success(None)
            return listener, True
        time.sleep(rule.delay_ms / 1000.0)
        return listener, False

    def on_rpc(self, peer: str, payload: bytes) -> Tuple[bytes, bool]:
        """RPC-dispatch seam. Returns (payload, handled); handled=True
        discards the message."""
        hit = self._match("rpc", peer)
        if hit is None:
            return payload, False
        rule, fire_index = hit
        logger.info("fault plan: %s rpc from %s", rule.kind, peer)
        if rule.kind in ("fail", "drop"):
            return payload, True
        if rule.kind == "delay":
            time.sleep(rule.delay_ms / 1000.0)
            return payload, False
        mutated = bytearray(payload)
        self._flip_byte(mutated, fire_index)
        return bytes(mutated), False

    def on_stage(self, stage: str, views, peer: str = "") -> None:
        """Reduce-pipeline seam (DeviceShuffleIO decode/staging): fired
        with the block's host views AFTER transport delivered them
        intact. ``corrupt`` flips one byte in place — the adversary the
        decode-stage checksum gate exists for; ``fail``/``drop`` raise
        :class:`InjectedFault` (a failed decode); ``delay`` stalls the
        stage body. Read-only views (mapped page-cache windows) can't
        be corrupted honestly, so ``corrupt`` degrades to a raise.

        The engine task seams also fire here (stages ``map_task`` /
        ``reduce_task``, empty ``views``) passing the owning executor
        id as ``peer`` — ``stage:delay:0:delay_ms=...:stage=map_task,
        peer=exec-1`` slows exactly one executor, the skew injector the
        telemetry straggler tests use."""
        hit = self._match("stage", peer, stage=stage)
        if hit is None:
            return
        rule, fire_index = hit
        logger.info("fault plan: %s in pipeline stage %s", rule.kind, stage)
        if rule.kind == "delay":
            time.sleep(rule.delay_ms / 1000.0)
            return
        if rule.kind == "corrupt":
            for v in views:
                if len(v) and not getattr(v, "readonly", True):
                    self._flip_byte(v, fire_index)
                    return
        raise InjectedFault(f"injected {rule.kind} in pipeline stage {stage}")

    def on_push(self, phase: str, views, peer: str = "") -> bool:
        """Push-plane seam (shuffle/merge.py), two phases:

        - ``send`` (PushClient, before transmission): ``drop``/``fail``
          return True — the push message is silently lost, the merge
          endpoint's coverage stays incomplete and the reduce path
          keeps the original per-map locations; ``delay`` stalls then
          proceeds. ``push:drop:N`` is the canonical lost-push plan.
        - ``seal`` (MergeEndpoint, AFTER the merged segment's checksum
          was computed): ``corrupt`` flips one byte of the sealed
          segment in place, the adversary the reduce path's ordinary
          checksum gate must catch and answer with a fallback to the
          originals. ``push:corrupt:1`` is the canonical plan.

        Each phase matches only its own kinds, so a ``push:corrupt``
        rule never burns budget at the send phase and vice versa.
        Returns True when the push must be dropped (send phase only)."""
        kinds = ("corrupt",) if phase == "seal" else ("fail", "delay", "drop")
        hit = self._match("push", peer, kinds=kinds)
        if hit is None:
            return False
        rule, fire_index = hit
        logger.info("fault plan: %s push (%s phase) peer=%s", rule.kind, phase, peer)
        if rule.kind == "delay":
            time.sleep(rule.delay_ms / 1000.0)
            return False
        if rule.kind == "corrupt":
            for v in views or ():
                if len(v) and not getattr(v, "readonly", True):
                    self._flip_byte(v, fire_index)
                    break
            return False
        return True  # fail/drop: lost push

    def on_exec(self, peer: str = "", stage: str = "") -> None:
        """Executor-death seam (engine/worker.py, fired at task entry —
        the elastic layer's chaos rig, docs/RESILIENCE.md):

        - ``exec:kill:N[:peer=<id>]`` — ``os._exit(1)``: the process
          dies mid-task with no cleanup, exactly like an OOM kill or a
          preempted node. The driver's peer-loss path plus the elastic
          recovery in engine/cluster.py must carry the job.
        - ``exec:hang:N`` — the task thread blocks for ``delay_ms``
          (default 600 s, i.e. effectively forever at test scale): a
          live process that stops making progress, the straggler
          detector's prey.

        Only ``kill``/``hang`` match here, so exec rules never burn
        budget at other seams and vice versa. ``stage`` narrows the
        rule to one task kind (``map_task``/``reduce_task``), e.g.
        ``exec:kill:1:peer=proc-exec-1,stage=reduce_task`` kills that
        executor at its first *reduce* — the mid-reduce chaos case."""
        hit = self._match("exec", peer, stage=stage, kinds=("kill", "hang"))
        if hit is None:
            return
        rule, _ = hit
        logger.warning("fault plan: exec %s on %s", rule.kind, peer)
        if rule.kind == "kill":
            import os

            os._exit(1)
        time.sleep((rule.delay_ms or 600_000) / 1000.0)

    def on_driver(self, stage: str = "") -> bool:
        """Driver-death seam (control-plane HA chaos rig,
        docs/RESILIENCE.md "Control-plane HA"): consulted by the job
        engines at phase boundaries (stage ``reduce_phase`` between map
        and reduce). ``driver:kill:N[:stage=]`` returns True — the
        engine wipes the metadata hub (every registry entry, barrier
        count, and parked replica gone; leases re-grant under bumped
        epochs) and runs the re-adoption ladder. Only ``kill`` matches
        here, so driver rules never burn budget at other seams."""
        hit = self._match("driver", "", stage=stage, kinds=("kill",))
        if hit is None:
            return False
        logger.warning("fault plan: driver kill at stage %s", stage or "?")
        return True

    def on_block(self, views, peer: str = "") -> None:
        """Block-format seam (shuffle/fetcher.py ``_bad_block``): fired
        with a fetched group's landed block views BEFORE the checksum
        gate verifies them. ``block:corrupt_header:N`` finds the first
        *writable* view whose leading frame is columnar
        (shuffle/columnar.py magic behind the 4-byte length prefix) and
        flips one deterministic byte inside the frame's
        header + column-descriptor span — the narrowest adversary of
        the zero-copy decode path: a corrupted dtype code or offset
        table would mis-alias every row, so the gate must catch it
        before a single ``np.frombuffer`` view is built. A group with
        no writable columnar frame (pickle blocks, read-only mapped
        page-cache windows) matches nothing and burns no budget."""
        from sparkrdma_tpu.shuffle import columnar

        target = None
        for v in views or ():
            if getattr(v, "readonly", True) or len(v) < 4 + columnar._HDR.size:
                continue
            if bytes(v[4:6]) == columnar.MAGIC_BYTES:
                target = v
                break
        if target is None:
            return
        hit = self._match("block", peer, kinds=("corrupt_header",))
        if hit is None:
            return
        _rule, fire_index = hit
        logger.info("fault plan: corrupt columnar header from %s", peer or "?")
        span = columnar.header_span(memoryview(target)[4:])
        rng = random.Random((self.seed << 20) ^ fire_index)
        target[4 + rng.randrange(span)] ^= 0xFF

    def on_meta(self, shard: str = "") -> bool:
        """Metadata-peer-death seam (sparkrdma_tpu/metastore): consulted
        by the store at route time with the owner peer's name.
        ``meta:kill:N[:shard=meta-K]`` returns True — the store revokes
        that peer's lease, remaps its ranges, and the in-flight write
        fences with a stale epoch and retries against the former
        follower's copy. Only ``kill`` matches here."""
        hit = self._match("meta", "", kinds=("kill",), shard=shard)
        if hit is None:
            return False
        logger.warning("fault plan: metadata peer kill (%s)", shard or "?")
        return True


def _drop_channel(channel) -> None:
    try:
        channel.stop()
    except Exception:
        logger.exception("fault plan: dropping channel failed")


# ----------------------------------------------------------------------
# process-global installation
# ----------------------------------------------------------------------
_active: Optional[FaultPlan] = None
_install_lock = threading.Lock()


def active() -> Optional[FaultPlan]:
    """The installed plan, or None — THE hot-path check at every seam."""
    return _active


def install(plan: FaultPlan) -> FaultPlan:
    global _active
    with _install_lock:
        _active = plan
    logger.info("fault plan installed: %s (seed %d)", plan.spec, plan.seed)
    return plan


def uninstall() -> Optional[FaultPlan]:
    global _active
    with _install_lock:
        plan, _active = _active, None
    return plan


def ensure_installed(spec: str, seed: int = 0) -> Optional[FaultPlan]:
    """Conf-driven install (manager init): idempotent per spec+seed so
    every manager of an in-process cluster can call it."""
    if not spec:
        return None
    with _install_lock:
        global _active
        if _active is not None and _active.spec == spec and _active.seed == seed:
            return _active
        _active = FaultPlan.parse(spec, seed=seed)
    logger.info("fault plan installed from conf: %s (seed %d)", spec, seed)
    return _active


@contextlib.contextmanager
def installed(plan_or_spec, seed: int = 0):
    """``with faults.installed("read:fail:2"): ...`` — scoped install."""
    plan = (
        plan_or_spec
        if isinstance(plan_or_spec, FaultPlan)
        else FaultPlan.parse(plan_or_spec, seed=seed)
    )
    prev = active()
    install(plan)
    try:
        yield plan
    finally:
        with _install_lock:
            global _active
            _active = prev
