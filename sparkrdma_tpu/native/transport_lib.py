"""ctypes binding for the native transport data plane (transport.cpp).

Builds on first use with g++ (cached next to the source), exactly like
the arena binding. Falls back to None when the toolchain is missing —
callers then use the pure-Python transport (same wire format, same
semantics, slower per-byte path).
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "transport.cpp")

# SPARKRDMA_NATIVE_SANITIZE="thread,undefined" rebuilds the plane with
# -fsanitize=... into a separately cached .so (the CI native-tsan job;
# see docs/ANALYSIS.md). TSan-instrumented objects need the runtime
# loaded first: run under LD_PRELOAD=$(g++ -print-file-name=libtsan.so)
# or dlopen dies allocating static TLS.
_SANITIZE = os.environ.get("SPARKRDMA_NATIVE_SANITIZE", "").strip()

# SPARKRDMA_NATIVE_NO_IOURING=1 compiles the io_uring read backend OUT
# (-DSRT_NO_IOURING) into a separately cached .so — the CI matrix leg
# proving the submission plane stays tier-1-green and reports the pread
# fallback when the uapi header (or kernel) is absent.
_NO_IOURING = os.environ.get(
    "SPARKRDMA_NATIVE_NO_IOURING", ""
).strip() not in ("", "0")


def _so_path(base: str) -> str:
    tags = []
    if _SANITIZE:
        tags.append(_SANITIZE.replace(",", "-").replace("=", "_"))
    if _NO_IOURING:
        tags.append("nouring")
    if tags:
        return os.path.join(_HERE, f"{base}.{'.'.join(tags)}.so")
    return os.path.join(_HERE, f"{base}.so")


def _build_flags() -> list:
    flags = ["-O2", "-std=c++17", "-shared", "-fPIC", "-pthread"]
    if _SANITIZE:
        flags += [f"-fsanitize={_SANITIZE}", "-fno-sanitize-recover=all", "-g"]
    if _NO_IOURING:
        flags.append("-DSRT_NO_IOURING")
    return flags


_SO = _so_path("_libsrt_transport")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False

# completion kinds (transport.cpp)
COMP_SEND_DONE = 1
COMP_READ_DONE = 2
COMP_RECV = 3
COMP_CHANNEL_DOWN = 4
COMP_ACCEPT = 5

ST_OK = 0
ST_ERR = 1
ST_REMOTE_ERR = 2

# tpu.shuffle.native.readBackend values -> srt_set_read_backend codes
# (RB_* enum in transport.cpp)
READ_BACKENDS = {"auto": 0, "iouring": 1, "pread": 2, "mapped": 3}


class SrtComp(ctypes.Structure):
    _fields_ = [
        ("kind", ctypes.c_uint32),
        ("status", ctypes.c_uint32),
        ("channel", ctypes.c_uint64),
        ("wr_id", ctypes.c_uint64),
        ("payload", ctypes.c_void_p),
        ("payload_len", ctypes.c_uint64),
        ("aux", ctypes.c_uint32),
        ("_pad", ctypes.c_uint32),
    ]


def load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            if not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            ):
                subprocess.run(
                    ["g++", *_build_flags(), "-o", _SO, _SRC],
                    check=True,
                    capture_output=True,
                )
            lib = ctypes.CDLL(_SO)
        except (OSError, subprocess.CalledProcessError):
            _build_failed = True
            return None
        lib.srt_node_create.restype = ctypes.c_void_p
        lib.srt_node_create.argtypes = [ctypes.c_char_p, ctypes.c_uint16, ctypes.c_int]
        lib.srt_node_port.restype = ctypes.c_uint16
        lib.srt_node_port.argtypes = [ctypes.c_void_p]
        lib.srt_reg.restype = ctypes.c_uint32
        lib.srt_reg.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64]
        lib.srt_reg_file.restype = ctypes.c_uint32
        lib.srt_reg_file.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64,
            # backing-file identity from the caller's fstat of the
            # mapping fd: dev, ino, size, mtime_ns
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint64,
        ]
        lib.srt_dereg.restype = ctypes.c_int
        lib.srt_dereg.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        lib.srt_region_count.restype = ctypes.c_uint64
        lib.srt_region_count.argtypes = [ctypes.c_void_p]
        lib.srt_stat_file_reads.restype = ctypes.c_uint64
        lib.srt_stat_file_reads.argtypes = [ctypes.c_void_p]
        lib.srt_stat_streamed_reads.restype = ctypes.c_uint64
        lib.srt_stat_streamed_reads.argtypes = [ctypes.c_void_p]
        lib.srt_stat_split_parts.restype = ctypes.c_uint64
        lib.srt_stat_split_parts.argtypes = [ctypes.c_void_p]
        lib.srt_stat_block_stripes.restype = ctypes.c_uint64
        lib.srt_stat_block_stripes.argtypes = [ctypes.c_void_p]
        # submission plane: backend knob, availability probe, SQ stats
        lib.srt_set_read_backend.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.srt_uring_compiled.restype = ctypes.c_int
        lib.srt_uring_compiled.argtypes = []
        lib.srt_read_backend_effective.restype = ctypes.c_int
        lib.srt_read_backend_effective.argtypes = [ctypes.c_void_p]
        lib.srt_sq_force_probe_fail.argtypes = [ctypes.c_void_p, ctypes.c_int]
        for _stat in ("submits", "batches", "depth_hwm", "completions",
                      "backend_fallbacks"):
            fn = getattr(lib, f"srt_stat_sq_{_stat}")
            fn.restype = ctypes.c_uint64
            fn.argtypes = [ctypes.c_void_p]
        lib.srt_connect.restype = ctypes.c_uint64
        lib.srt_connect.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint16,
            ctypes.c_uint16, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
        ]
        lib.srt_post_send.restype = ctypes.c_int
        lib.srt_post_send.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int,
        ]
        lib.srt_post_read.restype = ctypes.c_int
        lib.srt_post_read.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint32,
        ]
        lib.srt_post_read_mapped.restype = ctypes.c_int
        lib.srt_post_read_mapped.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint32,
        ]
        lib.srt_unmap.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.srt_set_file_fastpath.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.srt_set_file_workers.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.srt_set_force_sendfile.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.srt_close_channel.restype = ctypes.c_int
        lib.srt_close_channel.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.srt_poll_cq.restype = ctypes.c_int
        lib.srt_poll_cq.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(SrtComp), ctypes.c_int, ctypes.c_int,
        ]
        lib.srt_free_payload.argtypes = [ctypes.c_void_p]
        lib.srt_node_stop.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


def available() -> bool:
    return load() is not None


def toolchain_available() -> bool:
    """True when the native plane is *buildable* here: g++ on PATH or a
    prebuilt .so already cached. Distinct from ``available()``, which
    also returns False when the build itself fails — tests must gate
    their skip on THIS so a transport.cpp compile breakage fails
    loudly instead of silently skipping. Cheap (no build triggered),
    so safe to call at pytest collection time."""
    return shutil.which("g++") is not None or os.path.exists(_SO)
