// Native off-heap arena for sparkrdma_tpu.
//
// TPU-native replacement for the reference's below-the-VM memory pokes:
// sun.misc.Unsafe.allocateMemory/copyMemory/freeMemory (reference:
// RdmaBuffer.java:41-53, 101-112) and the raw-address DirectByteBuffer
// constructor (RdmaBuffer.java:114-136). Provides page-aligned
// allocations outside the Python heap, addressable by id, with a
// process-wide allocation-statistics view (the RdmaBufferManager
// stop-time stats analogue, RdmaBufferManager.java:131-141).
//
// Exposed to Python via ctypes (no pybind11 in this environment).

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <unordered_map>

namespace {

struct Allocation {
  void* ptr;
  uint64_t size;
};

struct Arena {
  std::mutex mu;
  std::unordered_map<uint64_t, Allocation> allocs;
  std::atomic<uint64_t> next_id{1};
  std::atomic<uint64_t> live_bytes{0};
  std::atomic<uint64_t> total_allocs{0};
};

constexpr size_t kPageSize = 4096;

}  // namespace

extern "C" {

void* srt_arena_create() { return new Arena(); }

void srt_arena_destroy(void* arena_ptr) {
  Arena* a = static_cast<Arena*>(arena_ptr);
  {
    std::lock_guard<std::mutex> lock(a->mu);
    for (auto& kv : a->allocs) std::free(kv.second.ptr);
    a->allocs.clear();
  }
  delete a;
}

// Returns the allocation id, or 0 on failure. Address retrieved via srt_addr.
uint64_t srt_alloc(void* arena_ptr, uint64_t size) {
  Arena* a = static_cast<Arena*>(arena_ptr);
  void* ptr = nullptr;
  size_t padded = (size + kPageSize - 1) & ~(kPageSize - 1);
  if (posix_memalign(&ptr, kPageSize, padded) != 0) return 0;
  uint64_t id = a->next_id.fetch_add(1);
  {
    std::lock_guard<std::mutex> lock(a->mu);
    a->allocs[id] = {ptr, size};
  }
  a->live_bytes.fetch_add(size);
  a->total_allocs.fetch_add(1);
  return id;
}

void* srt_addr(void* arena_ptr, uint64_t id) {
  Arena* a = static_cast<Arena*>(arena_ptr);
  std::lock_guard<std::mutex> lock(a->mu);
  auto it = a->allocs.find(id);
  return it == a->allocs.end() ? nullptr : it->second.ptr;
}

uint64_t srt_size(void* arena_ptr, uint64_t id) {
  Arena* a = static_cast<Arena*>(arena_ptr);
  std::lock_guard<std::mutex> lock(a->mu);
  auto it = a->allocs.find(id);
  return it == a->allocs.end() ? 0 : it->second.size;
}

int srt_free(void* arena_ptr, uint64_t id) {
  Arena* a = static_cast<Arena*>(arena_ptr);
  Allocation alloc{nullptr, 0};
  {
    std::lock_guard<std::mutex> lock(a->mu);
    auto it = a->allocs.find(id);
    if (it == a->allocs.end()) return -1;
    alloc = it->second;
    a->allocs.erase(it);
  }
  std::free(alloc.ptr);
  a->live_bytes.fetch_sub(alloc.size);
  return 0;
}

void srt_copy(void* dst, const void* src, uint64_t n) { std::memcpy(dst, src, n); }

void srt_arena_stats(void* arena_ptr, uint64_t* total_allocs, uint64_t* live_bytes,
                     uint64_t* live_count) {
  Arena* a = static_cast<Arena*>(arena_ptr);
  *total_allocs = a->total_allocs.load();
  *live_bytes = a->live_bytes.load();
  std::lock_guard<std::mutex> lock(a->mu);
  *live_count = a->allocs.size();
}

}  // extern "C"
