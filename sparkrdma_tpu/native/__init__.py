from sparkrdma_tpu.native.arena import NativeArena, native_arena_available

__all__ = ["NativeArena", "native_arena_available"]
