// srt transport — the native data plane of the sparkrdma_tpu host path.
//
// Role: the libdisni/DiSNI equivalent of the reference (SURVEY.md §2.2).
// The reference is inoperable without a native verbs layer doing the
// actual per-byte work (ibv_post_send / ibv_poll_cq / connection
// management); this library is that layer for the TPU framework's host
// transport: a per-process endpoint ("node") with
//
//   - a region registry (the ProtectionDomain): mkey -> (ptr, len),
//     served under a mutex (IbvPd.regMr analogue, RdmaBuffer.java:81-88),
//   - an epoll event loop thread owning every socket: accepts, frame
//     parsing, passive one-sided READ service straight out of the
//     registry — application code never runs per served byte
//     (IBV_WR_RDMA_READ service, RdmaChannel.java:360-393),
//   - a completion queue the host language polls (ibv_poll_cq analogue):
//     SEND_DONE / READ_DONE / RECV / ACCEPT / CHANNEL_DOWN,
//   - one-sided READ: bytes stream directly into the caller-provided
//     destination buffer as they arrive, no staging copy.
//
// Wire format: byte-identical to sparkrdma_tpu/transport/wire.py (all
// big-endian), so native and pure-Python nodes interoperate:
//   SEND      = op(1) payload_len(4) payload
//   READ_REQ  = op(1) req_id(8) n(4) then n x [mkey(4) addr(8) len(4)]
//   READ_RESP = op(1) req_id(8) total_len(8) payload
//   READ_ERR  = op(1) req_id(8) msg_len(4) msg
//   HELLO     = op(1) port(4) id_len(2) executor_id
//   GOODBYE   = op(1)
//
// Threading: all public calls are thread-safe. Mutations of socket/epoll
// state are shipped to the loop thread via an eventfd-signalled command
// queue; the registry and completion queue have their own locks.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/mman.h>
#include <sys/sendfile.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/uio.h>
#include <limits.h>
#include <unistd.h>

#include <algorithm>
#include <array>
#include <atomic>
#include <memory>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

// io_uring backend: raw syscalls against <linux/io_uring.h> so no
// liburing dependency is ever required. Compiled out when the uapi
// header is missing or SRT_NO_IOURING is defined (the CI no-liburing
// matrix leg); availability on the RUNNING kernel is a separate
// runtime probe that latches ENOSYS/EPERM into a pread fallback.
#if defined(__linux__) && !defined(SRT_NO_IOURING) && \
    __has_include(<linux/io_uring.h>)
#define SRT_HAVE_IOURING 1
#include <linux/io_uring.h>
#include <sys/syscall.h>
#endif

namespace {

// A pthread_cond_timedwait that TIMES OUT corrupts this toolchain's
// TSan lock model (gcc-10 libtsan + glibc 2.31): the interceptor loses
// the waiter's internal release/reacquire, and from then on every
// operation on that mutex reports phantom double-locks and data races
// (reproduced with a 30-line provably-correct producer/consumer — the
// phantoms track cv.wait_for timeouts exactly and vanish with untimed
// waits). Under TSan, emulate the timed predicate wait with short
// untimed sleeps taken OUTSIDE the lock: identical semantics, wake
// latency bounded by the slice, and the instrumented build stays
// phantom-free so real races fail the CI job loudly.
template <class Pred>
bool cv_wait_ms(std::condition_variable& cv, std::unique_lock<std::mutex>& lk,
                int64_t timeout_ms, Pred pred) {
#if defined(__SANITIZE_THREAD__)
  (void)cv;
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (!pred()) {
    if (std::chrono::steady_clock::now() >= deadline) return pred();
    lk.unlock();
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
    lk.lock();
  }
  return true;
#else
  return cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred);
#endif
}

constexpr uint8_t OP_SEND = 1;
constexpr uint8_t OP_READ_REQ = 2;
constexpr uint8_t OP_READ_RESP = 3;
constexpr uint8_t OP_READ_ERR = 4;
constexpr uint8_t OP_HELLO = 5;
constexpr uint8_t OP_GOODBYE = 6;
// READ_REQ2: same layout as READ_REQ but announces the requester can
// read the server's files directly (same-host fast path). The server
// may answer READ_FILE instead of streaming READ_RESP when every block
// resolves to a file-backed region. The READ_FILE body leads with the
// server's host-proof path (an unguessable /dev/shm name); a client
// that cannot stat it is on another host and falls back to streaming,
// so colliding file paths across hosts can never serve wrong bytes.
// Wire v2 ops: both planes in this repo accept REQ2 (the Python plane
// streams); there is no cross-version negotiation with older binaries.
//   READ_REQ2 = op(1) req_id(8) n(4) then n x [mkey(4) addr(8) len(4)]
//   READ_FILE = op(1) req_id(8) body_len(4) body
//     body    = proof_len(2) proof_path n(4)
//               then n x [file_off(8) dev(8) ino(8) size(8) mtime_ns(8)
//                         plen(2) path]
// dev/ino/size/mtime_ns are the backing file's identity captured at
// REGISTRATION: the client checks them against fstat of the fd it
// opens, so a shuffle file unlinked and rewritten at the same path (a
// task re-attempt) between the READ_FILE answer and the pread can
// never serve the new file's bytes — identity mismatch falls back to
// streaming. dev+ino alone is NOT enough: ext4/tmpfs recycle inode
// numbers immediately, so a same-size rewrite can land on the same
// (dev, ino); the ns-resolution mtime (stable because shuffle files
// are immutable once committed and registered) breaks the tie.
constexpr uint8_t OP_READ_REQ2 = 9;
constexpr uint8_t OP_READ_FILE = 10;

constexpr uint32_t COMP_SEND_DONE = 1;
constexpr uint32_t COMP_READ_DONE = 2;
constexpr uint32_t COMP_RECV = 3;
constexpr uint32_t COMP_CHANNEL_DOWN = 4;
constexpr uint32_t COMP_ACCEPT = 5;

constexpr uint32_t ST_OK = 0;
constexpr uint32_t ST_ERR = 1;
constexpr uint32_t ST_REMOTE_ERR = 2;

inline uint16_t load_be16(const uint8_t* p) {
  return (uint16_t(p[0]) << 8) | uint16_t(p[1]);
}
inline uint32_t load_be32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}
inline uint64_t load_be64(const uint8_t* p) {
  return (uint64_t(load_be32(p)) << 32) | load_be32(p + 4);
}
inline void store_be32(uint8_t* p, uint32_t v) {
  p[0] = v >> 24; p[1] = v >> 16; p[2] = v >> 8; p[3] = v;
}
inline void store_be64(uint8_t* p, uint64_t v) {
  store_be32(p, v >> 32);
  store_be32(p + 4, (uint32_t)v);
}

struct Completion {
  uint32_t kind;
  uint32_t status;
  uint64_t channel;
  uint64_t wr_id;
  void* payload;        // RECV: data; ACCEPT: executor-id string (not NUL-terminated)
  uint64_t payload_len;
  uint32_t aux;         // ACCEPT: peer listen port
};

struct OutBuf {
  std::vector<uint8_t> data;
  size_t pos = 0;
  uint64_t wr_id = 0;    // nonzero: emit SEND_DONE when fully written
  bool last_of_wr = false;
  // zero-copy payload: when ext != nullptr the bytes are sent straight
  // from the registered region (pinned under pin_mkey) — the NIC-DMA
  // analogue of serving an RDMA READ without touching the data
  const uint8_t* ext = nullptr;
  uint64_t ext_len = 0;
  uint32_t pin_mkey = 0;
  // kernel-side zero-copy: when sf_fd >= 0 the bytes leave via
  // sendfile(socket <- backing file) — ZERO userspace copies on the
  // serving side (vs one for ext, two for data). ext stays set as the
  // memory fallback at the same pos when sendfile errors. The fd is
  // owned by this OutBuf (closed on completion or conn failure).
  int sf_fd = -1;
  uint64_t sf_off = 0;
};

struct PendingRead {
  uint64_t wr_id;
  uint8_t* dst;
  uint64_t expected;
  uint64_t received = 0;
  // original request blocks: kept for the same-host file path (per-
  // block pread placement) and for re-posting a plain READ_REQ when a
  // READ_FILE answer turns out not to be readable from here
  std::vector<std::array<uint64_t, 3>> blocks;
  // mapped delivery (srt_post_read_mapped): no caller dst — same-host
  // blocks come back as mmap records (completion aux=1), streamed
  // fallback lands in `owned` (malloc'd here, ownership passes to the
  // completion payload, aux=0)
  bool mapped = false;
  uint8_t* owned = nullptr;
};

// incremental frame-parser states
enum class RxState {
  OP,
  SEND_HDR, SEND_BODY,
  READQ_HDR, READQ_BLOCKS,
  READR_HDR, READR_BODY, READR_DRAIN,
  READE_HDR, READE_BODY,
  READF_HDR, READF_BODY,
  HELLO_HDR, HELLO_BODY,
};

struct Conn {
  int fd = -1;
  uint64_t id = 0;
  bool hello_done = false;       // inbound conns announce themselves first
  bool outbound = false;
  bool down = false;
  // loopback peers skip the sendfile serve path: measured on this rig,
  // loopback sendfile moves ~18% SLOWER than a userspace send (the
  // kernel page-pinning dance buys nothing without a DMA-capable NIC);
  // real remote peers get sendfile's zero-copy. Node::force_sendfile
  // overrides for tests/benches of the mechanism itself.
  bool peer_loopback = false;
  std::deque<OutBuf> outq;
  bool want_write = false;

  RxState st = RxState::OP;
  uint8_t hdr[16];
  size_t hdr_need = 0, hdr_got = 0;
  std::vector<uint8_t> body;
  size_t body_need = 0, body_got = 0;
  uint64_t cur_req = 0;
  uint64_t drain_left = 0;
  bool cur_req2 = false;            // server: READ_REQ2 (file-capable peer)
  PendingRead* cur_read = nullptr;  // owned by reads map

  std::unordered_map<uint64_t, PendingRead> reads;  // req_id -> pending

  // same-host fast-path state (client side): -1 unknown, 0 proven not
  // same-host (proof stat failed — permanent for this conn), 1 proven.
  // Transient file errors do NOT latch 0; they just stream that read.
  int files_ok = -1;
};

struct Command {
  enum Kind {
    ADD_CONN, SEND, READ, CLOSE_CONN, EVICT_MKEY,
    FILE_DONE, FILE_FALLBACK, STOP
  } kind;
  uint64_t channel = 0;
  int fd = -1;
  bool outbound = false;
  std::vector<uint8_t> data;
  uint64_t wr_id = 0;
  bool last_of_wr = false;
  // READ only: pending-read registration shipped to the loop thread,
  // which solely owns Conn::reads (no cross-thread map access)
  uint64_t req_id = 0;
  uint8_t* dst = nullptr;
  uint64_t expected = 0;
  std::vector<std::array<uint64_t, 3>> blocks;
  bool mapped = false;  // READ: mapped delivery requested
};

// one advertised backing file: path + offset + the registration-time
// identity the client must see when it opens the path
struct FileRef {
  std::string path;
  uint64_t off = 0;
  uint64_t dev = 0;
  uint64_t ino = 0;
  uint64_t size = 0;
  uint64_t mtime_ns = 0;
};

inline uint64_t stat_mtime_ns(const struct stat& st) {
  return (uint64_t)st.st_mtim.tv_sec * 1000000000ull +
         (uint64_t)st.st_mtim.tv_nsec;
}

inline bool stat_matches(const struct stat& st, uint64_t dev, uint64_t ino,
                         uint64_t size, uint64_t mtime_ns) {
  if ((uint64_t)st.st_dev != dev || (uint64_t)st.st_ino != ino) return false;
  // size==0 && mtime_ns==0 marks a MUTABLE backing (an shm slab whose
  // pages ARE the region memory: pread always returns current region
  // content, and its unguessable O_EXCL name makes a same-path rewrite
  // impossible) — dev/ino identity is sufficient there. Immutable
  // backings (committed shuffle files) carry the full identity because
  // ext4/tmpfs recycle inode numbers immediately on unlink+create.
  if (size == 0 && mtime_ns == 0) return true;
  return (uint64_t)st.st_size == size && stat_mtime_ns(st) == mtime_ns;
}

// one same-host pread job, executed on the file worker thread so a
// cold-cache disk read can never head-of-line block the epoll loop
// shared completion state for a SPLIT file task: a multi-block pread
// task fans out over the worker pool (the WR-list-striping analogue);
// the LAST part to finish posts the single FILE_DONE / FILE_FALLBACK,
// so no part can still be writing into dst when a fallback re-streams
struct TaskGroup {
  std::atomic<int> remaining{0};
  std::atomic<bool> failed{false};
};

struct FileTask {
  uint64_t channel = 0;
  uint64_t req_id = 0;
  uint8_t* dst = nullptr;
  std::vector<uint64_t> lens;
  std::vector<FileRef> files;
  bool mapped = false;           // mmap instead of pread
  std::vector<uint8_t> records;  // mapped result: n x 32B (ptr,len,base,maplen)
  std::shared_ptr<TaskGroup> group;  // non-null: one part of a split task
};

// one resolved read descriptor: a contiguous run of one validated file
// (fd already identity-checked) landing in a contiguous destination.
// `lens` keeps the block boundaries inside the run so the preadv2
// scatter backend can submit them as iovecs; io_uring submits the run
// as one SQE (the destination is contiguous across the run anyway).
struct ReadSqe {
  int fd = -1;
  uint64_t off = 0;
  uint8_t* dst = nullptr;
  std::vector<uint64_t> lens;
  uint64_t total = 0;
};

// read-backend knob values (mirrors tpu.shuffle.native.readBackend)
enum { RB_AUTO = 0, RB_IOURING = 1, RB_PREAD = 2, RB_MAPPED = 3 };

// SubmissionPlane: the single seam every same-host file read goes
// through. The loop thread enqueues logical read requests via
// plane_submit (which owns the striping/splitting policy that used to
// live inline in the READF_BODY frame handler); file workers drain
// them via plane_execute, which resolves blocks into ReadSqe runs and
// hands the runs to ONE of the interchangeable backends:
//
//   backend      submit path                    degradation
//   io_uring     batched SQEs, READ_FIXED when  ENOSYS/EPERM/old kernel
//                dst is inside a registered     -> pread (latched once,
//                segment snapshot               backend_fallbacks++);
//                                               short/failed CQE ->
//                                               per-run pread
//   pread        preadv2 scatter per run        ENOSYS -> pread loop
//   mapped-copy  mmap(MAP_POPULATE)+memcpy      mmap failure -> pread
//
// Mapped DELIVERY (records handed to the consumer in place, aux=1
// completions) is a completion mode, not a backend: plane_execute
// routes it internally, so no caller ever branches on
// pread-vs-mapped-vs-scatter.
//
// Fixed buffers: srt_reg/srt_reg_file record writable registered
// segments here; each worker's ring snapshots the list ONCE at ring
// creation and registers it via IORING_REGISTER_BUFFERS. Deregistering
// a recorded segment bumps seg_dead_gen, which disables READ_FIXED on
// every ring built against an older snapshot (plain IORING_OP_READ
// still flows) — a freed slab can never be written through a stale
// buf_index.
struct SubmissionPlane {
  std::atomic<int> backend{RB_AUTO};
  // io_uring availability: 0 unknown, 1 available, -1 unavailable
  // (probe failed), -2 forced unavailable (test seam)
  std::atomic<int> uring_state{0};
  std::atomic<int> force_probe_fail{0};
  // observable submission-queue accounting (transport.sq.* families)
  std::atomic<uint64_t> sq_submits{0};
  std::atomic<uint64_t> sq_batches{0};
  std::atomic<uint64_t> sq_depth_hwm{0};
  std::atomic<uint64_t> sq_completions{0};
  std::atomic<uint64_t> sq_backend_fallbacks{0};
  // fixed-buffer candidates: registered segments whose memory is
  // writable for the process lifetime of the rings built on them
  std::mutex seg_mu;
  std::vector<std::pair<uint64_t, uint64_t>> segs;
  std::atomic<uint64_t> seg_dead_gen{0};

  void add_segment(const void* ptr, uint64_t len) {
    if (!ptr || !len) return;
    std::lock_guard<std::mutex> g(seg_mu);
    // IORING_REGISTER_BUFFERS caps the iovec table at 1024 entries
    if (segs.size() >= 1024) return;
    segs.emplace_back((uint64_t)ptr, len);
  }
  void remove_segment(const void* ptr) {
    std::lock_guard<std::mutex> g(seg_mu);
    for (auto it = segs.begin(); it != segs.end(); ++it) {
      if (it->first == (uint64_t)ptr) {
        segs.erase(it);
        seg_dead_gen.fetch_add(1, std::memory_order_release);
        return;
      }
    }
  }
  void note_depth(uint64_t d) {
    uint64_t cur = sq_depth_hwm.load(std::memory_order_relaxed);
    while (d > cur && !sq_depth_hwm.compare_exchange_weak(cur, d)) {
    }
  }
};

struct Node {
  int listen_fd = -1;
  int epfd = -1;
  int evfd = -1;
  uint16_t port = 0;
  std::thread loop;
  std::atomic<bool> stopping{false};
  // host-identity proof for the same-host file fast path: an
  // unguessably-named empty file in /dev/shm. Its path rides in every
  // READ_FILE answer; a client that can stat it shares this host's
  // filesystem, so advertised backing-file paths are meaningful. This
  // is what prevents a deterministic shuffle-file path (same layout on
  // every host) from being opened on the WRONG host and silently
  // serving that host's bytes.
  std::string host_proof;

  struct Region {
    const uint8_t* ptr = nullptr;
    uint64_t len = 0;
    // pins: queued zero-copy sends referencing this memory. Dereg of a
    // pinned region BLOCKS until its last queued byte is flushed (the
    // MR-invalidation-ordering guarantee the reference gets from verbs:
    // memory may be reclaimed by the caller as soon as dereg returns)
    uint32_t pins = 0;
    bool dereg_wanted = false;
    // file backing (shm slab or mapped shuffle file): lets a same-host
    // peer pread the bytes straight from page cache instead of
    // streaming them through the socket
    std::string path;
    uint64_t file_off = 0;
    bool file_backed = false;
    // backing-file identity at registration time (READ_FILE wire doc)
    uint64_t file_dev = 0;
    uint64_t file_ino = 0;
    uint64_t file_size = 0;
    uint64_t file_mtime_ns = 0;
  };
  std::mutex reg_mu;
  std::condition_variable reg_cv;
  std::unordered_map<uint32_t, Region> regions;
  uint32_t next_mkey = 1;

  // client-side read-path accounting: how many READs completed via the
  // same-host pread fast path vs the streamed socket path (observable
  // from Python for tests and the bench harness)
  std::atomic<uint64_t> stat_file_reads{0};
  std::atomic<uint64_t> stat_streamed_reads{0};
  // sub-ranges created by striping a single large block's pread across
  // the worker pool (observable: tests assert the stripe engaged)
  std::atomic<uint64_t> stat_block_stripes{0};
  // parts created by splitting multi-block pread tasks (observable so
  // tests can assert the split actually engaged)
  std::atomic<uint64_t> stat_split_parts{0};
  // client knob: 0 forces plain READ_REQ (streamed) even when the peer
  // could answer READ_FILE — used to exercise/bench the remote path on
  // a single host. Mapped reads always probe the file path.
  std::atomic<int> file_fastpath{1};
  // server knob: serve file-backed regions via sendfile even to
  // loopback peers (tests/benches of the mechanism; see Conn comment)
  std::atomic<int> force_sendfile{0};

  std::mutex cq_mu;
  std::condition_variable cq_cv;
  std::deque<Completion> cq;

  std::mutex cmd_mu;
  std::deque<Command> cmds;

  std::mutex conn_mu;  // guards id->Conn* map (loop thread owns Conn bodies)
  std::unordered_map<uint64_t, Conn*> conns;
  uint64_t next_conn = 1;
  std::vector<Conn*> graveyard;  // loop-thread-only: dead conns awaiting free

  // file worker: executes same-host preads off the epoll loop.
  // file_pending is loop-thread-only: a PendingRead parks here while
  // its task is with the worker, so a dying Conn cannot free it
  // mid-pread and the destination keepalive stays owned until a
  // completion is posted.
  // Striped: several workers drain the task queue concurrently. On
  // rigs with spare kernel-side parallelism (this box: nproc=1 yet
  // 2-thread pread measures ~1.5x one thread) concurrent read groups
  // overlap their page-cache copies — the thread-pool analogue of the
  // reference posting WR lists on multiple QPs (RdmaChannel.java:54-56).
  // The vector itself is guarded by fw_mu (srt_set_file_workers can
  // grow it mid-run); the epoll loop never touches the vector — it
  // reads the atomic count, published AFTER each thread is live.
  std::vector<std::thread> file_workers;
  std::mutex fw_mu;
  std::atomic<size_t> file_worker_count{0};
  std::mutex ft_mu;
  std::condition_variable ft_cv;
  std::deque<FileTask> ftq;
  std::map<std::pair<uint64_t, uint64_t>, PendingRead> file_pending;

  // the read submission plane (backend choice, SQ stats, fixed-buffer
  // segment registry) — see the SubmissionPlane comment
  SubmissionPlane plane;

  void post(Completion c) {
    {
      std::lock_guard<std::mutex> g(cq_mu);
      cq.push_back(c);
    }
    cq_cv.notify_one();
  }
  void wake() {
    uint64_t one = 1;
    ssize_t r = write(evfd, &one, sizeof(one));
    (void)r;
  }
  void enqueue(Command c) {
    {
      std::lock_guard<std::mutex> g(cmd_mu);
      cmds.push_back(std::move(c));
    }
    wake();
  }
};

int set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  return fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

// large socket buffers + no Nagle: the data plane moves 8 MiB READ
// groups; default loopback buffers throttle the pipeline hard
void tune_socket(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  int sz = 4 << 20;
  setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &sz, sizeof(sz));
  setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &sz, sizeof(sz));
}

// release one zero-copy pin; completes a deferred dereg at pin zero
void unpin_region(Node* n, uint32_t mkey) {
  bool erased = false;
  {
    std::lock_guard<std::mutex> g(n->reg_mu);
    auto it = n->regions.find(mkey);
    if (it == n->regions.end()) return;
    if (it->second.pins > 0) it->second.pins--;
    if (it->second.pins == 0 && it->second.dereg_wanted) {
      n->regions.erase(it);
      erased = true;
    }
  }
  if (erased) n->reg_cv.notify_all();
}

void arm(Node* n, Conn* c) {
  epoll_event ev{};
  ev.events = EPOLLIN | (c->want_write ? EPOLLOUT : 0);
  ev.data.ptr = c;
  epoll_ctl(n->epfd, EPOLL_CTL_MOD, c->fd, &ev);
}

void fail_conn(Node* n, Conn* c) {
  if (c->down) return;
  c->down = true;
  // fail every outstanding one-sided READ on this channel
  for (auto& kv : c->reads) {
    if (kv.second.owned) free(kv.second.owned);  // fallback blob undelivered
    Completion comp{};
    comp.kind = COMP_READ_DONE;
    comp.status = ST_ERR;
    comp.channel = c->id;
    comp.wr_id = kv.second.wr_id;
    n->post(comp);
  }
  c->reads.clear();
  // ...and every queued-but-unflushed send, so no listener is orphaned
  // (the latch invariant of the Python channel, channel.py _latch_error)
  for (auto& ob : c->outq) {
    if (ob.sf_fd >= 0) close(ob.sf_fd);
    if (ob.ext) unpin_region(n, ob.pin_mkey);
    if (ob.wr_id && ob.last_of_wr) {
      Completion comp{};
      comp.kind = COMP_SEND_DONE;
      comp.status = ST_ERR;
      comp.channel = c->id;
      comp.wr_id = ob.wr_id;
      n->post(comp);
    }
  }
  c->outq.clear();
  Completion comp{};
  comp.kind = COMP_CHANNEL_DOWN;
  comp.channel = c->id;
  n->post(comp);
  epoll_ctl(n->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
  close(c->fd);
  c->fd = -1;
  // retire the Conn: out of the id map now (commands will fail cleanly),
  // freed at the next loop iteration so events already fetched in this
  // epoll batch can still look at c->down safely
  {
    std::lock_guard<std::mutex> g(n->conn_mu);
    n->conns.erase(c->id);
  }
  n->graveyard.push_back(c);
}

void queue_out(Node* n, Conn* c, std::vector<uint8_t> data, uint64_t wr_id,
               bool last) {
  if (c->down) {
    if (wr_id && last) {
      Completion comp{};
      comp.kind = COMP_SEND_DONE;
      comp.status = ST_ERR;
      comp.channel = c->id;
      comp.wr_id = wr_id;
      n->post(comp);
    }
    return;
  }
  OutBuf ob;
  ob.data = std::move(data);
  ob.wr_id = wr_id;
  ob.last_of_wr = last;
  c->outq.push_back(std::move(ob));
  if (!c->want_write) {
    c->want_write = true;
    arm(n, c);
  }
}

void flush_out(Node* n, Conn* c) {
  while (!c->outq.empty()) {
    OutBuf& ob = c->outq.front();
    const uint8_t* base = ob.ext ? ob.ext : ob.data.data();
    const size_t size = ob.ext ? (size_t)ob.ext_len : ob.data.size();
    // kernel path first: sendfile moves page-cache pages into the
    // socket with no userspace copy. Any failure (EINVAL on an exotic
    // fs, etc.) degrades to the pinned-memory send at the same pos —
    // the file and the region hold identical bytes by construction.
    while (ob.sf_fd >= 0 && ob.pos < size) {
      off_t off = (off_t)(ob.sf_off + ob.pos);
      ssize_t w = sendfile(c->fd, ob.sf_fd, &off, size - ob.pos);
      if (w > 0) {
        ob.pos += (size_t)w;
      } else if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        return;  // EPOLLOUT stays armed
      } else {
        close(ob.sf_fd);
        ob.sf_fd = -1;  // degrade to memory send below
      }
    }
    while (ob.pos < size) {
      ssize_t w = send(c->fd, base + ob.pos, size - ob.pos, MSG_NOSIGNAL);
      if (w > 0) {
        ob.pos += (size_t)w;
      } else if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        return;  // EPOLLOUT stays armed
      } else {
        fail_conn(n, c);
        return;
      }
    }
    if (ob.sf_fd >= 0) close(ob.sf_fd);
    if (ob.ext) unpin_region(n, ob.pin_mkey);
    if (ob.wr_id && ob.last_of_wr) {
      Completion comp{};
      comp.kind = COMP_SEND_DONE;
      comp.status = ST_OK;
      comp.channel = c->id;
      comp.wr_id = ob.wr_id;
      n->post(comp);
    }
    c->outq.pop_front();
  }
  if (c->want_write) {
    c->want_write = false;
    arm(n, c);
  }
}

// serve a one-sided READ_REQ entirely in native code: resolve each
// (mkey, addr, len) block against the registry, pin the regions, and
// queue zero-copy responses sent straight out of registered memory —
// no per-byte application copy, the NIC-DMA analogue. A concurrent
// dereg of a pinned region blocks until its bytes are flushed
// (verbs MR-invalidation ordering, RdmaBuffer.java:81-88).
void serve_read(Node* n, Conn* c, uint64_t req_id,
                const std::vector<std::array<uint64_t, 3>>& blocks) {
  uint64_t total = 0;
  std::vector<std::pair<const uint8_t*, uint64_t>> views;
  // per-block backing file for the sendfile path: (path, abs offset,
  // identity) when the region is file-backed, else empty path
  struct SfRef { std::string path; uint64_t off, dev, ino, size, mtime_ns; };
  std::vector<SfRef> sf;
  {
    std::lock_guard<std::mutex> g(n->reg_mu);
    for (auto& b : blocks) {
      auto it = n->regions.find((uint32_t)b[0]);
      // overflow-safe bounds check: addr+len can wrap in uint64
      if (it == n->regions.end() || it->second.dereg_wanted ||
          b[1] > it->second.len || b[2] > it->second.len - b[1]) {
        std::string msg = "region resolve failed (mkey " +
                          std::to_string(b[0]) + ")";
        std::vector<uint8_t> out(1 + 8 + 4 + msg.size());
        out[0] = OP_READ_ERR;
        store_be64(&out[1], req_id);
        store_be32(&out[9], (uint32_t)msg.size());
        memcpy(&out[13], msg.data(), msg.size());
        queue_out(n, c, std::move(out), 0, false);
        return;
      }
      views.emplace_back(it->second.ptr + b[1], b[2]);
      if (it->second.file_backed) {
        sf.push_back({it->second.path, it->second.file_off + b[1],
                      it->second.file_dev, it->second.file_ino,
                      it->second.file_size, it->second.file_mtime_ns});
      } else {
        sf.push_back({std::string(), 0, 0, 0, 0, 0});
      }
      total += b[2];
    }
    // pin while still under the lock so no dereg can slip between
    // resolution and enqueue
    for (auto& b : blocks) n->regions[(uint32_t)b[0]].pins++;
  }
  std::vector<uint8_t> hdr(1 + 8 + 8);
  hdr[0] = OP_READ_RESP;
  store_be64(&hdr[1], req_id);
  store_be64(&hdr[9], total);
  queue_out(n, c, std::move(hdr), 0, false);
  if (c->down) {
    // queue_out dropped the header; drop the pins too
    for (auto& b : blocks) unpin_region(n, (uint32_t)b[0]);
    return;
  }
  for (size_t i = 0; i < blocks.size(); i++) {
    OutBuf ob;
    ob.ext = views[i].first;
    ob.ext_len = views[i].second;
    ob.pin_mkey = (uint32_t)blocks[i][0];
    if (!sf[i].path.empty() &&
        (!c->peer_loopback || n->force_sendfile.load())) {
      // file-backed region: serve by sendfile (zero userspace copies)
      // when the path still names the registered file; the pinned
      // memory view above remains the in-place fallback either way.
      // Loopback peers keep the userspace send — see Conn::peer_loopback.
      int fd = open(sf[i].path.c_str(), O_RDONLY);
      if (fd >= 0) {
        struct stat fst;
        if (fstat(fd, &fst) == 0 &&
            stat_matches(fst, sf[i].dev, sf[i].ino, sf[i].size,
                         sf[i].mtime_ns)) {
          ob.sf_fd = fd;
          ob.sf_off = sf[i].off;
        } else {
          close(fd);
        }
      }
    }
    c->outq.push_back(std::move(ob));
  }
  if (!c->want_write && !blocks.empty()) {
    c->want_write = true;
    arm(n, c);
  }
  // push what the socket will take right away rather than waiting a
  // poll cycle
  if (!c->down) flush_out(n, c);
}

// READ_REQ2 from a file-capable peer: when every block resolves to a
// file-backed region, answer with (path, offset) metadata instead of
// bytes — the peer preads straight from page cache. Falls back to the
// streaming serve_read otherwise.
void serve_read2(Node* n, Conn* c, uint64_t req_id,
                 const std::vector<std::array<uint64_t, 3>>& blocks) {
  std::vector<FileRef> files;
  if (!n->host_proof.empty()) {
    std::lock_guard<std::mutex> g(n->reg_mu);
    for (auto& b : blocks) {
      auto it = n->regions.find((uint32_t)b[0]);
      if (it == n->regions.end() || it->second.dereg_wanted ||
          b[1] > it->second.len || b[2] > it->second.len - b[1] ||
          !it->second.file_backed) {
        files.clear();
        break;
      }
      files.push_back({it->second.path, it->second.file_off + b[1],
                       it->second.file_dev, it->second.file_ino,
                       it->second.file_size, it->second.file_mtime_ns});
    }
  }
  if (files.empty() || blocks.empty()) {
    serve_read(n, c, req_id, blocks);  // mixed/unbacked/invalid: stream
    return;
  }
  size_t body_len = 2 + n->host_proof.size() + 4;
  for (auto& f : files) body_len += 8 * 5 + 2 + f.path.size();
  if (body_len > (2u << 20)) {
    // the client hard-fails READ_FILE bodies over 4 MiB as malformed;
    // an enormous block count is better served by streaming anyway
    serve_read(n, c, req_id, blocks);
    return;
  }
  std::vector<uint8_t> out(1 + 8 + 4 + body_len);
  out[0] = OP_READ_FILE;
  store_be64(&out[1], req_id);
  store_be32(&out[9], (uint32_t)body_len);
  size_t off = 13;
  out[off] = (uint8_t)(n->host_proof.size() >> 8);
  out[off + 1] = (uint8_t)(n->host_proof.size() & 0xff);
  memcpy(&out[off + 2], n->host_proof.data(), n->host_proof.size());
  off += 2 + n->host_proof.size();
  store_be32(&out[off], (uint32_t)files.size());
  off += 4;
  for (auto& f : files) {
    store_be64(&out[off], f.off);
    store_be64(&out[off + 8], f.dev);
    store_be64(&out[off + 16], f.ino);
    store_be64(&out[off + 24], f.size);
    store_be64(&out[off + 32], f.mtime_ns);
    out[off + 40] = (uint8_t)(f.path.size() >> 8);
    out[off + 41] = (uint8_t)(f.path.size() & 0xff);
    memcpy(&out[off + 42], f.path.data(), f.path.size());
    off += 42 + f.path.size();
  }
  queue_out(n, c, std::move(out), 0, false);
  if (!c->down) flush_out(n, c);
}

// (re)send a READ request frame for an already-registered PendingRead.
// use_file_op selects READ_REQ2 (file-capable) vs plain READ_REQ.
void send_read_frame(Node* n, Conn* c, uint64_t req_id,
                     const std::vector<std::array<uint64_t, 3>>& blocks,
                     bool use_file_op) {
  std::vector<uint8_t> frame(1 + 8 + 4 + blocks.size() * 16);
  frame[0] = use_file_op ? OP_READ_REQ2 : OP_READ_REQ;
  store_be64(&frame[1], req_id);
  store_be32(&frame[9], (uint32_t)blocks.size());
  for (size_t i = 0; i < blocks.size(); i++) {
    uint8_t* b = &frame[13 + i * 16];
    store_be32(b, (uint32_t)blocks[i][0]);
    store_be64(b + 4, blocks[i][1]);
    store_be32(b + 12, (uint32_t)blocks[i][2]);
  }
  queue_out(n, c, std::move(frame), 0, false);
  if (!c->down) flush_out(n, c);
}

// same-host pread execution, on the file worker thread. Every fd —
// cached or freshly opened — is validated against the (dev, ino) the
// server captured at REGISTRATION, so neither a stale cached fd nor a
// shuffle file unlinked and rewritten at the same path (a task
// re-attempt) can serve wrong bytes; mismatch falls back to streaming.
// mapped delivery: mmap each block's file range instead of pread-ing
// it — ZERO copies on the client too; the consumer reads page-cache
// pages in place (the true same-host DMA analogue). Record layout per
// block: user_ptr(8) len(8) map_base(8) map_len(8), all host-endian —
// this never crosses the wire, it goes straight to the local caller.
bool do_file_task_mapped(FileTask& t) {
  size_t page = (size_t)sysconf(_SC_PAGESIZE);
  std::vector<std::array<uint64_t, 4>> maps;
  bool ok = true;
  for (size_t i = 0; i < t.files.size() && ok; i++) {
    const FileRef& f = t.files[i];
    int fd = open(f.path.c_str(), O_RDONLY);
    if (fd < 0) { ok = false; break; }
    struct stat fst;
    if (fstat(fd, &fst) != 0 ||
        !stat_matches(fst, f.dev, f.ino, f.size, f.mtime_ns)) {
      close(fd);
      ok = false;
      break;
    }
    uint64_t aligned = f.off & ~(uint64_t)(page - 1);
    uint64_t delta = f.off - aligned;
    uint64_t map_len = t.lens[i] + delta;
    // MAP_POPULATE prefaults the whole window on the file worker
    // thread: the consumer's first pass then runs at touch speed
    // instead of soft-faulting once per page mid-sum (the measured
    // gap between mapped-consumed and the consume roofline). Kernels
    // or filesystems that refuse populate fall back to plain mmap —
    // correctness is identical, only first-touch cost moves.
    int flags = MAP_SHARED;
#ifdef MAP_POPULATE
    flags |= MAP_POPULATE;
#endif
    void* base = mmap(nullptr, (size_t)map_len, PROT_READ, flags, fd,
                      (off_t)aligned);
#ifdef MAP_POPULATE
    if (base == MAP_FAILED)
      base = mmap(nullptr, (size_t)map_len, PROT_READ, MAP_SHARED, fd,
                  (off_t)aligned);
#endif
    close(fd);  // the mapping keeps the inode alive
    if (base == MAP_FAILED) { ok = false; break; }
    maps.push_back({(uint64_t)base + delta, t.lens[i], (uint64_t)base,
                    map_len});
  }
  if (!ok) {
    for (auto& m : maps) munmap((void*)m[2], (size_t)m[3]);
    return false;
  }
  t.records.resize(maps.size() * 32);
  for (size_t i = 0; i < maps.size(); i++)
    memcpy(t.records.data() + i * 32, maps[i].data(), 32);
  return true;
}

// Reclaim the mappings described by an n x 32B mapped-read record blob
// (user_ptr, len, map_base, map_len per record, host-endian) that will
// never reach its consumer. Dropped queued FILE_DONE commands and
// undelivered aux=1 completions must come through here before their
// blob is freed, else every record's page-cache mmap leaks for the
// process lifetime.
void unmap_mapped_records(const void* recs, size_t len) {
  const uint8_t* p = (const uint8_t*)recs;
  for (size_t off = 0; off + 32 <= len; off += 32) {
    uint64_t base, mlen;
    memcpy(&base, p + off + 16, sizeof(base));
    memcpy(&mlen, p + off + 24, sizeof(mlen));
    if (base) munmap((void*)base, (size_t)mlen);
  }
}

// scatter-read one contiguous file run into a contiguous destination:
// one preadv2 per <=IOV_MAX iovec batch (the block boundaries become
// iovec entries, so a reducer's run of adjacent partition chunks costs
// one syscall instead of one per chunk). ENOSYS — no preadv2 on this
// kernel — and short reads degrade to the plain pread loop; bytes and
// layout are identical either way.
static bool read_run_scatter(int fd, uint64_t off, uint8_t* dst,
                             const uint64_t* lens, size_t n_lens) {
  uint64_t total = 0;
  for (size_t i = 0; i < n_lens; i++) total += lens[i];
  uint64_t got = 0;
#if defined(__linux__) && defined(RWF_NOWAIT)
  static std::atomic<bool> preadv2_ok{true};
  if (preadv2_ok.load(std::memory_order_relaxed) && n_lens > 1) {
    std::vector<struct iovec> iov(n_lens);
    uint64_t o = 0;
    for (size_t i = 0; i < n_lens; i++) {
      iov[i].iov_base = dst + o;
      iov[i].iov_len = (size_t)lens[i];
      o += lens[i];
    }
    size_t first = 0;
    while (got < total) {
      // drop fully-read iovecs, trim the partial head
      while (first < iov.size() && iov[first].iov_len == 0) first++;
      int cnt = (int)std::min((size_t)IOV_MAX, iov.size() - first);
      ssize_t r = preadv2(fd, &iov[first], cnt, (off_t)(off + got), 0);
      if (r < 0 && errno == ENOSYS) {
        preadv2_ok.store(false, std::memory_order_relaxed);
        break;  // pread fallback below finishes the run
      }
      if (r <= 0) break;
      got += (uint64_t)r;
      uint64_t adv = (uint64_t)r;
      for (size_t i = first; i < iov.size() && adv; i++) {
        size_t take = std::min((size_t)adv, iov[i].iov_len);
        iov[i].iov_base = (uint8_t*)iov[i].iov_base + take;
        iov[i].iov_len -= take;
        adv -= take;
      }
    }
  }
#endif
  while (got < total) {
    ssize_t r = pread(fd, dst + got, (size_t)(total - got),
                      (off_t)(off + got));
    if (r <= 0) return false;
    got += (uint64_t)r;
  }
  return true;
}

// resolve a FileTask's (path, identity, off, len) blocks into coalesced
// contiguous runs with validated fds — shared by EVERY backend, so the
// identity checks and the run coalescing can never diverge between
// them. fds stay owned by the worker's fd_cache; descriptors borrow.
static bool resolve_runs(FileTask& t,
                         std::unordered_map<std::string, int>& fd_cache,
                         std::vector<ReadSqe>& out) {
  uint64_t dst_off = 0;
  for (size_t i = 0; i < t.files.size(); i++) {
    uint64_t len = t.lens[i];
    const FileRef& f = t.files[i];
    int fd = -1;
    auto it = fd_cache.find(f.path);
    if (it != fd_cache.end()) {
      struct stat fst;
      if (fstat(it->second, &fst) == 0 &&
          stat_matches(fst, f.dev, f.ino, f.size, f.mtime_ns)) {
        fd = it->second;
      } else {
        close(it->second);  // unlinked/recreated: drop the stale fd
        fd_cache.erase(it);
      }
    }
    if (fd < 0) {
      fd = open(f.path.c_str(), O_RDONLY);
      if (fd < 0) return false;
      struct stat fst;
      if (fstat(fd, &fst) != 0 ||
          !stat_matches(fst, f.dev, f.ino, f.size, f.mtime_ns)) {
        // the path now names a DIFFERENT file than the one registered
        close(fd);
        return false;
      }
      if (fd_cache.size() >= 64) {
        // bound the cache: never pin unlinked tmpfs inodes (and fds)
        // for the process lifetime
        for (auto& kv : fd_cache) close(kv.second);
        fd_cache.clear();
      }
      fd_cache[f.path] = fd;
    }
    // coalesce the contiguous run starting at i — same inode, offsets
    // back-to-back (a reducer's adjacent partition chunks in one spill
    // file) — into one descriptor instead of one per block
    ReadSqe s;
    s.fd = fd;
    s.off = f.off;
    s.dst = t.dst + dst_off;
    s.lens.push_back(len);
    s.total = len;
    size_t j = i + 1;
    while (j < t.files.size() && t.files[j].path == f.path &&
           t.files[j].dev == f.dev && t.files[j].ino == f.ino &&
           t.files[j].off == f.off + s.total) {
      s.lens.push_back(t.lens[j]);
      s.total += t.lens[j];
      j++;
    }
    dst_off += s.total;
    out.push_back(std::move(s));
    i = j - 1;
  }
  return true;
}

// mapped-COPY backend: mmap the run's file window and memcpy into the
// destination — the same page-cache bytes as pread through a different
// kernel path (page-table walk instead of a read syscall per run).
// Distinct from mapped DELIVERY, which hands the mapping itself to the
// consumer. mmap refusal degrades to pread in the caller.
static bool sqe_mapped_copy(const ReadSqe& s) {
  size_t page = (size_t)sysconf(_SC_PAGESIZE);
  uint64_t aligned = s.off & ~(uint64_t)(page - 1);
  uint64_t delta = s.off - aligned;
  size_t map_len = (size_t)(s.total + delta);
  int flags = MAP_SHARED;
#ifdef MAP_POPULATE
  flags |= MAP_POPULATE;
#endif
  void* base = mmap(nullptr, map_len, PROT_READ, flags, s.fd, (off_t)aligned);
#ifdef MAP_POPULATE
  if (base == MAP_FAILED)
    base = mmap(nullptr, map_len, PROT_READ, MAP_SHARED, s.fd, (off_t)aligned);
#endif
  if (base == MAP_FAILED) return false;
  memcpy(s.dst, (const uint8_t*)base + delta, (size_t)s.total);
  munmap(base, map_len);
  return true;
}

#ifdef SRT_HAVE_IOURING
static int sys_io_uring_setup(unsigned entries, struct io_uring_params* p) {
  return (int)syscall(__NR_io_uring_setup, entries, p);
}
static int sys_io_uring_enter(int fd, unsigned to_submit,
                              unsigned min_complete, unsigned flags) {
  return (int)syscall(__NR_io_uring_enter, fd, to_submit, min_complete, flags,
                      nullptr, 0);
}
static int sys_io_uring_register(int fd, unsigned opcode, const void* arg,
                                 unsigned nr_args) {
  return (int)syscall(__NR_io_uring_register, fd, opcode, arg, nr_args);
}

// One ring per file-worker thread: single submitter by construction,
// so no ring locking anywhere. Created lazily on the worker's first
// uring-backed task, torn down when the worker exits. SQPOLL is
// deliberately NOT requested — it needs privileges/5.13+ for unpinned
// use and burns a core busy-polling, which the consume lanes want.
struct UringRing {
  int ring_fd = -1;
  unsigned entries = 0;
  uint8_t* sq_ring = nullptr;
  size_t sq_ring_len = 0;
  uint8_t* cq_ring = nullptr;
  size_t cq_ring_len = 0;
  struct io_uring_sqe* sqes = nullptr;
  size_t sqes_len = 0;
  unsigned* sq_head = nullptr;
  unsigned* sq_tail = nullptr;
  unsigned* sq_mask = nullptr;
  unsigned* sq_array = nullptr;
  unsigned* cq_head = nullptr;
  unsigned* cq_tail = nullptr;
  unsigned* cq_mask = nullptr;
  struct io_uring_cqe* cqes = nullptr;
  // fixed-buffer snapshot: registered ONCE at ring creation; READ_FIXED
  // is used only while plane.seg_dead_gen still matches dead_gen
  bool fixed_ok = false;
  uint64_t dead_gen = 0;
  std::vector<std::pair<uint64_t, uint64_t>> bufs;
  bool ready = false;

  void destroy() {
    if (sqes) munmap(sqes, sqes_len);
    if (cq_ring && cq_ring != sq_ring) munmap(cq_ring, cq_ring_len);
    if (sq_ring) munmap(sq_ring, sq_ring_len);
    if (ring_fd >= 0) close(ring_fd);
    sqes = nullptr;
    cq_ring = nullptr;
    sq_ring = nullptr;
    ring_fd = -1;
    ready = false;
  }
  ~UringRing() { destroy(); }
};

static bool uring_init(UringRing& r, SubmissionPlane& plane) {
  struct io_uring_params p;
  memset(&p, 0, sizeof(p));
  int fd = sys_io_uring_setup(64, &p);
  if (fd < 0) return false;
  r.ring_fd = fd;
  r.entries = p.sq_entries;
  size_t sq_len = p.sq_off.array + p.sq_entries * sizeof(unsigned);
  size_t cq_len = p.cq_off.cqes + p.cq_entries * sizeof(struct io_uring_cqe);
  bool single = (p.features & IORING_FEAT_SINGLE_MMAP) != 0;
  if (single) sq_len = cq_len = std::max(sq_len, cq_len);
  void* sq = mmap(nullptr, sq_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd,
                  IORING_OFF_SQ_RING);
  if (sq == MAP_FAILED) {
    r.destroy();
    return false;
  }
  r.sq_ring = (uint8_t*)sq;
  r.sq_ring_len = sq_len;
  if (single) {
    r.cq_ring = r.sq_ring;
    r.cq_ring_len = sq_len;
  } else {
    void* cq = mmap(nullptr, cq_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd,
                    IORING_OFF_CQ_RING);
    if (cq == MAP_FAILED) {
      r.destroy();
      return false;
    }
    r.cq_ring = (uint8_t*)cq;
    r.cq_ring_len = cq_len;
  }
  r.sqes_len = p.sq_entries * sizeof(struct io_uring_sqe);
  void* se = mmap(nullptr, r.sqes_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd,
                  IORING_OFF_SQES);
  if (se == MAP_FAILED) {
    r.destroy();
    return false;
  }
  r.sqes = (struct io_uring_sqe*)se;
  r.sq_head = (unsigned*)(r.sq_ring + p.sq_off.head);
  r.sq_tail = (unsigned*)(r.sq_ring + p.sq_off.tail);
  r.sq_mask = (unsigned*)(r.sq_ring + p.sq_off.ring_mask);
  r.sq_array = (unsigned*)(r.sq_ring + p.sq_off.array);
  r.cq_head = (unsigned*)(r.cq_ring + p.cq_off.head);
  r.cq_tail = (unsigned*)(r.cq_ring + p.cq_off.tail);
  r.cq_mask = (unsigned*)(r.cq_ring + p.cq_off.ring_mask);
  r.cqes = (struct io_uring_cqe*)(r.cq_ring + p.cq_off.cqes);
  // fixed-buffer registration: ONE snapshot, ONE register call, for
  // the ring's whole lifetime. Registration pins the pages, so a slab
  // deregistered later stays resident until the ring closes — the
  // dead_gen check above only stops NEW READ_FIXED submissions from
  // addressing it. Failure (RLIMIT_MEMLOCK, unmappable segment)
  // degrades to plain IORING_OP_READ; never fatal.
  {
    std::lock_guard<std::mutex> g(plane.seg_mu);
    r.bufs = plane.segs;
  }
  r.dead_gen = plane.seg_dead_gen.load(std::memory_order_acquire);
  if (!r.bufs.empty()) {
    std::vector<struct iovec> iov;
    bool fits = true;
    for (auto& s : r.bufs) {
      if (s.second > (1ull << 30)) {  // kernel per-iovec cap
        fits = false;
        break;
      }
      iov.push_back({(void*)s.first, (size_t)s.second});
    }
    if (fits && !iov.empty() &&
        sys_io_uring_register(fd, IORING_REGISTER_BUFFERS, iov.data(),
                              (unsigned)iov.size()) == 0)
      r.fixed_ok = true;
    if (!r.fixed_ok) r.bufs.clear();
  }
  r.ready = true;
  return true;
}

// submit the resolved runs as batched SQEs and reap their CQEs. One
// SQE per run (the destination is contiguous across a run); batches
// bounded by the ring size. Short or failed CQEs are finished by the
// pread scatter path per run — bytes identical, counted as fallbacks.
static bool uring_exec(SubmissionPlane& pl, UringRing& r,
                       const std::vector<ReadSqe>& rs) {
  bool fixed_usable =
      r.fixed_ok &&
      pl.seg_dead_gen.load(std::memory_order_acquire) == r.dead_gen;
  std::vector<uint64_t> got(rs.size(), 0);
  size_t done = 0;
  while (done < rs.size()) {
    unsigned batch = (unsigned)std::min((size_t)r.entries, rs.size() - done);
    unsigned tail = *r.sq_tail;
    for (unsigned k = 0; k < batch; k++) {
      const ReadSqe& s = rs[done + k];
      unsigned idx = (tail + k) & *r.sq_mask;
      struct io_uring_sqe* e = &r.sqes[idx];
      memset(e, 0, sizeof(*e));
      e->fd = s.fd;
      e->addr = (uint64_t)s.dst;
      // sqe.len is 32-bit: cap the request; a capped (short) read is
      // completed by the pread fallback below
      e->len = (uint32_t)std::min<uint64_t>(s.total, 1u << 30);
      e->off = s.off;
      e->user_data = done + k;
      int bi = -1;
      if (fixed_usable) {
        for (size_t b = 0; b < r.bufs.size(); b++) {
          uint64_t lo = r.bufs[b].first;
          uint64_t hi = lo + r.bufs[b].second;
          if ((uint64_t)s.dst >= lo && (uint64_t)s.dst + s.total <= hi) {
            bi = (int)b;
            break;
          }
        }
      }
      if (bi >= 0) {
        e->opcode = IORING_OP_READ_FIXED;
        e->buf_index = (uint16_t)bi;
      } else {
        e->opcode = IORING_OP_READ;
      }
      r.sq_array[idx] = idx;
    }
    __atomic_store_n(r.sq_tail, tail + batch, __ATOMIC_RELEASE);
    pl.sq_submits.fetch_add(batch, std::memory_order_relaxed);
    pl.note_depth(batch);
    unsigned submitted = 0;
    while (submitted < batch) {
      int ret = sys_io_uring_enter(r.ring_fd, batch - submitted,
                                   batch - submitted, IORING_ENTER_GETEVENTS);
      if (ret < 0) {
        if (errno == EINTR) continue;
        return false;  // ring wedged: caller degrades the whole task
      }
      submitted += (unsigned)ret;
    }
    pl.sq_batches.fetch_add(1, std::memory_order_relaxed);
    unsigned head = *r.cq_head;
    unsigned reaped = 0;
    while (reaped < batch) {
      unsigned ctail = __atomic_load_n(r.cq_tail, __ATOMIC_ACQUIRE);
      if (head == ctail) {
        int ret = sys_io_uring_enter(r.ring_fd, 0, 1, IORING_ENTER_GETEVENTS);
        if (ret < 0 && errno != EINTR) return false;
        continue;
      }
      while (head != ctail && reaped < batch) {
        struct io_uring_cqe* cqe = &r.cqes[head & *r.cq_mask];
        uint64_t ud = cqe->user_data;
        if (cqe->res > 0 && ud < rs.size()) got[ud] = (uint64_t)cqe->res;
        head++;
        reaped++;
      }
      __atomic_store_n(r.cq_head, head, __ATOMIC_RELEASE);
    }
    done += batch;
  }
  for (size_t i = 0; i < rs.size(); i++) {
    const ReadSqe& s = rs[i];
    if (got[i] < s.total) {
      // short or failed: redo the run via the scatter path (the rare
      // path re-reads a prefix; correctness over cleverness here)
      pl.sq_backend_fallbacks.fetch_add(1, std::memory_order_relaxed);
      if (!read_run_scatter(s.fd, s.off, s.dst, s.lens.data(),
                            s.lens.size()))
        return false;
    }
    pl.sq_completions.fetch_add(1, std::memory_order_relaxed);
  }
  return true;
}
#endif  // SRT_HAVE_IOURING

// per-worker backend state: the lazily-created ring (when compiled in)
struct WorkerRing {
#ifdef SRT_HAVE_IOURING
  UringRing ring;
#endif
  bool tried = false;
  bool counted_fail = false;
};

// availability probe, latched once per node: can this kernel do
// io_uring at all? The force_probe_fail seam makes the probe behave
// exactly like an ENOSYS kernel (tests + the read:enosys fault kind).
static bool plane_uring_probe(SubmissionPlane& pl) {
#ifndef SRT_HAVE_IOURING
  int st = pl.uring_state.load(std::memory_order_relaxed);
  if (st == 0 && pl.uring_state.compare_exchange_strong(st, -1))
    pl.sq_backend_fallbacks.fetch_add(1, std::memory_order_relaxed);
  return false;
#else
  if (pl.force_probe_fail.load(std::memory_order_relaxed)) {
    int st = pl.uring_state.load(std::memory_order_relaxed);
    if (st != -2 && pl.uring_state.compare_exchange_strong(st, -2))
      pl.sq_backend_fallbacks.fetch_add(1, std::memory_order_relaxed);
    return false;
  }
  int st = pl.uring_state.load(std::memory_order_relaxed);
  if (st < 0) return false;
  if (st == 0) {
    struct io_uring_params p;
    memset(&p, 0, sizeof(p));
    int fd = sys_io_uring_setup(4, &p);
    int now = fd >= 0 ? 1 : -1;
    if (fd >= 0) close(fd);
    int expect = 0;
    if (pl.uring_state.compare_exchange_strong(expect, now)) {
      if (now < 0)
        pl.sq_backend_fallbacks.fetch_add(1, std::memory_order_relaxed);
    }
    return pl.uring_state.load(std::memory_order_relaxed) == 1;
  }
  return true;
#endif
}

static bool plane_uring_ready(Node* n, WorkerRing& wr) {
  if (!plane_uring_probe(n->plane)) return false;
#ifndef SRT_HAVE_IOURING
  (void)wr;
  return false;
#else
  if (!wr.tried) {
    wr.tried = true;
    uring_init(wr.ring, n->plane);
  }
  if (!wr.ring.ready && !wr.counted_fail) {
    // the node-level probe passed but THIS worker's ring failed
    // (fd/memlock limits): this worker degrades to pread, once
    wr.counted_fail = true;
    n->plane.sq_backend_fallbacks.fetch_add(1, std::memory_order_relaxed);
  }
  return wr.ring.ready;
#endif
}

// SubmissionPlane execution, worker-thread side: resolve the task's
// blocks into runs and drive them through the selected backend. THE
// place where pread-vs-mapped-vs-scatter-vs-uring is decided — no
// caller branches on it.
bool plane_execute(Node* n, FileTask& t,
                   std::unordered_map<std::string, int>& fd_cache,
                   WorkerRing& wr) {
  SubmissionPlane& pl = n->plane;
  if (t.mapped) {
    // mapped DELIVERY: completion mode, not a backend (see plane doc)
    pl.sq_batches.fetch_add(1, std::memory_order_relaxed);
    pl.sq_submits.fetch_add(t.files.size(), std::memory_order_relaxed);
    pl.note_depth(t.files.size());
    if (!do_file_task_mapped(t)) return false;
    pl.sq_completions.fetch_add(t.files.size(), std::memory_order_relaxed);
    return true;
  }
  std::vector<ReadSqe> runs;
  if (!resolve_runs(t, fd_cache, runs)) return false;
  int want = pl.backend.load(std::memory_order_relaxed);
  if (want == RB_AUTO || want == RB_IOURING) {
#ifdef SRT_HAVE_IOURING
    if (plane_uring_ready(n, wr)) {
      if (uring_exec(pl, wr.ring, runs)) return true;
      // wedged ring mid-task: count and degrade this task to pread
      pl.sq_backend_fallbacks.fetch_add(1, std::memory_order_relaxed);
    }
#else
    plane_uring_ready(n, wr);  // latches the fallback, counted once
#endif
  }
  bool mapped_copy = want == RB_MAPPED;
  pl.sq_batches.fetch_add(1, std::memory_order_relaxed);
  pl.note_depth(runs.size());
  for (auto& s : runs) {
    pl.sq_submits.fetch_add(1, std::memory_order_relaxed);
    bool ok = mapped_copy
                  ? sqe_mapped_copy(s)
                  : read_run_scatter(s.fd, s.off, s.dst, s.lens.data(),
                                     s.lens.size());
    if (!ok && mapped_copy) {
      // filesystem refused the mapping: degrade the run to pread
      pl.sq_backend_fallbacks.fetch_add(1, std::memory_order_relaxed);
      ok = read_run_scatter(s.fd, s.off, s.dst, s.lens.data(),
                            s.lens.size());
    }
    if (!ok) return false;
    pl.sq_completions.fetch_add(1, std::memory_order_relaxed);
  }
  return true;
}

void file_worker_main(Node* n) {
  std::unordered_map<std::string, int> fd_cache;
  WorkerRing ring;
  while (true) {
    FileTask t;
    {
      std::unique_lock<std::mutex> lk(n->ft_mu);
      n->ft_cv.wait(lk, [&] { return !n->ftq.empty() || n->stopping.load(); });
      if (n->ftq.empty()) break;  // stopping and drained
      t = std::move(n->ftq.front());
      n->ftq.pop_front();
    }
    bool ok = plane_execute(n, t, fd_cache, ring);
    if (t.group) {
      // one part of a split task: only the LAST finisher completes
      // the request (success only if every part succeeded)
      if (!ok) t.group->failed.store(true);
      if (t.group->remaining.fetch_sub(1) != 1) continue;
      ok = !t.group->failed.load();
    }
    Command cmd;
    cmd.kind = ok ? Command::FILE_DONE : Command::FILE_FALLBACK;
    cmd.channel = t.channel;
    cmd.req_id = t.req_id;
    cmd.data = std::move(t.records);  // mapped: mmap records for the CQ
    n->enqueue(std::move(cmd));
  }
  for (auto& kv : fd_cache) close(kv.second);
}

// SubmissionPlane entry point, loop-thread side: schedule one logical
// read request (already parked in file_pending) onto the worker pool.
// The striping/splitting policy lives HERE — behind the plane seam —
// not in the frame handler, so every backend composes with it.
void plane_submit(Node* n, FileTask&& t) {
  // multi-block pread tasks fan out over the worker pool (the
  // WR-list striping analogue): contiguous block ranges, each
  // part's dst pre-offset, one shared completion. Mapped tasks
  // stay whole (their records must keep request order). The pool
  // can grow mid-run (srt_set_file_workers), so read the atomic
  // count — never the vector, which mutates under fw_mu.
  size_t nworkers = n->file_worker_count.load(std::memory_order_acquire);
  uint64_t total_bytes = 0;
  for (uint64_t L : t.lens) total_bytes += L;
  // intra-block striping: a single fat block (the common
  // one-partition fetch) would otherwise ride one worker while
  // the rest of the pool idles. Expand any block >= 4MB into
  // contiguous sub-ranges of the SAME file (offset advanced,
  // identity fields unchanged) so the byte-balanced split below
  // can spread ONE block across file_workers threads. Only for
  // the pread path: dst placement is cumulative over lens, so
  // sub-block boundaries are invisible downstream; mapped tasks
  // keep per-block records and must stay whole.
  if (!t.mapped && nworkers > 1) {
    std::vector<FileRef> xfiles;
    std::vector<uint64_t> xlens;
    for (size_t i = 0; i < t.files.size(); i++) {
      uint64_t blen = t.lens[i];
      // each sub-range stays >= 1MB so the stripe never degrades
      // into syscall-overhead-dominated slivers
      size_t sparts = (size_t)std::min<uint64_t>(
          (uint64_t)nworkers, blen / (1ull << 20));
      if (blen >= (4ull << 20) && sparts > 1) {
        uint64_t chunk = (blen + sparts - 1) / sparts;
        for (uint64_t done = 0; done < blen; done += chunk) {
          FileRef sub = t.files[i];
          sub.off += done;
          xfiles.push_back(std::move(sub));
          xlens.push_back(std::min(chunk, blen - done));
        }
        n->stat_block_stripes.fetch_add((blen + chunk - 1) / chunk);
        continue;
      }
      xfiles.push_back(std::move(t.files[i]));
      xlens.push_back(blen);
    }
    t.files = std::move(xfiles);
    t.lens = std::move(xlens);
  }
  // split only when the work amortizes the dispatch (a few MB
  // floor) and balance parts by BYTES, not block count — one fat
  // block among small ones must not leave a part doing all the
  // copying while the others pay pure thread overhead
  if (!t.mapped && nworkers > 1 && t.files.size() > 1 &&
      total_bytes >= (4ull << 20)) {
    size_t parts = std::min(nworkers, t.files.size());
    auto grp = std::make_shared<TaskGroup>();
    std::vector<FileTask> subs;
    uint64_t off = 0, acc = 0, remaining_bytes = total_bytes;
    FileTask s;
    s.channel = t.channel;
    s.req_id = t.req_id;
    s.group = grp;
    s.dst = t.dst;
    for (size_t i = 0; i < t.files.size(); i++) {
      s.files.push_back(std::move(t.files[i]));
      s.lens.push_back(t.lens[i]);
      acc += t.lens[i];
      off += t.lens[i];
      remaining_bytes -= t.lens[i];
      bool more_parts = subs.size() + 1 < parts;
      bool more_files = i + 1 < t.files.size();
      if (more_parts && more_files) {
        // close this part when stopping NOW lands closer to its
        // fair share (remaining bytes / remaining parts) than
        // absorbing the next block would — keeps parts byte-
        // balanced even when one fat block sits among small ones
        uint64_t share = (acc + remaining_bytes) / (parts - subs.size());
        uint64_t next = t.lens[i + 1];
        uint64_t over = acc + next > share ? acc + next - share : 0;
        uint64_t under = share > acc ? share - acc : 0;
        if (acc >= share || over > under) {
          subs.push_back(std::move(s));
          s = FileTask();
          s.channel = t.channel;
          s.req_id = t.req_id;
          s.group = grp;
          s.dst = t.dst + off;
          acc = 0;
        }
      }
    }
    subs.push_back(std::move(s));
    // set the count BEFORE any part is enqueued
    grp->remaining.store((int)subs.size());
    n->stat_split_parts.fetch_add(subs.size());
    {
      std::lock_guard<std::mutex> g(n->ft_mu);
      for (auto& sub : subs) n->ftq.push_back(std::move(sub));
    }
    n->ft_cv.notify_all();
  } else {
    {
      std::lock_guard<std::mutex> g(n->ft_mu);
      n->ftq.push_back(std::move(t));
    }
    n->ft_cv.notify_one();
  }
}

void handle_frame_ingest(Node* n, Conn* c, const uint8_t* data, size_t len);

// consume as many bytes as the state machine wants from [data, data+len)
size_t ingest(Node* n, Conn* c, const uint8_t* data, size_t len) {
  size_t used = 0;
  while (used < len && !c->down) {
    switch (c->st) {
      case RxState::OP: {
        uint8_t op = data[used++];
        c->hdr_got = 0;
        switch (op) {
          case OP_SEND: c->st = RxState::SEND_HDR; c->hdr_need = 4; break;
          case OP_READ_REQ:
            c->cur_req2 = false;
            c->st = RxState::READQ_HDR; c->hdr_need = 12; break;
          case OP_READ_REQ2:
            c->cur_req2 = true;
            c->st = RxState::READQ_HDR; c->hdr_need = 12; break;
          case OP_READ_RESP: c->st = RxState::READR_HDR; c->hdr_need = 16; break;
          case OP_READ_ERR: c->st = RxState::READE_HDR; c->hdr_need = 12; break;
          case OP_READ_FILE: c->st = RxState::READF_HDR; c->hdr_need = 12; break;
          case OP_HELLO: c->st = RxState::HELLO_HDR; c->hdr_need = 6; break;
          case OP_GOODBYE: fail_conn(n, c); return used;
          default: fail_conn(n, c); return used;
        }
        break;
      }
      case RxState::SEND_HDR:
      case RxState::READQ_HDR:
      case RxState::READR_HDR:
      case RxState::READE_HDR:
      case RxState::READF_HDR:
      case RxState::HELLO_HDR: {
        size_t take = std::min(len - used, c->hdr_need - c->hdr_got);
        memcpy(c->hdr + c->hdr_got, data + used, take);
        c->hdr_got += take;
        used += take;
        if (c->hdr_got < c->hdr_need) break;
        if (c->st == RxState::SEND_HDR) {
          c->body_need = load_be32(c->hdr);
          c->body.resize(c->body_need);
          c->body_got = 0;
          c->st = c->body_need ? RxState::SEND_BODY : RxState::OP;
          if (!c->body_need) {
            Completion comp{};
            comp.kind = COMP_RECV;
            comp.channel = c->id;
            comp.payload = nullptr;
            comp.payload_len = 0;
            n->post(comp);
          }
        } else if (c->st == RxState::READQ_HDR) {
          c->cur_req = load_be64(c->hdr);
          c->body_need = (size_t)load_be32(c->hdr + 8) * 16;
          c->body.resize(c->body_need);
          c->body_got = 0;
          if (c->body_need == 0) {
            // zero-block READ: answer an empty response immediately
            serve_read(n, c, c->cur_req, {});
            c->st = RxState::OP;
          } else {
            c->st = RxState::READQ_BLOCKS;
          }
        } else if (c->st == RxState::READR_HDR) {
          uint64_t req = load_be64(c->hdr);
          uint64_t total = load_be64(c->hdr + 8);
          auto it = c->reads.find(req);
          if (it == c->reads.end() || it->second.expected != total) {
            // unknown or mismatched: drain to keep framing intact
            if (it != c->reads.end()) {
              Completion comp{};
              comp.kind = COMP_READ_DONE;
              comp.status = ST_ERR;
              comp.channel = c->id;
              comp.wr_id = it->second.wr_id;
              n->post(comp);
              c->reads.erase(it);
            }
            c->drain_left = total;
            c->st = total ? RxState::READR_DRAIN : RxState::OP;
          } else {
            c->cur_req = req;
            c->cur_read = &it->second;
            if (it->second.mapped && !it->second.dst && total) {
              // mapped request answered by streaming (remote peer or
              // unbacked region): land in a malloc'd blob whose
              // ownership passes to the completion payload
              it->second.owned = (uint8_t*)malloc(total);
              if (!it->second.owned) {
                // allocation failure fails THIS read, not the process:
                // drain the body to keep framing intact
                Completion comp{};
                comp.kind = COMP_READ_DONE;
                comp.status = ST_ERR;
                comp.channel = c->id;
                comp.wr_id = it->second.wr_id;
                n->post(comp);
                c->reads.erase(it);
                c->cur_read = nullptr;
                c->drain_left = total;
                c->st = RxState::READR_DRAIN;
                break;
              }
              it->second.dst = it->second.owned;
            }
            c->st = total ? RxState::READR_BODY : RxState::OP;
            if (!total) {
              n->stat_streamed_reads++;
              Completion comp{};
              comp.kind = COMP_READ_DONE;
              comp.status = ST_OK;
              comp.channel = c->id;
              comp.wr_id = it->second.wr_id;
              n->post(comp);
              c->reads.erase(it);
              c->cur_read = nullptr;
            }
          }
        } else if (c->st == RxState::READE_HDR) {
          c->cur_req = load_be64(c->hdr);
          c->body_need = load_be32(c->hdr + 8);
          c->body.resize(c->body_need);
          c->body_got = 0;
          if (c->body_need == 0) {
            // empty error message: still complete the pending read
            c->st = RxState::READE_BODY;
            handle_frame_ingest(n, c, c->body.data(), 0);
            c->st = RxState::OP;
          } else {
            c->st = RxState::READE_BODY;
          }
        } else if (c->st == RxState::READF_HDR) {
          c->cur_req = load_be64(c->hdr);
          c->body_need = load_be32(c->hdr + 8);
          if (c->body_need == 0 || c->body_need > (4u << 20)) {
            fail_conn(n, c);  // malformed READ_FILE
            return used;
          }
          c->body.resize(c->body_need);
          c->body_got = 0;
          c->st = RxState::READF_BODY;
        } else {  // HELLO_HDR
          c->body_need = load_be16(c->hdr + 4);
          c->body.resize(c->body_need);
          c->body_got = 0;
          c->st = RxState::HELLO_BODY;
          if (!c->body_need) {
            // zero-length id: still emit ACCEPT
            Completion comp{};
            comp.kind = COMP_ACCEPT;
            comp.channel = c->id;
            comp.aux = load_be32(c->hdr);
            comp.payload = nullptr;
            comp.payload_len = 0;
            n->post(comp);
            c->hello_done = true;
            c->st = RxState::OP;
          }
        }
        break;
      }
      case RxState::SEND_BODY:
      case RxState::READQ_BLOCKS:
      case RxState::READE_BODY:
      case RxState::READF_BODY:
      case RxState::HELLO_BODY: {
        size_t take = std::min(len - used, c->body_need - c->body_got);
        memcpy(c->body.data() + c->body_got, data + used, take);
        c->body_got += take;
        used += take;
        if (c->body_got < c->body_need) break;
        handle_frame_ingest(n, c, c->body.data(), c->body.size());
        c->st = RxState::OP;
        break;
      }
      case RxState::READR_BODY: {
        PendingRead* pr = c->cur_read;
        size_t take = std::min<uint64_t>(len - used, pr->expected - pr->received);
        memcpy(pr->dst + pr->received, data + used, take);
        pr->received += take;
        used += take;
        if (pr->received == pr->expected) {
          n->stat_streamed_reads++;
          Completion comp{};
          comp.kind = COMP_READ_DONE;
          comp.status = ST_OK;
          comp.channel = c->id;
          comp.wr_id = pr->wr_id;
          if (pr->owned) {
            // mapped request, streamed answer: deliver the blob
            // (aux=0 -> contiguous copied bytes, receiver frees)
            comp.payload = pr->owned;
            comp.payload_len = pr->expected;
            comp.aux = 0;
            pr->owned = nullptr;
          }
          n->post(comp);
          c->reads.erase(c->cur_req);
          c->cur_read = nullptr;
          c->st = RxState::OP;
        }
        break;
      }
      case RxState::READR_DRAIN: {
        size_t take = std::min<uint64_t>(len - used, c->drain_left);
        c->drain_left -= take;
        used += take;
        if (!c->drain_left) c->st = RxState::OP;
        break;
      }
    }
  }
  return used;
}

// completed-body dispatch for SEND / READ_REQ / READ_ERR / HELLO
void handle_frame_ingest(Node* n, Conn* c, const uint8_t* data, size_t len) {
  switch (c->st) {
    case RxState::SEND_BODY: {
      void* p = malloc(len ? len : 1);
      memcpy(p, data, len);
      Completion comp{};
      comp.kind = COMP_RECV;
      comp.channel = c->id;
      comp.payload = p;
      comp.payload_len = len;
      n->post(comp);
      break;
    }
    case RxState::READQ_BLOCKS: {
      std::vector<std::array<uint64_t, 3>> blocks(len / 16);
      for (size_t i = 0; i < blocks.size(); i++) {
        const uint8_t* b = data + i * 16;
        blocks[i] = {load_be32(b), load_be64(b + 4), load_be32(b + 12)};
      }
      if (c->cur_req2)
        serve_read2(n, c, c->cur_req, blocks);
      else
        serve_read(n, c, c->cur_req, blocks);
      break;
    }
    case RxState::READF_BODY: {
      auto it = c->reads.find(c->cur_req);
      if (it == c->reads.end()) break;  // late/unknown: nothing to do
      // parse proof_len(2) proof_path then
      // n x [file_off(8) dev(8) ino(8) size(8) mtime_ns(8) plen(2) path]
      std::vector<FileRef> files;
      bool parsed = len >= 2;
      bool same_host = false;
      size_t off = 0;
      if (parsed) {
        uint16_t prooflen = load_be16(data);
        parsed = (size_t)2 + prooflen + 4 <= len && prooflen > 0;
        if (parsed) {
          // host-identity gate: the proof path is unguessable, so being
          // able to stat it proves we share the server's filesystem.
          // Without this, a deterministic shuffle-file path existing on
          // BOTH hosts would silently serve the wrong host's bytes.
          std::string proof((const char*)data + 2, prooflen);
          struct stat st;
          same_host = stat(proof.c_str(), &st) == 0;
          off = 2 + prooflen;
        }
      }
      if (parsed && same_host) {
        uint32_t nf = load_be32(data + off);
        off += 4;
        parsed = false;
        if (nf == it->second.blocks.size()) {
          parsed = true;
          for (uint32_t i = 0; parsed && i < nf; i++) {
            if (off + 42 > len) { parsed = false; break; }
            uint64_t foff = load_be64(data + off);
            uint64_t fdev = load_be64(data + off + 8);
            uint64_t fino = load_be64(data + off + 16);
            uint64_t fsize = load_be64(data + off + 24);
            uint64_t fmt = load_be64(data + off + 32);
            uint16_t plen = load_be16(data + off + 40);
            if (off + 42 + plen > len) { parsed = false; break; }
            files.push_back({std::string((const char*)data + off + 42, plen),
                             foff, fdev, fino, fsize, fmt});
            off += 42 + plen;
          }
        }
      }
      if (parsed && same_host) {
        // hand the preads to the file worker; the pending read parks in
        // the node-level map so this Conn's death cannot free it while
        // the worker is writing into its destination
        c->files_ok = 1;
        FileTask t;
        t.channel = c->id;
        t.req_id = c->cur_req;
        t.dst = it->second.dst;
        t.mapped = it->second.mapped;
        for (auto& b : it->second.blocks) t.lens.push_back(b[2]);
        t.files = std::move(files);
        n->file_pending.emplace(std::make_pair(c->id, c->cur_req),
                                std::move(it->second));
        c->reads.erase(it);
        // hand the request to the submission plane: striping,
        // splitting and backend choice all live behind that one seam
        plane_submit(n, std::move(t));
      } else {
        // different host (proof unreachable): latch the fast path off
        // for this conn. A malformed frame just streams this one read.
        if (parsed && !same_host) c->files_ok = 0;
        send_read_frame(n, c, c->cur_req, it->second.blocks, false);
      }
      break;
    }
    case RxState::READE_BODY: {
      auto it = c->reads.find(c->cur_req);
      if (it != c->reads.end()) {
        void* p = malloc(len ? len : 1);
        memcpy(p, data, len);
        Completion comp{};
        comp.kind = COMP_READ_DONE;
        comp.status = ST_REMOTE_ERR;
        comp.channel = c->id;
        comp.wr_id = it->second.wr_id;
        comp.payload = p;
        comp.payload_len = len;
        n->post(comp);
        c->reads.erase(it);
      }
      break;
    }
    case RxState::HELLO_BODY: {
      void* p = malloc(len ? len : 1);
      memcpy(p, data, len);
      Completion comp{};
      comp.kind = COMP_ACCEPT;
      comp.channel = c->id;
      comp.aux = load_be32(c->hdr);
      comp.payload = p;
      comp.payload_len = len;
      n->post(comp);
      c->hello_done = true;
      break;
    }
    default:
      break;
  }
}

bool fd_peer_is_loopback(int fd) {
  sockaddr_in a{};
  socklen_t l = sizeof a;
  if (getpeername(fd, (sockaddr*)&a, &l) != 0) return false;
  return a.sin_family == AF_INET &&
         (ntohl(a.sin_addr.s_addr) >> 24) == 127;
}

void loop_main(Node* n) {
  epoll_event evs[64];
  std::vector<uint8_t> bufv(1 << 18);  // per-loop staging for headers/RPC
  uint8_t* buf = bufv.data();
  const size_t buf_sz = bufv.size();
  while (true) {
    for (Conn* dead : n->graveyard) delete dead;
    n->graveyard.clear();
    int k = epoll_wait(n->epfd, evs, 64, 100);
    if (k < 0) {
      if (errno == EINTR) continue;
      break;
    }
    for (int i = 0; i < k; i++) {
      void* tag = evs[i].data.ptr;
      if (tag == &n->evfd) {
        uint64_t junk;
        ssize_t r = read(n->evfd, &junk, sizeof(junk));
        (void)r;
        // drain commands
        while (true) {
          Command cmd;
          {
            std::lock_guard<std::mutex> g(n->cmd_mu);
            if (n->cmds.empty()) break;
            cmd = std::move(n->cmds.front());
            n->cmds.pop_front();
          }
          if (cmd.kind == Command::STOP) {
            // fail every live conn FIRST: this releases all zero-copy
            // pins (unblocking any dereg waiter safely) and fails all
            // outstanding reads/sends before the loop dies
            std::vector<Conn*> live;
            {
              std::lock_guard<std::mutex> g(n->conn_mu);
              for (auto& kv : n->conns) live.push_back(kv.second);
            }
            for (Conn* v : live) fail_conn(n, v);
            // parked file-pending reads complete as errors
            for (auto& kv : n->file_pending) {
              Completion comp{};
              comp.kind = COMP_READ_DONE;
              comp.status = ST_ERR;
              comp.channel = kv.first.first;
              comp.wr_id = kv.second.wr_id;
              n->post(comp);
            }
            n->file_pending.clear();
            // fail_conn pushed every conn into the graveyard; the
            // normal top-of-loop sweep will never run again, so free
            // them here (srt_node_stop only frees what's in n->conns)
            for (Conn* dead : n->graveyard) delete dead;
            n->graveyard.clear();
            return;
          }
          Conn* c = nullptr;
          {
            std::lock_guard<std::mutex> g(n->conn_mu);
            auto it = n->conns.find(cmd.channel);
            if (it != n->conns.end()) c = it->second;
          }
          if (cmd.kind == Command::ADD_CONN && c) {
            c->peer_loopback = fd_peer_is_loopback(c->fd);
            epoll_event ev{};
            ev.events = EPOLLIN;
            ev.data.ptr = c;
            epoll_ctl(n->epfd, EPOLL_CTL_ADD, c->fd, &ev);
          } else if (cmd.kind == Command::SEND && c) {
            queue_out(n, c, std::move(cmd.data), cmd.wr_id, cmd.last_of_wr);
            if (!c->down) flush_out(n, c);
          } else if (cmd.kind == Command::SEND && !c) {
            if (cmd.wr_id && cmd.last_of_wr) {
              Completion comp{};
              comp.kind = COMP_SEND_DONE;
              comp.status = ST_ERR;
              comp.channel = cmd.channel;
              comp.wr_id = cmd.wr_id;
              n->post(comp);
            }
          } else if (cmd.kind == Command::READ) {
            if (!c || c->down) {
              Completion comp{};
              comp.kind = COMP_READ_DONE;
              comp.status = ST_ERR;
              comp.channel = cmd.channel;
              comp.wr_id = cmd.wr_id;
              n->post(comp);
            } else {
              PendingRead pr;
              pr.wr_id = cmd.wr_id;
              pr.dst = cmd.dst;
              pr.expected = cmd.expected;
              pr.blocks = cmd.blocks;
              pr.mapped = cmd.mapped;
              c->reads.emplace(cmd.req_id, std::move(pr));
              // first try the same-host file path unless this channel
              // already proved the peer's files unreachable (or the
              // node knob forces streaming; mapped reads always probe)
              send_read_frame(n, c, cmd.req_id, cmd.blocks,
                              c->files_ok != 0 &&
                                  (cmd.mapped ||
                                   n->file_fastpath.load() != 0));
            }
          } else if (cmd.kind == Command::CLOSE_CONN && c) {
            // flush what we can, then drop
            if (!c->down) flush_out(n, c);
            fail_conn(n, c);
          } else if (cmd.kind == Command::EVICT_MKEY) {
            // a dereg timed out on this mkey's pins: kill every conn
            // still holding queued zero-copy sends from it (fail_conn
            // unpins), so the blocked dereg can complete safely
            uint32_t mk = (uint32_t)cmd.req_id;
            std::vector<Conn*> victims;
            {
              std::lock_guard<std::mutex> g(n->conn_mu);
              for (auto& kv : n->conns) {
                for (auto& ob : kv.second->outq) {
                  if (ob.ext && ob.pin_mkey == mk) {
                    victims.push_back(kv.second);
                    break;
                  }
                }
              }
            }
            for (Conn* v : victims) fail_conn(n, v);
          } else if (cmd.kind == Command::FILE_DONE ||
                     cmd.kind == Command::FILE_FALLBACK) {
            auto key = std::make_pair(cmd.channel, cmd.req_id);
            auto fit = n->file_pending.find(key);
            if (fit == n->file_pending.end()) {
              // the pending read is gone (STOP already errored it):
              // a mapped FILE_DONE still carries live mmap records
              if (cmd.kind == Command::FILE_DONE && !cmd.data.empty())
                unmap_mapped_records(cmd.data.data(), cmd.data.size());
            } else {
              PendingRead pr = std::move(fit->second);
              n->file_pending.erase(fit);
              if (cmd.kind == Command::FILE_DONE) {
                n->stat_file_reads++;
                Completion comp{};
                comp.kind = COMP_READ_DONE;
                comp.status = ST_OK;
                comp.channel = cmd.channel;
                comp.wr_id = pr.wr_id;
                if (pr.mapped) {
                  // aux=1: payload is n x 32B mmap records; receiver
                  // owns the mappings (srt_unmap) and the record blob
                  comp.aux = 1;
                  comp.payload_len = cmd.data.size();
                  if (!cmd.data.empty()) {
                    comp.payload = malloc(cmd.data.size());
                    memcpy(comp.payload, cmd.data.data(), cmd.data.size());
                  }
                }
                n->post(comp);
              } else if (c && !c->down) {
                // transient file failure: stream THIS read; the conn's
                // files_ok latch is untouched (only a host-proof miss
                // disables the fast path permanently)
                c->reads.emplace(cmd.req_id, std::move(pr));
                auto rit = c->reads.find(cmd.req_id);
                send_read_frame(n, c, cmd.req_id, rit->second.blocks, false);
              } else {
                Completion comp{};
                comp.kind = COMP_READ_DONE;
                comp.status = ST_ERR;
                comp.channel = cmd.channel;
                comp.wr_id = pr.wr_id;
                n->post(comp);
              }
            }
          }
        }
        continue;
      }
      if (tag == &n->listen_fd) {
        while (true) {
          int fd = accept4(n->listen_fd, nullptr, nullptr, SOCK_NONBLOCK);
          if (fd < 0) break;
          tune_socket(fd);
          Conn* c = new Conn();
          c->fd = fd;
          c->peer_loopback = fd_peer_is_loopback(fd);
          {
            std::lock_guard<std::mutex> g(n->conn_mu);
            c->id = n->next_conn++;
            n->conns[c->id] = c;
          }
          epoll_event ev{};
          ev.events = EPOLLIN;
          ev.data.ptr = c;
          epoll_ctl(n->epfd, EPOLL_CTL_ADD, fd, &ev);
        }
        continue;
      }
      Conn* c = (Conn*)tag;
      if (c->down) continue;
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
        fail_conn(n, c);
        continue;
      }
      if (evs[i].events & EPOLLOUT) flush_out(n, c);
      if (c->down) continue;
      if (evs[i].events & EPOLLIN) {
        while (true) {
          // mid-READ-payload: receive straight into the caller's
          // destination buffer — one kernel->user copy, no staging
          if (c->st == RxState::READR_BODY && c->cur_read) {
            PendingRead* pr = c->cur_read;
            size_t want = (size_t)(pr->expected - pr->received);
            ssize_t r = recv(c->fd, pr->dst + pr->received, want, 0);
            if (r > 0) {
              pr->received += (uint64_t)r;
              if (pr->received == pr->expected) {
                n->stat_streamed_reads++;
                Completion comp{};
                comp.kind = COMP_READ_DONE;
                comp.status = ST_OK;
                comp.channel = c->id;
                comp.wr_id = pr->wr_id;
                if (pr->owned) {
                  // mapped request, streamed answer (same hand-off as
                  // the ingest-path completion below): blob ownership
                  // passes to the completion payload
                  comp.payload = pr->owned;
                  comp.payload_len = pr->expected;
                  comp.aux = 0;
                  pr->owned = nullptr;
                }
                n->post(comp);
                c->reads.erase(c->cur_req);
                c->cur_read = nullptr;
                c->st = RxState::OP;
              }
              continue;
            } else if (r == 0) {
              fail_conn(n, c);
              break;
            } else if (errno == EAGAIN || errno == EWOULDBLOCK) {
              break;
            } else {
              fail_conn(n, c);
              break;
            }
          }
          ssize_t r = recv(c->fd, buf, buf_sz, 0);
          if (r > 0) {
            size_t used = 0;
            while (used < (size_t)r && !c->down)
              used += ingest(n, c, buf + used, (size_t)r - used);
            if (c->down) break;
          } else if (r == 0) {
            fail_conn(n, c);
            break;
          } else if (errno == EAGAIN || errno == EWOULDBLOCK) {
            break;
          } else {
            fail_conn(n, c);
            break;
          }
        }
      }
    }
  }
}

}  // namespace

extern "C" {

struct srt_comp_c {
  uint32_t kind;
  uint32_t status;
  uint64_t channel;
  uint64_t wr_id;
  void* payload;
  uint64_t payload_len;
  uint32_t aux;
  uint32_t _pad;
};

void* srt_node_create(const char* host, uint16_t base_port, int max_retries) {
  // A peer dying mid-transfer turns the next write() into SIGPIPE,
  // which would kill the whole process instead of surfacing EPIPE to
  // the channel's failure path. Ignore it process-wide so broken pipes
  // degrade to ordinary send errors the retry ladder can handle.
  signal(SIGPIPE, SIG_IGN);
  Node* n = new Node();
  n->epfd = epoll_create1(0);
  n->evfd = eventfd(0, EFD_NONBLOCK);
  // bind with port retries (RdmaNode.java:75-97)
  for (int attempt = 0; attempt < max_retries; attempt++) {
    uint16_t port = base_port == 0 ? 0 : base_port + attempt;
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port);
    inet_pton(AF_INET, host, &addr.sin_addr);
    if (bind(fd, (sockaddr*)&addr, sizeof(addr)) == 0 && listen(fd, 128) == 0) {
      set_nonblock(fd);
      n->listen_fd = fd;
      socklen_t alen = sizeof(addr);
      getsockname(fd, (sockaddr*)&addr, &alen);
      n->port = ntohs(addr.sin_port);
      break;
    }
    close(fd);
  }
  if (n->listen_fd < 0) {
    close(n->epfd);
    close(n->evfd);
    delete n;
    return nullptr;
  }
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.ptr = &n->listen_fd;
  epoll_ctl(n->epfd, EPOLL_CTL_ADD, n->listen_fd, &ev);
  ev.events = EPOLLIN;
  ev.data.ptr = &n->evfd;
  epoll_ctl(n->epfd, EPOLL_CTL_ADD, n->evfd, &ev);
  // host-identity proof for the same-host file fast path (see the
  // READ_FILE wire comment): 128 random bits from /dev/urandom. The
  // pid in the name lets the Python-side sweeper reclaim proofs of
  // crashed processes (atexit never runs on SIGKILL/OOM).
  {
    uint8_t rnd[16];
    int ufd = open("/dev/urandom", O_RDONLY);
    if (ufd >= 0 && read(ufd, rnd, sizeof(rnd)) == (ssize_t)sizeof(rnd)) {
      char name[96];
      size_t pos = 0;
      pos += snprintf(name, sizeof(name), "/dev/shm/srt-host-%d-",
                      (int)getpid());
      for (int i = 0; i < 16; i++)
        pos += snprintf(name + pos, sizeof(name) - pos, "%02x", rnd[i]);
      int pfd = open(name, O_CREAT | O_EXCL | O_WRONLY, 0644);
      if (pfd >= 0) {
        close(pfd);
        n->host_proof = name;
      }
    }
    if (ufd >= 0) close(ufd);
  }
  n->loop = std::thread(loop_main, n);
  {
    std::lock_guard<std::mutex> g(n->fw_mu);
    n->file_workers.emplace_back(file_worker_main, n);
    n->file_worker_count.store(1, std::memory_order_release);
  }
  return n;
}

uint16_t srt_node_port(void* np) { return ((Node*)np)->port; }

// -- region registry (ProtectionDomain) ---------------------------------
uint32_t srt_reg(void* np, const void* ptr, uint64_t len) {
  Node* n = (Node*)np;
  std::lock_guard<std::mutex> g(n->reg_mu);
  uint32_t mkey = n->next_mkey++;
  Node::Region r;
  r.ptr = (const uint8_t*)ptr;
  r.len = len;
  n->regions[mkey] = r;
  // plain registrations are caller-writable memory: fixed-buffer
  // candidates for io_uring rings created after this point
  n->plane.add_segment(ptr, len);
  return mkey;
}

// register a region whose bytes are identical to [file_off, file_off+len)
// of the file at `path` (an shm slab or a mapped shuffle file): same-host
// peers may pread it directly instead of streaming through the socket.
// The caller supplies the backing file's identity from fstat of the SAME
// fd that backs the mapping — never from a fresh stat(path), which would
// race a concurrent rewrite of the path (identity would describe the new
// file while the region memory holds the old bytes). size=0 && mtime_ns=0
// declares a MUTABLE backing (shm slab: the file pages ARE the region) —
// identity is then dev/ino only (see READ_FILE wire doc). dev==0 &&
// ino==0 means "no identity": registered as a plain streamed region.
uint32_t srt_reg_file(void* np, const void* ptr, uint64_t len,
                      const char* path, uint64_t file_off,
                      uint64_t dev, uint64_t ino,
                      uint64_t size, uint64_t mtime_ns) {
  Node* n = (Node*)np;
  std::lock_guard<std::mutex> g(n->reg_mu);
  uint32_t mkey = n->next_mkey++;
  Node::Region r;
  r.ptr = (const uint8_t*)ptr;
  r.len = len;
  r.path = path ? path : "";
  r.file_off = file_off;
  r.file_backed = path && path[0] && (dev || ino);
  if (r.file_backed) {
    r.file_dev = dev;
    r.file_ino = ino;
    r.file_size = size;
    r.file_mtime_ns = mtime_ns;
  }
  n->regions[mkey] = r;
  // only MUTABLE backings (shm slabs: the mempool's segments, mapped
  // read-write) are fixed-buffer candidates — immutable spill-file
  // registrations are typically read-only mappings, and one unwritable
  // iovec fails the whole IORING_REGISTER_BUFFERS call
  if (!r.file_backed || (size == 0 && mtime_ns == 0))
    n->plane.add_segment(ptr, len);
  return mkey;
}

int srt_dereg(void* np, uint32_t mkey) {
  Node* n = (Node*)np;
  std::unique_lock<std::mutex> lk(n->reg_mu);
  auto it = n->regions.find(mkey);
  if (it == n->regions.end()) return -1;
  // drop the fixed-buffer candidate NOW (the caller intends to free
  // the memory): bumping seg_dead_gen stops every ring built on an
  // older snapshot from submitting READ_FIXED against it
  n->plane.remove_segment(it->second.ptr);
  if (it->second.pins == 0) {
    n->regions.erase(it);
    return 0;
  }
  // Zero-copy sends are in flight from this memory: block until the
  // loop thread flushes them (caller may free the memory on return —
  // the verbs ibv_dereg_mr contract). A peer that stops draining its
  // socket could hold the pin forever, so after a grace period the
  // offending connections are killed (the QP-error analogue), which
  // releases the pins. Never erase while pinned — that would let the
  // caller unmap memory the loop is still send()ing from.
  it->second.dereg_wanted = true;
  auto gone = [&] { return n->regions.find(mkey) == n->regions.end(); };
  // NOTE: `stopping` is deliberately NOT a wake-to-erase condition —
  // between the flag being set and the loop thread processing STOP,
  // queued zero-copy sends can still flush from this memory. Progress
  // is guaranteed instead: a live loop either drains the pins, or the
  // EVICT below kills the holding conns (unpinning), or STOP's
  // fail-all-conns unpins; each path erases the region and notifies.
  if (!cv_wait_ms(n->reg_cv, lk, 5000, gone)) {
    lk.unlock();
    Command cmd;
    cmd.kind = Command::EVICT_MKEY;
    cmd.req_id = mkey;  // reuse the field; EVICT has no req semantics
    n->enqueue(std::move(cmd));
    lk.lock();
  }
  if (!cv_wait_ms(n->reg_cv, lk, 30000, gone)) {
    // loop thread dead or wedged: leak the region entry rather than
    // risk a use-after-free. dereg_wanted stays set, so no future
    // serve can resolve this mkey.
    return -1;
  }
  return 0;
}

// client-side read-path counters (tests + bench): READs completed via
// the same-host pread fast path vs the streamed socket path
uint64_t srt_stat_file_reads(void* np) {
  return ((Node*)np)->stat_file_reads.load();
}

uint64_t srt_stat_streamed_reads(void* np) {
  return ((Node*)np)->stat_streamed_reads.load();
}

uint64_t srt_stat_block_stripes(void* np) {
  return ((Node*)np)->stat_block_stripes.load();
}
uint64_t srt_stat_split_parts(void* np) {
  return ((Node*)np)->stat_split_parts.load();
}

// -- submission plane ---------------------------------------------------
// read backend knob (tpu.shuffle.native.readBackend): 0 auto (io_uring
// when the kernel has it, else pread), 1 io_uring (degrades to pread
// when unavailable), 2 pread/preadv2, 3 mapped-copy
void srt_set_read_backend(void* np, int b) {
  if (b < RB_AUTO || b > RB_MAPPED) b = RB_AUTO;
  ((Node*)np)->plane.backend.store(b);
}

// 1 when the library was built with io_uring support compiled in
int srt_uring_compiled(void) {
#ifdef SRT_HAVE_IOURING
  return 1;
#else
  return 0;
#endif
}

// the backend buffer-destination reads will actually use right now:
// resolves `auto` and runs the availability probe (1 io_uring, 2
// pread, 3 mapped-copy). The CI no-liburing matrix leg asserts this
// reports the pread fallback.
int srt_read_backend_effective(void* np) {
  Node* n = (Node*)np;
  int want = n->plane.backend.load();
  if (want == RB_PREAD || want == RB_MAPPED) return want;
  return plane_uring_probe(n->plane) ? RB_IOURING : RB_PREAD;
}

// test seam (read:enosys fault kind): make the availability probe
// behave exactly like an ENOSYS kernel. Clearing it un-latches the
// forced state so auto detection can run again.
void srt_sq_force_probe_fail(void* np, int on) {
  Node* n = (Node*)np;
  n->plane.force_probe_fail.store(on ? 1 : 0);
  if (!on) {
    int st = -2;
    n->plane.uring_state.compare_exchange_strong(st, 0);
  }
}

// submission-queue accounting (transport.sq.* metric families)
uint64_t srt_stat_sq_submits(void* np) {
  return ((Node*)np)->plane.sq_submits.load();
}
uint64_t srt_stat_sq_batches(void* np) {
  return ((Node*)np)->plane.sq_batches.load();
}
uint64_t srt_stat_sq_depth_hwm(void* np) {
  return ((Node*)np)->plane.sq_depth_hwm.load();
}
uint64_t srt_stat_sq_completions(void* np) {
  return ((Node*)np)->plane.sq_completions.load();
}
uint64_t srt_stat_sq_backend_fallbacks(void* np) {
  return ((Node*)np)->plane.sq_backend_fallbacks.load();
}

uint64_t srt_region_count(void* np) {
  Node* n = (Node*)np;
  std::lock_guard<std::mutex> g(n->reg_mu);
  return n->regions.size();
}

// -- channels -----------------------------------------------------------
// connect + send the HELLO preamble; blocking in the caller's thread
// (the connect retry/timeout policy lives in the host language, like
// RdmaNode.getRdmaChannel's retry loop)
// kind: 0 = RPC, 1 = DATA (rides the high byte of the hello port word,
// mirroring wire.py pack_hello — reference channel roles,
// RdmaChannel.java:110-154)
uint64_t srt_connect(void* np, const char* host, uint16_t port,
                     uint16_t my_port, const char* my_id, int timeout_ms,
                     int kind) {
  Node* n = (Node*)np;
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    hostent* he = gethostbyname(host);
    if (!he) { close(fd); return 0; }
    memcpy(&addr.sin_addr, he->h_addr, he->h_length);
  }
  timeval tv{timeout_ms / 1000, (timeout_ms % 1000) * 1000};
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  if (connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    close(fd);
    return 0;
  }
  size_t idlen = strlen(my_id);
  std::vector<uint8_t> hello(1 + 4 + 2 + idlen);
  hello[0] = OP_HELLO;
  // kind arrives pre-composed from Python as (kind << 8) | index; the
  // shift lands kind in hello-word byte 3 and the striping index in
  // byte 2 (wire.split_hello_word layout)
  store_be32(&hello[1], ((uint32_t)(kind & 0xffff) << 16) | (my_port & 0xffff));
  hello[5] = idlen >> 8;
  hello[6] = idlen & 0xff;
  memcpy(&hello[7], my_id, idlen);
  size_t off = 0;
  while (off < hello.size()) {
    ssize_t w = send(fd, hello.data() + off, hello.size() - off, MSG_NOSIGNAL);
    if (w <= 0) { close(fd); return 0; }
    off += (size_t)w;
  }
  tune_socket(fd);
  set_nonblock(fd);
  Conn* c = new Conn();
  c->fd = fd;
  c->outbound = true;
  uint64_t id;
  {
    std::lock_guard<std::mutex> g(n->conn_mu);
    id = n->next_conn++;
    c->id = id;
    n->conns[id] = c;
  }
  Command cmd;
  cmd.kind = Command::ADD_CONN;
  cmd.channel = id;
  n->enqueue(std::move(cmd));
  return id;
}

// post one SEND frame; when wr_id != 0 and last != 0, a SEND_DONE
// completion fires once the bytes hit the socket
int srt_post_send(void* np, uint64_t channel, const void* data, uint64_t len,
                  uint64_t wr_id, int last) {
  Node* n = (Node*)np;
  std::vector<uint8_t> frame(1 + 4 + len);
  frame[0] = OP_SEND;
  store_be32(&frame[1], (uint32_t)len);
  memcpy(&frame[5], data, len);
  Command cmd;
  cmd.kind = Command::SEND;
  cmd.channel = channel;
  cmd.data = std::move(frame);
  cmd.wr_id = wr_id;
  cmd.last_of_wr = last != 0;
  n->enqueue(std::move(cmd));
  return 0;
}

// one process-wide READ request-id source shared by both post paths
// (ids must be unique per connection; two counters could collide)
std::atomic<uint64_t> g_next_req{1};

// post a one-sided READ of n_blocks remote (mkey, addr, len) triples;
// bytes stream straight into dst; READ_DONE(wr_id) on completion
int srt_post_read(void* np, uint64_t channel, uint64_t wr_id, void* dst,
                  const uint64_t* blocks, uint32_t n_blocks) {
  Node* n = (Node*)np;
  uint64_t total = 0;
  std::vector<std::array<uint64_t, 3>> blks(n_blocks);
  for (uint32_t i = 0; i < n_blocks; i++) {
    blks[i] = {blocks[i * 3], blocks[i * 3 + 1], blocks[i * 3 + 2]};
    total += blocks[i * 3 + 2];
  }
  uint64_t req_id = g_next_req.fetch_add(1);
  Command cmd;
  cmd.kind = Command::READ;
  cmd.channel = channel;
  cmd.wr_id = wr_id;
  cmd.req_id = req_id;
  cmd.dst = (uint8_t*)dst;
  cmd.expected = total;
  cmd.blocks = std::move(blks);
  n->enqueue(std::move(cmd));
  return 0;
}

// post a one-sided READ with MAPPED delivery: no destination buffer.
// Same-host file-backed blocks complete with aux=1 and a payload of
// n x 32B host-endian records [user_ptr, len, map_base, map_len] — the
// caller reads the bytes in place (zero copies end to end) and MUST
// srt_unmap(map_base, map_len) each record, then srt_free_payload the
// record blob. A streamed answer (remote peer / unbacked region)
// completes with aux=0 and a malloc'd contiguous payload the caller
// frees with srt_free_payload. Mappings outstanding at process exit
// are reclaimed by the OS.
int srt_post_read_mapped(void* np, uint64_t channel, uint64_t wr_id,
                         const uint64_t* blocks, uint32_t n_blocks) {
  Node* n = (Node*)np;
  uint64_t total = 0;
  std::vector<std::array<uint64_t, 3>> blks(n_blocks);
  for (uint32_t i = 0; i < n_blocks; i++) {
    blks[i] = {blocks[i * 3], blocks[i * 3 + 1], blocks[i * 3 + 2]};
    total += blocks[i * 3 + 2];
  }
  uint64_t req_id = g_next_req.fetch_add(1);
  Command cmd;
  cmd.kind = Command::READ;
  cmd.channel = channel;
  cmd.wr_id = wr_id;
  cmd.req_id = req_id;
  cmd.dst = nullptr;
  cmd.expected = total;
  cmd.blocks = std::move(blks);
  cmd.mapped = true;
  n->enqueue(std::move(cmd));
  return 0;
}

void srt_unmap(void* base, uint64_t len) { munmap(base, (size_t)len); }

// 0 forces plain READ_REQ (streamed) for non-mapped reads — bench /
// remote-path-simulation knob; 1 restores the default REQ2 probe
void srt_set_file_fastpath(void* np, int on) {
  ((Node*)np)->file_fastpath.store(on);
}

// serve file-backed regions via sendfile even to loopback peers
// (tests/benches; loopback normally keeps the faster userspace send)
void srt_set_force_sendfile(void* np, int on) {
  ((Node*)np)->force_sendfile.store(on);
}

// grow the file-worker pool to k threads (never shrinks; clamped to
// [1, 16]). Concurrent read groups then overlap their page-cache
// copies — the QP-striping analogue (see Node::file_workers).
void srt_set_file_workers(void* np, int k) {
  Node* n = (Node*)np;
  if (k < 1) k = 1;
  if (k > 16) k = 16;
  // the vector mutates only under fw_mu; the loop thread reads the
  // atomic count (published after each thread is live), so growing
  // after traffic has started is safe
  std::lock_guard<std::mutex> g(n->fw_mu);
  while ((int)n->file_workers.size() < k && !n->stopping.load()) {
    n->file_workers.emplace_back(file_worker_main, n);
    n->file_worker_count.store(n->file_workers.size(),
                               std::memory_order_release);
  }
}

int srt_close_channel(void* np, uint64_t channel) {
  Node* n = (Node*)np;
  Command cmd;
  cmd.kind = Command::CLOSE_CONN;
  cmd.channel = channel;
  n->enqueue(std::move(cmd));
  return 0;
}

// -- completion queue ---------------------------------------------------
int srt_poll_cq(void* np, srt_comp_c* out, int max, int timeout_ms) {
  Node* n = (Node*)np;
  std::unique_lock<std::mutex> lk(n->cq_mu);
  if (n->cq.empty()) {
    cv_wait_ms(n->cq_cv, lk, timeout_ms, [&] { return !n->cq.empty(); });
  }
  int k = 0;
  while (k < max && !n->cq.empty()) {
    Completion c = n->cq.front();
    n->cq.pop_front();
    out[k].kind = c.kind;
    out[k].status = c.status;
    out[k].channel = c.channel;
    out[k].wr_id = c.wr_id;
    out[k].payload = c.payload;
    out[k].payload_len = c.payload_len;
    out[k].aux = c.aux;
    k++;
  }
  return k;
}

void srt_free_payload(void* p) { free(p); }

void srt_node_stop(void* np) {
  Node* n = (Node*)np;
  bool was = n->stopping.exchange(true);
  if (was) return;
  n->reg_cv.notify_all();  // release any dereg waiting on pinned sends
  if (!n->host_proof.empty()) unlink(n->host_proof.c_str());
  Command cmd;
  cmd.kind = Command::STOP;
  n->enqueue(std::move(cmd));
  n->loop.join();
  // the worker drains queued tasks (their destination buffers stay
  // alive until this function returns), then exits on `stopping`
  n->ft_cv.notify_all();
  {
    std::lock_guard<std::mutex> g(n->fw_mu);
    for (auto& w : n->file_workers)
      if (w.joinable()) w.join();
  }
  // commands queued behind STOP (or enqueued by workers finishing
  // after the loop exited) are never drained by the loop; a mapped
  // FILE_DONE among them still owns its page-cache mmaps
  {
    std::lock_guard<std::mutex> g(n->cmd_mu);
    for (auto& cmd : n->cmds)
      if (cmd.kind == Command::FILE_DONE && !cmd.data.empty())
        unmap_mapped_records(cmd.data.data(), cmd.data.size());
    n->cmds.clear();
  }
  close(n->listen_fd);
  {
    std::lock_guard<std::mutex> g(n->conn_mu);
    for (auto& kv : n->conns) {
      if (kv.second->fd >= 0) close(kv.second->fd);
      delete kv.second;
    }
    n->conns.clear();
  }
  close(n->epfd);
  close(n->evfd);
  {
    std::lock_guard<std::mutex> g(n->cq_mu);
    for (auto& c : n->cq) {
      if (c.payload) {
        // an undelivered mapped completion (aux=1) owns the mappings
        // its records describe, not just the record blob
        if (c.aux == 1) unmap_mapped_records(c.payload, c.payload_len);
        free(c.payload);
      }
    }
    n->cq.clear();
  }
  delete n;
}

}  // extern "C"
