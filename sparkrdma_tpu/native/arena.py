"""ctypes binding for the native off-heap arena (arena.cpp).

Builds the shared library on first use with g++ (cached next to the
source). If the toolchain is unavailable the caller falls back to
anonymous ``mmap`` allocations (sparkrdma_tpu.memory.buffer) — same
semantics, same page alignment, slightly slower alloc path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "arena.cpp")

# same SPARKRDMA_NATIVE_SANITIZE contract as transport_lib.py: build a
# sanitizer-instrumented .so under its own cache name
from sparkrdma_tpu.native.transport_lib import _SANITIZE, _build_flags  # noqa: E402

_SO = os.path.join(
    _HERE,
    "_libsrt_arena.%s.so" % _SANITIZE.replace(",", "-").replace("=", "_")
    if _SANITIZE
    else "_libsrt_arena.so",
)

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            if not os.path.exists(_SO) or (
                os.path.exists(_SRC) and os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            ):
                subprocess.run(
                    ["g++", *_build_flags(), "-o", _SO, _SRC],
                    check=True,
                    capture_output=True,
                )
            lib = ctypes.CDLL(_SO)
        except (OSError, subprocess.CalledProcessError):
            _build_failed = True
            return None
        lib.srt_arena_create.restype = ctypes.c_void_p
        lib.srt_arena_destroy.argtypes = [ctypes.c_void_p]
        lib.srt_alloc.restype = ctypes.c_uint64
        lib.srt_alloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.srt_addr.restype = ctypes.c_void_p
        lib.srt_addr.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.srt_size.restype = ctypes.c_uint64
        lib.srt_size.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.srt_free.restype = ctypes.c_int
        lib.srt_free.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.srt_copy.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64]
        lib.srt_arena_stats.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        _lib = lib
    return _lib


def native_arena_available() -> bool:
    return _load() is not None


class NativeArena:
    """One native arena; usually the process-wide shared instance."""

    _shared: Optional["NativeArena"] = None
    _shared_lock = threading.Lock()

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError("native arena unavailable (g++ build failed)")
        self._lib = lib
        self._arena = ctypes.c_void_p(lib.srt_arena_create())

    @classmethod
    def shared(cls) -> "NativeArena":
        with cls._shared_lock:
            if cls._shared is None:
                cls._shared = cls()
            return cls._shared

    def alloc(self, size: int) -> Tuple[int, memoryview]:
        alloc_id = self._lib.srt_alloc(self._arena, size)
        if alloc_id == 0:
            raise MemoryError(f"native arena failed to allocate {size} bytes")
        addr = self._lib.srt_addr(self._arena, alloc_id)
        buf = (ctypes.c_char * size).from_address(addr)
        return alloc_id, memoryview(buf).cast("B")

    def free(self, alloc_id: int) -> None:
        self._lib.srt_free(self._arena, alloc_id)

    def stats(self) -> Tuple[int, int, int]:
        """(total_allocs, live_bytes, live_count)."""
        t = ctypes.c_uint64()
        b = ctypes.c_uint64()
        c = ctypes.c_uint64()
        self._lib.srt_arena_stats(self._arena, ctypes.byref(t), ctypes.byref(b), ctypes.byref(c))
        return t.value, b.value, c.value
