"""Test harness: force an 8-device virtual CPU mesh before jax imports.

Mirrors the reference's missing-but-implied multi-node-without-a-cluster
strategy (SURVEY.md §4): all sharding/collective tests run on
``--xla_force_host_platform_device_count=8`` CPU devices so CI needs no
TPU slice.

On-chip subset: ``SRT_TPU_TESTS=1 python -m pytest tests -m tpu -q``
skips the CPU pin so the ``tpu``-marked tests (tests/test_on_chip.py)
run against the REAL platform — closing the gap between "tests green
on the CPU farm" and "works on hardware" without dragging the whole
suite through the chip tunnel.
"""

import os

if os.environ.get("SRT_TPU_TESTS"):
    # real platform (TPU via the axon plugin); only `-m tpu` tests
    # should be selected in this mode
    import jax  # noqa: F401
else:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    # The axon TPU plugin (this image's tunnel to the real chip) overrides
    # JAX_PLATFORMS at import time; pin the platform via jax.config too so
    # CI sharding tests always see the 8 virtual CPU devices.
    import jax  # noqa: E402

    jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "tpu: on-chip test (run with SRT_TPU_TESTS=1 python -m pytest -m tpu)",
    )
    config.addinivalue_line(
        "markers",
        "faults: fault-injection test (exercises the resilience retry "
        "ladder via sparkrdma_tpu.testing.faults or transport seams)",
    )
    # race harness: SPARKRDMA_LOCK_ORDER=1 arms the lock-order detector
    # for the whole session (sparkrdma_tpu/analysis/lockorder.py) and
    # fails it on acquisition-order cycles or blocking calls under
    # hot-path locks; unset, the plugin is inert
    if not config.pluginmanager.has_plugin("sparkrdma-lockorder"):
        from sparkrdma_tpu.analysis import pytest_plugin

        config.pluginmanager.register(pytest_plugin, "sparkrdma-lockorder")
