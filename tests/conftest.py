"""Test harness: force an 8-device virtual CPU mesh before jax imports.

Mirrors the reference's missing-but-implied multi-node-without-a-cluster
strategy (SURVEY.md §4): all sharding/collective tests run on
``--xla_force_host_platform_device_count=8`` CPU devices so CI needs no
TPU slice.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon TPU plugin (this image's tunnel to the real chip) overrides
# JAX_PLATFORMS at import time; pin the platform via jax.config too so
# CI sharding tests always see the 8 virtual CPU devices.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
