"""Partition-location-table correctness under chunked mapping — the
property the reference implies but never checks (SURVEY.md §4:
RdmaMappedFile.java:165-209)."""

import os

import pytest

from sparkrdma_tpu.memory import MappedFile, ProtectionDomain


def _write_file(tmp_path, partition_lengths):
    path = str(tmp_path / "shuffle_0_0.data")
    payload = b"".join(
        bytes([i % 251]) * n for i, n in enumerate(partition_lengths)
    )
    with open(path, "wb") as f:
        f.write(payload)
    return path, payload


def test_chunked_mapping_locations(tmp_path):
    lengths = [5000, 0, 12000, 300, 70000, 1, 0, 9999]
    path, payload = _write_file(tmp_path, lengths)
    pd = ProtectionDomain()
    mf = MappedFile(path, pd, block_size=16384, partition_lengths=lengths)
    off = 0
    for pid, n in enumerate(lengths):
        loc = mf.get_partition_location(pid)
        assert loc.length == n
        if n:
            # one-sided READ through the PD returns exactly the partition bytes
            got = bytes(pd.resolve(loc.mkey, loc.address, loc.length))
            assert got == payload[off : off + n]
            # local short-circuit view agrees
            assert bytes(mf.get_partition_view(pid)) == got
        off += n
    assert pd.region_count() >= 2  # multiple chunks were registered
    mf.dispose()
    assert pd.region_count() == 0
    assert not os.path.exists(path)


def test_single_chunk_small_file(tmp_path):
    lengths = [10, 20, 30]
    path, payload = _write_file(tmp_path, lengths)
    pd = ProtectionDomain()
    mf = MappedFile(path, pd, block_size=8 << 20, partition_lengths=lengths)
    assert pd.region_count() == 1
    loc = mf.get_partition_location(2)
    assert bytes(pd.resolve(loc.mkey, loc.address, loc.length)) == payload[30:60]
    mf.dispose()


def test_length_mismatch_rejected(tmp_path):
    path, _ = _write_file(tmp_path, [100])
    pd = ProtectionDomain()
    with pytest.raises(ValueError):
        MappedFile(path, pd, block_size=4096, partition_lengths=[99])
    os.unlink(path)
