"""dp x sp x tp train step vs a single-device reference of the same math."""

import numpy as np
import pytest

import jax.numpy as jnp

from sparkrdma_tpu.models.transformer_step import (
    TransformerStep,
    init_params,
    make_training_mesh,
    reference_step,
)


def _data(b=4, s=16, d=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(b, s, d)).astype(np.float32)
    y = rng.normal(size=(b, s, d)).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


def test_mesh_shape_is_dp_sp_tp():
    mesh = make_training_mesh()
    assert mesh.axis_names == ("dp", "sp", "tp")
    assert mesh.devices.size == 8


def test_sharded_step_matches_reference():
    mesh = make_training_mesh()
    tp = mesh.shape["tp"]
    params = init_params(16, n_heads=4, d_hidden=32, tp=tp)
    x, y = _data()
    step = TransformerStep(mesh, n_heads=4, lr=0.1)
    pl, xl, yl = step.place(params, x, y)
    loss, new = step.step(pl, xl, yl)

    ref_loss, ref_new = reference_step(
        {k: jnp.asarray(v) for k, v in params.items()}, x, y, n_heads=4, lr=0.1
    )
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
    # exact math on a CPU f32 mesh: the sharded backward must agree with
    # the single-device reference to float rounding, not just "roughly"
    for k in params:
        np.testing.assert_allclose(
            np.asarray(new[k]), np.asarray(ref_new[k]), rtol=1e-5, atol=1e-7,
            err_msg=f"param {k}",
        )


def test_loss_decreases_over_steps():
    mesh = make_training_mesh()
    params = init_params(16, n_heads=4, d_hidden=32, tp=mesh.shape["tp"], seed=1)
    x, y = _data(seed=1)
    step = TransformerStep(mesh, n_heads=4, lr=0.2)
    pl, xl, yl = step.place(params, x, y)
    losses = []
    for _ in range(5):
        loss, pl = step.step(pl, xl, yl)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_ulysses_flash_train_step_matches_reference():
    """The ulysses+flash schedule (two all_to_alls + the Pallas kernel
    with its custom VJP) trains identically to the single-device
    reference — seq-sharded TRAINING through the flash kernel."""
    mesh = make_training_mesh()
    tp = mesh.shape["tp"]
    params = init_params(16, n_heads=4, d_hidden=32, tp=tp)
    x, y = _data()
    step = TransformerStep(mesh, n_heads=4, lr=0.1, attn="ulysses")
    pl, xl, yl = step.place(params, x, y)
    loss, new = step.step(pl, xl, yl)

    ref_loss, ref_new = reference_step(
        {k: jnp.asarray(v) for k, v in params.items()}, x, y, n_heads=4, lr=0.1
    )
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(new[k]), np.asarray(ref_new[k]), rtol=1e-4, atol=1e-6,
            err_msg=f"param {k}",
        )


def test_ulysses_rejects_indivisible_heads_over_sp():
    mesh = make_training_mesh()
    if mesh.shape["sp"] < 2:
        pytest.skip("needs sp >= 2")
    with pytest.raises(ValueError):
        TransformerStep(mesh, n_heads=3, attn="ulysses")


def test_run_steps_loop_matches_stepwise():
    """The whole-loop-in-one-jit runner must produce exactly the same
    trajectory as repeated step() calls."""
    mesh = make_training_mesh()
    params = init_params(16, n_heads=4, d_hidden=32, tp=mesh.shape["tp"], seed=2)
    x, y = _data(seed=2)
    step = TransformerStep(mesh, n_heads=4, lr=0.1, attn="ulysses")
    pl, xl, yl = step.place(params, x, y)
    l1, p1 = step.step(pl, xl, yl)
    l2, p2 = step.step(p1, xl, yl)
    l_loop, p_loop = step.run_steps(pl, xl, yl, 2)
    np.testing.assert_allclose(float(l_loop), float(l2), rtol=1e-6)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(p_loop[k]), np.asarray(p2[k]), rtol=1e-6, atol=1e-8,
            err_msg=f"param {k}",
        )
