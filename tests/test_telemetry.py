"""Cluster telemetry plane: ring-buffer semantics, heartbeat delivery
(push and pull), straggler detection under injected one-executor skew,
missed-heartbeat tolerance, OpenMetrics exposition format, and the
flight recorder — ISSUE 5's tentpole acceptance tests."""

import json
import re
import time
import urllib.request

import pytest

from sparkrdma_tpu.obs import (
    Heartbeater,
    MetricsRegistry,
    OpenMetricsServer,
    TelemetryHub,
    TimeSeriesRing,
    extract_snapshot,
    render_openmetrics,
)
from sparkrdma_tpu.testing import faults
from sparkrdma_tpu.utils.config import TpuShuffleConf


# ---------------------------------------------------------------------------
# time-series ring units
# ---------------------------------------------------------------------------

def test_ring_same_bucket_merges_deltas_and_refreshes_gauges():
    ring = TimeSeriesRing(size=8, interval_ms=100)
    ring.append(1000, 1, counters={"c": 5}, gauges={"g": {"value": 1, "hwm": 1}},
                histograms={"h": {"count": 1, "sum": 2.0}})
    ring.append(1050, 2, counters={"c": 3}, gauges={"g": {"value": 9, "hwm": 9}},
                histograms={"h": {"count": 2, "sum": 4.0}})
    assert len(ring) == 1  # same wall bucket (1000//100 == 1050//100)
    w = ring.windows()[0]
    assert w.counters["c"] == 8
    assert w.gauges["g"]["value"] == 9  # latest sample wins
    assert w.histograms["h"] == {"count": 3, "sum": 6.0}
    assert w.seq == 2 and w.wall_ms == 1050
    ring.append(1100, 3, counters={"c": 1})
    assert len(ring) == 2  # next bucket


def test_ring_is_bounded_and_rollup_sums_retained_windows():
    ring = TimeSeriesRing(size=4, interval_ms=10)
    for i in range(10):
        ring.append(i * 10, i + 1, counters={"c": 1})
    assert len(ring) == 4  # oldest evicted
    assert ring.rollup()["counters"]["c"] == 4
    assert ring.rollup(last=2)["counters"]["c"] == 2
    assert [w["seq"] for w in ring.to_list(last=2)] == [9, 10]


# ---------------------------------------------------------------------------
# heartbeater units
# ---------------------------------------------------------------------------

def test_heartbeater_emits_interval_deltas_against_moving_baseline():
    reg = MetricsRegistry()
    got = []
    hb = Heartbeater(reg, "e0", interval_ms=50, send=got.append)
    reg.counter("t.n", role="e0").inc(10)
    hb.beat()
    reg.counter("t.n", role="e0").inc(4)
    hb.beat()
    hb.beat()  # idle interval
    assert [p["seq"] for p in got] == [1, 2, 3]
    assert got[0]["counters"]["t.n{role=e0}"] == 10
    assert got[1]["counters"]["t.n{role=e0}"] == 4
    assert "t.n{role=e0}" not in got[2]["counters"]  # zero deltas pruned
    assert all(p["executor_id"] == "e0" and p["v"] == 1 for p in got)


def test_heartbeater_outbox_mode_bounded_and_drained():
    reg = MetricsRegistry()
    hb = Heartbeater(reg, "e1", interval_ms=50, outbox_size=3)
    for _ in range(5):
        reg.counter("t.n").inc()
        hb.beat()
    drained = hb.drain()
    assert len(drained) == 3  # bounded: oldest dropped
    assert [p["seq"] for p in drained] == [3, 4, 5]  # seq keeps counting
    assert hb.drain() == []


def test_heartbeater_pause_skips_beats_and_resume_recovers():
    reg = MetricsRegistry()
    got = []
    hb = Heartbeater(reg, "e2", interval_ms=50, send=got.append)
    hb.beat()
    hb.pause()
    assert hb.beat() is None
    hb.resume()
    hb.beat()
    assert [p["seq"] for p in got] == [1, 2]


# ---------------------------------------------------------------------------
# hub units: ingest, gaps, missed heartbeats, detection
# ---------------------------------------------------------------------------

def _payload(eid, seq, wall, counters=None, hists=None):
    return {"v": 1, "executor_id": eid, "seq": seq, "wall_ms": wall,
            "interval_ms": 100, "counters": counters or {},
            "gauges": {}, "histograms": hists or {}}


def test_hub_folds_payloads_and_tracks_series():
    reg = MetricsRegistry()
    hub = TelemetryHub(role="drv", registry=reg, interval_ms=100, ring_size=8)
    for seq in range(1, 4):
        hub.ingest(_payload("e0", seq, seq * 100, {"transport.read_bytes": 10}))
    assert hub.executors() == ["e0"]
    assert len(hub.series("e0")) == 3
    assert hub.rollups()["e0"]["counters"]["transport.read_bytes"] == 30
    s = hub.summary()
    assert s["executors"]["e0"]["windows"] == 3
    assert s["missed_heartbeats"] == 0
    hub.ingest({"bogus": True})  # malformed: dropped, counted
    assert reg.snapshot()["counters"]["telemetry.bad_payloads{role=drv}"] == 1
    hub.stop()


def test_hub_seq_jump_records_gap_and_missed_gauge():
    reg = MetricsRegistry()
    hub = TelemetryHub(role="drv", registry=reg, interval_ms=100, ring_size=8)
    hub.ingest(_payload("e0", 1, 100))
    hub.ingest(_payload("e0", 5, 500))  # 3 heartbeats lost in transit
    wins = hub.series("e0").windows()
    assert [w.gap for w in wins] == [False, True]
    missed = reg.snapshot()["gauges"]["telemetry.missed_heartbeats{role=drv}"]
    assert missed["value"] == 3
    hub.stop()


def test_hub_wall_clock_silence_counts_missed_once_and_marks_resume_gap():
    reg = MetricsRegistry()
    hub = TelemetryHub(role="drv", registry=reg, interval_ms=100, ring_size=8)
    hub.ingest(_payload("e0", 1, 100))
    hub.ingest(_payload("e1", 1, 110))
    # e1 goes silent; e0's later heartbeats advance the hub's clock
    assert hub.check_missed(now_ms=200) == []  # within 2.5 intervals
    hub.ingest(_payload("e0", 2, 600))
    missed = reg.snapshot()["gauges"]["telemetry.missed_heartbeats{role=drv}"]
    assert missed["value"] == 1
    assert hub.summary()["executors"]["e1"]["missed"] is True
    hub.ingest(_payload("e0", 3, 900))  # silence continues: counted ONCE
    missed = reg.snapshot()["gauges"]["telemetry.missed_heartbeats{role=drv}"]
    assert missed["value"] == 1
    # e1 resumes: its next window carries the gap marker and re-arms
    hub.ingest(_payload("e1", 2, 900))
    assert hub.series("e1").windows()[-1].gap is True
    assert hub.summary()["executors"]["e1"]["missed"] is False
    hub.stop()


def test_straggler_detector_flags_busy_outlier_only():
    reg = MetricsRegistry()
    hub = TelemetryHub(role="drv", registry=reg, interval_ms=100,
                       ring_size=16, straggler_z=3)
    # three executors, identical work; e1's map tasks run 20x longer
    for seq in range(1, 4):
        for eid, ms in (("e0", 10.0), ("e1", 200.0), ("e2", 11.0)):
            hub.ingest(_payload(
                eid, seq, seq * 100,
                {f"transport.read_bytes{{role={eid}}}": 1 << 20},
                {f"engine.task_ms{{kind=map,role={eid}}}":
                 {"count": 2, "sum": ms}},
            ))
    rep = hub.straggler_report()
    assert rep["stragglers"] == ["e1"]
    flags = rep["executors"]["e1"]["flags"]
    assert flags and flags[0]["kind"] == "busy"
    # gauges follow the report (updated online on ingest)
    gauges = reg.snapshot()["gauges"]
    assert gauges["telemetry.straggler{executor=e1,role=drv}"]["value"] == 1
    assert gauges["telemetry.straggler{executor=e0,role=drv}"]["value"] == 0
    assert gauges["telemetry.stragglers{role=drv}"]["value"] == 1
    hub.stop()


def test_straggler_detector_needs_three_participants():
    reg = MetricsRegistry()
    hub = TelemetryHub(role="drv", registry=reg, interval_ms=100, ring_size=8)
    for eid, ms in (("e0", 10.0), ("e1", 500.0)):
        hub.ingest(_payload(eid, 1, 100, None,
                            {"engine.task_ms": {"count": 1, "sum": ms}}))
    assert hub.straggler_report()["stragglers"] == []  # 2 < MIN_PARTICIPANTS
    hub.stop()


def test_straggler_advisory_reaches_health_registry():
    from sparkrdma_tpu.resilience import SourceHealthRegistry

    reg = MetricsRegistry()
    health = SourceHealthRegistry(TpuShuffleConf(), role="drv")
    hub = TelemetryHub(role="drv", registry=reg, health=health,
                       interval_ms=100, ring_size=8)
    for seq in (1, 2):
        for eid, ms in (("e0", 10.0), ("e1", 400.0), ("e2", 12.0)):
            hub.ingest(_payload(eid, seq, seq * 100, None,
                                {"engine.task_ms": {"count": 1, "sum": ms}}))
    assert set(health.suspects()) == {"e1"}
    # advisory only: no circuit opened
    assert health.states() == {} or all(
        s == "closed" for s in health.states().values()
    )
    hub.stop()


# ---------------------------------------------------------------------------
# OpenMetrics exposition
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\\n])*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\\n])*")*\})? \S+$'
)


def _validate_openmetrics(text):
    """Line-format validator: every line is HELP, TYPE, EOF, or a
    sample matching the exposition grammar; every sample's family was
    declared by a TYPE line first; document ends with # EOF."""
    lines = text.splitlines()
    assert lines[-1] == "# EOF"
    typed = {}
    for ln in lines[:-1]:
        if ln.startswith("# HELP ") or ln.startswith("# TYPE "):
            _, kind, family, rest = ln.split(" ", 3)
            if kind == "TYPE":
                typed[family] = rest
            continue
        assert _SAMPLE_RE.match(ln), f"bad sample line: {ln!r}"
        name = ln.split("{", 1)[0].split(" ", 1)[0]
        candidates = {name}
        for suffix in ("_total", "_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                candidates.add(name[: -len(suffix)])
        assert candidates & typed.keys(), f"sample without TYPE: {ln!r}"
    return typed


def test_render_openmetrics_validates_and_maps_names():
    reg = MetricsRegistry()
    reg.counter("transport.read_bytes", role="exec-0", purpose="data").inc(42)
    reg.gauge("reader.inflight_bytes", role="exec-0").set(7)
    h = reg.histogram("rpc.handle_ms", bounds=(1, 10), role="exec-0")
    for v in (0.5, 5, 100):
        h.observe(v)
    text = render_openmetrics(reg.snapshot())
    typed = _validate_openmetrics(text)
    assert typed["transport_read_bytes"] == "counter"
    assert typed["reader_inflight_bytes"] == "gauge"
    assert typed["reader_inflight_bytes_hwm"] == "gauge"
    assert typed["rpc_handle_ms"] == "histogram"
    assert ('transport_read_bytes_total{purpose="data",role="exec-0"} 42'
            in text)
    # cumulative buckets + +Inf == count
    assert 'rpc_handle_ms_bucket{le="1",role="exec-0"} 1' in text
    assert 'rpc_handle_ms_bucket{le="10",role="exec-0"} 2' in text
    assert 'rpc_handle_ms_bucket{le="+Inf",role="exec-0"} 3' in text
    assert 'rpc_handle_ms_count{role="exec-0"} 3' in text


def test_openmetrics_escapes_label_values():
    reg = MetricsRegistry()
    reg.counter("x.n", note='quote " back \\ slash').inc()
    text = render_openmetrics(reg.snapshot())
    _validate_openmetrics(text)
    assert 'note="quote \\" back \\\\ slash"' in text


def test_extract_snapshot_finds_registry_in_artifacts():
    reg = MetricsRegistry()
    reg.counter("x.n").inc(3)
    snap = reg.snapshot()
    assert extract_snapshot(snap)["counters"]["x.n"] == 3
    assert extract_snapshot({"obs_registry": snap})["counters"]["x.n"] == 3
    assert extract_snapshot({"registry": snap})["counters"]["x.n"] == 3
    with pytest.raises(ValueError):
        extract_snapshot({"workloads": []})


def test_openmetrics_http_server_scrapes():
    reg = MetricsRegistry()
    reg.counter("x.scraped").inc(9)
    srv = OpenMetricsServer(lambda: render_openmetrics(reg.snapshot()))
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=5
        ) as resp:
            body = resp.read().decode("utf-8")
            ctype = resp.headers["Content-Type"]
    finally:
        srv.stop()
    assert "openmetrics-text" in ctype
    assert "x_scraped_total 9" in body
    _validate_openmetrics(body)


# ---------------------------------------------------------------------------
# e2e: in-process cluster (push path)
# ---------------------------------------------------------------------------

def test_context_e2e_straggler_flagged_under_injected_skew(tmp_path):
    """ISSUE 5 acceptance: >= 2 executors with >= 3 windows each on the
    driver hub; under a one-executor injected delay the detector flags
    exactly that executor."""
    from sparkrdma_tpu.engine.context import TpuContext

    conf = TpuShuffleConf({
        "tpu.shuffle.obs.telemetry.intervalMs": "40",
        "tpu.shuffle.shuffleWriteBlockSize": "65536",
        "tpu.shuffle.shuffleReadBlockSize": "65536",
    })
    spec = "stage:delay:0:delay_ms=150,stage=map_task,peer=exec-1"
    with faults.installed(spec):
        with TpuContext(num_executors=3, conf=conf) as ctx:
            data = [(f"k{i % 50}", 1) for i in range(2000)]
            out = (ctx.parallelize(data, num_partitions=6)
                   .reduce_by_key(lambda a, b: a + b).collect())
            assert len(out) == 50
            deadline = time.monotonic() + 10
            hub = ctx.driver.telemetry
            while time.monotonic() < deadline:
                if (len(hub.executors()) >= 3
                        and all(len(hub.series(e)) >= 3
                                for e in hub.executors())
                        and hub.straggler_report()["stragglers"]):
                    break
                time.sleep(0.05)
            ctx.telemetry_flush()
            assert len(hub.executors()) >= 2
            for e in hub.executors():
                assert len(hub.series(e)) >= 3
            rep = hub.straggler_report()
            assert rep["stragglers"] == ["exec-1"]  # it, and only it
            assert set(ctx.driver.health.suspects()) == {"exec-1"}
            snap = ctx.driver.metrics_snapshot()
            assert snap["telemetry"]["stragglers"] == ["exec-1"]


def test_context_e2e_lost_heartbeat_tolerated():
    """A paused (lost) heartbeater never fails the job: the gap is
    recorded, telemetry.missed_heartbeats increments, results are
    correct."""
    from sparkrdma_tpu.engine.context import TpuContext
    from sparkrdma_tpu.obs import get_registry

    conf = TpuShuffleConf({"tpu.shuffle.obs.telemetry.intervalMs": "30"})
    with TpuContext(num_executors=2, conf=conf) as ctx:
        hub = ctx.driver.telemetry
        lost = ctx.heartbeaters[1]
        g_missed = get_registry().gauge(
            "telemetry.missed_heartbeats", role=ctx.driver.executor_id
        )
        # both executors heartbeat at least once, then exec-1 goes dark
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and len(hub.executors()) < 2:
            time.sleep(0.02)
        assert len(hub.executors()) == 2
        before = g_missed.value
        lost.pause()
        data = [(f"k{i % 20}", 1) for i in range(500)]
        out = (ctx.parallelize(data, num_partitions=4)
               .reduce_by_key(lambda a, b: a + b).collect())
        assert len(out) == 20  # job unaffected
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and g_missed.value <= before:
            time.sleep(0.05)
        assert g_missed.value > before
        lost.resume()
        eid = lost.executor_id
        deadline = time.monotonic() + 10
        while (time.monotonic() < deadline
               and not any(w.gap for w in hub.series(eid).windows())):
            time.sleep(0.05)
        assert any(w.gap for w in hub.series(eid).windows())


def test_context_e2e_flight_recorder_names_failed_group(tmp_path):
    """On FetchFailedError the hub dumps a JSON artifact that loads and
    names the failed group."""
    from sparkrdma_tpu.engine.context import TpuContext
    from sparkrdma_tpu.shuffle.errors import FetchFailedError, ShuffleError

    conf = TpuShuffleConf({
        "tpu.shuffle.obs.telemetry.intervalMs": "40",
        "tpu.shuffle.resilience.maxFetchAttempts": "2",
        "tpu.shuffle.resilience.retryBackoffMs": "5",
        "tpu.shuffle.obs.telemetry.flightDir": str(tmp_path),
    })
    with faults.installed("read:fail:0"):
        with TpuContext(num_executors=2, conf=conf) as ctx:
            data = [(f"k{i % 10}", 1) for i in range(200)]
            with pytest.raises(ShuffleError):
                (ctx.parallelize(data, num_partitions=4)
                 .reduce_by_key(lambda a, b: a + b).collect())
            path = ctx.driver.telemetry.last_flight_path
            assert path is not None and path.startswith(str(tmp_path))
            with open(path) as f:
                doc = json.load(f)
    assert doc["kind"] == "sparkrdma_flight_record"
    assert doc["error"]["type"] == FetchFailedError.__name__
    failed = doc["failed_group"]
    assert failed["shuffle_id"] >= 1 and "partition_id" in failed
    assert "source" in failed  # the manager the fetch was aimed at
    assert doc["executors"]  # per-executor ring windows present
    assert "source_health" in doc and "stragglers" in doc


# ---------------------------------------------------------------------------
# e2e: multi-process cluster (pull path over the task protocol)
# ---------------------------------------------------------------------------

def test_cluster_e2e_pull_path_builds_driver_time_series():
    from sparkrdma_tpu.engine.cluster import ClusterContext

    conf = TpuShuffleConf({"tpu.shuffle.obs.telemetry.intervalMs": "50"})
    with ClusterContext(num_executors=2, conf=conf) as cc:
        def mk(i):
            return lambda: iter(
                [(f"k{j % 20}", 1) for j in range(i * 300, (i + 1) * 300)]
            )

        res = cc.run_map_reduce(
            [mk(i) for i in range(4)], num_partitions=4,
            reduce_fn=lambda it: sum(v for _, v in it),
        )
        assert sum(res) == 1200
        hub = cc.driver.telemetry
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if (len(hub.executors()) >= 2
                    and all(len(hub.series(e)) >= 3 for e in hub.executors())):
                break
            time.sleep(0.05)
        assert sorted(hub.executors()) == ["proc-exec-0", "proc-exec-1"]
        for e in hub.executors():
            assert len(hub.series(e)) >= 3
        # the workers' engine.task_ms instrumentation crossed the wire
        roll = hub.rollups()
        assert any(
            k.startswith("engine.task_ms")
            for e in roll for k in roll[e]["histograms"]
        )


# ---------------------------------------------------------------------------
# CLI egress
# ---------------------------------------------------------------------------

def test_obs_cli_openmetrics_and_from_snapshot(tmp_path):
    from sparkrdma_tpu.obs.__main__ import main

    reg_file = tmp_path / "artifact.json"
    reg = MetricsRegistry()
    reg.counter("cli.n", role="x").inc(5)
    reg_file.write_text(json.dumps({"obs_registry": reg.snapshot()}))
    out_file = tmp_path / "out.prom"
    rc = main(["--openmetrics", str(out_file),
               "--from-snapshot", str(reg_file)])
    assert rc == 0
    text = out_file.read_text()
    _validate_openmetrics(text)
    assert 'cli_n_total{role="x"} 5' in text


def test_obs_cli_flight_recorder_pretty_printer(tmp_path, capsys):
    from sparkrdma_tpu.obs.__main__ import main

    reg = MetricsRegistry()
    hub = TelemetryHub(role="drv", registry=reg, interval_ms=100, ring_size=8)
    hub.ingest(_payload("e0", 1, 100, {"c": 1}))
    err = RuntimeError("boom")
    err.shuffle_id, err.partition_id = 7, 3
    path = hub.flight_record("unit_abort", error=err,
                             path=str(tmp_path / "flight.json"))
    hub.stop()
    rc = main(["--flight-recorder", path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "unit_abort" in out
    assert "shuffle_id=7" in out and "partition_id=3" in out
    assert "e0: 1 windows" in out
