"""Critical-path attribution engine: synthetic-DAG extraction units,
tracer causal-edge semantics (record parentage, epoch-skewed merges),
the tier-1 e2e — a real in-process cluster job whose TimeBreakdown
covers >= 90% of the job wall and whose Perfetto export carries the
cross-role publish -> resolve -> fetch flow chain — and the perf-trend
regression gate over the committed bench ledgers."""

import json
from pathlib import Path

import pytest

from sparkrdma_tpu.obs import Tracer, to_chrome_trace
from sparkrdma_tpu.obs.attr import attribute, classify
from sparkrdma_tpu.obs.critpath import PSpan, extract, spans_from_chrome
from sparkrdma_tpu.obs.trace import collect_spans_with_epochs

REPO_ROOT = Path(__file__).resolve().parents[1]


# ---------------------------------------------------------------------------
# synthetic DAG: the walk must follow explicit edges, not span length
# ---------------------------------------------------------------------------

def test_extract_prefers_causal_edges_over_long_spans():
    spans = [
        PSpan("shuffle.fetch", "e", 1, 0, 0.0, 4.0),
        PSpan("reader.pipeline.decode", "e", 2, 0, 4.0, 7.0, follows=[1]),
        PSpan("reader.pipeline.merge", "e", 3, 0, 7.0, 10.0, follows=[2]),
        # distractor: long concurrent span with no causal edges — a
        # naive "pick the longest" would attribute everything here
        PSpan("shuffle.push", "e", 4, 0, 0.0, 9.0),
    ]
    path = extract(spans, 0.0, 10.0)
    chain = [s.name for s in path.segments if s.kind == "span"]
    assert chain == [
        "shuffle.fetch", "reader.pipeline.decode", "reader.pipeline.merge",
    ]
    assert not [s for s in path.segments if s.kind == "gap"]
    assert path.coverage == pytest.approx(1.0)


def test_extract_emits_gap_segments_for_untraced_time():
    spans = [
        PSpan("shuffle.fetch", "e", 1, 0, 0.0, 2.0),
        PSpan("reader.pipeline.merge", "e", 2, 0, 7.0, 10.0),
    ]
    path = extract(spans, 0.0, 10.0)
    kinds = [(s.kind, round(s.t0, 6), round(s.t1, 6)) for s in path.segments]
    assert kinds == [("span", 0.0, 2.0), ("gap", 2.0, 7.0), ("span", 7.0, 10.0)]
    assert path.coverage == pytest.approx(0.5)
    # segments tile the window exactly — nothing double-counted or lost
    assert sum(s.dur_s for s in path.segments) == pytest.approx(path.wall_s)


def test_extract_untraced_tail_is_a_gap():
    """Nothing running at the window end: the tail must be accounted as
    idle, not silently dropped from the segment list."""
    spans = [PSpan("shuffle.fetch", "e", 1, 0, 0.0, 3.0)]
    path = extract(spans, 0.0, 10.0)
    assert sum(s.dur_s for s in path.segments) == pytest.approx(10.0)
    assert path.coverage == pytest.approx(0.3)


def test_attribute_folds_categories_with_known_longest_path():
    spans = [
        PSpan("engine.task", "d", 1, 0, 0.0, 5.0),
        PSpan("shuffle.fetch", "e", 2, 1, 5.0, 8.0, follows=[1]),
        PSpan("reader.pipeline.decode", "e", 3, 0, 8.0, 9.0, follows=[2]),
    ]
    bd = attribute(extract(spans, 0.0, 10.0))
    assert bd.wall_ms == pytest.approx(10_000.0)
    assert bd.categories["device-compute"] == pytest.approx(5_000.0)
    assert bd.categories["host-read"] == pytest.approx(3_000.0)
    assert bd.categories["decode"] == pytest.approx(1_000.0)
    assert bd.categories["idle-untraced"] == pytest.approx(1_000.0)
    assert bd.coverage == pytest.approx(0.9)
    assert sum(bd.categories.values()) == pytest.approx(bd.wall_ms)


def test_classify_longest_prefix_wins():
    assert classify("shuffle.fetch_request") == "rpc"
    assert classify("shuffle.fetch") == "host-read"
    assert classify("shuffle.collective.wave") == "dma-wave"
    assert classify("tenant.queue_wait") == "queue-wait"
    assert classify("something.novel") == "other"


# ---------------------------------------------------------------------------
# tracer causal-edge semantics
# ---------------------------------------------------------------------------

def test_record_attaches_contextvar_parent():
    tr = Tracer(role="t-rec-parent")
    with tr.span("outer", trace_id=5) as outer:
        child = tr.record("child", 0.0, 1.0)
    assert child.parent_id == outer.span_id
    assert child.trace_id == 5


def test_two_fake_epoch_tracers_merge_onto_one_timeline():
    """Spans from processes with different wall anchors normalize onto
    one axis: a span starting 1 s into a process whose epoch is 2000
    lands at wall 2001, after a span at 1005 from an epoch-1000 peer."""
    t_a = Tracer(role="epoch-a", epoch=1000.0)
    t_b = Tracer(role="epoch-b", epoch=2000.0)
    sp_a = t_a.record("shuffle.fetch", 5.0, 6.0)
    sp_b = t_b.record("reader.pipeline.decode", 1.0, 2.0)
    sp_b.add_follows(sp_a)
    pairs = collect_spans_with_epochs([t_a, t_b])
    assert pairs == [(sp_a, 1000.0), (sp_b, 2000.0)]
    path = extract(pairs, 1005.0, 2002.0)
    names = [(s.kind, s.name) for s in path.segments]
    assert ("span", "shuffle.fetch") in names
    assert ("span", "reader.pipeline.decode") in names
    # the decode span follows the fetch span across the epoch seam, so
    # the interval between them is one explicit gap, not a dead walk
    segs = path.segments
    assert segs[0].name == "shuffle.fetch"
    assert segs[-1].name == "reader.pipeline.decode"
    # override map re-anchors a role wholesale
    pairs2 = collect_spans_with_epochs([t_b], epochs={"epoch-b": 0.0})
    assert pairs2[0][1] == 0.0


def test_heartbeat_carries_epoch_anchor_to_hub():
    from sparkrdma_tpu.obs import get_registry
    from sparkrdma_tpu.obs.telemetry import Heartbeater, TelemetryHub
    from sparkrdma_tpu.obs.trace import epoch_anchor

    hub = TelemetryHub(role="t-epoch-hub", interval_ms=50)
    hb = Heartbeater(get_registry(), "epoch-exec", interval_ms=50,
                     send=hub.ingest)
    try:
        payload = hb.beat()
        assert payload is not None
        hub.ingest(payload)
        anchors = hub.epoch_anchors()
        assert anchors["epoch-exec"] == pytest.approx(
            epoch_anchor(), abs=0.01
        )
    finally:
        hub.stop()


# ---------------------------------------------------------------------------
# tier-1 e2e: real cluster job -> breakdown coverage + flow-event chain
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def job_artifacts():
    from sparkrdma_tpu.engine.context import TpuContext
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    conf = TpuShuffleConf({})
    with TpuContext(num_executors=2, conf=conf, task_threads=4) as ctx:
        rdd = (
            ctx.parallelize(range(8000), 4)
            .map(lambda x: (x % 97, 1))
            .reduce_by_key(lambda a, b: a + b, num_partitions=4)
        )
        out = dict(ctx.run_job(rdd))
        bd = ctx.last_breakdown
        snap = ctx.metrics_snapshot()
        doc = to_chrome_trace()
    return {"out": out, "breakdown": bd, "snapshot": snap, "trace": doc}


def test_e2e_breakdown_covers_90pct_of_job_wall(job_artifacts):
    assert job_artifacts["out"][0] == 8000 // 97 + 1
    bd = job_artifacts["breakdown"]
    assert bd is not None
    assert bd.coverage >= 0.9, bd.render()
    traced_ms = sum(
        v for k, v in bd.categories.items() if k != "idle-untraced"
    )
    assert traced_ms >= 0.9 * bd.wall_ms, bd.render()
    # the verdict also rides the metrics snapshot for artifact embedding
    assert job_artifacts["snapshot"]["breakdown"]["coverage"] >= 0.9


def test_e2e_perfetto_has_cross_role_publish_resolve_fetch_chain(job_artifacts):
    doc = job_artifacts["trace"]
    pid_names = {
        e["pid"]: e["args"]["name"]
        for e in doc["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    spans = {
        e["args"]["span_id"]: e
        for e in doc["traceEvents"]
        if e.get("ph") == "X" and (e.get("args") or {}).get("span_id")
    }
    edges = set()
    finish = 0
    for e in doc["traceEvents"]:
        if e.get("cat") != "critpath":
            continue
        if e.get("ph") == "f":
            finish += 1
            continue
        if e.get("ph") != "s":
            continue
        src = spans.get(e["args"]["from_span"])
        dst = spans.get(e["args"]["to_span"])
        if src and dst:
            edges.add((
                src["name"], pid_names.get(src["pid"]),
                dst["name"], pid_names.get(dst["pid"]),
            ))
    assert finish > 0  # every flow start pairs with a finish
    execs = {r for _, r, _, _ in edges} | {r for _, _, _, r in edges}
    assert any(r and r.startswith("exec-") for r in execs)
    # executor publish -> driver publish record (cross-role)
    assert any(
        s == "shuffle.publish" and sr != "driver"
        and d == "shuffle.publish" and dr == "driver"
        for s, sr, d, dr in edges
    ), edges
    # driver publish record -> driver resolve
    assert ("shuffle.publish", "driver", "shuffle.resolve", "driver") in edges
    # driver resolve -> executor fetch (cross-role)
    assert any(
        s == "shuffle.resolve" and sr == "driver"
        and d == "shuffle.fetch" and dr != "driver"
        for s, sr, d, dr in edges
    ), edges


def test_spans_from_chrome_round_trips_follows(job_artifacts):
    spans = spans_from_chrome(job_artifacts["trace"])
    by_name = {}
    for p in spans:
        by_name.setdefault(p.name, []).append(p)
    assert "job.run" in by_name
    resolves = by_name.get("shuffle.resolve", [])
    assert resolves and any(p.follows for p in resolves)


def test_critical_path_cli_over_saved_trace(job_artifacts, tmp_path, capsys):
    from sparkrdma_tpu.obs.__main__ import main as obs_main

    f = tmp_path / "trace.json"
    f.write_text(json.dumps(job_artifacts["trace"]))
    assert obs_main(["--critical-path", str(f)]) == 0
    out = capsys.readouterr().out
    assert "window: job.run span" in out
    assert "coverage" in out
    assert "top segments:" in out


def test_critical_path_cli_over_stored_breakdown(job_artifacts, tmp_path,
                                                 capsys):
    from sparkrdma_tpu.obs.__main__ import main as obs_main

    f = tmp_path / "artifact.json"
    f.write_text(json.dumps(
        {"workloads": [], "breakdown": job_artifacts["breakdown"].to_dict()}
    ))
    assert obs_main(["--critical-path", str(f)]) == 0
    out = capsys.readouterr().out
    assert "stored breakdown" in out


def test_critpath_knob_disables_attribution():
    from sparkrdma_tpu.engine.context import TpuContext
    from sparkrdma_tpu.utils.config import TpuShuffleConf

    conf = TpuShuffleConf({"tpu.shuffle.obs.critpath.enabled": "false"})
    assert conf.critpath_enabled is False
    with TpuContext(num_executors=1, conf=conf, task_threads=2) as ctx:
        rdd = ctx.parallelize(range(100), 2).map(lambda x: (x % 5, 1)) \
            .reduce_by_key(lambda a, b: a + b, num_partitions=2)
        ctx.run_job(rdd)
        assert ctx.last_breakdown is None


# ---------------------------------------------------------------------------
# perf-trend engine (obs/trend.py)
# ---------------------------------------------------------------------------

def _write(path: Path, doc: dict) -> None:
    path.write_text(json.dumps(doc))


def test_trend_covers_every_committed_bench_round():
    from sparkrdma_tpu.obs.trend import build_trend

    trend = build_trend(str(REPO_ROOT))
    assert trend["rounds"]["bench"] == [1, 2, 3, 4, 5, 6, 7, 8, 9, 10]
    assert not trend["errors"], trend["errors"]
    assert not trend["regressions"], trend["regressions"]
    assert trend["num_series"] > 100
    # every skip is loud: a row and a reason, never a silent drop
    assert all(s["row"] and s["reason"] for s in trend["skipped"])
    tracked = [
        n for n, t in trend["series"].items() if t.get("tracked")
    ]
    assert any("gbps" in n for n in tracked)


def test_trend_gate_fails_on_synthetic_regression(tmp_path):
    from sparkrdma_tpu.obs.trend import main as trend_main

    _write(tmp_path / "BENCH_r01.json",
           {"parsed": {"metric": "m", "read_gbps": 10.0}})
    _write(tmp_path / "BENCH_r02.json",
           {"parsed": {"metric": "m", "read_gbps": 4.0}})
    argv = ["--dir", str(tmp_path), "--out", str(tmp_path / "TREND.json"),
            "--md", str(tmp_path / "TREND.md"), "--check"]
    assert trend_main(argv) == 1
    trend = json.loads((tmp_path / "TREND.json").read_text())
    assert trend["regressions"][0]["series"] == "bench.read_gbps"


def test_trend_gate_fails_on_unclassifiable_row(tmp_path):
    from sparkrdma_tpu.obs.trend import main as trend_main

    _write(tmp_path / "BENCH_r01.json",
           {"parsed": {"mystery": "what is this"}})
    argv = ["--dir", str(tmp_path), "--out", str(tmp_path / "TREND.json"),
            "--md", str(tmp_path / "TREND.md"), "--check"]
    assert trend_main(argv) == 2


def test_trend_stale_series_chart_but_do_not_gate(tmp_path):
    from sparkrdma_tpu.obs.trend import build_trend, main as trend_main

    # a_gbps drops 60% between r01 and r02 but vanishes from the
    # newest round (r03) — historical fact, not an actionable gate
    _write(tmp_path / "BENCH_r01.json",
           {"parsed": {"a_gbps": 10.0, "b_gbps": 5.0}})
    _write(tmp_path / "BENCH_r02.json",
           {"parsed": {"a_gbps": 4.0, "b_gbps": 5.0}})
    _write(tmp_path / "BENCH_r03.json", {"parsed": {"b_gbps": 5.1}})
    argv = ["--dir", str(tmp_path), "--out", str(tmp_path / "TREND.json"),
            "--md", str(tmp_path / "TREND.md"), "--check"]
    assert trend_main(argv) == 0
    trend = build_trend(str(tmp_path))
    assert trend["series"]["bench.a_gbps"].get("stale") is True


def test_trend_rig_normalized_gate_forgives_slower_rig(tmp_path):
    from sparkrdma_tpu.obs.trend import build_trend, main as trend_main

    # the rig halved (probe 2.0 -> 1.0) and read_gbps halved with it:
    # the roofline fraction is flat, so nothing actionable regressed —
    # and the probe itself never gates (it measures the machine)
    _write(tmp_path / "BENCH_r01.json",
           {"parsed": {"read_gbps": 1.6, "exchange_loopback_gbps": 2.0}})
    _write(tmp_path / "BENCH_r02.json",
           {"parsed": {"read_gbps": 0.8, "exchange_loopback_gbps": 1.0}})
    argv = ["--dir", str(tmp_path), "--out", str(tmp_path / "TREND.json"),
            "--md", str(tmp_path / "TREND.md"), "--check"]
    assert trend_main(argv) == 0
    trend = build_trend(str(tmp_path))
    assert trend["series"]["bench.exchange_loopback_gbps"].get(
        "rig_probe") is True
    assert trend["series"]["bench.read_gbps"].get(
        "rel_delta_normalized") == 0.0


def test_trend_rig_normalized_gate_still_catches_code_regressions(tmp_path):
    from sparkrdma_tpu.obs.trend import main as trend_main

    # same rig both rounds (probe flat) but read_gbps dropped 60%:
    # normalization must not launder a genuine regression
    _write(tmp_path / "BENCH_r01.json",
           {"parsed": {"read_gbps": 1.6, "exchange_loopback_gbps": 2.0}})
    _write(tmp_path / "BENCH_r02.json",
           {"parsed": {"read_gbps": 0.64, "exchange_loopback_gbps": 2.0}})
    argv = ["--dir", str(tmp_path), "--out", str(tmp_path / "TREND.json"),
            "--md", str(tmp_path / "TREND.md"), "--check"]
    assert trend_main(argv) == 1
    trend = json.loads((tmp_path / "TREND.json").read_text())
    assert trend["regressions"][0]["series"] == "bench.read_gbps"
    assert trend["regressions"][0]["rig_normalized"] is True


def test_trend_flattens_workloads_and_soak(tmp_path):
    from sparkrdma_tpu.obs.trend import build_trend

    _write(tmp_path / "WORKLOADS_r01.json", {
        "generated_unix": 1, "scale": 0.1,
        "workloads": [
            {"workload": "pagerank", "seconds": 1.5, "records_per_s": 200},
            {"workload": "terasort_engine", "seconds": 2.0,
             "note": "free text", "breakdown": None},
        ],
    })
    _write(tmp_path / "SOAK_r01.json", {
        "args": {"seconds": 20},
        "ok": True,
        "checks": {"hwm_flat": True, "zero_job_failures": False},
    })
    trend = build_trend(str(tmp_path))
    s = trend["series"]
    assert s["workloads.pagerank.records_per_s"]["latest"] == 200
    assert s["workloads.terasort_engine.seconds"]["latest"] == 2.0
    assert s["soak.ok"]["latest"] == 1.0
    assert s["soak.checks.hwm_flat"]["latest"] == 1.0
    assert s["soak.checks.zero_job_failures"]["latest"] == 0.0
    assert not trend["errors"], trend["errors"]
    reasons = {x["reason"] for x in trend["skipped"]}
    assert "run-config" in reasons      # soak args subtree
    assert "string-metadata" in reasons  # the note field
