"""Flagship workload: device TeraSort over the 8-device mesh.

Workload-level truth per SURVEY.md §4: golden-result comparison of the
exchange-path output vs a plain host sort (the reference validated by
comparing RDMA-path TeraSort output to stock sort shuffle)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparkrdma_tpu.models.terasort import TeraSorter
from sparkrdma_tpu.ops.sort import (
    device_sort,
    merge_received,
    pack_by_partition,
    radix_partition,
    split_sorted,
)
from sparkrdma_tpu.parallel.mesh import make_mesh


def test_radix_partition_ranges():
    keys = jnp.array([0, 1 << 29, 1 << 30, 3 << 30, 0xFFFFFFFF], dtype=jnp.uint32)
    dest = radix_partition(keys, 4)
    assert list(np.asarray(dest)) == [0, 0, 1, 3, 3]


def test_pack_by_partition_layout_and_overflow():
    vals = jnp.array([10, 20, 30, 40, 50], dtype=jnp.uint32)
    dest = jnp.array([1, 0, 1, 1, 0], dtype=jnp.int32)
    slab, counts, overflowed = pack_by_partition(vals, dest, 2, capacity=4, fill=0)
    assert not bool(overflowed)
    assert list(np.asarray(counts)) == [2, 3]
    assert list(np.asarray(slab)[0, :2]) == [20, 50]  # input order preserved
    assert list(np.asarray(slab)[1, :3]) == [10, 30, 40]
    _, _, overflowed = pack_by_partition(vals, dest, 2, capacity=2, fill=0)
    assert bool(overflowed)


def test_device_sort_matches_numpy():
    rng = np.random.default_rng(3)
    keys = rng.integers(0, 1 << 32, size=20_000, dtype=np.uint32)
    np.testing.assert_array_equal(
        np.asarray(jax.jit(device_sort)(jnp.asarray(keys))), np.sort(keys)
    )


def test_split_sorted_matches_pack_semantics():
    """split_sorted on sorted keys == pack_by_partition row contents
    (up to within-row order, which split_sorted additionally sorts)."""
    rng = np.random.default_rng(4)
    keys = rng.integers(0, 1 << 32, size=8192, dtype=np.uint32)
    p, cap = 8, 2048
    skeys = jnp.sort(jnp.asarray(keys))
    slab, counts, overflowed = split_sorted(skeys, p, cap, 32, fill=0)
    assert not bool(overflowed)
    dest = radix_partition(jnp.asarray(keys), p)
    pslab, pcounts, _ = pack_by_partition(jnp.asarray(keys), dest, p, cap, fill=0)
    np.testing.assert_array_equal(np.asarray(counts), np.asarray(pcounts))
    for e in range(p):
        c = int(np.asarray(counts)[e])
        np.testing.assert_array_equal(
            np.asarray(slab)[e, :c], np.sort(np.asarray(pslab)[e, :c])
        )
        assert (np.asarray(slab)[e, c:] == 0).all()  # fill beyond count


def test_split_sorted_overflow_and_edges():
    # all keys in partition 0 -> overflow at small capacity
    skeys = jnp.sort(jnp.asarray(np.arange(100, dtype=np.uint32)))
    _, counts, overflowed = split_sorted(skeys, 4, 32, 32, fill=0)
    assert bool(overflowed)
    assert int(np.asarray(counts)[0]) == 32  # clamped
    # exact range-edge keys land in the owning partition (half-open)
    edge = jnp.asarray([0, 1 << 30, (1 << 30) + 1, 3 << 30], dtype=jnp.uint32)
    slab, counts, overflowed = split_sorted(edge, 4, 4, 32, fill=0)
    assert not bool(overflowed)
    assert list(np.asarray(counts)) == [1, 2, 0, 1]
    assert list(np.asarray(slab)[1, :2]) == [1 << 30, (1 << 30) + 1]


def test_merge_received_masks_padding():
    slab = jnp.array([[5, 99, 99], [3, 1, 99]], dtype=jnp.uint32)
    counts = jnp.array([1, 2], dtype=jnp.int32)
    merged, total = merge_received(slab, counts, 0xFFFFFFFF)
    assert int(total) == 3
    assert list(np.asarray(merged)[:3]) == [1, 3, 5]


@pytest.mark.parametrize("n", [1024, 100_000])
def test_terasort_matches_numpy(n):
    rng = np.random.default_rng(42)
    keys = rng.integers(0, 1 << 32, size=n, dtype=np.uint32)
    sorter = TeraSorter(make_mesh())
    out = sorter.sort(keys)
    np.testing.assert_array_equal(out, np.sort(keys))


def test_terasort_skewed_keys_overflow_retry():
    """All keys in one range: the first capacity class overflows and the
    host retries with doubled buckets (pool-style re-rounding)."""
    keys = np.zeros(4096, dtype=np.uint32)  # every key -> partition 0
    sorter = TeraSorter(make_mesh(), capacity_factor=1.25)
    out = sorter.sort(keys)
    np.testing.assert_array_equal(out, keys)


def test_terasort_on_2d_mesh():
    rng = np.random.default_rng(7)
    keys = rng.integers(0, 1 << 32, size=8192, dtype=np.uint32)
    sorter = TeraSorter(make_mesh(num_slices=2))
    np.testing.assert_array_equal(sorter.sort(keys), np.sort(keys))


def test_terasort_non_multiple_length():
    rng = np.random.default_rng(3)
    keys = rng.integers(0, 1 << 32, size=1000, dtype=np.uint32)  # 1000 % 8 != 0
    sorter = TeraSorter(make_mesh())
    np.testing.assert_array_equal(sorter.sort(keys), np.sort(keys))
