"""The full-stack e2e TeraSort workload at CI scale.

benchmarks/run_workloads.py's ``terasort_e2e`` is the round artifact's
headline workload (host map sorts -> registered publish -> location
RPC -> one-sided READ -> HBM staging -> device merge, verified by
on-device sortedness + order-invariant checksums). Running it tiny
here keeps the artifact path exercised by CI, not just by the round
driver (the round-2 native breakage would have been caught by exactly
this)."""

import importlib.util
import os

import pytest

from sparkrdma_tpu.native.transport_lib import toolchain_available

_spec = importlib.util.spec_from_file_location(
    "run_workloads",
    os.path.join(os.path.dirname(__file__), "..", "benchmarks", "run_workloads.py"),
)
run_workloads = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(run_workloads)


def test_e2e_terasort_python_transport():
    from sparkrdma_tpu.obs import get_registry

    # reducer runs on executor e2e-0; the planner tags its counters
    # with the fetching executor's role
    pulls = get_registry().counter("device_fetch.plane.pulls", role="e2e-0")
    falls = get_registry().counter(
        "device_fetch.plane.fallbacks", role="e2e-0"
    )
    p0, f0 = pulls.value, falls.value
    run_workloads.bench_e2e_terasort(0.002, "python", reducers=4, executors=2)
    rec = run_workloads.RECORDS[-1]
    assert rec["workload"] == "terasort_e2e"
    assert rec["verified"].startswith("count+sum+xor+sorted")
    # observability rides in the artifact record
    m = rec["metrics"]
    assert m["registered_pool_allocs_by_class"]
    assert m["hbm_pool_allocs_by_class"]
    assert m["hbm_spill_count"] == 0
    # single-process harness: every arena is mesh-visible, so the
    # device fetch plane (DESIGN.md §17) pulls the peer executor's
    # blocks HBM->HBM — and the checksum verification above already
    # proved those pulled bytes correct end to end
    assert pulls.value - p0 > 0, "device plane did not engage in e2e"
    assert falls.value - f0 == 0


# gate on the TOOLCHAIN, not available(): a transport.cpp compile
# breakage must fail this test, not skip it
@pytest.mark.skipif(not toolchain_available(), reason="no g++ toolchain")
def test_e2e_terasort_native_transport():
    # device_fetch=False: this test pins the native HOST plane, which
    # the (mesh-visible, same-process) device plane would otherwise
    # short-circuit entirely
    run_workloads.bench_e2e_terasort(
        0.002, "native", reducers=4, executors=2, device_fetch=False
    )
    rec = run_workloads.RECORDS[-1]
    assert rec["transport"] == "native"
    m = rec["metrics"]
    # the reducer pulls half its blocks from the co-located peer
    # executor over the native plane: every one of those READs must
    # have taken the same-host pread fast path
    assert m["transport"] == "NativeTpuNode"
    assert m["reads_samehost_fast_path"] > 0
    assert m["reads_streamed"] == 0
