"""Pipelined reduce plane (DESIGN.md §16): stage overlap is real and
measured via reader.pipeline.*, delivery order is invariant under decode
parallelism, and abort/early-close drains release every in-flight
item's resources (pool returns == gets)."""

import time

import pytest

from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.shuffle.reader.pipeline import ReduceTaskPipeline
from sparkrdma_tpu.utils.config import TpuShuffleConf


# ---------------------------------------------------------------------------
# pipeline overlap
# ---------------------------------------------------------------------------

def test_reduce_pipeline_stages_overlap():
    """With per-stage sleeps the sum of stage busy time must exceed the
    wall — the overlap the pipeline exists to buy — and the
    reader.pipeline.* metrics must record it."""
    get_registry().reset()
    d = 0.04

    def mk(stage):
        def fn(i, *_prev):
            time.sleep(d)
            return (stage, i)

        return fn

    pipe = ReduceTaskPipeline(
        mk("fetch"), mk("decode"), mk("stage"), mk("merge"),
        parallelism=2, depth=2, double_buffer=True, role="t-overlap",
    )
    report = pipe.run(range(6))
    assert report.results == [("merge", i) for i in range(6)]
    # 6 items x 4 stages x d of busy; a serial loop would wall 24d.
    assert report.busy_total_s > report.wall_s
    assert report.overlap_s > 0

    snap = get_registry().snapshot(prefix="reader.pipeline")
    stage_keys = [k for k in snap["histograms"] if "stage_ms" in k]
    for s in ("fetch", "decode", "stage", "merge"):
        assert any(f"stage={s}" in k for k in stage_keys)
    for k in stage_keys:
        if "role=t-overlap" in k:
            assert snap["histograms"][k]["count"] == 6
    (ok,) = [
        k for k in snap["histograms"]
        if "overlap_ms" in k and "role=t-overlap" in k
    ]
    assert snap["histograms"][ok]["sum"] > 0
    # every item left the pipeline: the inflight gauge is back to zero
    (gk,) = [
        k for k in snap["gauges"] if "inflight" in k and "role=t-overlap" in k
    ]
    assert snap["gauges"][gk]["value"] == 0
    assert snap["gauges"][gk]["hwm"] >= 2  # bounded concurrency happened


# ---------------------------------------------------------------------------
# ordering under parallelism
# ---------------------------------------------------------------------------

def test_reduce_pipeline_parallelism_preserves_order():
    """The sequencer re-orders decode-pool output to source order:
    parallelism=4 with adversarial per-item decode skew delivers the
    EXACT sequence parallelism=1 (today's serial ordering) does."""

    def decode_fn(i, fetched):
        # items 0, 3, 6, ... decode slow: under parallelism their
        # successors finish first and sit in the reorder buffer
        time.sleep(0.03 if i % 3 == 0 else 0.001)
        return ("dec", i)

    def run(parallelism):
        pipe = ReduceTaskPipeline(
            None, decode_fn, None, None,
            parallelism=parallelism, depth=3, double_buffer=False,
            role=f"t-order-{parallelism}",
        )
        return list(pipe.stream(range(10)))

    serial = run(1)
    assert serial == [("dec", i) for i in range(10)]
    assert run(4) == serial


# ---------------------------------------------------------------------------
# abort / early close drain without delivering or leaking
# ---------------------------------------------------------------------------

def test_reduce_pipeline_abort_drains_without_delivering():
    """The first decode error latches: the failed item and the tail of
    the batch never deliver, every fetched item is delivered OR
    discarded exactly once, and the error re-raises after the drain."""
    get_registry().reset()
    acquired, released, delivered = [], [], []

    def fetch_fn(i):
        acquired.append(i)
        return ("blk", i)

    def decode_fn(i, blk):
        if i == 3:
            raise RuntimeError("decode boom")
        time.sleep(0.005)
        return ("dec", i)

    def discard_fn(stage, item, value):
        released.append((stage, item))

    pipe = ReduceTaskPipeline(
        fetch_fn, decode_fn, None, None,
        parallelism=2, depth=2, double_buffer=False, role="t-abort",
        discard_fn=discard_fn,
    )
    with pytest.raises(RuntimeError, match="decode boom"):
        for out in pipe.stream(range(8)):
            delivered.append(out)
    assert ("dec", 3) not in delivered
    assert len(delivered) < 8
    # exactly-once resource accounting: pool returns == gets
    assert len(delivered) + len(released) == len(acquired)
    snap = get_registry().snapshot(prefix="reader.pipeline")
    (gk,) = [
        k for k in snap["gauges"] if "inflight" in k and "role=t-abort" in k
    ]
    assert snap["gauges"][gk]["value"] == 0


def test_reduce_pipeline_early_close_drains():
    """A consumer abandoning the stream mid-run (generator close) takes
    the abort path: everything in flight drains through discard_fn, no
    item is lost and the inflight gauge returns to zero."""
    get_registry().reset()
    acquired, released = [], []

    def fetch_fn(i):
        acquired.append(i)
        return ("blk", i)

    def decode_fn(i, blk):
        time.sleep(0.005)
        return ("dec", i)

    def discard_fn(stage, item, value):
        released.append((stage, item))

    pipe = ReduceTaskPipeline(
        fetch_fn, decode_fn, None, None,
        parallelism=2, depth=2, double_buffer=False, role="t-close",
        discard_fn=discard_fn,
    )
    stream = pipe.stream(range(16))
    first = next(stream)
    assert first == ("dec", 0)
    stream.close()  # synchronous: returns after the drain completes
    assert len(acquired) >= 1
    # the one delivered item + every discarded one == every fetched one
    assert 1 + len(released) == len(acquired)
    snap = get_registry().snapshot(prefix="reader.pipeline")
    (gk,) = [
        k for k in snap["gauges"] if "inflight" in k and "role=t-close" in k
    ]
    assert snap["gauges"][gk]["value"] == 0


# ---------------------------------------------------------------------------
# real reader: pipelined output byte-identical, no pool leaks
# ---------------------------------------------------------------------------

def _counter(snap, name):
    return sum(
        v for k, v in snap["counters"].items() if k.split("{")[0] == name
    )


def _pool_balance(snap):
    """Outstanding registered-pool buffers: gets minus (returns+frees)."""
    gets = _counter(snap, "mempool.hits") + _counter(snap, "mempool.misses")
    return gets - _counter(snap, "mempool.returns") - _counter(snap, "mempool.frees")


def _run_cluster_read(parallelism, abandon_after=None):
    """One-executor cluster (local fetches: deterministic stream order),
    two map outputs, read everything back — or abandon the reader after
    ``abandon_after`` records. Returns the consumed record list."""
    conf = TpuShuffleConf(
        {
            "tpu.shuffle.shuffleWriteMethod": "wrapper",
            "tpu.shuffle.shuffleWriteBlockSize": "65536",
            "tpu.shuffle.shuffleReadBlockSize": "65536",
            "tpu.shuffle.reduce.parallelism": str(parallelism),
        }
    )
    driver = TpuShuffleManager(conf, is_driver=True)
    ex = TpuShuffleManager(conf, is_driver=False, executor_id="rp-0")
    try:
        handle = BaseShuffleHandle(
            shuffle_id=0, num_maps=2, partitioner=HashPartitioner(2)
        )
        driver.register_shuffle(handle)
        records = [(f"key-{i % 53}", i) for i in range(1500)]
        for map_id in range(2):
            w = ex.get_writer(handle, map_id)
            w.write(iter(records))
            w.stop(True)
        ex.finalize_maps(0)
        reader = ex.get_reader(handle, 0, 2)
        out = []
        try:
            for rec in reader.read():
                out.append(rec)
                if abandon_after is not None and len(out) >= abandon_after:
                    break
        finally:
            reader.close()
        return out
    finally:
        ex.stop()
        driver.stop()


def test_reader_pipelined_output_byte_identical():
    """reduce.parallelism=1 (the serial loop's ordering) and =4 must
    deliver the exact same record sequence, and neither run may leak
    pooled registered buffers."""
    snap0 = get_registry().snapshot(prefix="mempool")
    base0 = _pool_balance(snap0)
    serial = _run_cluster_read(1)
    assert len(serial) == 3000
    pipelined = _run_cluster_read(4)
    assert pipelined == serial
    snap1 = get_registry().snapshot(prefix="mempool")
    assert _pool_balance(snap1) == base0, "reader leaked pooled buffers"


def test_reader_early_close_releases_streams():
    """Abandoning a pipelined read mid-stream must still release every
    fetched stream's registered slice: pool returns == gets once the
    managers stop."""
    snap0 = get_registry().snapshot(prefix="mempool")
    base0 = _pool_balance(snap0)
    got = _run_cluster_read(4, abandon_after=10)
    assert len(got) == 10
    snap1 = get_registry().snapshot(prefix="mempool")
    assert _pool_balance(snap1) == base0, (
        "early-closed reader leaked pooled buffers"
    )
