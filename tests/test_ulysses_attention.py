"""Ulysses (all-to-all SP) attention vs dense reference and vs ring."""

import numpy as np
import pytest

import jax.numpy as jnp

from sparkrdma_tpu.ops.ring_attention import RingAttention, reference_attention
from sparkrdma_tpu.ops.ulysses_attention import UlyssesAttention
from sparkrdma_tpu.parallel.mesh import make_mesh


def _inputs(b=2, s=64, h=8, d=16, seed=0):
    rng = np.random.default_rng(seed)
    def mk():
        return jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))

    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(causal):
    q, k, v = _inputs()
    ul = UlyssesAttention(make_mesh())
    out = ul(q, k, v, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_ulysses_matches_ring():
    q, k, v = _inputs(seed=3)
    mesh = make_mesh()
    out_u = UlyssesAttention(mesh)(q, k, v)
    out_r = RingAttention(mesh)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out_u), np.asarray(out_r), rtol=2e-4, atol=2e-5
    )


def test_ulysses_rejects_indivisible_heads():
    q, k, v = _inputs(h=6)  # 6 heads over 8 shards
    with pytest.raises(ValueError):
        UlyssesAttention(make_mesh())(q, k, v)


def test_ulysses_without_flash_kernel():
    q, k, v = _inputs(seed=5)
    out = UlyssesAttention(make_mesh())(q, k, v, use_flash=False)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_ulysses_flash_path_is_trainable():
    """Long-context TRAINING through the SP stack: grad flows through
    the two all-to-alls AND the Pallas flash kernel (custom VJP), and
    matches autodiff through the dense reference."""
    import jax

    q, k, v = _inputs(seed=5)
    rng = np.random.default_rng(9)
    ct = jnp.asarray(rng.normal(size=q.shape).astype(np.float32))
    ul = UlyssesAttention(make_mesh())

    def f(q, k, v):
        return (ul(q, k, v, causal=True, use_flash=True) * ct).sum()

    def g(q, k, v):
        return (reference_attention(q, k, v, causal=True) * ct).sum()

    gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip(("dq", "dk", "dv"), gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
            err_msg=f"{name} mismatch through ulysses+flash",
        )
