"""Tenancy layer — fair-share dispatch, admission, quotas, isolation.

Proofs for the multi-tenant serving story (docs/DESIGN.md §19): the
deficit-round-robin pools cannot be convoyed by a large tenant, the
admission queue bounds in-flight jobs with a deadline, byte quotas
backpressure the offending tenant without touching its neighbors, and
the tenant dimension threads through breakers and the obs registry.
"""

import threading
import time

import pytest

from sparkrdma_tpu import tenancy
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.tenancy import (
    AdmissionController,
    AdmissionTimeout,
    FairShareExecutor,
    QuotaBroker,
    tenant_scope,
)
from sparkrdma_tpu.tenancy import quota as _quota
from sparkrdma_tpu.utils.config import TpuShuffleConf


@pytest.fixture(autouse=True)
def _clean_quota_table():
    _quota.reset()
    yield
    _quota.reset()


# ---------------------------------------------------------------------------
# FairShareExecutor
# ---------------------------------------------------------------------------
def test_fairshare_single_tenant_is_fifo():
    with FairShareExecutor(1) as ex:
        order = []
        futs = [ex.submit(lambda i=i: order.append(i)) for i in range(20)]
        for f in futs:
            f.result()
    assert order == list(range(20))


def test_fairshare_no_convoy():
    """A tenant with a huge queue cannot convoy a small tenant: the
    small tenant's 5 tasks finish while the big queue is still long."""
    done = []
    lock = threading.Lock()

    def work(tag):
        time.sleep(0.002)
        with lock:
            done.append(tag)

    ex = FairShareExecutor(1, quantum_ms=4)
    with tenant_scope("big"):
        big = [ex.submit(work, ("big", i)) for i in range(80)]
    with tenant_scope("small"):
        small = [ex.submit(work, ("small", i)) for i in range(5)]
    for f in small + big:
        f.result()
    ex.shutdown()
    # under FIFO the small tenant's last completion index would be >= 80;
    # under DRR it lands well inside the big tenant's drain
    last_small = max(i for i, tag in enumerate(done) if tag[0] == "small")
    assert last_small < 40, f"small tenant convoyed: finished at {last_small}"


def test_fairshare_weighted_dispatch_ratio():
    """Weight 3 vs 1 with equal task costs → ~3:1 completions while
    both stay backlogged."""
    counts = {"a": 0, "b": 0}
    lock = threading.Lock()

    def work(t):
        time.sleep(0.002)
        with lock:
            counts[t] += 1

    ex = FairShareExecutor(1, weights={"a": 3, "b": 1}, quantum_ms=4)
    with tenant_scope("a"):
        fa = [ex.submit(work, "a") for _ in range(200)]
    with tenant_scope("b"):
        fb = [ex.submit(work, "b") for _ in range(200)]
    # sample while both queues are still backlogged
    while True:
        with lock:
            total = counts["a"] + counts["b"]
        if total >= 80:
            break
        time.sleep(0.005)
    with lock:
        a, b = counts["a"], counts["b"]
    ex.shutdown(wait=False, cancel_futures=True)
    for f in fa + fb:
        if not f.cancelled():
            f.exception()
    ratio = a / max(1, b)
    assert 1.8 <= ratio <= 5.0, f"expected ~3:1 dispatch, got {a}:{b}"


def test_fairshare_runtime_charging_balances_task_seconds():
    """Tenant 'slow' runs 4x-longer tasks at equal weight: DRR charged
    by measured runtime should push its completed-task COUNT to ~1/4
    of 'fast', keeping task-seconds near parity."""
    counts = {"slow": 0, "fast": 0}
    lock = threading.Lock()

    def work(t, dt):
        time.sleep(dt)
        with lock:
            counts[t] += 1

    ex = FairShareExecutor(1, quantum_ms=4)
    with tenant_scope("slow"):
        fs = [ex.submit(work, "slow", 0.008) for _ in range(100)]
    with tenant_scope("fast"):
        ff = [ex.submit(work, "fast", 0.002) for _ in range(100)]
    while True:
        with lock:
            secs_slow = counts["slow"] * 0.008
            secs_fast = counts["fast"] * 0.002
        if secs_slow + secs_fast >= 0.25:
            break
        time.sleep(0.005)
    ex.shutdown(wait=False, cancel_futures=True)
    for f in fs + ff:
        if not f.cancelled():
            f.exception()
    assert secs_fast > 0 and secs_slow > 0
    share = secs_slow / (secs_slow + secs_fast)
    assert 0.25 <= share <= 0.75, (
        f"task-seconds skewed: slow={secs_slow:.3f}s fast={secs_fast:.3f}s"
    )


def test_fairshare_post_shutdown_submit_raises():
    ex = FairShareExecutor(1)
    ex.shutdown()
    with pytest.raises(RuntimeError):
        ex.submit(lambda: None)


def test_fairshare_propagates_exceptions_and_tenant_scope():
    seen = {}

    def work():
        seen["tenant"] = tenancy.current_tenant()
        raise ValueError("boom")

    with FairShareExecutor(2) as ex:
        with tenant_scope("alice"):
            f = ex.submit(work)
        with pytest.raises(ValueError):
            f.result()
    assert seen["tenant"] == "alice"  # workers re-enter the submit scope


# ---------------------------------------------------------------------------
# AdmissionController
# ---------------------------------------------------------------------------
def test_admission_bounds_inflight_and_deadline():
    ac = AdmissionController(max_inflight=2, queue_timeout_ms=30_000)
    ac.acquire("a")
    ac.acquire("a")
    assert ac.inflight == 2
    with pytest.raises(AdmissionTimeout):
        ac.acquire("b", timeout_ms=50)
    ac.release()
    ac.acquire("b", timeout_ms=1000)  # capacity freed → admitted
    assert ac.inflight == 2
    ac.release()
    ac.release()
    assert ac.inflight == 0


def test_admission_queue_is_fifo():
    ac = AdmissionController(max_inflight=1, queue_timeout_ms=30_000)
    ac.acquire("t0")
    order = []
    lock = threading.Lock()

    def queued(name):
        ac.acquire(name, timeout_ms=10_000)
        with lock:
            order.append(name)
        ac.release()

    threads = []
    for name in ("t1", "t2", "t3"):
        t = threading.Thread(target=queued, args=(name,), daemon=True)
        t.start()
        threads.append(t)
        # let each waiter enqueue before the next (FIFO order fixed)
        deadline = time.monotonic() + 5
        while ac.queued < len(threads) and time.monotonic() < deadline:
            time.sleep(0.005)
    ac.release()
    for t in threads:
        t.join(timeout=10)
    assert order == ["t1", "t2", "t3"]


# ---------------------------------------------------------------------------
# QuotaBroker
# ---------------------------------------------------------------------------
def test_quota_blocks_offender_not_neighbors():
    br = QuotaBroker("mempool", quota_bytes=100, block_max_ms=60_000)
    br.charge("a", 80)
    blocked = threading.Event()
    passed = threading.Event()

    def offender():
        blocked.set()
        br.charge("a", 80)  # over quota while holding bytes → waits
        passed.set()

    t = threading.Thread(target=offender, daemon=True)
    t.start()
    blocked.wait(5)
    time.sleep(0.05)
    assert not passed.is_set(), "over-quota charge should block"
    # a neighbor at the same instant sails through
    t0 = time.perf_counter()
    br.charge("b", 80)
    assert time.perf_counter() - t0 < 0.5
    br.release("b", 80)
    # releasing the offender's held bytes unblocks it
    br.release("a", 80)
    assert passed.wait(5), "release did not unblock the offender"
    t.join(timeout=5)
    assert br.usage("a") == 80


def test_quota_progress_guarantees():
    br = QuotaBroker("hbm", quota_bytes=100, block_max_ms=100)
    # oversize first allocation admits immediately (usage == 0)
    t0 = time.perf_counter()
    br.charge("a", 500)
    assert time.perf_counter() - t0 < 0.5
    # held-and-over-quota blocks, but only until block_max_ms
    t0 = time.perf_counter()
    br.charge("a", 50)
    dt = time.perf_counter() - t0
    assert 0.05 <= dt < 2.0, f"expected ~100ms bounded stall, got {dt:.3f}s"
    snap = get_registry().snapshot(prefix="tenant.quota_overruns")
    assert sum(snap.get("counters", {}).values()) >= 1


def test_mempool_quota_integration():
    from sparkrdma_tpu.memory.buffer_manager import TpuBufferManager
    from sparkrdma_tpu.memory.registry import ProtectionDomain

    conf = TpuShuffleConf({"tpu.shuffle.tenancy.mempoolQuotaBytes": "32k"})
    _quota.install(conf)
    mgr = TpuBufferManager(ProtectionDomain())
    with tenant_scope("hog"):
        b1 = mgr.get(16 * 1024)
        b2 = mgr.get(16 * 1024)  # at quota now (2 × 16 KiB classes)
    blocked = threading.Event()
    passed = threading.Event()
    grabbed = []

    def hog_more():
        with tenant_scope("hog"):
            blocked.set()
            grabbed.append(mgr.get(16 * 1024))
            passed.set()

    t = threading.Thread(target=hog_more, daemon=True)
    t.start()
    blocked.wait(5)
    time.sleep(0.05)
    assert not passed.is_set(), "third 16k buffer should block at the 32k quota"
    with tenant_scope("quiet"):
        q = mgr.get(16 * 1024)  # neighbor unaffected
        mgr.put(q)
    mgr.put(b1)  # frees 16k of 'hog' → the blocked get proceeds
    assert passed.wait(5)
    t.join(timeout=5)
    mgr.put(b2)
    mgr.put(grabbed[0])
    broker = _quota.broker("mempool")
    assert broker is not None and broker.usage("hog") == 0
    mgr.stop()


def test_buffer_free_releases_quota_charge():
    from sparkrdma_tpu.memory.buffer_manager import TpuBufferManager
    from sparkrdma_tpu.memory.registry import ProtectionDomain

    conf = TpuShuffleConf({"tpu.shuffle.tenancy.mempoolQuotaBytes": "64k"})
    _quota.install(conf)
    mgr = TpuBufferManager(ProtectionDomain())
    with tenant_scope("t"):
        buf = mgr.get(16 * 1024)
    buf.free()  # bypasses put(): the tag must still release
    assert _quota.broker("mempool").usage("t") == 0
    mgr.stop()


def test_hbm_spill_prefers_over_quota_tenant():
    from sparkrdma_tpu.ops.hbm_arena import DeviceBufferManager

    # per-tenant override: only 'hog' is capped; 'quiet' stays unlimited
    conf = TpuShuffleConf({"tpu.shuffle.tenancy.quota.hog.hbmBytes": "16k"})
    _quota.install(conf)
    # budget fits two 64k slabs; the third forces a spill
    mgr = DeviceBufferManager(max_bytes=128 * 1024)
    with tenant_scope("quiet"):
        old = mgr.get(64 * 1024)  # oldest → the LRU victim by age
    with tenant_scope("hog"):
        hogged = mgr.get(64 * 1024)  # 64k held vs 16k quota → over
    with tenant_scope("quiet"):
        newer = mgr.get(64 * 1024)  # needs room: must evict 'hog', not LRU
    assert hogged.spilled, "over-quota tenant's slab should be the victim"
    assert not old.spilled, "in-quota LRU slab wrongly chosen over offender"
    for b in (old, hogged, newer):
        mgr.put(b)
    mgr.stop()


# ---------------------------------------------------------------------------
# manager pool lifecycle (create-vs-close race)
# ---------------------------------------------------------------------------
def test_manager_map_pool_post_close_raises():
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager

    conf = TpuShuffleConf()
    mgr = TpuShuffleManager(conf, is_driver=True)
    pool = mgr.map_pool
    assert pool is not None
    mgr.stop()
    with pytest.raises(RuntimeError):
        _ = mgr.map_pool
    # the pre-stop pool handle is shut down too: submits raise
    with pytest.raises(RuntimeError):
        pool.submit(lambda: None)


def test_manager_pool_create_close_race_never_leaks():
    """Hammer lazy map_pool creation against stop(): afterwards the
    manager must hold NO pool and every obtained pool must be dead."""
    from sparkrdma_tpu.shuffle.manager import TpuShuffleManager

    for _ in range(15):
        conf = TpuShuffleConf()
        mgr = TpuShuffleManager(conf, is_driver=True)
        obtained = []
        start = threading.Barrier(3)

        def grab():
            start.wait()
            try:
                obtained.append(mgr.map_pool)
            except RuntimeError:
                pass  # post-close access: the clean outcome

        def close():
            start.wait()
            mgr.stop()

        threads = [
            threading.Thread(target=grab),
            threading.Thread(target=grab),
            threading.Thread(target=close),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert mgr._map_pool is None
        for pool in obtained:
            with pytest.raises(RuntimeError):
                pool.submit(lambda: None)


# ---------------------------------------------------------------------------
# breaker scoping + e2e labels
# ---------------------------------------------------------------------------
def test_breaker_keys_scoped_per_tenant():
    from sparkrdma_tpu.resilience import SourceHealthRegistry

    conf = TpuShuffleConf({"tpu.shuffle.resilience.circuitFailureThreshold": 2})
    health = SourceHealthRegistry(conf, role="t")
    with tenant_scope("noisy"):
        health.record_failure("exec-1")
        health.record_failure("exec-1")
        assert not health.allow("exec-1")
    # same peer, different tenant: separate breaker, still closed
    with tenant_scope("quiet"):
        assert health.allow("exec-1")
    assert health.allow("exec-1")  # default tenant uses the bare key
    states = health.states()
    assert states.get("noisy:exec-1") == "open"
    assert "quiet:exec-1" in states and states["quiet:exec-1"] == "closed"


def test_two_tenant_concurrent_jobs_correct_and_labeled():
    from sparkrdma_tpu.engine.context import TpuContext

    reg = get_registry()
    before = reg.snapshot(prefix="admission.admitted")
    conf = TpuShuffleConf({"tpu.shuffle.tenancy.weights": "alice:2,bob:1"})
    results = {}
    errors = []
    with TpuContext(num_executors=2, conf=conf, task_threads=4) as ctx:
        def job(tenant, n, mod):
            try:
                rdd = (
                    ctx.parallelize(range(n), 4)
                    .map(lambda x: (x % mod, 1))
                    .reduce_by_key(lambda a, b: a + b, num_partitions=4)
                )
                results[tenant] = dict(ctx.run_job(rdd, tenant=tenant))
            except Exception as e:  # noqa: BLE001
                errors.append((tenant, e))

        threads = [
            threading.Thread(target=job, args=("alice", 3000, 7)),
            threading.Thread(target=job, args=("bob", 600, 5)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    assert not errors, errors
    assert results["alice"] == {k: len(range(k, 3000, 7)) for k in range(7)}
    assert results["bob"] == {k: len(range(k, 600, 5)) for k in range(5)}
    snap = reg.snapshot()
    admitted = reg.delta(before, prefix="admission.admitted")["counters"]
    assert admitted.get("admission.admitted{tenant=alice}", 0) >= 1
    assert admitted.get("admission.admitted{tenant=bob}", 0) >= 1
    task_keys = [k for k in snap["histograms"] if k.startswith("tenant.task_ms")]
    assert any("tenant=alice" in k for k in task_keys)
    assert any("tenant=bob" in k for k in task_keys)
    engine_keys = [k for k in snap["histograms"] if k.startswith("engine.task_ms")]
    assert any("tenant=alice" in k for k in engine_keys)

def test_charge_pagecache_stalls_offender_bounded_not_neighbors():
    """The submission plane's mapped-read charge seam (DESIGN.md §24,
    ``quota.charge_pagecache``): an over-quota tenant's next mapped
    fetch stalls — bounded by ``quotaBlockMaxMs`` — while ANOTHER
    tenant's mapped fetch flows untouched, and the returned release
    callable is once-only no matter how many completion paths call it."""
    conf = TpuShuffleConf({
        "tpu.shuffle.tenancy.pageCacheQuotaBytes": "100",
        "tpu.shuffle.tenancy.quotaBlockMaxMs": "300",
    })
    _quota.install(conf)
    rel_a1 = _quota.charge_pagecache("a", 80)
    blocked = threading.Event()
    passed = threading.Event()
    releases = []

    def offender():
        blocked.set()
        releases.append(_quota.charge_pagecache("a", 80))  # over quota
        passed.set()

    t = threading.Thread(target=offender, daemon=True)
    t.start()
    blocked.wait(5)
    time.sleep(0.05)
    assert not passed.is_set(), "over-quota mapped charge should stall"
    # isolation: tenant b's mapped fetch flows while a is stalled
    t0 = time.perf_counter()
    rel_b = _quota.charge_pagecache("b", 80)
    assert time.perf_counter() - t0 < 0.5
    rel_b()
    # releasing a's held delivery unblocks the stalled fetch
    rel_a1()
    assert passed.wait(5), "release did not unblock the stalled fetch"
    t.join(timeout=5)
    broker = _quota.broker("pagecache")
    assert broker.usage("a") == 80
    # release-once: failure cleanup AND last-stream-close may both call
    releases[0]()
    releases[0]()
    assert broker.usage("a") == 0
    # the stall is BOUNDED even with no release at all
    rel_c = _quota.charge_pagecache("c", 80)
    t0 = time.perf_counter()
    rel_c2 = _quota.charge_pagecache("c", 80)
    dt = time.perf_counter() - t0
    assert 0.1 <= dt < 2.0, f"expected ~300ms bounded stall, got {dt:.3f}s"
    rel_c()
    rel_c2()


def test_charge_pagecache_noop_without_broker():
    assert _quota.broker("pagecache") is None
    rel = _quota.charge_pagecache("t", 1 << 20)  # must not charge or raise
    rel()
    rel()
