"""Multi-host bootstrap (parallel/multihost.py): arg plumbing and
idempotence of initialize(), global_mesh construction, and a real
2-process jax.distributed CPU run driving a psum over the global mesh.

The reference equivalent is the lazy full-mesh connect machinery
(RdmaNode.java:281-353) plus the driver announce fan-out; here scale-out
is jax.distributed + the (dcn, exec) mesh (SURVEY.md §2.4).
"""

import os
import socket
import subprocess
import sys
import textwrap
from unittest import mock

import jax
import pytest

from sparkrdma_tpu.parallel import multihost


def test_initialize_single_process_is_noop():
    # num_processes <= 1 must never touch jax.distributed
    with mock.patch.object(jax.distributed, "initialize") as init:
        multihost.initialize(num_processes=1)
        multihost.initialize(num_processes=0)
    init.assert_not_called()


def test_initialize_plumbs_args():
    with mock.patch.object(jax.distributed, "initialize") as init:
        multihost.initialize(
            coordinator_address="host0:1234", num_processes=4, process_id=2
        )
    init.assert_called_once_with(
        coordinator_address="host0:1234", num_processes=4, process_id=2
    )


def test_initialize_idempotent_on_already_initialized():
    # the reference's startRdmaNodeIfMissing semantics: a second call
    # must be a no-op, not an error
    with mock.patch.object(
        jax.distributed,
        "initialize",
        side_effect=RuntimeError("distributed runtime is already initialized"),
    ):
        multihost.initialize(
            coordinator_address="host0:1234", num_processes=4, process_id=2
        )


def test_initialize_propagates_real_errors():
    with mock.patch.object(
        jax.distributed,
        "initialize",
        side_effect=RuntimeError("connection refused"),
    ):
        with pytest.raises(RuntimeError, match="connection refused"):
            multihost.initialize(
                coordinator_address="host0:1234", num_processes=4, process_id=1
            )


def test_global_mesh_spans_all_devices():
    mesh = multihost.global_mesh()
    import math

    assert math.prod(mesh.shape.values()) == len(jax.devices())
    # single-slice meshes collapse to the exec axis; multi-slice adds dcn
    assert "exec" in mesh.axis_names
    assert set(mesh.axis_names) <= {"dcn", "exec"}


def test_local_device_indices_cover_local_devices():
    idx = multihost.local_device_indices()
    assert len(idx) == len(jax.local_devices())
    assert sorted(idx) == list(idx)


_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    rank = int(sys.argv[1]); port = sys.argv[2]

    from sparkrdma_tpu.parallel import multihost

    multihost.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=rank
    )
    # idempotence under a LIVE runtime, not a mock
    multihost.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=rank
    )
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 4, jax.devices()

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = multihost.global_mesh()
    assert len(mesh.devices.flat) == 4

    # one collective over the global mesh proves the bootstrap wired the
    # processes together: every shard contributes its global index
    idx = multihost.local_device_indices()
    arr = jax.make_array_from_single_device_arrays(
        (4,),
        NamedSharding(mesh, P(tuple(mesh.axis_names))),
        [
            jax.device_put(jnp.asarray([float(i)]), d)
            for i, d in zip(idx, jax.local_devices())
        ],
    )
    total = jax.jit(
        lambda x: jnp.sum(x), out_shardings=NamedSharding(mesh, P())
    )(arr)
    assert float(total) == 0.0 + 1 + 2 + 3, float(total)
    print(f"RANK{rank}_OK")
    """
)


def test_two_process_distributed_cpu_bootstrap(tmp_path):
    """Real jax.distributed: 2 processes x 2 CPU devices -> a 4-device
    global mesh and a cross-process psum."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    env = {**os.environ, "PYTHONPATH": os.getcwd()}
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(rank), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for rank in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=110)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"RANK{rank}_OK" in out, out
