"""Pallas bitonic sort: correctness of every pipeline piece in
interpreter mode (conftest pins CPU), with MAX_BLOCK_ELEMS shrunk so the
multi-round wide-stage path is exercised at test sizes.

The network sorts via Batcher's alternating-direction formulation —
element i of a run-length-k round ascends iff bit log2(k) of i is 0 —
so there is no sequence reversal anywhere (Pallas TPU has no ``rev``
lowering; reference role: the reduce-side merge-sort, SURVEY.md §3.3).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import sparkrdma_tpu.ops.pallas_sort as ps


@pytest.fixture
def small_block(monkeypatch):
    monkeypatch.setattr(ps, "MAX_BLOCK_ELEMS", 1 << 12)


def _rand(n, seed=0, dtype=np.uint32):
    rng = np.random.default_rng(seed)
    if dtype == np.uint32:
        return rng.integers(0, 1 << 32, size=n, dtype=np.uint32)
    return rng.integers(-(1 << 31), 1 << 31, size=n, dtype=np.int32)


def test_presort_rows_alternates_directions():
    x = jnp.asarray(_rand(1024, dtype=np.int32))
    v = np.asarray(ps.presort_rows(x, 256)).reshape(4, 256)
    for r in range(4):
        expect = np.sort(np.asarray(x).reshape(4, 256)[r])
        if r % 2:
            expect = expect[::-1]
        assert np.array_equal(v[r], expect)


@pytest.mark.parametrize("n_log", [13, 14, 16])
@pytest.mark.parametrize("dtype", [np.uint32, np.int32])
def test_sort_flat_small_blocks(small_block, n_log, dtype):
    """Exercises presort -> local_sort_blocks -> apply_stage ->
    merge_block across several wide rounds."""
    x = _rand(1 << n_log, seed=n_log, dtype=dtype)
    got = np.asarray(ps.sort_flat(jnp.asarray(x), row_len=512))
    assert got.dtype == x.dtype
    assert np.array_equal(got, np.sort(x))


def test_sort_flat_skewed_keys(small_block):
    """Constant runs and near-sorted data (degenerate comparator
    inputs)."""
    n = 1 << 13
    x = np.concatenate(
        [np.zeros(n // 2, np.uint32), np.full(n // 2, 7, np.uint32)]
    )
    got = np.asarray(ps.sort_flat(jnp.asarray(x), row_len=512))
    assert np.array_equal(got, np.sort(x))
    y = np.arange(n, dtype=np.uint32)[::-1].copy()
    got = np.asarray(ps.sort_flat(jnp.asarray(y), row_len=512))
    assert np.array_equal(got, np.arange(n, dtype=np.uint32))


def test_sort_flat_small_n_falls_back():
    x = _rand(1 << 10)
    got = np.asarray(ps.sort_flat(jnp.asarray(x)))
    assert np.array_equal(got, np.sort(x))


def test_sort_flat_rejects_bad_shapes():
    with pytest.raises(ValueError, match="power-of-two"):
        ps.sort_flat(jnp.zeros(1000, jnp.uint32))
    with pytest.raises(ValueError, match="row_len"):
        ps.sort_flat(jnp.zeros(1 << 13, jnp.uint32), row_len=100)


def test_sort_flat_jit_composes(small_block):
    """sort_flat must trace cleanly inside an outer jit (the bench and
    TeraSorter call it under jit)."""
    x = _rand(1 << 13, seed=3)
    f = jax.jit(lambda v: ps.sort_flat(v, row_len=512).sum())
    expect = int(np.sort(x).astype(np.uint64).sum() & 0xFFFFFFFF)
    assert int(f(jnp.asarray(x))) == expect
