from io import BytesIO

from sparkrdma_tpu.locations import (
    BlockLocation,
    PartitionLocation,
    ShuffleManagerId,
    read_locations,
    write_locations,
)


def test_block_location_roundtrip():
    loc = BlockLocation(address=0xDEADBEEF00, length=12345, mkey=7)
    buf = BytesIO()
    loc.write(buf)
    assert buf.tell() == BlockLocation.SERIALIZED_SIZE
    buf.seek(0)
    assert BlockLocation.read(buf) == loc


def test_manager_id_roundtrip_and_equality():
    a = ShuffleManagerId("host-a.example", 4440, "exec-1")
    b = ShuffleManagerId("host-b.example", 9999, "exec-1")
    c = ShuffleManagerId("host-a.example", 4440, "exec-2")
    # equality/hash on executor_id only (reference equality on blockManagerId)
    assert a == b and hash(a) == hash(b)
    assert a != c
    assert ShuffleManagerId.from_bytes(a.to_bytes()) == a
    rt = ShuffleManagerId.from_bytes(a.to_bytes())
    assert (rt.host, rt.port, rt.executor_id) == (a.host, a.port, a.executor_id)
    assert len(a.to_bytes()) == a.serialized_size()


def test_partition_location_list_roundtrip():
    mid = ShuffleManagerId("h", 1, "e0")
    locs = [
        PartitionLocation(mid, i, BlockLocation(i * 100, i, i + 1)) for i in range(10)
    ]
    buf = BytesIO()
    write_locations(buf, locs)
    buf.seek(0)
    assert read_locations(buf) == locs
