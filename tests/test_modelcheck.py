"""Model checker regression suite (docs/ANALYSIS.md "Model checking").

Two jobs: (1) replay the checked-in failing-seed fixtures — one per
protocol model, each recorded against a seeded mutant — and prove the
reproduction is deterministic (same violation, three runs in a row);
(2) smoke the unmutated tree with a short seeded random walk so a real
interleaving bug in merge seal / replica promotion / speculation /
quota backpressure fails tier-1, not just nightly.
"""

import glob
import json
import os

import pytest

from sparkrdma_tpu.analysis.modelcheck.explore import (
    load_artifact,
    random_walk,
    replay_artifact,
    save_artifact,
)
from sparkrdma_tpu.analysis.modelcheck.models import MODELS
from sparkrdma_tpu.analysis.modelcheck.mutants import MUTANTS

FIXTURE_DIR = os.path.join(
    os.path.dirname(__file__), "fixtures", "modelcheck"
)
FIXTURES = sorted(glob.glob(os.path.join(FIXTURE_DIR, "*.json")))


def test_fixture_per_model():
    # one recorded failing schedule per registered protocol model
    covered = {load_artifact(p)["model"] for p in FIXTURES}
    assert covered == set(MODELS)


@pytest.mark.parametrize(
    "path", FIXTURES, ids=[os.path.basename(p) for p in FIXTURES]
)
def test_recorded_seed_replays_deterministically(path):
    artifact = load_artifact(path)
    assert artifact["mutant"] in MUTANTS  # fixture names a live mutant
    reproduced = [replay_artifact(artifact) for _ in range(3)]
    assert reproduced[0] is not None, (
        f"{os.path.basename(path)} no longer reproduces — if the "
        "protocol legitimately changed, re-record the fixture with "
        "--emit-dir and check in the new artifact"
    )
    # identical violation text every run: replay is deterministic
    assert len(set(reproduced)) == 1
    assert reproduced[0] == artifact["violation"]


@pytest.mark.parametrize(
    "path", FIXTURES, ids=[os.path.basename(p) for p in FIXTURES]
)
def test_unmutated_tree_passes_recorded_schedule(path):
    # the same schedule is CLEAN without the mutant: the fixture
    # pins the oracle's teeth, not a bug in the shipped tree
    artifact = dict(load_artifact(path))
    artifact.pop("mutant", None)
    assert replay_artifact(artifact) is None


def test_artifact_round_trip(tmp_path):
    artifact = load_artifact(FIXTURES[0])
    out = tmp_path / "roundtrip.json"
    save_artifact(artifact, str(out))
    assert load_artifact(str(out)) == artifact


@pytest.mark.parametrize("model_name", sorted(MODELS))
def test_random_walk_smoke(model_name):
    outcome = random_walk(model_name, walks=5, seed=0)
    assert outcome["failure"] is None, outcome["failure"]
