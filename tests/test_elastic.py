"""Elastic cluster layer (docs/DESIGN.md §21, docs/RESILIENCE.md
"Elasticity"): executor-loss survival, map-output replication,
speculative execution, and the detachable shuffle-service daemon —
plus the exec fault grammar and the page-cache quota ledger that ride
along. The chaos cases run REAL worker processes and kill them with
``os._exit`` mid-job; byte-identity of the final result is the bar."""

import collections
import json
import subprocess
import sys
import time

import pytest

from sparkrdma_tpu.engine.cluster import ClusterContext
from sparkrdma_tpu.locations import (
    BlockLocation,
    PartitionLocation,
    ShuffleManagerId,
)
from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.rpc import PublishPartitionLocationsMsg, RpcMsg
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
from sparkrdma_tpu.testing import faults as _faults
from sparkrdma_tpu.testing.faults import FaultPlan, FaultRule
from sparkrdma_tpu.utils.config import TpuShuffleConf

WORDS = ["tpu", "shuffle", "rdma", "mesh", "ici", "dcn"]


# NOTE on closures: task functions must be created by factories (not
# plain module-level defs) so cloudpickle serializes them BY VALUE —
# worker subprocesses cannot import this test module by name.
def _make_map(seed, n=600):
    def fn():
        for i in range(n):
            yield (WORDS[(seed * 7 + i) % len(WORDS)], 1)

    return fn


def _counts_reducer():
    def red(it):
        acc = collections.Counter()
        for k, v in it:
            acc[k] += v
        return dict(acc)

    return red


def _expected(num_maps, n=600):
    expected = collections.Counter()
    for s in range(num_maps):
        for i in range(n):
            expected[WORDS[(s * 7 + i) % len(WORDS)]] += 1
    return expected


def _merged(parts):
    merged = collections.Counter()
    for p in parts:
        merged.update(p)
    return merged


def _collector():
    def collect(it):
        return sorted(it)

    return collect


# ----------------------------------------------------------------------
# chaos: executor kill mid-reduce -> lineage recompute of ITS maps only
# ----------------------------------------------------------------------
def test_exec_kill_mid_reduce_recomputes_only_lost_maps():
    """proc-exec-1 is hard-killed at its first REDUCE task entry. The
    job must complete byte-identically; the recovery must re-run
    exactly the two maps exec-1 owned (6 maps round-robined over 3
    workers -> maps 1 and 4) and count ONE recompute event."""
    reg = get_registry()
    rec_maps0 = reg.counter("elastic.recomputed_maps", role="driver").value
    recov0 = reg.counter("elastic.recoveries", role="driver").value
    stage0 = reg.counter("engine.stage_recomputes").value

    conf = TpuShuffleConf({
        "tpu.shuffle.faultPlan": "exec:kill:1:peer=proc-exec-1,stage=reduce_task",
    })
    try:
        with ClusterContext(num_executors=3, conf=conf) as cc:
            parts = cc.run_map_reduce(
                [_make_map(s) for s in range(6)], num_partitions=6,
                reduce_fn=_counts_reducer(),
            )
            # the dead worker was pruned from the dispatch set
            assert len(cc.workers) == 2
    finally:
        _faults.uninstall()

    merged = _merged(parts)
    assert sum(merged.values()) == 6 * 600
    assert merged == _expected(6)
    # recompute scoped to the killed executor's lineage: 2 maps, 1 event
    assert reg.counter("elastic.recomputed_maps", role="driver").value - rec_maps0 == 2
    assert reg.counter("elastic.recoveries", role="driver").value - recov0 == 1
    assert reg.counter("engine.stage_recomputes").value - stage0 == 1


# ----------------------------------------------------------------------
# chaos: same kill, but replicas cover the loss -> ZERO recompute
# ----------------------------------------------------------------------
def test_exec_kill_with_replication_skips_recompute():
    """With ``elastic.replicas=1`` every map output is mirrored to the
    next peer in the ring. The same mid-reduce kill now costs zero
    recomputed maps: the driver promotes exec-1's replicas and the
    re-issued reduce range pulls from the replica holder."""
    reg = get_registry()
    rec_maps0 = reg.counter("elastic.recomputed_maps", role="driver").value
    promos0 = reg.counter("elastic.replica_promotions", role="driver").value

    conf = TpuShuffleConf({
        "tpu.shuffle.faultPlan": "exec:kill:1:peer=proc-exec-1,stage=reduce_task",
        "tpu.shuffle.elastic.replicas": "1",
    })
    try:
        with ClusterContext(num_executors=3, conf=conf) as cc:
            parts = cc.run_map_reduce(
                [_make_map(s) for s in range(6)], num_partitions=6,
                reduce_fn=_counts_reducer(),
            )
    finally:
        _faults.uninstall()

    assert _merged(parts) == _expected(6)
    assert reg.counter("elastic.recomputed_maps", role="driver").value == rec_maps0
    assert reg.counter("elastic.replica_promotions", role="driver").value > promos0


# ----------------------------------------------------------------------
# speculation: the delayed executor gets flagged and its range cloned
# ----------------------------------------------------------------------
def test_speculation_clones_flagged_straggler():
    """proc-exec-2 is slowed at one map (feeding the telemetry
    straggler detector a real busy-ms outlier) and then wedged for
    2.5 s at its reduce. With speculation on, the driver's monitor
    must flag exactly that executor, clone its in-flight range onto a
    healthy peer, and take the clone's result — byte-identically."""
    reg = get_registry()
    specs0 = reg.counter("elastic.speculations", role="driver").value
    wins0 = reg.counter("elastic.speculation_wins", role="driver").value

    conf = TpuShuffleConf({
        "tpu.shuffle.faultPlan": (
            "stage:delay:1:peer=proc-exec-2,stage=map_task,delay_ms=1200;"
            "stage:delay:1:peer=proc-exec-2,stage=reduce_task,delay_ms=2500"
        ),
        "tpu.shuffle.elastic.speculation": "true",
        "tpu.shuffle.elastic.speculationCheckMs": "100",
        "tpu.shuffle.obs.telemetry.intervalMs": "100",
        "tpu.shuffle.obs.telemetry.stragglerZ": "1",
    })
    try:
        with ClusterContext(num_executors=4, conf=conf) as cc:
            parts = cc.run_map_reduce(
                [_make_map(s) for s in range(8)], num_partitions=4,
                reduce_fn=_counts_reducer(),
            )
            report = cc.driver.telemetry.straggler_report()
            assert "proc-exec-2" in report["stragglers"]
            assert "proc-exec-2" in report["suspect_keys"]
    finally:
        _faults.uninstall()

    assert _merged(parts) == _expected(8)
    assert reg.counter("elastic.speculations", role="driver").value > specs0
    assert reg.counter("elastic.speculation_wins", role="driver").value > wins0


# ----------------------------------------------------------------------
# shuffle-service daemon: handoff, then survive the executor's death
# ----------------------------------------------------------------------
def test_shuffle_service_handoff_survives_executor_kill():
    """A detached ``python -m sparkrdma_tpu.elastic.service`` process
    adopts proc-exec-0's committed map outputs (hard links + re-mmap,
    no byte copy). While the executor lives the daemon is invisible;
    after a SIGKILL + peer-loss the daemon's locations are promoted
    and the surviving worker reads the SAME bytes from the daemon."""
    from sparkrdma_tpu.elastic.service import _recv_obj, _send_obj
    import socket as socket_mod

    def svc_request(port, obj):
        with socket_mod.create_connection(("127.0.0.1", port), timeout=10) as s:
            s.settimeout(10)
            _send_obj(s, obj)
            return _recv_obj(s)

    svc = None
    try:
        with ClusterContext(num_executors=2) as cc:
            handle = BaseShuffleHandle(
                shuffle_id=cc._next_shuffle_id(),
                num_maps=4,
                partitioner=HashPartitioner(4),
            )
            cc.driver.register_shuffle(handle)
            items = list(enumerate(_make_map(s, n=300) for s in range(4)))
            cc._run_map_phase(handle, items, "default", recompute=False)

            def read_all(worker):
                return worker.request({
                    "kind": "reduce", "handle": handle, "start": 0, "end": 4,
                    "reduce_fn": _collector(), "tenant": "default",
                })

            baseline = read_all(cc.workers[1])
            assert len(baseline) == 4 * 300

            conf_json = json.dumps(cc.conf.to_dict())
            svc = subprocess.Popen(
                [
                    sys.executable, "-m", "sparkrdma_tpu.elastic.service",
                    "--service-id", "svc-test", "--conf", conf_json,
                ],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            )
            deadline = time.monotonic() + 30
            port = None
            while time.monotonic() < deadline:
                line = svc.stdout.readline()
                if not line:
                    raise RuntimeError("service daemon exited before announcing")
                if line.startswith("SERVICE_PORT "):
                    port = int(line.split()[1])
                    break
            assert port is not None
            assert svc_request(port, {"kind": "ping"})["result"] == "pong"

            # executor 0 hands its blocks over: metadata only, the
            # daemon republishes them as replicas (parked, invisible)
            adopted = cc.workers[0].request(
                {"kind": "handoff", "service": ("127.0.0.1", port)}
            )
            assert adopted == 2  # exec-0 owned maps 0 and 2
            assert read_all(cc.workers[1]) == baseline  # still invisible

            # now the executor dies; the daemon's copies get promoted
            w0 = cc.workers[0]
            w0.proc.kill()
            w0.proc.wait(timeout=10)
            dead = cc._reap_dead()
            assert [w.executor_id for w in dead] == ["proc-exec-0"]

            assert read_all(cc.workers[0]) == baseline  # survivor reads daemon

            assert svc_request(port, {"kind": "stop"})["ok"]
            svc.wait(timeout=15)
            svc = None
    finally:
        if svc is not None:
            svc.kill()


# ----------------------------------------------------------------------
# fault grammar: the exec seam
# ----------------------------------------------------------------------
def test_exec_fault_rule_parse():
    r = FaultRule.parse("exec:kill:1:peer=proc-exec-1,stage=reduce_task")
    assert (r.op, r.kind, r.count) == ("exec", "kill", 1)
    assert r.peer == "proc-exec-1" and r.stage == "reduce_task"
    r = FaultRule.parse("exec:hang:2:delay_ms=50")
    assert (r.op, r.kind, r.count, r.delay_ms) == ("exec", "hang", 2, 50)
    with pytest.raises(ValueError):
        FaultRule.parse("exec:explode:1")


def test_exec_hang_blocks_for_delay():
    plan = FaultPlan.parse("exec:hang:1:delay_ms=30")
    t0 = time.perf_counter()
    plan.on_exec("exec-0", stage="map_task")
    assert time.perf_counter() - t0 >= 0.025
    assert plan.injected_count("exec", "hang") == 1
    # budget exhausted: the next entry sails through instantly
    t0 = time.perf_counter()
    plan.on_exec("exec-0", stage="map_task")
    assert time.perf_counter() - t0 < 0.02


def test_exec_kill_filters_never_fire_off_target():
    """A kill rule scoped by peer/stage must NOT fire elsewhere — if
    it did, this test process would be dead (os._exit)."""
    plan = FaultPlan.parse("exec:kill:1:peer=proc-exec-9,stage=reduce_task")
    plan.on_exec("proc-exec-1", stage="reduce_task")  # wrong peer
    plan.on_exec("proc-exec-9", stage="map_task")  # wrong stage
    assert plan.injected_count("exec", "kill") == 0
    # non-exec rules never burn budget at the exec seam and vice versa
    plan2 = FaultPlan.parse("read:fail:1")
    plan2.on_exec("proc-exec-1", stage="map_task")
    assert plan2.total_injected == 0


# ----------------------------------------------------------------------
# page-cache quota ledger (mapped zero-copy fetches)
# ----------------------------------------------------------------------
def test_pagecache_quota_broker_install_and_ledger():
    from sparkrdma_tpu.tenancy import quota

    quota.reset()
    try:
        # unconfigured -> no broker, the mapped fetch path stays free
        quota.install(TpuShuffleConf())
        assert quota.broker("pagecache") is None
        quota.reset()

        conf = TpuShuffleConf({"tpu.shuffle.tenancy.pageCacheQuotaBytes": "1m"})
        quota.install(conf)
        b = quota.broker("pagecache")
        assert b is not None
        b.charge("tenant-a", 512 * 1024)
        assert b.usage("tenant-a") == 512 * 1024
        b.release("tenant-a", 512 * 1024)
        assert b.usage("tenant-a") == 0
    finally:
        quota.reset()


# ----------------------------------------------------------------------
# wire: the elastic trailing extension
# ----------------------------------------------------------------------
def _loc(pid, length, mkey, replica_of="", source_map=-1, eid="e"):
    return PartitionLocation(
        ShuffleManagerId("host", 1234, eid),
        pid,
        BlockLocation(
            0, length, mkey, replica_of=replica_of, source_map=source_map
        ),
    )


def test_publish_msg_elastic_extension_roundtrip():
    locs = [
        _loc(0, 100, 7, replica_of="proc-exec-1", source_map=3, eid="svc"),
        _loc(1, 200, 8),
    ]
    msg = PublishPartitionLocationsMsg(5, -1, locs, trace_id=0xE1A)
    out = [RpcMsg.parse_segment(s) for s in msg.to_segments(4096)]
    got = sorted(
        (loc for m in out for loc in m.locations),
        key=lambda loc: loc.partition_id,
    )
    assert got[0].block.replica_of == "proc-exec-1"
    assert got[0].block.source_map == 3
    assert got[0].block.is_replica
    assert not got[1].block.is_replica and got[1].block.source_map == -1
    assert all(m.trace_id == 0xE1A for m in out)


def test_publish_msg_without_elastic_tags_is_byte_identical_legacy():
    locs = [_loc(0, 64, 3), _loc(1, 64, 4)]
    msg = PublishPartitionLocationsMsg(2, -1, locs)
    baseline = PublishPartitionLocationsMsg(
        2, -1,
        [
            PartitionLocation(
                loc.manager_id, loc.partition_id,
                BlockLocation(loc.block.address, loc.block.length, loc.block.mkey),
            )
            for loc in locs
        ],
    )
    assert msg.to_segments(4096) == baseline.to_segments(4096)


def test_publish_msg_elastic_ext_survives_segmentation():
    """Replica identities stay attached to THEIR location across
    segment splits (per-segment extension tables, variable items)."""
    locs = [
        _loc(i, 10 + i, 100 + i, replica_of=f"proc-exec-{i % 4}",
             source_map=i, eid="svc")
        for i in range(40)
    ]
    msg = PublishPartitionLocationsMsg(9, -1, locs)
    segments = msg.to_segments(256)
    assert len(segments) > 1
    got = []
    for seg in segments:
        got.extend(RpcMsg.parse_segment(seg).locations)
    assert len(got) == 40
    for i, loc in enumerate(sorted(got, key=lambda x: x.partition_id)):
        assert loc.block.replica_of == f"proc-exec-{i % 4}"
        assert loc.block.source_map == i


# ----------------------------------------------------------------------
# advisory plumbing: tenant-scoped suspect keys
# ----------------------------------------------------------------------
def test_health_registry_applies_suspect_keys():
    from sparkrdma_tpu.resilience.health import SourceHealthRegistry

    reg = SourceHealthRegistry(TpuShuffleConf(), role="t")
    reg.apply_straggler_report({
        "suspect_keys": ["proc-exec-2", "team-b:proc-exec-3"],
        "stragglers": ["ignored-when-keys-present"],
        "generated_wall_ms": 1,
    })
    assert set(reg.suspects()) == {"proc-exec-2", "team-b:proc-exec-3"}
    # a suspect never opens the circuit: advisory only
    assert reg.allow("proc-exec-2")
    # older hubs without suspect_keys fall back to the bare list
    reg.apply_straggler_report({"stragglers": ["proc-exec-4"]})
    assert set(reg.suspects()) == {"proc-exec-4"}
    # and an empty report clears the slate
    reg.apply_straggler_report({"suspect_keys": []})
    assert reg.suspects() == {}


# ----------------------------------------------------------------------
# in-process engine: executor loss behind the partition router
# ----------------------------------------------------------------------
def test_inprocess_context_survives_executor_loss_with_replication():
    """TpuContext.lose_executor: with ring replication on, dropping an
    executor after the map stage leaves the shuffle fully covered by
    promoted replicas — a re-read of the same materialized shuffle
    completes byte-identically with zero stage recomputes."""
    from sparkrdma_tpu.engine.context import TpuContext

    conf = TpuShuffleConf({"tpu.shuffle.elastic.replicas": "1"})
    ctx = TpuContext(num_executors=3, conf=conf)
    try:
        words = [WORDS[i % 6] for i in range(3000)]
        rdd = (
            ctx.parallelize(words, 6)
            .map(lambda w: (w, 1))
            .reduce_by_key(lambda a, b: a + b)
        )
        expected = dict(collections.Counter(words))
        assert dict(rdd.collect()) == expected  # materializes the shuffle

        reg = get_registry()
        recomputes0 = reg.counter("engine.stage_recomputes").value
        promos0 = reg.counter(
            "elastic.replica_promotions", role=ctx.driver.executor_id
        ).value

        ctx.lose_executor(ctx.executors[1].executor_id)
        assert len(ctx.executors) == 2

        # same materialized shuffle, re-read through the survivors
        assert dict(rdd.collect()) == expected
        assert reg.counter("engine.stage_recomputes").value == recomputes0
        assert (
            reg.counter(
                "elastic.replica_promotions", role=ctx.driver.executor_id
            ).value
            > promos0
        )
    finally:
        ctx.stop()
