"""Device shuffle IO: HBM -> registered host memory -> one-sided READ -> HBM."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparkrdma_tpu.shuffle.device_io import DeviceShuffleIO
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.utils.config import TpuShuffleConf


@pytest.fixture
def cluster():
    conf = TpuShuffleConf()
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="exec-0")
    ex1 = TpuShuffleManager(conf, is_driver=False, executor_id="exec-1")
    yield conf, driver, ex0, ex1
    ex0.stop()
    ex1.stop()
    driver.stop()


def test_device_block_shuffle_roundtrip(cluster):
    conf, driver, ex0, ex1 = cluster
    handle = BaseShuffleHandle(shuffle_id=1, num_maps=2, partitioner=HashPartitioner(4))
    driver.register_shuffle(handle)

    io0 = DeviceShuffleIO(ex0)
    io1 = DeviceShuffleIO(ex1)
    try:
        # each executor publishes two device-array partitions
        a = {0: jnp.arange(100, dtype=jnp.uint8), 1: jnp.ones((300,), jnp.uint8)}
        b = {2: jnp.full((50,), 7, jnp.uint8), 3: jnp.zeros((200,), jnp.uint8)}
        io0.publish_device_blocks(1, a)
        io1.publish_device_blocks(1, b)

        # ex0 pulls everything (partitions 2,3 are remote one-sided READs,
        # 0,1 short-circuit locally)
        got = io0.fetch_device_blocks(1, 0, 4)
        assert set(got) == {0, 1, 2, 3}
        np.testing.assert_array_equal(
            np.frombuffer(got[0][0].read(), np.uint8), np.arange(100, dtype=np.uint8)
        )
        np.testing.assert_array_equal(
            np.frombuffer(got[2][0].read(), np.uint8), np.full((50,), 7, np.uint8)
        )
        # fetched blocks live in HBM slabs under the device pool budget
        assert io0.device_buffers.in_use_bytes > 0
        for bufs in got.values():
            for buf in bufs:
                buf.free()
        assert io0.device_buffers.in_use_bytes == 0
    finally:
        io0.stop()
        io1.stop()


def test_unpublish_releases_registered_buffers(cluster):
    conf, driver, ex0, ex1 = cluster
    handle = BaseShuffleHandle(shuffle_id=2, num_maps=1, partitioner=HashPartitioner(1))
    driver.register_shuffle(handle)
    io0 = DeviceShuffleIO(ex0)
    try:
        before = ex0.node.pd.region_count()
        io0.publish_device_blocks(2, {0: jnp.arange(64, dtype=jnp.uint8)})
        assert ex0.node.pd.region_count() > before or True  # pooled reuse possible
        io0.unpublish(2)
        # pooled buffer returned; a new publish reuses it
        io0.publish_device_blocks(2, {0: jnp.arange(64, dtype=jnp.uint8)})
        io0.unpublish(2)
    finally:
        io0.stop()
