"""Device shuffle IO: HBM -> registered host memory -> one-sided READ -> HBM."""

import numpy as np
import pytest

import jax.numpy as jnp

from sparkrdma_tpu.shuffle.device_io import DeviceShuffleIO
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.utils.config import TpuShuffleConf


@pytest.fixture
def cluster():
    # python transport: several tests here script TpuChannel read
    # behavior (fault/deadline/ordering) at the python verb layer; the
    # auto default would resolve to native and bypass those seams
    conf = TpuShuffleConf({"tpu.shuffle.transport": "python"})
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="exec-0")
    ex1 = TpuShuffleManager(conf, is_driver=False, executor_id="exec-1")
    yield conf, driver, ex0, ex1
    ex0.stop()
    ex1.stop()
    driver.stop()


def test_device_block_shuffle_roundtrip(cluster):
    conf, driver, ex0, ex1 = cluster
    handle = BaseShuffleHandle(shuffle_id=1, num_maps=2, partitioner=HashPartitioner(4))
    driver.register_shuffle(handle)

    io0 = DeviceShuffleIO(ex0)
    io1 = DeviceShuffleIO(ex1)
    try:
        # each executor publishes two device-array partitions
        a = {0: jnp.arange(100, dtype=jnp.uint8), 1: jnp.ones((300,), jnp.uint8)}
        b = {2: jnp.full((50,), 7, jnp.uint8), 3: jnp.zeros((200,), jnp.uint8)}
        io0.publish_device_blocks(1, a)
        io1.publish_device_blocks(1, b)

        # ex0 pulls everything (partitions 2,3 are remote one-sided READs,
        # 0,1 short-circuit locally)
        got = io0.fetch_device_blocks(1, 0, 4)
        assert set(got) == {0, 1, 2, 3}
        np.testing.assert_array_equal(
            np.frombuffer(got[0][0].read(), np.uint8), np.arange(100, dtype=np.uint8)
        )
        np.testing.assert_array_equal(
            np.frombuffer(got[2][0].read(), np.uint8), np.full((50,), 7, np.uint8)
        )
        # fetched blocks live in HBM slabs under the device pool budget
        assert io0.device_buffers.in_use_bytes > 0
        for bufs in got.values():
            for buf in bufs:
                buf.free()
        assert io0.device_buffers.in_use_bytes == 0
    finally:
        io0.stop()
        io1.stop()


def test_fetch_under_hbm_budget_pressure_spills_and_survives():
    """A tight ``hbm.maxBytes`` forces staged blocks to spill to the
    host tier DURING a fetch; held buffers stay readable (transparent
    host-tier read), restore on demand, and the budget never exceeds
    the cap. This drives SURVEY §7.3(4)'s tiered HBM->host store
    through the real publish/fetch stack rather than the pool alone."""
    conf = TpuShuffleConf({"tpu.shuffle.hbm.maxBytes": str(64 * 1024)})
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="sp-0")
    ex1 = TpuShuffleManager(conf, is_driver=False, executor_id="sp-1")
    parts = 6
    handle = BaseShuffleHandle(
        shuffle_id=9, num_maps=2, partitioner=HashPartitioner(parts)
    )
    driver.register_shuffle(handle)
    io0, io1 = DeviceShuffleIO(ex0), DeviceShuffleIO(ex1)
    rng = np.random.default_rng(5)
    # 12 blocks x 16 KiB class = 192 KiB of staging demand vs a 64 KiB cap
    data = {
        (m, p): rng.integers(0, 256, 16 * 1024 - 128, dtype=np.uint8)
        for m in range(2)
        for p in range(parts)
    }
    try:
        io0.publish_device_blocks(9, {p: data[(0, p)] for p in range(parts)})
        io1.publish_device_blocks(9, {p: data[(1, p)] for p in range(parts)})
        held = io0.fetch_device_blocks(9, 0, parts, timeout_s=60)
        pool = io0.device_buffers
        assert pool.spill_count > 0, "cap of 4 slabs never spilled"
        assert pool.in_use_bytes <= 64 * 1024
        spilled = [b for bufs in held.values() for b in bufs if b.spilled]
        assert spilled, "no held buffer ended up on the host tier"
        # every block byte-exact, whichever tier it lives on
        for p, bufs in held.items():
            got = sorted(b.read(0, b.length) for b in bufs)
            want = sorted(data[(m, p)].tobytes() for m in range(2))
            assert got == want, f"partition {p} bytes differ under spill"
        # explicit restore works and respects the cap by evicting others
        spilled[0].ensure_device()
        assert not spilled[0].spilled
        assert pool.in_use_bytes <= 64 * 1024
        for bufs in held.values():
            for b in bufs:
                b.free()
        assert pool.in_use_bytes == 0
    finally:
        io0.stop()
        io1.stop()
        ex0.stop()
        ex1.stop()
        driver.stop()


def test_fetch_fault_surfaces_and_leaks_nothing(cluster, monkeypatch):
    """Inject a READ fault at the verb seam during a device-block
    fetch: the caller gets FetchFailedError (engine recompute signal,
    SURVEY §5.1 #9) and BOTH pools drain — staged HBM slabs freed,
    every in-flight registered destination buffer reclaimed by
    whichever of caller/listener turns out to be its last owner."""
    import threading

    from sparkrdma_tpu.shuffle.errors import FetchFailedError
    from sparkrdma_tpu.transport.channel import ChannelError, TpuChannel

    conf, driver, ex0, ex1 = cluster
    handle = BaseShuffleHandle(
        shuffle_id=5, num_maps=2, partitioner=HashPartitioner(4)
    )
    driver.register_shuffle(handle)
    io0, io1 = DeviceShuffleIO(ex0), DeviceShuffleIO(ex1)
    rng = np.random.default_rng(3)
    try:
        io0.publish_device_blocks(
            5, {p: rng.integers(0, 256, 5000, np.uint8) for p in range(4)}
        )
        io1.publish_device_blocks(
            5, {p: rng.integers(0, 256, 5000, np.uint8) for p in range(4)}
        )
        state = {"remaining": 1}
        lock = threading.Lock()
        original = TpuChannel.read_in_queue

        def flaky(self, listener, dst_views, blocks):
            with lock:
                inject = state["remaining"] > 0
                if inject:
                    state["remaining"] -= 1
            if inject:
                listener.on_failure(ChannelError("injected device-fetch fault"))
                return
            return original(self, listener, dst_views, blocks)

        monkeypatch.setattr(TpuChannel, "read_in_queue", flaky)
        with pytest.raises(FetchFailedError):
            io0.fetch_device_blocks(5, 0, 4, timeout_s=30)
        # nothing leaked on either tier
        assert io0.device_buffers.in_use_bytes == 0
        # all registered destination buffers back in the pool: a clean
        # retry (fault healed) succeeds and is byte-exact
        state["remaining"] = 0
        got = io0.fetch_device_blocks(5, 0, 4, timeout_s=30)
        assert sum(len(b) for b in got.values()) == 8
        for bufs in got.values():
            for b in bufs:
                b.free()
        assert io0.device_buffers.in_use_bytes == 0
    finally:
        io0.stop()
        io1.stop()


def test_fetch_deadline_is_total_not_per_block(cluster, monkeypatch):
    """One slow peer costs at most ONE timeout: ``timeout_s`` is a
    deadline for the whole fetch (RdmaShuffleFetcherIterator.scala:
    108-122 semantics), so wall stays ~timeout_s even with every
    remote block wedged — not n_blocks x timeout_s."""
    import threading
    import time as _time

    from sparkrdma_tpu.shuffle.errors import FetchFailedError
    from sparkrdma_tpu.transport.channel import TpuChannel

    conf, driver, ex0, ex1 = cluster
    handle = BaseShuffleHandle(
        shuffle_id=11, num_maps=2, partitioner=HashPartitioner(4)
    )
    driver.register_shuffle(handle)
    io0, io1 = DeviceShuffleIO(ex0), DeviceShuffleIO(ex1)
    rng = np.random.default_rng(7)
    timers = []
    try:
        io0.publish_device_blocks(
            11, {p: rng.integers(0, 256, 5000, np.uint8) for p in range(4)}
        )
        io1.publish_device_blocks(
            11, {p: rng.integers(0, 256, 5000, np.uint8) for p in range(4)}
        )

        def wedged(self, listener, dst_views, blocks):
            # every remote read "completes" far beyond the deadline
            t = threading.Timer(30.0, lambda: listener.on_success(None))
            t.daemon = True
            timers.append(t)
            t.start()

        monkeypatch.setattr(TpuChannel, "read_in_queue", wedged)
        t0 = _time.perf_counter()
        with pytest.raises(FetchFailedError, match="deadline"):
            io0.fetch_device_blocks(11, 0, 4, timeout_s=1.5)
        wall = _time.perf_counter() - t0
        # 4 wedged blocks: per-block waits would take ~6 s; one shared
        # deadline takes ~1.5 s
        assert wall < 4.0, f"fetch wall {wall:.1f}s — deadline not shared"
        assert io0.device_buffers.in_use_bytes == 0
    finally:
        for t in timers:
            t.cancel()
        io0.stop()
        io1.stop()


def test_fetch_stages_in_arrival_order(cluster, monkeypatch):
    """A delayed block must not hold up the staging of blocks that
    already arrived: staging is completion-driven, so the slow block
    stages LAST regardless of issue order."""
    import threading

    from sparkrdma_tpu.transport.channel import TpuChannel

    conf, driver, ex0, ex1 = cluster
    handle = BaseShuffleHandle(
        shuffle_id=12, num_maps=1, partitioner=HashPartitioner(4)
    )
    driver.register_shuffle(handle)
    io0, io1 = DeviceShuffleIO(ex0), DeviceShuffleIO(ex1)
    rng = np.random.default_rng(9)
    slow_len = 7777  # unique length marks the delayed block
    try:
        # remote publisher: partition 0 (issued FIRST) is the slow one
        io1.publish_device_blocks(
            12,
            {
                0: rng.integers(0, 256, slow_len, np.uint8),
                **{p: rng.integers(0, 256, 5000, np.uint8) for p in (1, 2, 3)},
            },
        )
        original = TpuChannel.read_in_queue

        def delaying(self, listener, dst_views, blocks):
            if blocks[0][2] == slow_len:
                t = threading.Timer(
                    0.8, lambda: original(self, listener, dst_views, blocks)
                )
                t.daemon = True
                t.start()
                return
            return original(self, listener, dst_views, blocks)

        monkeypatch.setattr(TpuChannel, "read_in_queue", delaying)
        staged_lens = []
        real_stage = io0.device_buffers.stage_view

        def recording(view, valid_len=None, dtype=np.uint8):
            staged_lens.append(valid_len)
            return real_stage(view, valid_len, dtype)

        monkeypatch.setattr(io0.device_buffers, "stage_view", recording)
        got = io0.fetch_device_blocks(12, 0, 4, timeout_s=30)
        assert sum(len(b) for b in got.values()) == 4
        assert staged_lens[-1] == slow_len, (
            f"slow block staged at position {staged_lens.index(slow_len)} "
            f"of {len(staged_lens)} — staging followed issue order"
        )
        for bufs in got.values():
            for b in bufs:
                b.free()
    finally:
        io0.stop()
        io1.stop()


def test_mapped_fetch_fault_releases_late_delivery(cluster, monkeypatch):
    """Mapped-delivery ownership dance under failure: when one mapped
    read fails and another's delivery arrives AFTER the caller has
    abandoned the fetch, the listener (now the last owner) must
    release the delivery — mappings must never outlive the fetch."""
    import threading
    import time as _time

    from sparkrdma_tpu.shuffle.errors import FetchFailedError
    from sparkrdma_tpu.transport.channel import ChannelError

    conf, driver, ex0, ex1 = cluster
    handle = BaseShuffleHandle(
        shuffle_id=13, num_maps=1, partitioner=HashPartitioner(2)
    )
    driver.register_shuffle(handle)
    io0, io1 = DeviceShuffleIO(ex0), DeviceShuffleIO(ex1)
    rng = np.random.default_rng(21)
    released = []
    timers = []

    class FakeDelivery:
        def __init__(self, payload):
            self.views = [memoryview(payload)]
            self.mapped = True

        def release(self):
            released.append(True)

    try:
        io1.publish_device_blocks(
            13, {p: rng.integers(0, 256, 4000, np.uint8) for p in range(2)}
        )
        calls = {"n": 0}

        def fake_mapped(listener, blocks):
            calls["n"] += 1
            if calls["n"] == 1:
                # delivery arrives late, after the fetch has failed
                t = threading.Timer(
                    0.5,
                    lambda: listener.on_success(FakeDelivery(b"z" * blocks[0][2])),
                )
                t.daemon = True
                timers.append(t)
                t.start()
            else:
                listener.on_failure(ChannelError("injected mapped fault"))

        # force the mapped path regardless of transport flavor by
        # presenting a channel-like object with read_mapped_in_queue
        real_get = ex0.get_channel_to

        class MappedOnly:
            def __init__(self, ch):
                self._ch = ch

            def read_mapped_in_queue(self, listener, blocks):
                fake_mapped(listener, blocks)

        monkeypatch.setattr(
            ex0, "get_channel_to",
            lambda mid, purpose="rpc": MappedOnly(real_get(mid, purpose)),
        )
        with pytest.raises(FetchFailedError):
            io0.fetch_device_blocks(13, 0, 2, timeout_s=10)
        # the late delivery must have been released by the listener side
        deadline = _time.time() + 5
        while not released and _time.time() < deadline:
            _time.sleep(0.05)
        assert released, "late mapped delivery leaked (release never called)"
        assert io0.device_buffers.in_use_bytes == 0
    finally:
        for t in timers:
            t.cancel()
        io0.stop()
        io1.stop()


def test_unpublish_releases_registered_buffers(cluster):
    conf, driver, ex0, ex1 = cluster
    handle = BaseShuffleHandle(shuffle_id=2, num_maps=1, partitioner=HashPartitioner(1))
    driver.register_shuffle(handle)
    io0 = DeviceShuffleIO(ex0)
    try:
        before = ex0.node.pd.region_count()
        io0.publish_device_blocks(2, {0: jnp.arange(64, dtype=jnp.uint8)})
        assert ex0.node.pd.region_count() > before or True  # pooled reuse possible
        io0.unpublish(2)
        # pooled buffer returned; a new publish reuses it
        io0.publish_device_blocks(2, {0: jnp.arange(64, dtype=jnp.uint8)})
        io0.unpublish(2)
    finally:
        io0.stop()
