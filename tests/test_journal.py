"""Cluster event journal + USE-method capacity plane — ISSUE 20's
tentpole acceptance tests: HLC ordering under concurrent emitters,
idempotent gap-tolerant heartbeat merge, the zero-overhead off switch,
the two-tenant quota-backpressure capacity e2e, and the driver:kill
chaos e2e (merged order reproduces kill -> takeover -> adoption, flight
records attach events, diagnosis cites journal evidence)."""

import json
import threading
import time
from types import SimpleNamespace

import pytest

from sparkrdma_tpu.obs import journal as journal_mod
from sparkrdma_tpu.obs.capacity import RESOURCES, CapacityPlane
from sparkrdma_tpu.obs.diagnose import build_diagnosis
from sparkrdma_tpu.obs.journal import (
    HLC,
    EventJournal,
    JournalHub,
    extract_events,
    render_timeline,
    sort_key,
)
from sparkrdma_tpu.obs.metrics import MetricsRegistry, get_registry
from sparkrdma_tpu.obs.telemetry import Heartbeater, TelemetryHub
from sparkrdma_tpu.tenancy import quota as _quota
from sparkrdma_tpu.utils.config import TpuShuffleConf


@pytest.fixture(autouse=True)
def _fresh_journal():
    journal_mod.reset()
    yield
    journal_mod.reset()


# ---------------------------------------------------------------------------
# HLC units
# ---------------------------------------------------------------------------

def test_hlc_tick_is_monotonic_within_and_across_walls():
    c = HLC()
    assert c.tick(100) == (100, 0)
    assert c.tick(100) == (100, 1)  # same ms: counter breaks the tie
    assert c.tick(99) == (100, 2)   # wall went backward: l holds
    assert c.tick(101) == (101, 0)  # wall advanced: counter resets


def test_hlc_observe_orders_local_events_after_remote():
    a, b = HLC(), HLC()
    remote = a.tick(500)
    # b's wall is BEHIND a's (skew): observing must still order b's
    # next event after the message it received
    b.observe(remote, wall_ms=300)
    assert b.tick(300) > remote


# ---------------------------------------------------------------------------
# ordering property: concurrent emitters, heartbeat-shipped merge
# ---------------------------------------------------------------------------

def test_concurrent_emitters_merge_to_total_order():
    """Three processes (journals) emitting from four threads each,
    batches shipped concurrently: the merged journal is totally ordered
    by (hlc, origin, seq), per-emitter seq order survives, nothing is
    duplicated or lost."""
    reg = MetricsRegistry()
    hub = JournalHub(reg, ring_size=1 << 14)
    journals = [
        EventJournal(f"exec-{i}", origin=f"proc-{i}", ring_size=1 << 12,
                     registry=reg)
        for i in range(3)
    ]
    per_thread = 50

    def emitter(j, t):
        cursor = 0
        for k in range(per_thread):
            j.emit("autotune.adjust", executor=j.role, beat=k, thread=t)
            if k % 7 == 0:
                batch = j.events_since(cursor)
                if batch:
                    cursor = batch[-1]["seq"]
                    hub.ingest(batch)

    threads = [
        threading.Thread(target=emitter, args=(j, t))
        for j in journals for t in range(4)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    for j in journals:  # final flush
        hub.ingest(j.events())

    merged = hub.merged()
    total = 3 * 4 * per_thread
    assert len(merged) == total
    keys = [sort_key(e) for e in merged]
    assert keys == sorted(keys)
    assert len(set(keys)) == total  # total order: no ties, no dups
    for origin in ("proc-0", "proc-1", "proc-2"):
        seqs = [e["seq"] for e in merged if e["origin"] == origin]
        assert seqs == sorted(seqs)  # per-emitter order preserved
        assert len(seqs) == 4 * per_thread


def test_hub_ingest_folds_causality_into_local_clock():
    """An event emitted by the hub's process AFTER ingesting a remote
    batch must sort after the remote events, regardless of wall skew."""
    reg = MetricsRegistry()
    future = 10_000_000_000_000  # remote wall far ahead of local
    remote = EventJournal("exec-9", origin="proc-9", registry=reg,
                          clock=lambda: future / 1000.0)
    local = journal_mod.configure(role="driver", registry=reg)
    hub = JournalHub(reg)
    hub.ingest(remote.events_since(0) or [remote.emit("circuit.open")])
    after = local.emit("slo.page")
    assert sort_key(after) > sort_key(remote.events()[-1])


# ---------------------------------------------------------------------------
# idempotent merge, one-beat redundancy, gap tolerance
# ---------------------------------------------------------------------------

def test_merge_is_idempotent_under_replay():
    reg = MetricsRegistry()
    hub = JournalHub(reg)
    j = EventJournal("e0", origin="p0", registry=reg)
    batch = [j.emit("quota.block", tenant="t1") for _ in range(5)]
    assert hub.ingest(batch) == 5
    assert hub.ingest(batch) == 0  # replay folds to nothing
    assert hub.ingest(list(reversed(batch))) == 0
    assert len(hub.merged()) == 5
    assert hub.summary()["duplicates"] == 10


def test_one_beat_redundancy_survives_single_lost_heartbeat():
    """The heartbeater re-ships the previous beat's batch, so dropping
    any ONE payload loses nothing and counts no gap."""
    reg = MetricsRegistry()
    j = EventJournal("e0", origin="p0", registry=reg)
    got = []
    hb = Heartbeater(reg, "e0", interval_ms=50, send=got.append)
    hb.attach_journal(j)
    for k in range(4):
        j.emit("admission.enqueue", queue_depth=k)
        hb.beat()
    assert [len(p.get("journal", [])) for p in got] == [1, 2, 2, 2]
    hub = JournalHub(reg)
    for i, payload in enumerate(got):
        if i == 1:  # the lost heartbeat
            continue
        hub.ingest(payload["journal"])
    merged = hub.merged()
    assert [e["seq"] for e in merged] == [1, 2, 3, 4]  # nothing lost
    assert hub.summary()["gaps"] == 0


def test_gap_is_counted_but_never_fatal():
    """Two consecutive lost beats exceed the redundancy budget: the seq
    jump is counted under journal.gaps and the merge proceeds."""
    reg = MetricsRegistry()
    j = EventJournal("e0", origin="p0", registry=reg)
    events = [j.emit("straggler.flag", executor=f"e{k}") for k in range(6)]
    hub = JournalHub(reg)
    hub.ingest(events[:2])
    hub.ingest(events[5:])  # seq 3,4,5 vanished with their beats
    assert hub.summary()["gaps"] == 3
    assert [e["seq"] for e in hub.merged()] == [1, 2, 6]


# ---------------------------------------------------------------------------
# off switch
# ---------------------------------------------------------------------------

def test_disabled_journal_emit_is_a_none_check():
    journal_mod.configure(
        TpuShuffleConf({"tpu.shuffle.obs.journal.enabled": "false"}),
        role="proc",
    )
    assert journal_mod.active_journal() is None
    assert journal_mod.emit("quota.block", tenant="t") is None
    with pytest.raises(RuntimeError):
        journal_mod.get_journal()


def test_set_enabled_preserves_seq_and_ring():
    j = journal_mod.configure(role="proc", registry=MetricsRegistry())
    j.emit("circuit.open")
    journal_mod.set_enabled(False)
    assert journal_mod.emit("circuit.close") is None  # swallowed
    journal_mod.set_enabled(True)
    e = journal_mod.emit("circuit.close")
    assert journal_mod.active_journal() is j  # same object restored
    assert e["seq"] == 2  # seq continuity across the flip
    assert [x["kind"] for x in j.events()] == ["circuit.open",
                                               "circuit.close"]


def test_heartbeat_payload_omits_journal_when_disabled():
    journal_mod.configure(enabled=False)
    reg = MetricsRegistry()
    got = []
    hb = Heartbeater(reg, "e0", interval_ms=50, send=got.append)
    reg.counter("t.n").inc()
    hb.beat()
    assert "journal" not in got[0]


# ---------------------------------------------------------------------------
# ring bound
# ---------------------------------------------------------------------------

def test_journal_ring_is_bounded_and_keeps_newest():
    j = EventJournal("e0", origin="p0", ring_size=16,
                     registry=MetricsRegistry())
    for k in range(100):
        j.emit("autotune.adjust", beat=k)
    ev = j.events()
    assert len(ev) == 16
    assert ev[-1]["seq"] == 100  # newest survive


# ---------------------------------------------------------------------------
# capacity plane: two-tenant quota backpressure e2e
# ---------------------------------------------------------------------------

def test_capacity_names_blocked_resource_as_binding():
    """tenant-hog blocks at a tiny mempool quota while tenant-quiet
    stays in budget: the USE report must name mempool as THE binding
    resource, with less headroom than every other resource shows
    utilization."""
    _quota.reset()
    conf = TpuShuffleConf({
        "tpu.shuffle.tenancy.quota.hog.mempoolBytes": "1k",
        "tpu.shuffle.tenancy.quotaBlockMaxMs": "2000",
    })
    _quota.install(conf)
    broker = _quota.broker("mempool")
    broker.charge("hog", 1024)   # at quota
    broker.charge("quiet", 128)  # unconstrained neighbor
    blocked = threading.Thread(
        target=broker.charge, args=("hog", 512), daemon=True
    )
    blocked.start()
    deadline = time.monotonic() + 2.0
    while broker.waiting() == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    try:
        assert broker.waiting() == 1
        # fresh registry: the binding verdict must come from the live
        # broker state, not whatever lifetime counters earlier test
        # files left in the process-wide registry
        plane = CapacityPlane(conf, MetricsRegistry(), role="driver")
        report = plane.capacity_report(refresh=True)
        assert set(report["resources"]) == set(RESOURCES)
        binding = report["binding"]
        assert binding["resource"] == "mempool"
        assert binding["utilization"] == 1.0
        assert binding["headroom"] == 0.0
        for name, row in report["resources"].items():
            if name == "mempool":
                continue
            util = row["utilization"]
            assert util is None or binding["headroom"] < 1.0 - util + 1e-9
    finally:
        broker.release("hog", 1024)
        blocked.join(timeout=5)
        _quota.reset()


def test_capacity_blocked_in_interval_pins_utilization():
    """A quota hit BETWEEN two evaluations (usage already released at
    evaluation time) still pins that interval's utilization at 1.0 via
    the block-counter delta."""
    _quota.reset()
    conf = TpuShuffleConf({
        "tpu.shuffle.tenancy.quota.hog.mempoolBytes": "1k",
        "tpu.shuffle.tenancy.quotaBlockMaxMs": "20",
    })
    _quota.install(conf)
    broker = _quota.broker("mempool")
    try:
        plane = CapacityPlane(conf, get_registry(), role="driver")
        plane.evaluate()  # baseline: no blocks yet this interval
        broker.charge("hog", 1024)
        broker.charge("hog", 512)  # blocks, overruns after 20 ms
        broker.release("hog", 1536)  # ledger reads 0 again
        row = {r["resource"]: r for r in plane.evaluate()}["mempool"]
        assert row["utilization"] == 1.0
        assert row["detail"].get("blocked_in_interval") == 1
    finally:
        _quota.reset()


def test_capacity_disabled_by_knob():
    conf = TpuShuffleConf({"tpu.shuffle.obs.capacity.enabled": "false"})
    plane = CapacityPlane(conf, MetricsRegistry())
    assert plane.maybe_evaluate() is False


# ---------------------------------------------------------------------------
# exports: extraction, timeline, Chrome instants, diagnosis evidence
# ---------------------------------------------------------------------------

def test_extract_events_handles_every_artifact_shape():
    ev = [{"kind": "slo.page", "hlc": [5, 0], "origin": "p", "seq": 1,
           "wall_ms": 5}]
    assert extract_events(ev) == ev
    assert extract_events({"journal": ev}) == ev
    assert extract_events({"slo": {"journal": ev}}) == ev
    assert extract_events({"journal": {"events": ev}}) == ev
    assert extract_events({"nothing": 1}) == []
    text = render_timeline(ev)
    assert "slo.page" in text and "1 events" in text


def test_chrome_trace_carries_journal_instants():
    from sparkrdma_tpu.obs.trace import to_chrome_trace

    ev = [{"kind": "meta.takeover", "hlc": [7, 0], "origin": "p",
           "seq": 1, "wall_ms": 7, "executor": "e0"}]
    doc = to_chrome_trace(tracers=[], journal_events=ev)
    instants = [e for e in doc["traceEvents"] if e.get("ph") == "i"]
    assert len(instants) == 1
    inst = instants[0]
    assert inst["name"] == "meta.takeover"
    assert inst["ts"] == 7000 and inst["args"]["hlc"] == [7, 0]


def test_diagnosis_gains_saturated_resource_cause():
    plane = SimpleNamespace(capacity_report=lambda refresh=True: {
        "enabled": True, "evaluations": 3,
        "resources": {"mempool": {"utilization": 1.0, "saturation": 9,
                                  "errors": 0, "detail": {}}},
        "binding": {"resource": "mempool", "utilization": 1.0,
                    "headroom": 0.0, "saturation": 9, "errors": 0},
    })
    hub = SimpleNamespace(capacity=plane, journal=None, role="driver")
    breach = {"objective": "o", "kind": "latency", "severity": "page",
              "wall_ms": 1000}
    diag = build_diagnosis(hub, breach, registry=MetricsRegistry())
    sat = [c for c in diag["causes"] if c["cause"] == "saturated-resource"]
    assert len(sat) == 1
    assert sat[0]["detail"]["resource"] == ["mempool"]
    assert diag["evidence"]["capacity"]["binding"]["resource"] == "mempool"


# ---------------------------------------------------------------------------
# chaos e2e: driver:kill through a real in-process cluster
# ---------------------------------------------------------------------------

def test_driver_kill_e2e_journal_flight_record_and_diagnosis(tmp_path):
    """ISSUE 20 acceptance: the merged journal's HLC order reproduces
    driver.kill -> meta.takeover -> meta.adopt, the flight record
    attaches the last-N events, and build_diagnosis cites a journal
    event as ranked evidence."""
    from sparkrdma_tpu.engine.context import TpuContext
    from sparkrdma_tpu.testing import faults as _faults

    conf = TpuShuffleConf({
        "tpu.shuffle.faultPlan": "driver:kill:1:stage=reduce_phase",
    })
    try:
        with TpuContext(num_executors=2, conf=conf) as ctx:
            data = [(f"k-{i % 53}", 1) for i in range(3000)]
            rdd = ctx.parallelize(data, 6).reduce_by_key(lambda a, b: a + b)
            assert rdd.collect()
            ctx.telemetry_flush()
            hub = ctx.driver.telemetry
            assert hub is not None
            merged = hub.journal.merged()
            flight = hub.flight_record(
                "journal-e2e", path=str(tmp_path / "flight.json"))
            breach = {"objective": "task-p99", "kind": "latency",
                      "severity": "page",
                      "wall_ms": merged[-1]["wall_ms"] + 1}
            diag = build_diagnosis(hub, breach)
    finally:
        _faults.uninstall()

    kinds = [e["kind"] for e in merged]
    ki = kinds.index("driver.kill")
    ti = next(i for i in range(ki + 1, len(kinds))
              if kinds[i] == "meta.takeover")
    ai = next(i for i in range(ti + 1, len(kinds))
              if kinds[i] == "meta.adopt")
    assert ki < ti < ai
    keys = [sort_key(e) for e in merged]
    assert keys == sorted(keys)

    doc = json.loads((tmp_path / "flight.json").read_text())
    attached = extract_events(doc)
    assert attached, "flight record must attach journal events"
    assert any(e["kind"] == "driver.kill" for e in attached)
    assert doc["capacity"]["binding"] is not None

    cited = [
        c for c in diag["causes"]
        if c["detail"].get("events") or c["detail"].get("journal_events")
    ]
    assert any(c["cause"] == "dead-metastore-peer" for c in cited), \
        diag["causes"]
