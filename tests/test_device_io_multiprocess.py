"""Device-block shuffle across real OS processes.

The reference's deployment unit is one endpoint per executor JVM
(RdmaNode per process); the in-process DeviceShuffleIO tests share a
process. Here a child process publishes device blocks into its own
registered memory and the parent's executor pulls them with one-sided
READs over real TCP and stages them into its own device pool — the
full cross-process path the driver's dryrun approximates with
threads.
"""

import multiprocessing as mp
import os

import numpy as np
import pytest

from sparkrdma_tpu.native.transport_lib import toolchain_available
from sparkrdma_tpu.shuffle.device_io import DeviceShuffleIO
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.utils.config import TpuShuffleConf

SHUFFLE_ID = 31
PARTS = 3


def _pattern(pid: int) -> np.ndarray:
    rng = np.random.default_rng(1000 + pid)
    return rng.integers(0, 256, 3000 + 700 * pid, dtype=np.uint8)


def _publisher_main(conf_dict, q_out, q_in):
    # child owns its own JAX runtime on CPU (the env var must be set
    # before import; see tests/conftest.py)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    conf = TpuShuffleConf(conf_dict)
    ex = TpuShuffleManager(conf, is_driver=False, executor_id="proc-pub")
    io = DeviceShuffleIO(ex)
    try:
        io.publish_device_blocks(
            SHUFFLE_ID, {p: _pattern(p) for p in range(PARTS)}
        )
        q_out.put("published")
        # keep serving one-sided READs until the parent is done
        assert q_in.get(timeout=120) == "stop"
    finally:
        io.stop()
        ex.stop()


@pytest.mark.parametrize(
    "transport",
    ["python", pytest.param("native", marks=pytest.mark.skipif(
        not toolchain_available(), reason="no g++ toolchain"))],
)
def test_cross_process_device_block_shuffle(transport):
    conf = TpuShuffleConf({"tpu.shuffle.transport": transport})
    driver = TpuShuffleManager(conf, is_driver=True)
    handle = BaseShuffleHandle(
        shuffle_id=SHUFFLE_ID, num_maps=1, partitioner=HashPartitioner(PARTS)
    )
    driver.register_shuffle(handle)

    ctx = mp.get_context("spawn")
    q_out, q_in = ctx.Queue(), ctx.Queue()
    child_conf = {
        "tpu.shuffle.transport": transport,
        "tpu.shuffle.driverPort": str(driver.node.port),
    }
    child = ctx.Process(
        target=_publisher_main, args=(child_conf, q_out, q_in), daemon=True
    )
    child.start()
    reader = TpuShuffleManager(
        TpuShuffleConf(dict(child_conf)), is_driver=False,
        executor_id="proc-read",
    )
    io = DeviceShuffleIO(reader)
    try:
        assert q_out.get(timeout=120) == "published"
        got = io.fetch_device_blocks(SHUFFLE_ID, 0, PARTS, timeout_s=60)
        assert set(got) == set(range(PARTS))
        for p in range(PARTS):
            (buf,) = got[p]
            want = _pattern(p)
            assert buf.length == want.nbytes
            assert buf.read(0, buf.length) == want.tobytes(), (
                f"partition {p} bytes differ across processes"
            )
            buf.free()
        if transport == "native":
            # co-located processes: every READ must ride the same-host
            # pread fast path, zero streamed
            m = io.metrics_snapshot()
            assert m["reads_samehost_fast_path"] == PARTS
            assert m["reads_streamed"] == 0
    finally:
        q_in.put("stop")
        io.stop()
        reader.stop()
        child.join(timeout=30)
        if child.is_alive():
            child.terminate()
        driver.stop()
