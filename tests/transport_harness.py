"""Shared harness for the rpc/data channel-split (head-of-line) tests.

Keeps the data channel continuously saturated with in-flight READs —
each completion reposts itself until ``stop_when`` is set — so a control
round-trip racing it is provably concurrent with data traffic. The
repost decision and the posted-count increment happen under one lock
hold: deciding to repost outside the lock would let the drain handshake
fire while a READ is still about to be posted.
"""

import threading


def saturate_reads_until(channel, mkey, nbytes, dsts, stop_when,
                         read_errs, drained):
    """Start one self-reposting READ per dst. READs of
    ``(mkey, 0, nbytes)`` repost until ``stop_when`` (an Event) is set;
    ``drained`` fires once every posted READ has completed. Returns a
    ``finish()`` callable: call it after ``stop_when`` is set to resolve
    the in-flight==0 handshake, then wait on ``drained``."""
    from sparkrdma_tpu.transport import FnListener

    state = {"posted": 0, "done": 0, "stop": False}
    lock = threading.Lock()

    def submit(dst):
        channel.read_in_queue(
            FnListener(lambda _, d=dst: on_read(d),
                       lambda e: (read_errs.append(e), drained.set())),
            [dst],
            [(mkey, 0, nbytes)],
        )

    def on_read(dst):
        with lock:
            state["done"] += 1
            repost = not (state["stop"] or stop_when.is_set())
            if repost:
                state["posted"] += 1
            elif state["done"] == state["posted"]:
                drained.set()
        if repost:
            submit(dst)

    for dst in dsts:
        with lock:
            state["posted"] += 1
        submit(dst)

    def finish():
        with lock:
            state["stop"] = True
            if state["done"] == state["posted"]:
                drained.set()
            return state["done"]

    return finish
