"""Drop-in SPI proof (SURVEY.md §5.1 invariant #1): a FOREIGN engine
(examples/minispark.py — its own conf, partitioner, handle, and builtin
shuffle; zero framework imports at module level) swaps its entire
shuffle plane for TpuShuffleManager by setting ONE config key, with the
user job unchanged — the reference's defining capability
(README.md:52-58, spark.shuffle.manager=...RdmaShuffleManager).
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))

from minispark import MiniConf, MiniSparkContext, wordcount_job  # noqa: E402

SWAP_KEY = "engine.shuffle.manager"
SWAP_VALUE = "sparkrdma_tpu.shuffle.TpuShuffleManager"


def _run(conf=None):
    ctx = MiniSparkContext(conf)
    try:
        return wordcount_job(ctx), ctx
    finally:
        ctx.stop()


def test_one_key_swaps_shuffle_plane_same_results():
    stock, stock_ctx = _run()
    swapped, ctx = _run(MiniConf().set(SWAP_KEY, SWAP_VALUE))
    assert stock == swapped
    # the swap genuinely instantiated the framework plane
    from sparkrdma_tpu.shuffle import TpuShuffleManager

    assert isinstance(ctx.driver, TpuShuffleManager)
    assert all(isinstance(e, TpuShuffleManager) for e in ctx.executors)
    # and the stock run never touched it
    from minispark import BuiltinShuffleManager

    assert isinstance(stock_ctx.driver, BuiltinShuffleManager)


def test_driver_port_written_back_into_engine_conf():
    # SparkConf semantics (RdmaShuffleManager.scala:183-184): the driver
    # records its negotiated listener port in the ENGINE's own mapping
    # so executors constructed from it later can connect
    conf = MiniConf().set(SWAP_KEY, SWAP_VALUE)
    ctx = MiniSparkContext(conf)
    try:
        assert conf.get("tpu.shuffle.driverPort") is not None
        assert int(conf["tpu.shuffle.driverPort"]) == ctx.driver.node.port
    finally:
        ctx.stop()


def test_swap_works_over_native_transport():
    from sparkrdma_tpu.native.transport_lib import available

    if not available():
        pytest.skip("native transport unavailable")
    stock, _ = _run()
    conf = (
        MiniConf()
        .set(SWAP_KEY, SWAP_VALUE)
        .set("tpu.shuffle.transport", "native")
    )
    swapped, ctx = _run(conf)
    assert stock == swapped
    from sparkrdma_tpu.transport.native_node import NativeTpuNode

    assert isinstance(ctx.driver.node, NativeTpuNode)


def test_engine_only_speaks_the_documented_spi():
    """The engine module must not import the framework at module scope
    (only the config-key class path connects them)."""
    import ast
    import inspect

    import minispark

    tree = ast.parse(inspect.getsource(minispark))
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            names = [a.name for a in node.names]
            mod = getattr(node, "module", "") or ""
            assert not mod.startswith("sparkrdma_tpu"), mod
            assert not any(n.startswith("sparkrdma_tpu") for n in names), names
