"""Buffer plane tests: pool reuse/leak accounting and slice refcounts —
properties the reference implies but never checks (SURVEY.md §4:
RdmaBufferManager.java:131-141, RdmaRegisteredBuffer.java:52-107)."""

import pytest

from sparkrdma_tpu.memory import (
    ProtectionDomain,
    RegisteredBuffer,
    TpuBuffer,
    TpuBufferManager,
)
from sparkrdma_tpu.memory.buffer_manager import MIN_BLOCK_SIZE, next_power_of_2


def test_power_of_two_rounding():
    assert next_power_of_2(1) == MIN_BLOCK_SIZE
    assert next_power_of_2(MIN_BLOCK_SIZE) == MIN_BLOCK_SIZE
    assert next_power_of_2(MIN_BLOCK_SIZE + 1) == 2 * MIN_BLOCK_SIZE
    assert next_power_of_2(100_000) == 131072


def test_buffer_write_read_and_registration():
    pd = ProtectionDomain()
    buf = TpuBuffer(pd, 1024)
    assert buf.mkey != 0
    buf.write(b"hello world", offset=100)
    assert buf.read(100, 11) == b"hello world"
    # the PD resolves one-sided reads into this region
    assert bytes(pd.resolve(buf.mkey, 100, 11)) == b"hello world"
    buf.free()
    with pytest.raises(KeyError):
        pd.resolve(buf.mkey, 0, 1)


def test_pd_bounds_check():
    pd = ProtectionDomain()
    buf = TpuBuffer(pd, 1024)
    with pytest.raises(KeyError):
        pd.resolve(buf.mkey, 1000, 100)
    buf.free()


def test_pool_reuse():
    pd = ProtectionDomain()
    mgr = TpuBufferManager(pd)
    a = mgr.get(10_000)
    assert a.length == MIN_BLOCK_SIZE  # rounded up to 16 KiB floor
    mgr.put(a)
    b = mgr.get(16_000)
    assert b is a  # LIFO reuse from the same size class
    assert mgr.stats()[MIN_BLOCK_SIZE] == 1  # only one real allocation
    mgr.stop()


def test_pool_prealloc():
    pd = ProtectionDomain()
    mgr = TpuBufferManager(pd, is_executor=True, max_agg_block=1 << 20, max_agg_prealloc=4)
    assert mgr.stats()[1 << 20] == 4
    bufs = [mgr.get(1 << 20) for _ in range(4)]
    assert mgr.stats()[1 << 20] == 4  # served from prealloc, no new allocs
    for buf in bufs:
        mgr.put(buf)
    mgr.stop()


def test_registered_buffer_slices_and_refcount():
    pd = ProtectionDomain()
    mgr = TpuBufferManager(pd)
    rb = RegisteredBuffer(mgr, 32 * 1024)
    s1 = rb.slice(1000)
    s2 = rb.slice(2000)
    assert s1.address == 0 and s2.address == 1000
    assert s1.mkey == s2.mkey == rb.mkey
    s1.view[:] = b"a" * 1000
    s2.view[:] = b"b" * 2000
    # slices resolve through the PD at their published (mkey, address)
    assert bytes(pd.resolve(s1.mkey, s1.address, 4)) == b"aaaa"
    assert bytes(pd.resolve(s2.mkey, s2.address, 4)) == b"bbbb"
    assert rb.ref_count() == 2
    s1.release()
    assert rb.ref_count() == 1
    s2.release()  # refcount 0 → returned to pool
    assert rb.ref_count() == 0
    reused = mgr.get(32 * 1024)
    assert reused.length == 32 * 1024
    mgr.stop()


def test_native_arena_stats_if_available():
    from sparkrdma_tpu.native.arena import NativeArena, native_arena_available

    if not native_arena_available():
        pytest.skip("native arena toolchain unavailable")
    arena = NativeArena.shared()
    total0, live0, count0 = arena.stats()
    aid, view = arena.alloc(4096)
    view[:5] = b"abcde"
    assert bytes(view[:5]) == b"abcde"
    total1, live1, count1 = arena.stats()
    assert total1 == total0 + 1 and count1 == count0 + 1
    del view
    arena.free(aid)
    _, live2, count2 = arena.stats()
    assert count2 == count0 and live2 == live0
