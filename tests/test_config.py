from sparkrdma_tpu.utils.config import ShuffleWriterMethod, TpuShuffleConf
from sparkrdma_tpu.utils.units import format_bytes, parse_bytes


def test_parse_bytes():
    assert parse_bytes("4k") == 4096
    assert parse_bytes("8m") == 8 << 20
    assert parse_bytes("25g") == 25 << 30
    assert parse_bytes("123") == 123
    assert parse_bytes(42) == 42
    assert parse_bytes("1kb") == 1024
    assert format_bytes(8 << 20) == "8m"


def test_defaults_match_reference_operating_point():
    c = TpuShuffleConf()
    assert c.recv_queue_depth == 2048
    assert c.send_queue_depth == 4096
    assert c.recv_wr_size == 4096
    assert c.shuffle_write_chunk_size == 128 << 10
    assert c.shuffle_write_flush_size == 256 << 10
    assert c.shuffle_write_block_size == 8 << 20
    assert c.shuffle_write_max_inmemory_per_executor == 25 << 30
    assert c.shuffle_read_block_size == 8 << 20
    assert c.max_bytes_in_flight == 128 << 20
    assert c.max_agg_block == 2 << 20
    assert c.max_agg_prealloc == 0
    assert c.shuffle_writer_method == ShuffleWriterMethod.WRAPPER
    assert not c.collect_shuffle_read_stats


def test_out_of_range_clamps_to_default():
    c = TpuShuffleConf({"tpu.shuffle.recvQueueDepth": "10"})  # below min 256
    assert c.recv_queue_depth == 2048
    c = TpuShuffleConf({"tpu.shuffle.recvQueueDepth": "garbage"})
    assert c.recv_queue_depth == 2048
    c = TpuShuffleConf({"tpu.shuffle.recvQueueDepth": "512"})
    assert c.recv_queue_depth == 512


def test_writer_method_parse():
    c = TpuShuffleConf({"tpu.shuffle.shuffleWriteMethod": "ChunkedPartitionAgg"})
    assert c.shuffle_writer_method == ShuffleWriterMethod.CHUNKED_PARTITION_AGG
    c = TpuShuffleConf({"tpu.shuffle.shuffleWriteMethod": "bogus"})
    assert c.shuffle_writer_method == ShuffleWriterMethod.WRAPPER


def test_driver_port_writeback():
    c = TpuShuffleConf()
    assert c.driver_port == 0
    c.set_driver_port(12345)
    assert c.driver_port == 12345
