"""A C-only peer on the native wire (examples/foreign_client.c).

The reference's transport is consumable from any JVM language because
DiSNI exposes a C ABI (pom.xml:67-81); the equivalent claim here is
that the wire format (transport/wire.py == native/transport.cpp) is
implementable from scratch in ~400 lines of C with no framework code.
This test drives the full choreography against a live Python driver +
executor:

  C client --HELLO + ManagerHello-->  driver
  C client --PublishPartitionLocations(own registered memory)--> driver
  C client --FetchPartitionLocations--> driver --locations--> C client
  C client --READ_REQ--> Python executor --READ_RESP bytes--> C client
  Python   --fetch locations of C shuffle--> driver
  Python   --READ_REQ--> C client --READ_RESP bytes--> Python

Both directions are verified byte-exact.
"""

import os
import shutil
import subprocess
import threading
import time

import numpy as np
import pytest

from sparkrdma_tpu.locations import BlockLocation, PartitionLocation
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.transport import FnListener
from sparkrdma_tpu.utils.config import TpuShuffleConf

FETCH_SHUFFLE = 21   # python publishes, C fetches
PUBLISH_SHUFFLE = 22  # C publishes, python fetches
C_PATTERN_LEN = 64 * 1024


def c_pattern() -> bytes:
    return bytes((i * 31 + 7) & 0xFF for i in range(C_PATTERN_LEN))


@pytest.mark.skipif(shutil.which("gcc") is None, reason="no C toolchain")
@pytest.mark.parametrize("transport", ["python", "native"])
def test_c_client_full_shuffle_choreography(tmp_path, transport):
    """Same C binary against both server planes: the pure-Python node
    and the C++ epoll node (transport.cpp) — one wire, three
    languages."""
    binary = tmp_path / "foreign_client"
    src = os.path.join(
        os.path.dirname(__file__), "..", "examples", "foreign_client.c"
    )
    subprocess.run(["gcc", "-O2", "-o", str(binary), src], check=True)

    conf = TpuShuffleConf({"tpu.shuffle.transport": transport})
    driver = TpuShuffleManager(conf, is_driver=True)
    ex0 = TpuShuffleManager(conf, is_driver=False, executor_id="py-0")
    ex0.start_node_if_missing()
    child = None
    regs = []
    try:
        driver.register_shuffle(
            BaseShuffleHandle(
                shuffle_id=FETCH_SHUFFLE, num_maps=2,
                partitioner=HashPartitioner(1),
            )
        )
        driver.register_shuffle(
            BaseShuffleHandle(
                shuffle_id=PUBLISH_SHUFFLE, num_maps=1,
                partitioner=HashPartitioner(1),
            )
        )
        # python side publishes TWO map outputs for partition 0, so the
        # C client must consume several locations of ONE partition
        rng = np.random.default_rng(17)
        py_payloads = [
            rng.integers(0, 256, n, dtype=np.uint8).tobytes()
            for n in (48_000, 23_000)
        ]
        for payload in py_payloads:
            reg = ex0.buffer_manager.get(len(payload))
            regs.append(reg)
            np.frombuffer(reg.view, np.uint8, len(payload))[:] = np.frombuffer(
                payload, np.uint8
            )
            ex0.publish_partition_locations(
                FETCH_SHUFFLE,
                -1,
                [
                    PartitionLocation(
                        ex0.local_manager_id,
                        0,
                        BlockLocation(0, len(payload), reg.mkey),
                    )
                ],
                num_map_outputs=1,
            )
        py_payload = b"".join(py_payloads)

        out_path = tmp_path / "fetched.bin"
        child = subprocess.Popen(
            [
                str(binary),
                "127.0.0.1",
                str(conf.driver_port),
                str(FETCH_SHUFFLE),
                str(PUBLISH_SHUFFLE),
                str(out_path),
            ],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            text=True,
        )
        ready = child.stdout.readline().split()
        assert ready and ready[0] == "READY", ready
        fetched = child.stdout.readline().split()
        assert fetched and fetched[0] == "FETCHED_OK", fetched
        assert int(fetched[1]) == len(py_payload)
        # direction 1: C pulled the python executor's bytes via READ_REQ
        assert out_path.read_bytes() == py_payload

        # direction 2: python fetches the C client's published partition
        locs = ex0.fetch_remote_partition_locations(
            PUBLISH_SHUFFLE, 0, 1
        ).result(timeout=30)
        assert len(locs) == 1
        loc = locs[0]
        assert loc.manager_id.executor_id == "c-client-0"
        assert loc.block.length == C_PATTERN_LEN
        dst = ex0.buffer_manager.get(loc.block.length)
        try:
            done = threading.Event()
            errs = []

            def on_fail(e):
                errs.append(e)
                done.set()

            ch = ex0.node.get_channel(
                loc.manager_id.host, loc.manager_id.port, "data"
            )
            ch.read_in_queue(
                FnListener(lambda _: done.set(), on_fail),
                [dst.view[: loc.block.length]],
                [(loc.block.mkey, loc.block.address, loc.block.length)],
            )
            assert done.wait(30), "READ from C client timed out"
            assert not errs, errs
            got = bytes(dst.view[: loc.block.length])
            assert got == c_pattern(), "C-served bytes differ"
        finally:
            ex0.buffer_manager.put(dst)

        child.stdin.close()  # shutdown signal
        assert child.wait(timeout=10) == 0
        child = None
    finally:
        if child is not None:
            child.kill()
            child.wait()
        for reg in regs:
            ex0.buffer_manager.put(reg)
        ex0.stop()
        driver.stop()
        time.sleep(0.1)
