"""Pallas flash attention vs dense reference (interpreter mode on CPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparkrdma_tpu.ops.pallas_attention import flash_attention
from sparkrdma_tpu.ops.ring_attention import reference_attention


def _inputs(b=1, s=96, h=2, d=32, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(causal):
    q, k, v = _inputs()
    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_flash_unpadded_vs_padded_seq():
    # seq length not a multiple of the block: padded kv rows must be masked
    q, k, v = _inputs(s=50)
    out = flash_attention(q, k, v, block_q=32, block_k=32)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_flash_multi_kv_blocks_online_softmax():
    # several k blocks exercise the running-max renormalization
    q, k, v = _inputs(s=256)
    out = flash_attention(q, k, v, block_q=64, block_k=32)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_flash_mismatched_block_sizes_cover_full_kv():
    """Regression: s_pad must divide by BOTH block sizes, or tail kv
    blocks are silently never attended."""
    q, k, v = _inputs(s=128)
    out = flash_attention(q, k, v, block_q=128, block_k=96)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_matches_dense_autodiff(causal):
    """The custom VJP (blockwise dq / dkdv kernels re-materializing
    probability tiles from the saved logsumexp) must agree with
    autodiff through the dense reference."""
    q, k, v = _inputs(s=96)
    rng = np.random.default_rng(7)
    ct = jnp.asarray(rng.normal(size=q.shape).astype(np.float32))

    def f(q, k, v):
        return (
            flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
            * ct
        ).sum()

    def g(q, k, v):
        return (reference_attention(q, k, v, causal=causal) * ct).sum()

    gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip(("dq", "dk", "dv"), gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
            err_msg=f"{name} mismatch ({causal=})",
        )


def test_flash_backward_mismatched_blocks():
    """Gradients stay exact when block_q != block_k (different sweep
    geometries in the dq and dkdv kernels)."""
    q, k, v = _inputs(s=128)
    f = lambda q, k, v: flash_attention(
        q, k, v, causal=True, block_q=64, block_k=32
    ).sum()
    g = lambda q, k, v: reference_attention(q, k, v, causal=True).sum()
    gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
        )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_padded_seq(causal):
    """s=50 with 32-blocks genuinely pads (s_pad=64): padded rows must
    contribute exactly zero gradient (lse pinned to +inf for dead
    rows, masked kv columns) and live-row gradients stay exact."""
    q, k, v = _inputs(s=50)
    rng = np.random.default_rng(11)
    ct = jnp.asarray(rng.normal(size=q.shape).astype(np.float32))

    def f(q, k, v):
        return (
            flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
            * ct
        ).sum()

    def g(q, k, v):
        return (reference_attention(q, k, v, causal=causal) * ct).sum()

    gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip(("dq", "dk", "dv"), gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
            err_msg=f"{name} mismatch under padding ({causal=})",
        )
