"""Device exchange plane tests (8-device virtual CPU mesh, conftest.py).

Covers the property targets SURVEY.md §4 lists as implied-but-unchecked
in the reference, transposed to the device plane: block round-trip
through the exchange, length-prefix integrity, and schedule equivalence
(all_to_all vs ring)."""

import numpy as np
import pytest


from sparkrdma_tpu.ops.exchange import (
    ExchangeProgram,
    pack_blocks,
    round_bucket,
    round_rows,
    unpack_blocks,
)
from sparkrdma_tpu.parallel.mesh import make_mesh


def _payload(src: int, dst: int) -> bytes:
    return bytes([src, dst]) * (37 + 13 * src + 7 * dst)


def test_round_bucket_power_of_two():
    assert round_bucket(1) == 1024
    assert round_bucket(1024) == 1024
    assert round_bucket(1025) == 2048
    assert round_bucket(100_000) == 131072


def test_pack_unpack_roundtrip():
    blocks = [b"alpha", b"", b"x" * 100]
    slab, counts = pack_blocks(blocks, 128)
    assert slab.shape == (3, 128)
    assert list(counts) == [5, 0, 100]
    assert unpack_blocks(slab, counts) == blocks


def test_pack_rejects_oversize():
    with pytest.raises(ValueError):
        pack_blocks([b"x" * 129], 128)


def _build_global_send(e: int, block: int):
    """Global [E*E, block] slab: shard s's row d holds _payload(s, d)."""
    rows = []
    counts = []
    for src in range(e):
        slab, cnt = pack_blocks([_payload(src, dst) for dst in range(e)], block)
        rows.append(slab)
        counts.append(cnt)
    return np.concatenate(rows, axis=0), np.concatenate(counts, axis=0)


@pytest.mark.parametrize("schedule", ["all_to_all", "ring"])
def test_exchange_delivers_every_block(schedule):
    mesh = make_mesh()
    prog = ExchangeProgram(mesh)
    e = prog.num_shards
    assert e == 8
    block = 512
    send, counts = _build_global_send(e, block)
    fn = prog.exchange if schedule == "all_to_all" else prog.ring_exchange
    recv, rcounts = fn(send, counts)
    recv = np.asarray(recv).reshape(e, e, block)
    rcounts = np.asarray(rcounts).reshape(e, e)
    for dst in range(e):
        got = unpack_blocks(recv[dst], rcounts[dst])
        assert got == [_payload(src, dst) for src in range(e)]


def test_exchange_compile_once():
    mesh = make_mesh()
    prog = ExchangeProgram(mesh)
    e = prog.num_shards
    send, counts = _build_global_send(e, 512)
    prog.exchange(send, counts)
    assert len(prog._all_to_all_cache) == 1
    prog.exchange(send, counts)  # same shapes: cache hit
    assert len(prog._all_to_all_cache) == 1
    prog.exchange(np.zeros((e * e, 1024), np.uint8), np.zeros((e * e,), np.int32))
    assert len(prog._all_to_all_cache) == 2


@pytest.mark.parametrize("schedule", ["all_to_all", "ring"])
def test_exchange_transfer_accounting(schedule):
    """Per-schedule counters record BOTH directions and wall time, so
    a2a-vs-ring claims can cite transfer counters (VERDICT r4 weak #6:
    send-side capacity alone can't back a schedule comparison)."""
    mesh = make_mesh()
    prog = ExchangeProgram(mesh)
    e = prog.num_shards
    block = 512
    send, counts = _build_global_send(e, block)
    label = "a2a" if schedule == "all_to_all" else "ring"
    fn = prog.exchange if schedule == "all_to_all" else prog.ring_exchange
    fn(send, counts)
    fn(send, counts)
    s = prog.stats[label]
    cap = e * e * block
    valid = sum(len(_payload(src, dst)) for src in range(e) for dst in range(e))
    assert s["exchanges"] == 2
    assert s["bytes_sent"] == 2 * cap
    assert s["bytes_received"] == 2 * cap
    # every staged byte arrived: the valid-byte counter equals the sum
    # of all length prefixes, proving receive-side accounting is real
    assert s["bytes_received_valid"] == 2 * valid
    assert s["time_s"] > 0.0
    # the other schedule's counters stay untouched
    other = prog.stats["ring" if label == "a2a" else "a2a"]
    assert other["exchanges"] == 0 and other["bytes_received_valid"] == 0
    # legacy aggregates still advance
    assert prog.exchanges == 2 and prog.bytes_moved == 2 * cap


def test_exchange_on_2d_mesh():
    """Multi-slice (dcn, exec) mesh: peer index order must match the
    dcn-major sharding order."""
    mesh = make_mesh(num_slices=2)  # (dcn=2, exec=4)
    prog = ExchangeProgram(mesh)
    e = prog.num_shards
    assert e == 8
    send, counts = _build_global_send(e, 512)
    recv, rcounts = prog.exchange(send, counts)
    recv = np.asarray(recv).reshape(e, e, 512)
    rcounts = np.asarray(rcounts).reshape(e, e)
    for dst in range(e):
        assert unpack_blocks(recv[dst], rcounts[dst]) == [
            _payload(src, dst) for src in range(e)
        ]


def test_round_rows_power_of_two():
    assert round_rows(1) == 1
    assert round_rows(3) == 4
    assert round_rows(4) == 4
    assert round_rows(5) == 8


def _build_global_send_multi(e: int, block: int, rpp: int):
    """Like _build_global_send but with ``rpp`` rows per (src, dst)
    pair, tagged so every row is distinguishable after the exchange."""
    rows, counts = [], []
    for src in range(e):
        blocks = [
            _payload(src, dst) + bytes([k])
            for dst in range(e)
            for k in range(rpp)
        ]
        slab, cnt = pack_blocks(blocks, block)
        rows.append(slab)
        counts.append(cnt)
    return np.concatenate(rows, axis=0), np.concatenate(counts, axis=0)


def test_exchange_row_bucketing_shares_programs():
    """Ragged row counts bucket to the same power-of-two program: a
    3-rows-per-peer stage pads to 4 and reuses the 4-rows-per-peer
    compilation, byte-exact after the pad rows are stripped."""
    mesh = make_mesh()
    prog = ExchangeProgram(mesh)
    e = prog.num_shards
    block = 512
    for rpp in (3, 4):
        send, counts = _build_global_send_multi(e, block, rpp)
        recv, rcounts = prog.exchange(send, counts)
        recv = np.asarray(recv).reshape(e, e, rpp, block)
        rcounts = np.asarray(rcounts).reshape(e, e, rpp)
        for dst in range(e):
            for src in range(e):
                assert unpack_blocks(recv[dst, src], rcounts[dst, src]) == [
                    _payload(src, dst) + bytes([k]) for k in range(rpp)
                ], f"rpp={rpp} src={src} dst={dst}"
    # both stages compiled into ONE cached program (rows bucketed 3->4)
    assert len(prog._all_to_all_cache) == 1
    assert round_rows(3) == round_rows(4) == 4
