"""Pipelined map plane (DESIGN.md "Pipelined map plane"): stage
overlap is real and measured, incremental publish feeds reducers
byte-identical input without breaking the driver barrier, and an abort
mid-pipeline never leaves a partial location set behind."""

import threading
import time

import pytest

from sparkrdma_tpu.obs import get_registry
from sparkrdma_tpu.shuffle.handle import BaseShuffleHandle, HashPartitioner
from sparkrdma_tpu.shuffle.manager import TpuShuffleManager
from sparkrdma_tpu.shuffle.writer.pipeline import MapTaskPipeline
from sparkrdma_tpu.utils.config import TpuShuffleConf


# ---------------------------------------------------------------------------
# pipeline overlap
# ---------------------------------------------------------------------------

def test_pipeline_stages_overlap():
    """With per-stage sleeps, the sum of stage busy time must exceed the
    wall — the overlap the pipeline exists to buy — and the
    writer.pipeline.* metrics must record it."""
    get_registry().reset()
    d = 0.05

    def sort_fn(i):
        time.sleep(d)
        return ("sorted", i)

    def stage_fn(i, s):
        time.sleep(d)
        return ("staged", i)

    def publish_fn(i, st):
        time.sleep(d)
        return ("published", i)

    pipe = MapTaskPipeline(
        sort_fn, stage_fn, publish_fn, parallelism=2, depth=2, role="t-overlap"
    )
    report = pipe.run(range(6))
    assert report.results == [("published", i) for i in range(6)]
    # 6 items x 3 stages x d of busy; a sequential run would wall 18d.
    # Any real overlap puts the wall strictly under the busy total.
    assert report.busy_total_s > report.wall_s
    assert report.overlap_s > 0

    snap = get_registry().snapshot(prefix="writer.pipeline")
    stage_keys = [k for k in snap["histograms"] if "stage_ms" in k]
    assert any("stage=sort" in k for k in stage_keys)
    assert any("stage=stage" in k for k in stage_keys)
    assert any("stage=publish" in k for k in stage_keys)
    for k in stage_keys:
        if "role=t-overlap" in k:
            assert snap["histograms"][k]["count"] == 6
    overlap_keys = [k for k in snap["histograms"] if "overlap_ms" in k]
    assert overlap_keys
    assert snap["histograms"][overlap_keys[0]]["sum"] > 0
    # every shard left the pipeline: the inflight gauge is back to zero
    (gk,) = [k for k in snap["gauges"] if "inflight" in k]
    assert snap["gauges"][gk]["value"] == 0
    assert snap["gauges"][gk]["hwm"] >= 2  # bounded concurrency happened


def test_pipeline_abort_skips_publish():
    """The first stage error latches; nothing downstream of it
    publishes, and run() re-raises the error after draining."""
    published = []
    entered = threading.Event()

    def sort_fn(i):
        if i == 1:
            entered.wait(5)  # let item 0 get ahead
            raise RuntimeError("boom")
        return i

    def publish_fn(i, st):
        published.append(i)
        entered.set()
        return i

    pipe = MapTaskPipeline(
        sort_fn, None, publish_fn, parallelism=2, depth=2, role="t-abort"
    )
    with pytest.raises(RuntimeError, match="boom"):
        pipe.run(range(8))
    # the failed item never published, and the abort latch stopped the
    # tail of the batch (item 0 may have raced through — that's the
    # per-shard atomicity the design asks for, not a partial shard)
    assert 1 not in published
    assert len(published) < 8


# ---------------------------------------------------------------------------
# incremental publish
# ---------------------------------------------------------------------------

def _incremental_conf(on: bool):
    return TpuShuffleConf(
        {
            "tpu.shuffle.shuffleWriteMethod": "chunkedpartitionagg",
            # smallest legal block/flush sizes (config clamps to
            # defaults below 64k/4k) so maps seal several blocks that
            # later commits' incremental windows can ship
            "tpu.shuffle.shuffleWriteBlockSize": "65536",
            "tpu.shuffle.shuffleWriteFlushSize": "4096",
            "tpu.shuffle.map.incrementalPublish": "true" if on else "false",
        }
    )


def _value(map_id: int, i: int) -> bytes:
    # deterministic and incompressible: the codec must not shrink
    # frames below the block-sealing threshold
    import hashlib

    return hashlib.sha256(f"{map_id}-{i}".encode()).digest() * 8


def _run_chunked(on: bool, probe=None):
    conf = _incremental_conf(on)
    driver = TpuShuffleManager(conf, is_driver=True)
    ex = TpuShuffleManager(conf, is_driver=False, executor_id="inc-0")
    try:
        handle = BaseShuffleHandle(
            shuffle_id=0, num_maps=3, partitioner=HashPartitioner(3)
        )
        driver.register_shuffle(handle)
        for map_id in range(3):
            w = ex.get_writer(handle, map_id)
            w.write(
                iter(
                    (f"k{(map_id * 2000 + i) % 97}", _value(map_id, i))
                    for i in range(2000)
                )
            )
            w.stop(True)
        if probe is not None:
            probe(driver)
        ex.finalize_maps(0)
        out = {}
        reader = ex.get_reader(handle, 0, 3)
        for k, v in reader.read():
            out.setdefault(k, []).append(v)
        return {k: sorted(vs) for k, vs in out.items()}
    finally:
        ex.stop()
        driver.stop()


def test_incremental_publish_is_byte_identical():
    """Reducers must see EXACTLY the same input whether locations went
    out incrementally or all at once — and the incremental run must
    actually have published early without completing the barrier."""
    get_registry().reset()
    baseline = _run_chunked(on=False)

    def probe(driver):
        # all 3 maps committed, finalize not yet called: incremental
        # location segments should have landed on the driver while the
        # map-output barrier stays OPEN (they carry num_map_outputs=0)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with driver._lock:
                if driver._partition_locations.get(0):
                    break
            time.sleep(0.02)
        with driver._lock:
            assert driver._partition_locations.get(0), (
                "no incremental locations reached the driver"
            )
            assert driver._maps_done.get(0, 0) == 0, (
                "barrier advanced before finalize — a fetch could have "
                "been answered from a partial location set"
            )

    incremental = _run_chunked(on=True, probe=probe)
    assert incremental == baseline

    snap = get_registry().snapshot(prefix="writer.incremental_publishes")
    assert sum(snap["counters"].values()) > 0, (
        "incremental mode never published early"
    )


def test_incremental_abort_leaves_no_usable_location_set():
    """A dirty failed map after incremental publishes must poison the
    shuffle: finalize refuses, and the driver barrier never completes —
    the already-uploaded locations are unreachable by any fetch."""
    from sparkrdma_tpu.shuffle.errors import ShuffleError

    get_registry().reset()
    conf = _incremental_conf(on=True)
    driver = TpuShuffleManager(conf, is_driver=True)
    ex = TpuShuffleManager(conf, is_driver=False, executor_id="inc-ab")
    try:
        handle = BaseShuffleHandle(
            shuffle_id=0, num_maps=2, partitioner=HashPartitioner(2)
        )
        driver.register_shuffle(handle)
        ok = ex.get_writer(handle, 0)
        ok.write(iter((f"k{i}", _value(0, i)) for i in range(2000)))
        ok.stop(True)  # commits; incremental segments upload
        bad = ex.get_writer(handle, 1)
        bad.write(iter((f"b{i}", _value(1, i)) for i in range(2000)))  # flushes
        bad.stop(False)  # dirty failure
        with pytest.raises(ShuffleError):
            ex.finalize_maps(0)
        with driver._lock:
            assert driver._maps_done.get(0, 0) == 0
    finally:
        ex.stop()
        driver.stop()
